"""devp2p + eth (PV62/63) wire messages.

Parity: khipu-eth/.../network/p2p/messages/ — WireProtocol.scala:13
(Hello/Disconnect/Ping/Pong), CommonMessages (Status/NewBlock/
SignedTransactions), PV62.scala:16 (GetBlockHeaders/BlockHeaders/
GetBlockBodies/BlockBodies/NewBlockHashes), PV63.scala:19 (GetNodeData/
NodeData/GetReceipts/Receipts). Frame payload = rlp(msg-code) ++
rlp(body) (p2p base codes 0x00-0x0f; eth sub-protocol offset 0x10).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.domain.block import Block, BlockBody
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.transaction import SignedTransaction
from khipu_tpu.evm.dataword import from_bytes, to_minimal_bytes

P2P_VERSION = 5
ETH_VERSION = 63
ETH_OFFSET = 0x10

# p2p base protocol codes
HELLO, DISCONNECT, PING, PONG = 0x00, 0x01, 0x02, 0x03
# eth codes (add ETH_OFFSET on the wire)
STATUS = 0x00
NEW_BLOCK_HASHES = 0x01
TRANSACTIONS = 0x02
GET_BLOCK_HEADERS = 0x03
BLOCK_HEADERS = 0x04
GET_BLOCK_BODIES = 0x05
BLOCK_BODIES = 0x06
NEW_BLOCK = 0x07
GET_NODE_DATA = 0x0D
NODE_DATA = 0x0E
GET_RECEIPTS = 0x0F
RECEIPTS = 0x10


def encode_message(code: int, body) -> bytes:
    """Frame payload: rlp(code) ++ rlp(body)."""
    return rlp_encode(to_minimal_bytes(code)) + rlp_encode(body)


def decode_message(payload: bytes) -> Tuple[int, object]:
    # rlp(code) is a single small int: 1 byte (0x80 = 0)
    code = 0 if payload[0] == 0x80 else payload[0]
    return code, rlp_decode(payload[1:])


@dataclass
class Hello:
    client_id: str
    capabilities: List[Tuple[str, int]] = field(
        default_factory=lambda: [("eth", ETH_VERSION)]
    )
    listen_port: int = 30303
    node_id: bytes = b"\x00" * 64
    p2p_version: int = P2P_VERSION

    def body(self):
        return [
            to_minimal_bytes(self.p2p_version),
            self.client_id.encode(),
            [[name.encode(), to_minimal_bytes(v)]
             for name, v in self.capabilities],
            to_minimal_bytes(self.listen_port),
            self.node_id,
        ]

    @staticmethod
    def from_body(b) -> "Hello":
        return Hello(
            p2p_version=from_bytes(b[0]),
            client_id=b[1].decode(errors="replace"),
            capabilities=[(c[0].decode(), from_bytes(c[1])) for c in b[2]],
            listen_port=from_bytes(b[3]),
            node_id=b[4],
        )


@dataclass
class Status:
    """eth Status (CommonMessages): protocol/network/TD/best/genesis."""

    protocol_version: int
    network_id: int
    total_difficulty: int
    best_hash: bytes
    genesis_hash: bytes

    def body(self):
        return [
            to_minimal_bytes(self.protocol_version),
            to_minimal_bytes(self.network_id),
            to_minimal_bytes(self.total_difficulty),
            self.best_hash,
            self.genesis_hash,
        ]

    @staticmethod
    def from_body(b) -> "Status":
        return Status(
            protocol_version=from_bytes(b[0]),
            network_id=from_bytes(b[1]),
            total_difficulty=from_bytes(b[2]),
            best_hash=b[3],
            genesis_hash=b[4],
        )


@dataclass
class GetBlockHeaders:
    """PV62: block (hash | number), maxHeaders, skip, reverse."""

    block: Union[int, bytes]
    max_headers: int = 1
    skip: int = 0
    reverse: bool = False

    def body(self):
        start = (
            self.block
            if isinstance(self.block, bytes)
            else to_minimal_bytes(self.block)
        )
        return [
            start,
            to_minimal_bytes(self.max_headers),
            to_minimal_bytes(self.skip),
            to_minimal_bytes(1 if self.reverse else 0),
        ]

    @staticmethod
    def from_body(b) -> "GetBlockHeaders":
        block = b[0] if len(b[0]) == 32 else from_bytes(b[0])
        return GetBlockHeaders(
            block, from_bytes(b[1]), from_bytes(b[2]), bool(from_bytes(b[3]))
        )


def encode_headers(headers: List[BlockHeader]):
    return [rlp_decode(h.encode()) for h in headers]


def decode_headers(body) -> List[BlockHeader]:
    return [BlockHeader.decode(rlp_encode(item)) for item in body]


def encode_bodies(bodies: List[BlockBody]):
    return [rlp_decode(b.encode()) for b in bodies]


def decode_bodies(body) -> List[BlockBody]:
    return [BlockBody.decode(rlp_encode(item)) for item in body]


def encode_transactions(txs: List[SignedTransaction]):
    return [rlp_decode(t.encode()) for t in txs]


def decode_transactions(body) -> List[SignedTransaction]:
    return [SignedTransaction.decode(rlp_encode(item)) for item in body]


def encode_new_block_hashes(pairs: List[Tuple[bytes, int]]):
    """NewBlockHashes (PV62.scala:16): [[hash, number], ...] — the
    lightweight announce sent to peers that don't get the full block."""
    return [[h, to_minimal_bytes(n)] for h, n in pairs]


def decode_new_block_hashes(body) -> List[Tuple[bytes, int]]:
    return [(item[0], from_bytes(item[1])) for item in body]


def encode_new_block(block: Block, td: int):
    return [rlp_decode(block.encode()), to_minimal_bytes(td)]


def decode_new_block(body) -> Tuple[Block, int]:
    return Block.decode(rlp_encode(body[0])), from_bytes(body[1])
