"""P2P network stack (khipu-eth/.../network/ role): ECIES, RLPx
handshake + framing, devp2p/eth wire messages, peers, discovery."""
