"""Peer connections over TCP: RLPx handshake -> Hello -> Status ->
message loop; plus the peer registry with blacklisting.

Parity: network/PeerManager.scala:40 (approve/create peer entities),
network/PeerEntity.scala:83 (per-peer mailbox, request-response
correlation), handshake/EtcHandshake.scala:161 (Hello exchange ->
Status -> fork check), blockchain/sync/HandshakedPeersService.scala
(blacklist with duration). Akka actors become one reader thread per
peer + callback dispatch; the snappy threshold follows p2p >= 5.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from khipu_tpu.base.crypto.secp256k1 import privkey_to_pubkey
from khipu_tpu.network import snappy_codec
from khipu_tpu.network.messages import (
    DISCONNECT,
    ETH_OFFSET,
    HELLO,
    PING,
    PONG,
    STATUS,
    Hello,
    Status,
    decode_message,
    encode_message,
)
from khipu_tpu.network.rlpx import AuthHandshake, FrameCodec
from khipu_tpu.base.rlp import rlp_encode
from khipu_tpu.evm.dataword import to_minimal_bytes


class PeerError(Exception):
    pass


def recv_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise PeerError("connection closed")
        out += chunk
    return out


class Peer:
    """One live connection. ``request(code, body)`` sends and blocks for
    the matching response code (PeerEntity's ask pattern)."""

    def __init__(self, sock: socket.socket, codec: FrameCodec,
                 remote_pub: bytes, inbound: bool):
        self.sock = sock
        self.codec = codec
        self.remote_pub = remote_pub
        self.inbound = inbound
        self.hello: Optional[Hello] = None
        self.status: Optional[Status] = None
        self.snappy = False
        self._send_lock = threading.Lock()
        # code -> FIFO of (Event, result-box) waiters
        self._waiters: Dict[int, list] = {}
        self._wlock = threading.Lock()
        self.handlers: Dict[int, Callable] = {}
        self.alive = True
        self._reader: Optional[threading.Thread] = None

    # ------------------------------------------------------------- wire

    def send(self, code: int, body) -> None:
        payload_body = rlp_encode(body)
        if self.snappy and code != HELLO:
            payload_body = snappy_codec.compress(payload_body)
        payload = rlp_encode(to_minimal_bytes(code)) + payload_body
        with self._send_lock:
            self.sock.sendall(self.codec.write_frame(payload))

    def _recv_exact(self, n: int) -> bytes:
        return recv_exact(self.sock, n)

    def recv(self) -> Tuple[int, object]:
        size = self.codec.read_header(self._recv_exact(32))
        wire = self._recv_exact(FrameCodec.frame_wire_size(size))
        payload = self.codec.read_frame(size, wire)
        code = 0 if payload[0] == 0x80 else payload[0]
        body_bytes = payload[1:]
        if self.snappy and code != HELLO:
            body_bytes = snappy_codec.decompress(body_bytes)
        from khipu_tpu.base.rlp import rlp_decode

        return code, rlp_decode(body_bytes)

    # -------------------------------------------------------- handshakes

    def exchange_hello(self, client_id: str, node_id: bytes) -> Hello:
        self.send(HELLO, Hello(client_id, node_id=node_id).body())
        code, body = self.recv()
        if code == DISCONNECT:
            raise PeerError(f"disconnected during hello: {body}")
        if code != HELLO:
            raise PeerError(f"expected Hello, got {code}")
        self.hello = Hello.from_body(body)
        # snappy from p2p v5 (MessageCodec.scala role)
        self.snappy = self.hello.p2p_version >= 5
        return self.hello

    def exchange_status(self, status: Status) -> Status:
        self.send(ETH_OFFSET + STATUS, status.body())
        code, body = self.recv()
        if code != ETH_OFFSET + STATUS:
            raise PeerError(f"expected Status, got {code}")
        remote = Status.from_body(body)
        if remote.genesis_hash != status.genesis_hash:
            raise PeerError("genesis mismatch")
        if remote.network_id != status.network_id:
            raise PeerError("network id mismatch")
        self.status = remote
        return remote

    # ------------------------------------------------------ message loop

    def start_loop(self) -> None:
        self._reader = threading.Thread(target=self._loop, daemon=True)
        self._reader.start()

    def _loop(self) -> None:
        try:
            while self.alive:
                code, body = self.recv()
                if code == PING:
                    self.send(PONG, [])
                    continue
                if code == DISCONNECT:
                    self.alive = False
                    break
                with self._wlock:
                    waiters = self._waiters.get(code)
                    if waiters:
                        event, box = waiters.pop(0)
                        box.append(body)
                        event.set()
                        continue
                handler = self.handlers.get(code)
                if handler is not None:
                    try:
                        reply = handler(body)
                        if reply is not None:
                            self.send(reply[0], reply[1])
                    except Exception:
                        pass
        except Exception:
            self.alive = False

    def request(self, send_code: int, body, reply_code: int,
                timeout: float = 5.0):
        """Send and block for the reply code (ask pattern)."""
        event = threading.Event()
        box: list = []
        waiter = (event, box)
        with self._wlock:
            self._waiters.setdefault(reply_code, []).append(waiter)
        try:
            self.send(send_code, body)
            deadline = time.time() + timeout
            while True:
                remaining = deadline - time.time()
                if remaining <= 0:
                    if event.is_set():  # reply landed at the buzzer
                        return box[0]
                    raise PeerError(f"timeout awaiting code {reply_code}")
                # wake periodically to notice a dead peer
                if event.wait(min(remaining, 0.25)):
                    return box[0]
                if not self.alive:
                    raise PeerError("peer died awaiting reply")
        finally:
            # drop the waiter if unanswered — a stale box would swallow
            # the NEXT reply for this code and desync pairing forever
            with self._wlock:
                waiters = self._waiters.get(reply_code, [])
                if waiter in waiters and not box:
                    waiters.remove(waiter)

    def disconnect(self, reason: int = 0x08) -> None:
        try:
            self.send(DISCONNECT, [to_minimal_bytes(reason)])
        except Exception:
            pass
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


@dataclass
class Blacklist:
    """Timed peer blacklist (HandshakedPeersService.BlacklistPeer)."""

    entries: Dict[bytes, float] = field(default_factory=dict)

    def add(self, node_id: bytes, duration: float = 600.0) -> None:
        self.entries[node_id] = time.time() + duration

    def is_blacklisted(self, node_id: bytes) -> bool:
        until = self.entries.get(node_id)
        if until is None:
            return False
        if time.time() >= until:
            del self.entries[node_id]
            return False
        return True


class PeerManager:
    """Listens, dials, runs the full handshake stack, keeps the
    registry (PeerManager.scala:40)."""

    def __init__(self, static_priv: bytes, client_id: str,
                 status_factory: Callable[[], Status],
                 max_peers: int = 25, fork_resolver=None):
        self.static_priv = static_priv
        self.node_id = privkey_to_pubkey(static_priv)
        self.client_id = client_id
        self.status_factory = status_factory
        self.max_peers = max_peers
        # DAO fork identity check, run right after the Status exchange
        # (EtcHandshake.respondToStatus -> respondToBlockHeaders)
        self.fork_resolver = fork_resolver
        self.peers: List[Peer] = []
        self._reserved = 0  # in-flight handshakes holding a peer slot
        self.blacklist = Blacklist()
        self._server: Optional[socket.socket] = None
        self._lock = threading.Lock()
        self.handlers: Dict[int, Callable] = {}

    # ------------------------------------------------------------ dialing

    def connect(self, host: str, port: int, remote_pub: bytes,
                timeout: float = 5.0) -> Peer:
        if self.blacklist.is_blacklisted(remote_pub):
            raise PeerError("peer is blacklisted")
        sock = socket.create_connection((host, port), timeout=timeout)
        try:
            hs = AuthHandshake(self.static_priv)
            auth = hs.create_auth(remote_pub)
            sock.sendall(auth)
            ack_prefix = recv_exact(sock, 2)
            size = struct.unpack(">H", ack_prefix)[0]
            ack = ack_prefix + recv_exact(sock, size)
            secrets = hs.handle_ack(ack)
            peer = Peer(sock, FrameCodec(secrets), remote_pub, inbound=False)
            self._finish(peer)
            return peer
        except Exception:
            try:
                sock.close()  # failed handshake must not leak the fd
            except OSError:
                pass
            raise

    def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(8)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self._server.getsockname()[1]

    def _accept_loop(self) -> None:
        while self._server is not None:
            try:
                sock, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle_inbound, args=(sock,), daemon=True
            ).start()

    def _handle_inbound(self, sock: socket.socket) -> None:
        try:
            prefix = recv_exact(sock, 2)
            size = struct.unpack(">H", prefix)[0]
            auth = prefix + recv_exact(sock, size)
            hs = AuthHandshake(self.static_priv)
            remote_pub = hs.handle_auth(auth)
            if self.blacklist.is_blacklisted(remote_pub):
                sock.close()
                return
            ack, secrets = hs.create_ack(remote_pub)
            sock.sendall(ack)
            peer = Peer(sock, FrameCodec(secrets), remote_pub, inbound=True)
            self._finish(peer)
        except Exception:
            try:
                sock.close()
            except OSError:
                pass

    def _finish(self, peer: Peer) -> None:
        # reserve the slot under ONE lock before the (blocking)
        # handshake — concurrent connects must not overshoot max_peers
        with self._lock:
            if len(self.peers) + self._reserved >= self.max_peers:
                peer.disconnect(reason=0x04)  # too many peers
                raise PeerError("too many peers")
            self._reserved += 1
        try:
            peer.exchange_hello(self.client_id, self.node_id)
            peer.exchange_status(self.status_factory())
            if self.fork_resolver is not None:
                from khipu_tpu.network.fork_resolver import (
                    ForkCheckFailed,
                    run_fork_challenge,
                )
                from khipu_tpu.network.messages import (
                    ETH_OFFSET as _EO,
                    GET_BLOCK_HEADERS as _GBH,
                )

                try:
                    run_fork_challenge(
                        peer,
                        self.fork_resolver,
                        serve_handler=self.handlers.get(_EO + _GBH),
                    )
                except ForkCheckFailed as e:
                    self.blacklist.add(peer.remote_pub)
                    peer.disconnect(reason=0x03)  # useless peer
                    raise PeerError(f"fork check failed: {e}")
            peer.handlers.update(self.handlers)
            peer.start_loop()
            with self._lock:
                self.peers.append(peer)
        finally:
            with self._lock:
                self._reserved -= 1

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            try:
                server.close()
            except OSError:
                pass
        for peer in list(self.peers):
            peer.disconnect()
        self.peers.clear()

    def best_peer(self) -> Optional[Peer]:
        """Highest-TD live peer (RegularSyncService.bestPeer:448)."""
        live = [p for p in self.peers if p.alive and p.status]
        if not live:
            return None
        return max(live, key=lambda p: p.status.total_difficulty)
