"""Node discovery v4: Kademlia over UDP.

Parity: khipu-eth/.../network/rlpx/discovery/ —
NodeDiscoveryService.scala:68,135 (ping/pong/findnode/neighbours over
Akka UDP with RLP + signature), KRoutingTable.scala:23 + KBucket:286
(k=16 buckets, XOR distance). Packets follow the discv4 wire format:
hash(32) || signature(65) || packet-type(1) || rlp(body); node identity
is the 64-byte secp256k1 pubkey, node id distance = XOR of keccak256
of the pubkeys.
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    SignatureError,
    ecdsa_recover,
    ecdsa_sign,
    privkey_to_pubkey,
)
from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.evm.dataword import from_bytes, to_minimal_bytes

PING, PONG, FINDNODE, NEIGHBOURS = 0x01, 0x02, 0x03, 0x04
K_BUCKET = 16
EXPIRATION = 60


@dataclass(frozen=True)
class NodeRecord:
    pubkey: bytes  # 64 bytes
    ip: str
    udp_port: int
    tcp_port: int

    @property
    def node_id_hash(self) -> bytes:
        return keccak256(self.pubkey)

    def endpoint(self):
        return [
            socket.inet_aton(self.ip),
            to_minimal_bytes(self.udp_port),
            to_minimal_bytes(self.tcp_port),
        ]


def _distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


class KRoutingTable:
    """XOR-metric buckets, k=16, LRU eviction of stale entries
    (KRoutingTable.scala:23)."""

    def __init__(self, self_pubkey: bytes):
        self.self_hash = keccak256(self_pubkey)
        self.buckets: List[List[NodeRecord]] = [[] for _ in range(256)]
        self._lock = threading.Lock()

    def _bucket_of(self, record: NodeRecord) -> int:
        d = _distance(self.self_hash, record.node_id_hash)
        return d.bit_length() - 1 if d else 0

    def add(self, record: NodeRecord) -> None:
        with self._lock:
            bucket = self.buckets[self._bucket_of(record)]
            for i, existing in enumerate(bucket):
                if existing.pubkey == record.pubkey:
                    del bucket[i]  # refresh to most-recent position
                    break
            bucket.append(record)
            if len(bucket) > K_BUCKET:
                bucket.pop(0)  # evict least-recently-seen

    def remove(self, pubkey: bytes) -> None:
        with self._lock:
            for bucket in self.buckets:
                for i, existing in enumerate(bucket):
                    if existing.pubkey == pubkey:
                        del bucket[i]
                        return

    def closest(self, target_hash: bytes, k: int = K_BUCKET) -> List[NodeRecord]:
        with self._lock:
            everyone = [r for bucket in self.buckets for r in bucket]
        return sorted(
            everyone, key=lambda r: _distance(r.node_id_hash, target_hash)
        )[:k]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


def encode_packet(priv: bytes, ptype: int, body) -> bytes:
    data = bytes([ptype]) + rlp_encode(body)
    recid, r, s = ecdsa_sign(keccak256(data), priv)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([recid])
    inner = sig + data
    return keccak256(inner) + inner


def decode_packet(packet: bytes) -> Tuple[bytes, int, object]:
    """-> (sender_pubkey, packet_type, body); raises on bad hash/sig."""
    if len(packet) < 32 + 65 + 1:
        raise ValueError("short packet")
    phash, sig, data = packet[:32], packet[32:97], packet[97:]
    if keccak256(packet[32:]) != phash:
        raise ValueError("bad packet hash")
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    pubkey = ecdsa_recover(keccak256(data), sig[64], r, s)
    return pubkey, data[0], rlp_decode(data[1:])


class DiscoveryService:
    """UDP ping/pong/findnode/neighbours responder + lookup client
    (NodeDiscoveryService.scala:68)."""

    def __init__(self, priv: bytes, ip: str = "127.0.0.1", port: int = 0):
        self.priv = priv
        self.pubkey = privkey_to_pubkey(priv)
        self.table = KRoutingTable(self.pubkey)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((ip, port))
        self.ip, self.port = self.sock.getsockname()
        self._pongs: Dict[bytes, float] = {}
        self._sent_pings: Dict[bytes, float] = {}  # hash -> sent time
        self._pings_lock = threading.Lock()  # recv thread vs callers
        self._neighbours: List[list] = []
        self._running = False
        self._thread: Optional[threading.Thread] = None

    @property
    def record(self) -> NodeRecord:
        return NodeRecord(self.pubkey, self.ip, self.port, self.port)

    def start(self) -> None:
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        try:
            self.sock.close()
        except OSError:
            pass

    # ------------------------------------------------------------- wire

    def _expiration(self):
        return to_minimal_bytes(int(time.time()) + EXPIRATION)

    def ping(self, node: NodeRecord) -> None:
        body = [
            to_minimal_bytes(4),
            self.record.endpoint(),
            node.endpoint(),
            self._expiration(),
        ]
        packet = encode_packet(self.priv, PING, body)
        now = time.time()
        # prune unanswered pings older than the protocol expiration —
        # bounds memory and stops ancient pong replays being accepted
        with self._pings_lock:
            for h in [
                h for h, t in self._sent_pings.items()
                if now - t >= EXPIRATION
            ]:
                del self._sent_pings[h]
            self._sent_pings[packet[:32]] = now
        try:
            self.sock.sendto(packet, (node.ip, node.udp_port))
        except OSError:
            pass

    def find_node(self, node: NodeRecord, target_pub: bytes) -> None:
        self._send(node, FINDNODE, [target_pub, self._expiration()])

    def _send(self, node: NodeRecord, ptype: int, body) -> None:
        packet = encode_packet(self.priv, ptype, body)
        try:
            self.sock.sendto(packet, (node.ip, node.udp_port))
        except OSError:
            pass

    def _loop(self) -> None:
        while self._running:
            try:
                packet, addr = self.sock.recvfrom(1280)
            except OSError:
                return
            # any single malformed packet (bad RLP, short body, bogus
            # IP bytes) must never kill the receive thread — it is the
            # node's only ear
            try:
                pubkey, ptype, body = decode_packet(packet)
                self._handle(pubkey, addr, ptype, body, packet)
            except Exception:
                continue

    def _handle(self, pubkey, addr, ptype, body, packet: bytes) -> None:
        sender = NodeRecord(pubkey, addr[0], addr[1], addr[1])
        if ptype == PING:
            exp = from_bytes(body[3])
            if exp < time.time():
                return
            self.table.add(sender)
            # discv4: PONG echoes the PING packet's hash; peers drop
            # pongs that do not
            self._send(
                sender, PONG,
                [sender.endpoint(), packet[:32], self._expiration()],
            )
        elif ptype == PONG:
            # accept only pongs answering a ping WE sent (echoed hash
            # check) — unsolicited pongs would poison the table
            echoed = body[1]
            with self._pings_lock:
                sent_at = self._sent_pings.pop(echoed, None)
            if sent_at is None or time.time() - sent_at >= EXPIRATION:
                return
            self.table.add(sender)
            self._pongs[pubkey] = time.time()
        elif ptype == FINDNODE:
            target = body[0]
            closest = self.table.closest(keccak256(target))
            nodes = [
                r.endpoint()[:3] + [r.pubkey] for r in closest
            ]
            self._send(
                sender, NEIGHBOURS, [nodes, self._expiration()]
            )
        elif ptype == NEIGHBOURS:
            for item in body[0]:
                ip = socket.inet_ntoa(item[0])
                rec = NodeRecord(
                    item[3], ip, from_bytes(item[1]), from_bytes(item[2])
                )
                self._neighbours.append(rec)
                self.table.add(rec)

    # ------------------------------------------------------------ lookup

    def bootstrap(self, seeds: List[NodeRecord],
                  timeout: float = 2.0) -> int:
        """Ping seeds, then iteratively findnode toward ourselves until
        the table stops growing (the discv4 self-lookup)."""
        for seed in seeds:
            self.ping(seed)
        deadline = time.time() + timeout
        last = -1
        while time.time() < deadline:
            if len(self.table) != last:
                last = len(self.table)
                for node in self.table.closest(keccak256(self.pubkey)):
                    self.find_node(node, self.pubkey)
            time.sleep(0.05)
        return len(self.table)
