"""Header/block structural validators.

Parity: validators/BlockHeaderValidator.scala:36 (difficulty, gas
limit/used, timestamp, number, extra-data :54-197 — PoW seal check is
pluggable and off by default, matching how fixture/replay chains are
driven) and BlockValidator.scala:19 (tx root :82, ommers hash :102,
receipts root :121, log bloom :142).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from khipu_tpu.config import BlockchainConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.receipt import Receipt
from khipu_tpu.ledger.bloom import bloom_union
from khipu_tpu.validators.roots import (
    ommers_hash,
    receipts_root,
    transactions_root,
)

MAX_EXTRA_DATA = 32
GAS_LIMIT_BOUND_DIVISOR = 1024
MIN_GAS_LIMIT = 5000


class ValidationError(Exception):
    pass


class HeaderValidationError(ValidationError):
    pass


class BlockHeaderValidator:
    """Structural + parent-linked header checks. ``seal_check`` is a
    hook for a PoW validator (consensus/pow) — None skips seal
    validation (fixture chains, fast sync headers-only mode)."""

    def __init__(
        self,
        bc: BlockchainConfig,
        difficulty_fn: Optional[Callable[[BlockHeader, BlockHeader], int]] = None,
        seal_check: Optional[Callable[[BlockHeader], bool]] = None,
    ):
        self.bc = bc
        self.difficulty_fn = difficulty_fn
        self.seal_check = seal_check

    def validate(self, header: BlockHeader, parent: BlockHeader) -> None:
        if header.number != parent.number + 1:
            raise HeaderValidationError(
                f"number {header.number} != parent+1 ({parent.number + 1})"
            )
        if header.parent_hash != parent.hash:
            raise HeaderValidationError("parent hash mismatch")
        if len(header.extra_data) > MAX_EXTRA_DATA:
            raise HeaderValidationError("extra data > 32 bytes")
        if header.unix_timestamp <= parent.unix_timestamp:
            raise HeaderValidationError("timestamp not after parent")
        if header.gas_used > header.gas_limit:
            raise HeaderValidationError("gasUsed > gasLimit")
        limit_delta = abs(header.gas_limit - parent.gas_limit)
        if limit_delta >= parent.gas_limit // GAS_LIMIT_BOUND_DIVISOR:
            raise HeaderValidationError("gas limit delta out of bounds")
        if header.gas_limit < MIN_GAS_LIMIT:
            raise HeaderValidationError("gas limit below minimum")
        marker = self.bc.dao_fork_extra_data
        if marker is not None and (
            self.bc.dao_fork_block_number
            <= header.number
            < self.bc.dao_fork_block_number
            + self.bc.dao_fork_extra_data_range
        ):
            # pro-fork consensus rule (geth PR#2814): the first N
            # blocks after the DAO fork must carry the marker exactly
            if header.extra_data != marker:
                raise HeaderValidationError(
                    "missing dao-hard-fork extra data in fork window"
                )
        if self.difficulty_fn is not None:
            expected = self.difficulty_fn(header, parent)
            if header.difficulty != expected:
                raise HeaderValidationError(
                    f"difficulty {header.difficulty} != calculated {expected}"
                )
        if self.seal_check is not None and not self.seal_check(header):
            raise HeaderValidationError("invalid PoW seal")


class OmmersValidator:
    """Ommer consensus rules (validators/OmmersValidator.scala): at most
    2 ommers, no duplicates, each a valid header whose parent is an
    ancestor of the including block within 6 generations, none equal to
    an ancestor, none already included by a recent block."""

    MAX_OMMERS = 2
    GENERATION_LIMIT = 6

    @staticmethod
    def validate(blockchain, block: Block, header_lookup=None,
                 block_lookup=None, header_validator=None) -> None:
        """``header_lookup(n)``/``block_lookup(n)`` override the chain
        DB for blocks not yet persisted (an open commit window validates
        blocks whose parents live only in the window);
        ``header_validator`` additionally validates each ommer header
        against its parent (the Scala OmmersValidator runs the full
        BlockHeaderValidator on ommers)."""
        ommers = block.body.ommers
        if not ommers:
            return
        if len(ommers) > OmmersValidator.MAX_OMMERS:
            raise ValidationError(f"{len(ommers)} ommers > 2")
        if len({o.hash for o in ommers}) != len(ommers):
            raise ValidationError("duplicate ommers")

        def get_header(num):
            if header_lookup is not None:
                h = header_lookup(num)
                if h is not None:
                    return h
            return blockchain.get_header_by_number(num)

        def get_block(num):
            if block_lookup is not None:
                b = block_lookup(num)
                if b is not None:
                    return b
            return blockchain.get_block_by_number(num)

        # ancestors of the including block (hashes + headers), depth 7,
        # collected by WALKING parent_hash links — the block may sit on
        # a non-canonical branch, so looking up the canonical header at
        # each height would check the wrong lineage (the reference walks
        # getNBlocksBack from the block's parent)
        n = block.number
        ancestors = {}
        lineage: List[BlockHeader] = []
        cur_hash = block.header.parent_hash
        cur_num = n - 1
        for _depth in range(1, OmmersValidator.GENERATION_LIMIT + 2):
            if cur_num < 0:
                break
            h = None
            cand = get_header(cur_num)
            if cand is not None and cand.hash == cur_hash:
                h = cand
            else:
                by_hash = getattr(blockchain, "get_header_by_hash", None)
                if by_hash is not None:
                    h = by_hash(cur_hash)
            if h is None:
                break
            ancestors[h.hash] = h
            lineage.append(h)
            cur_hash = h.parent_hash
            cur_num -= 1
        # ommers already included by recent blocks ON THIS LINEAGE
        # (bodies whose stored block no longer matches the lineage
        # header are skipped, not trusted)
        seen = set()
        for h in lineage[: OmmersValidator.GENERATION_LIMIT]:
            b = get_block(h.number)
            if b is None or b.hash != h.hash:
                continue
            for o in b.body.ommers:
                seen.add(o.hash)

        for o in ommers:
            if o.hash in ancestors or o.hash == block.hash:
                raise ValidationError("ommer is an ancestor")
            if o.hash in seen:
                raise ValidationError("ommer already included")
            if not 0 < n - o.number <= OmmersValidator.GENERATION_LIMIT:
                raise ValidationError(
                    f"ommer depth {n - o.number} outside 1..6"
                )
            parent = ancestors.get(o.parent_hash)
            if parent is None:
                raise ValidationError(
                    "ommer's parent is not a recent ancestor"
                )
            if o.number != parent.number + 1:
                raise ValidationError(
                    f"ommer number {o.number} != parent+1 "
                    f"({parent.number + 1})"
                )
            if header_validator is not None:
                try:
                    header_validator.validate(o, parent)
                except HeaderValidationError as e:
                    raise ValidationError(f"invalid ommer header: {e}")


class BlockValidator:
    """Body-vs-header consistency (BlockValidator.scala:19)."""

    @staticmethod
    def validate_body(block: Block) -> None:
        header = block.header
        troot = transactions_root(block.body.transactions)
        if troot != header.transactions_root:
            raise ValidationError(
                f"tx root {troot.hex()} != header "
                f"{header.transactions_root.hex()}"
            )
        ohash = ommers_hash(block.body.ommers)
        if ohash != header.ommers_hash:
            raise ValidationError("ommers hash mismatch")

    @staticmethod
    def validate_receipts(
        header: BlockHeader, receipts: Sequence[Receipt]
    ) -> None:
        rroot = receipts_root(receipts)
        if rroot != header.receipts_root:
            raise ValidationError(
                f"receipts root {rroot.hex()} != header "
                f"{header.receipts_root.hex()}"
            )
        bloom = bloom_union(r.logs_bloom for r in receipts)
        if bloom != header.logs_bloom:
            raise ValidationError("log bloom mismatch")
