"""List roots over ephemeral tries (validators/MptListValidator.scala
role, used by BlockValidator.scala:82-142): the i-th item is stored at
key rlp(i), value = item RLP; root must match the header field.

The ephemeral build goes through the level-synchronous bulk path —
these are exactly the "build a whole small trie at once" workloads the
TPU batch hasher exists for (host hasher at this size; same code path).
"""

from __future__ import annotations

from typing import List, Sequence

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.rlp import rlp_encode
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.receipt import Receipt
from khipu_tpu.domain.transaction import SignedTransaction
from khipu_tpu.evm.dataword import to_minimal_bytes
from khipu_tpu.trie.bulk import bulk_build


def _list_root(encoded_items: Sequence[bytes]) -> bytes:
    pairs = [
        (rlp_encode(to_minimal_bytes(i)), item)
        for i, item in enumerate(encoded_items)
    ]
    root, _ = bulk_build(pairs)
    return root


def transactions_root(txs: Sequence[SignedTransaction]) -> bytes:
    """BlockValidator.validateTransactionRoot (:82)."""
    return _list_root([tx.encode() for tx in txs])


def receipts_root(receipts: Sequence[Receipt]) -> bytes:
    """BlockValidator.validateReceipts (:121)."""
    return _list_root([r.encode() for r in receipts])


def ommers_hash(ommers: Sequence[BlockHeader]) -> bytes:
    """kec256(rlp(ommer list)) (BlockValidator :102)."""
    from khipu_tpu.base.rlp import rlp_decode

    return keccak256(
        rlp_encode([rlp_decode(o.encode()) for o in ommers])
    )
