"""Consensus validators (khipu-eth/.../validators/)."""

from khipu_tpu.validators.roots import (
    ommers_hash,
    receipts_root,
    transactions_root,
)
from khipu_tpu.validators.validators import (
    BlockValidator,
    HeaderValidationError,
    BlockHeaderValidator,
    ValidationError,
)

__all__ = [
    "BlockHeaderValidator",
    "BlockValidator",
    "HeaderValidationError",
    "ValidationError",
    "ommers_hash",
    "receipts_root",
    "transactions_root",
]
