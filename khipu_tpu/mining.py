"""Miner: seal blocks from the pending pool with Ethash.

Parity: mining/Miner.scala:40 + mining/BlockGenerator.scala:31 — the
generator prepares a block via the ledger (prepareBlock role: execute
pending txs, fill the roots), the miner searches a nonce whose
hashimoto result satisfies the difficulty bound, then the block is
saved and the mined txs leave the pool (RegularSyncService.scala:419).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import KhipuConfig
from khipu_tpu.consensus.ethash import EthashCache, mine
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.ledger.ledger import BlockExecutionError
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.txpool import PendingTransactionsPool


class Miner:
    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        tx_pool: PendingTransactionsPool,
        coinbase: bytes,
        ethash_cache: Optional[EthashCache] = None,
        full_size: Optional[int] = None,
        peer_manager=None,
        use_dataset: bool = False,
        dag_dir: Optional[str] = None,
    ):
        self.blockchain = blockchain
        self.config = config
        self.tx_pool = tx_pool
        self.coinbase = coinbase
        self.cache = ethash_cache  # None = seal-less (dev chains)
        self.full_size = full_size
        # miner-grade sealing: precompute + file-cache the full DAG
        # (EthashDataset) so each attempt costs ACCESSES reads instead
        # of ACCESSES x DATASET_PARENTS cache mixes
        # (Ethash.scala:65-164,196)
        self._dataset = None
        if use_dataset and ethash_cache is not None:
            from khipu_tpu.consensus.ethash import EthashDataset

            self._dataset = EthashDataset(
                ethash_cache, full_size, cache_dir=dag_dir
            )
        # with a peer manager, every sealed block is pushed to peers
        # (BroadcastNewBlocks role, RegularSyncService.scala:306)
        self.peer_manager = peer_manager
        self._builder = ChainBuilder.from_head(blockchain, config)

    def _select_txs(self) -> List:
        """Pending txs ordered (sender, nonce); invalid ones dropped at
        execution time by retrying without the offender."""
        txs = self.tx_pool.pending()
        return sorted(
            txs, key=lambda t: (t.sender or b"", t.tx.nonce)
        )

    def mine_next(self) -> Block:
        """Prepare, (optionally) seal, save one block; returns it."""
        head = self.blockchain.get_block_by_number(
            self.blockchain.best_block_number
        )
        self._builder.head = head
        txs = self._select_txs()
        while True:
            try:
                block = self._builder.add_block(
                    tuple(txs), coinbase=self.coinbase
                )
                break
            except BlockExecutionError as e:
                # drop the offending tx (stale nonce / drained balance)
                index = getattr(e, "index", None)
                if index is None or index >= len(txs):
                    raise
                evicted = txs.pop(index)
                self.tx_pool.remove_mined([evicted])
        if self.cache is not None:
            # re-seal: mine a nonce over the prepared header
            header = block.header
            pow_hash = keccak256(header.encode_without_nonce())
            if self._dataset is not None:
                from khipu_tpu.consensus.ethash import mine_full

                nonce, mix = mine_full(
                    self._dataset, pow_hash, header.difficulty
                )
            else:
                nonce, mix = mine(
                    self.cache, pow_hash, header.difficulty,
                    full_size=self.full_size,
                )
            import dataclasses

            sealed_header = dataclasses.replace(
                header, nonce=nonce.to_bytes(8, "big"), mix_hash=mix
            )
            # re-save under the sealed hash: save_block OVERWRITES the
            # number-keyed stores in place (no window where header N is
            # missing for concurrent readers); only the stale unsealed
            # hash->number mapping is dropped afterwards
            sealed = Block(sealed_header, block.body)
            receipts = self.blockchain.get_receipts(block.number) or []
            td = self.blockchain.get_total_difficulty(block.number) or 0
            unsealed_hash = block.hash
            self.blockchain.save_block(sealed, receipts, td)
            self.blockchain.storages.block_numbers.remove(unsealed_hash)
            self._builder.head = sealed
            block = sealed
        self.tx_pool.remove_mined(block.body.transactions)
        if self.peer_manager is not None:
            from khipu_tpu.sync.regular_sync import broadcast_new_block

            td = self.blockchain.get_total_difficulty(block.number) or 0
            broadcast_new_block(self.peer_manager, block, td)
        return block
