"""Sharded distributed node-cache cluster (P6 scaled out).

Parity: khipu-eth/.../storage/DistributedNodeStorage.scala:13-57 and
NodeEntity.scala:28-50 — the reference spreads its MPT node cache
across an Akka cluster by hash shard with automatic failover. Here the
shards are gRPC bridge endpoints (bridge.py GetNodeData/PutNodeData)
and the Akka cluster-sharding machinery becomes an explicit consistent
-hash ring (ring.py), a replica-failover read client (client.py) and a
health/membership prober (health.py) — the same shape as a sharded
parameter-server tier: deterministic placement, bounded retry,
circuit breakers, and per-shard observability.
"""

from khipu_tpu.cluster.ring import HashRing
from khipu_tpu.cluster.client import (
    CircuitBreaker,
    ShardedNodeClient,
    ShardMetrics,
)
from khipu_tpu.cluster.health import HealthMonitor
from khipu_tpu.cluster.rebalance import (
    RebalanceAborted,
    RebalanceError,
    Rebalancer,
    movement_plan,
)
from khipu_tpu.cluster.ring import RingSnapshot

__all__ = [
    "HashRing",
    "RingSnapshot",
    "CircuitBreaker",
    "ShardedNodeClient",
    "ShardMetrics",
    "HealthMonitor",
    "Rebalancer",
    "RebalanceError",
    "RebalanceAborted",
    "movement_plan",
]
