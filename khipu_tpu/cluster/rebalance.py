"""Live ring resize: crash-safe shard join/retire with epoch fencing.

Parity target: the Akka cluster-sharding rebalance DistributedNodeStorage
leaned on — shards hand off their entities when membership changes,
while reads keep flowing. Rebuilt here as an explicit three-phase state
machine over the epoch-fenced ring (cluster/ring.py):

1. **plan** — ``begin_transition`` stages the next epoch beside the
   committed one and ``movement_plan`` diffs the two snapshots into the
   exact half-open point ranges whose replica chain changes. Only those
   ranges move: ~1/N of the keyspace for one joining shard, never a
   full reshuffle.
2. **stream** — negotiated by capability (``EngineInfo``): when every
   endpoint on both ends is Kesque-backed, pull raw whole-frame
   segment chunks over ``StreamSegments`` (segments are the unit of
   bulk movement — docs/cluster.md); otherwise pull the owning
   shard's keys in bounded batches over the paged ``StreamNodeData``
   RPC. Either way every value is verified by content address on
   receipt and pushed to each *gaining* owner through the same
   ``put_node_data`` path the PR-4 backfill uses (the server
   re-verifies before admitting).
   While the transition is open the client writes to BOTH epochs'
   owners and reads new-then-old, so no read can miss a key mid-move.
3. **cutover** — only after every moved range reports ``done`` and
   every push landed does ``commit_transition`` atomically promote the
   next epoch; the configured full ring and the health prober pick up
   the membership change inside the same critical section, so there is
   no crash window between "ring says the shard owns keys" and "the
   rest of the plane knows it exists".

Crash contract (chaos seams ``rebalance.plan`` / ``rebalance.stream``
/ ``rebalance.cutover`` / ``rebalance.retire``): an ``InjectedDeath``
at ANY seam leaves the committed epoch serving — the transition either
never opened, or is still open with the old owners authoritative.
``recover()`` then resumes (re-streams from scratch — both RPCs are
idempotent) when every target member still answers a ping, or rolls
back deterministically, re-recording the keys already streamed as
movement debt via the client's ``_record_missed`` anti-entropy. A
member dying mid-rebalance (a HealthMonitor verdict) aborts the same
way through ``on_membership_event``. Correctness never *depends* on
the recorded debt — a resumed rebalance re-streams everything — the
debt only lets a plain backfill square a partially-streamed shard
that re-joins without a rebalance.

Lock discipline (KL004): ``_lock`` guards state flips only and is
never held across an RPC; the one nested order is
``Rebalancer._lock -> HashRing._lock`` (cutover/abort), and nothing
acquires them in reverse.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.native.keccak import keccak256_batch
from khipu_tpu.chaos import fault_point
from khipu_tpu.cluster.ring import (
    RING_SIZE,
    RingSnapshot,
    _point,
)
from khipu_tpu.observability.profiler import HOST, LEDGER
from khipu_tpu.observability.trace import span

IDLE = "idle"
PLANNING = "planning"
STREAMING = "streaming"
CUTOVER = "cutover"


class RebalanceError(Exception):
    """A rebalance failed and was rolled back to the committed epoch."""


class RebalanceAborted(RebalanceError):
    """The rebalance was aborted (member death, operator, or a failed
    stage); the committed epoch is authoritative and unchanged."""


class MovedRange:
    """One half-open point range ``[lo, hi)`` whose replica chain
    differs between two epochs. ``sources`` is the OLD chain (every
    endpoint guaranteed to hold the range), ``gainers`` the endpoints
    that own it in the new epoch but not the old."""

    __slots__ = ("lo", "hi", "sources", "gainers")

    def __init__(self, lo: int, hi: int, sources: Tuple[str, ...],
                 gainers: Tuple[str, ...]):
        self.lo = lo
        self.hi = hi
        self.sources = sources
        self.gainers = gainers

    def __repr__(self) -> str:  # debugging aid only
        return (f"MovedRange([{self.lo:#x},{self.hi:#x}) "
                f"{self.sources}->{self.gainers})")


def movement_plan(old: RingSnapshot,
                  new: RingSnapshot) -> List[MovedRange]:
    """Diff two ring snapshots into the exact point ranges that change
    ownership. Replica chains are constant between adjacent points of
    the UNION of both snapshots' vnode points, so one representative
    lookup per segment is exact — no key sampling involved."""
    pts = sorted(set(old.points) | set(new.points))
    if not pts:
        return []
    out: List[MovedRange] = []

    def emit(lo: int, hi: int, rep: int) -> None:
        old_chain = old.chain_at(rep)
        new_chain = new.chain_at(rep)
        gainers = tuple(
            ep for ep in new_chain if ep not in old_chain
        )
        if not gainers:
            return
        out.append(MovedRange(lo, hi, tuple(old_chain), gainers))

    for i in range(len(pts) - 1):
        # keys in [pts[i], pts[i+1]) all resolve past pts[i]
        emit(pts[i], pts[i + 1], pts[i])
    # wrap segment: [last, 2^64) and [0, first) share one chain
    emit(pts[-1], RING_SIZE, pts[-1])
    emit(0, pts[0], pts[-1])
    return out


def moved_fraction(plan: Sequence[MovedRange]) -> float:
    """Fraction of the keyspace the plan moves (gauge + docs)."""
    return sum(r.hi - r.lo for r in plan) / RING_SIZE


class Rebalancer:
    """Drives one membership change at a time over a
    ``ShardedNodeClient``. Thread-safe: ``join``/``retire`` run on the
    caller's thread; ``on_membership_event`` (health verdicts) and
    ``abort`` may interrupt from another thread between batches."""

    def __init__(
        self,
        client,
        batch: int = 384,
        pressure: float = 0.88,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.client = client
        self.batch = max(1, batch)
        # segment-ship chunk size (both ends kesque-backed): raw
        # whole-frame bytes per StreamSegments pull
        self.chunk_bytes = 1 << 20
        self._pressure = pressure
        self.log = log or (lambda s: None)
        self._lock = threading.Lock()
        self.state = IDLE
        # one pending operation: ("join"|"retire", endpoint, targets)
        self._pending: Optional[Tuple[str, str, Tuple[str, ...]]] = None
        self._abort_reason: Optional[str] = None
        # keys already pushed per gaining endpoint THIS attempt — the
        # abort path re-records them as anti-entropy debt
        self._streamed: Dict[str, Set[bytes]] = {}
        self.keys_streamed = 0  # cumulative, the watchdog progress gauge
        self.keys_placed = 0  # (key, gainer) placements that landed
        self.completed = 0
        self.aborts = 0
        self.segment_chunks = 0  # raw chunks moved by segment-ship
        self.last_moved_fraction = 0.0
        client.attach_rebalancer(self)
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "rebalance", self._registry_samples
            )
        except Exception:
            pass

    # ------------------------------------------------------- operations

    def join(self, endpoint: str) -> int:
        """Add ``endpoint`` to the serving membership: stage the next
        epoch, stream the ranges it gains, cut over atomically.
        Returns the number of keys streamed. Raises
        ``RebalanceAborted``/``RebalanceError`` with the committed
        epoch intact on any failure."""
        ring = self.client.ring
        if endpoint in ring.members:
            raise ValueError(f"{endpoint} is already a ring member")
        targets = tuple(ring.members) + (endpoint,)
        self._begin("join", endpoint, targets)
        # breaker/metrics/channel so _call can reach the new shard;
        # health tracking waits for cutover (a probe-driven ring.add
        # of a half-streamed shard would bypass the fence)
        self.client.admit_endpoint(endpoint)
        return self._drive()

    def retire(self, endpoint: str) -> int:
        """Remove ``endpoint`` from the serving membership: stream the
        ranges the survivors gain FROM it, cut over, then drop it from
        the configured ring and the health prober. Returns keys
        streamed."""
        fault_point("rebalance.retire")
        ring = self.client.ring
        if endpoint not in ring.members:
            raise ValueError(f"{endpoint} is not a ring member")
        if len(ring.members) < 2:
            raise ValueError("cannot retire the last member")
        targets = tuple(
            m for m in ring.members if m != endpoint
        )
        self._begin("retire", endpoint, targets)
        return self._drive()

    def recover(self) -> str:
        """Settle a rebalance a crash (or an abort signal with no
        driving thread) left mid-flight. Deterministic: resumes —
        re-streaming from scratch, both RPCs are idempotent — when
        every target member answers a ping, rolls back to the
        committed epoch otherwise. Returns ``"idle"``, ``"resumed"``
        or ``"rolled_back"``."""
        with self._lock:
            pending = self._pending
            if pending is None:
                return IDLE
            self._abort_reason = None
        ring = self.client.ring
        if not ring.in_transition:
            # died before begin_transition (rebalance.plan seam) or a
            # health verdict already dropped the staged epoch: nothing
            # moved ownership, so rolling back is pure bookkeeping
            self._finish_abort("recover: no transition open")
            return "rolled_back"
        targets = ring.next_snapshot.members
        if all(self.client.ping(m) for m in targets):
            self.log(f"rebalance: resuming {pending[0]} {pending[1]}")
            self._drive()
            return "resumed"
        self._abort("recover: target member unreachable")
        self._finish_abort("recover: target member unreachable")
        return "rolled_back"

    def abort(self, reason: str = "operator") -> bool:
        """Roll back an in-flight rebalance to the committed epoch.
        Safe from any thread; True when a rebalance was actually
        aborted."""
        return self._abort(reason)

    # ----------------------------------------------------- health hook

    def on_membership_event(self, endpoint: str, alive: bool) -> None:
        """Called by the client BEFORE a mark_dead/mark_alive mutates
        the ring: any membership change under an open transition
        invalidates the staged plan, so abort back to the committed
        epoch (the next join/retire re-plans against reality)."""
        ring = self.client.ring
        if not ring.in_transition and self._pending is None:
            return
        verdict = "died" if not alive else "re-joined"
        self._abort(f"member {endpoint} {verdict} mid-rebalance")

    # -------------------------------------------------------- internals

    def _begin(self, kind: str, endpoint: str,
               targets: Tuple[str, ...]) -> None:
        with self._lock:
            if self._pending is not None:
                raise RuntimeError(
                    f"a rebalance is already in flight: {self._pending}"
                )
            self._pending = (kind, endpoint, targets)
            self._abort_reason = None
            self._streamed = {}
            self.state = PLANNING

    def _drive(self) -> int:
        """Plan + stream + cutover for the pending operation. Any
        plain Exception rolls back and re-raises as RebalanceError;
        InjectedDeath (BaseException) propagates untouched — that IS
        the crash the recover() contract covers."""
        kind, endpoint, targets = self._pending
        ring = self.client.ring
        try:
            with span("rebalance", kind=kind, endpoint=endpoint):
                if ring.in_transition:
                    old, new = ring.snapshot, ring.next_snapshot
                else:
                    fault_point("rebalance.plan")
                    old, new = ring.begin_transition(targets)
                plan = movement_plan(old, new)
                self.last_moved_fraction = moved_fraction(plan)
                self.log(
                    f"rebalance: {kind} {endpoint} epoch "
                    f"{old.epoch}->{new.epoch}, "
                    f"{len(plan)} ranges, "
                    f"{self.last_moved_fraction:.3f} of keyspace"
                )
                with self._lock:
                    self._check_abort()
                    self.state = STREAMING
                streamed = self._stream(plan, old, new)
                self._cutover(kind, endpoint)
                return streamed
        except RebalanceAborted as e:
            self._finish_abort(str(e))
            raise
        except Exception as e:
            self._abort(f"{type(e).__name__}: {e}")
            self._finish_abort(str(e))
            raise RebalanceError(
                f"rebalance {kind} {endpoint} failed: {e}"
            ) from e

    def _check_abort(self) -> None:
        """Caller holds ``_lock``."""
        if self._abort_reason is not None:
            raise RebalanceAborted(self._abort_reason)

    def _stream(self, plan: List[MovedRange], old: RingSnapshot,
                new: RingSnapshot) -> int:
        """Move every planned range, picking the transport by
        capability negotiation: when EVERY endpoint on both ends of
        the plan (losing sources and gaining owners) reports the
        kesque engine, ship raw verified segments in bulk; otherwise
        — or if the bulk path fails mid-flight — fall back to the
        paged ``StreamNodeData`` walk. Both transports are idempotent
        (content-addressed pushes), so a half-done segment-ship
        attempt followed by a paged pass still lands exactly the
        planned keys — a mixed-backend join can only ever commit at
        the old or the new epoch, never in between."""
        endpoints = sorted(
            {ep for r in plan for ep in r.sources}
            | {ep for r in plan for ep in r.gainers}
        )
        if plan and self._all_kesque(endpoints):
            try:
                return self._stream_segment_ship(plan, old, new)
            except RebalanceAborted:
                raise
            except Exception as e:
                self.log(
                    f"rebalance: segment-ship failed "
                    f"({type(e).__name__}: {e}); falling back to "
                    "paged StreamNodeData"
                )
        return self._stream_paged(plan, old, new)

    def _all_kesque(self, endpoints: List[str]) -> bool:
        """Capability probe: True iff every endpoint answers
        ``EngineInfo`` with the kesque engine. Any probe failure (old
        peer without the RPC, unreachable shard) means "negotiate
        down" — never "fail the rebalance"."""
        probe = getattr(self.client, "engine_info", None)
        if probe is None:
            return False
        for ep in endpoints:
            try:
                name, _manifest = probe(ep)
            except Exception:
                return False
            if name != "kesque":
                return False
        return True

    def _stream_segment_ship(self, plan: List[MovedRange],
                             old: RingSnapshot,
                             new: RingSnapshot) -> int:
        """The bulk transport: pull raw whole-frame chunks of every
        source segment, recompute each record's content address (the
        keccak IS the key — receipt-time verification, same argument
        as the paged path's check), keep the keys inside the moved
        ranges, and place them to the gaining owners. No per-key
        cursor walk on the source: the segment manifest is the whole
        work list, and a chunk is a single sequential read."""
        from khipu_tpu.storage.kesque import TAG_NODE, decode_record
        from khipu_tpu.storage.segment import scan_frames

        by_chain: Dict[Tuple[str, ...], List[Tuple[int, int]]] = {}
        for r in plan:
            by_chain.setdefault(r.sources, []).append((r.lo, r.hi))
        streamed = 0
        for chain, ranges in sorted(by_chain.items()):
            source, manifest = self._segment_manifest(chain)
            for topic, seq, _size in manifest:
                offset, done = 0, False
                while not done:
                    with self._lock:
                        self._check_abort()
                    fault_point("rebalance.stream")
                    t0 = time.perf_counter()
                    raw, offset, done = self.client.stream_segments(
                        source, topic, seq, offset, self.chunk_bytes
                    )
                    if not raw:
                        break
                    frames, end = scan_frames(raw)
                    if end != len(raw):
                        # a chunk is whole frames by contract: short
                        # scan = corruption in flight
                        raise RebalanceError(
                            f"corrupt segment chunk from {source} "
                            f"({topic}/{seq}@{offset})"
                        )
                    values = []
                    for _off, payload in frames:
                        tag, _k, value = decode_record(payload)
                        if tag != TAG_NODE or not value:
                            continue  # only node records move
                        values.append(value)
                    pairs = []
                    # one native batch hash per chunk: the recomputed
                    # address is both the key and the receipt check
                    for h, value in zip(keccak256_batch(values), values):
                        pt = _point(h)
                        if any(lo <= pt < hi for lo, hi in ranges):
                            pairs.append((h, value))
                    self.segment_chunks += 1
                    LEDGER.record("kesque.ship", HOST, len(raw),
                                  duration=time.perf_counter() - t0)
                    if pairs:
                        streamed += len(pairs)
                        self.keys_streamed += len(pairs)
                        self._place(pairs, old, new)
        return streamed

    def _segment_manifest(self, chain: Tuple[str, ...]):
        """``(source, [(topic, seq, size), ...])`` from the first
        chain replica that answers as kesque-backed."""
        last: Optional[Exception] = None
        for source in chain:
            try:
                name, manifest = self.client.engine_info(source)
            except Exception as e:
                last = e
                continue
            if name == "kesque":
                return source, manifest
        raise RebalanceError(
            f"no kesque source replica in {chain}: {last}"
        )

    def _stream_paged(self, plan: List[MovedRange], old: RingSnapshot,
                      new: RingSnapshot) -> int:
        """The portable transport: pull every moved range from a
        current owner cursor-paged and push it to the gaining owners.
        Raises on the first batch that cannot be completed — partial
        movement never cuts over."""
        streamed = 0
        # one cursor walk per distinct source chain: each shard is
        # asked once for all the ranges it is losing
        by_chain: Dict[Tuple[str, ...], List[Tuple[int, int]]] = {}
        for r in plan:
            by_chain.setdefault(r.sources, []).append((r.lo, r.hi))
        for chain, ranges in sorted(by_chain.items()):
            cursor = b""
            while True:
                with self._lock:
                    self._check_abort()
                fault_point("rebalance.stream")
                done, cursor, pairs = self._pull(
                    chain, ranges, cursor
                )
                if pairs:
                    streamed += len(pairs)
                    self.keys_streamed += len(pairs)
                    self._place(pairs, old, new)
                if done:
                    break
        return streamed

    def _pull(self, chain: Tuple[str, ...],
              ranges: List[Tuple[int, int]], cursor: bytes):
        """One StreamNodeData batch from the first source replica that
        answers; every value is verified by content address before it
        is accepted — a corrupt stream aborts the rebalance rather
        than silently dropping (or worse, forwarding) a key."""
        last: Optional[Exception] = None
        for source in chain:
            try:
                done, nxt, pairs = self.client.stream_node_data(
                    source, ranges, cursor, self.batch
                )
            except Exception as e:
                last = e
                continue
            for h, v in pairs:
                if keccak256(v) != h:
                    raise RebalanceError(
                        f"corrupt stream from {source}: "
                        f"value does not match {h.hex()[:16]}"
                    )
            return done, nxt, pairs
        raise RebalanceError(
            f"no source replica in {chain} could stream: {last}"
        )

    def _place(self, pairs, old: RingSnapshot,
               new: RingSnapshot) -> None:
        """Route a verified batch to each key's gaining owners."""
        per_gainer: Dict[str, Dict[bytes, bytes]] = {}
        for h, v in pairs:
            pt = _point(h)
            old_chain = old.chain_at(pt)
            for ep in new.chain_at(pt):
                if ep not in old_chain:
                    per_gainer.setdefault(ep, {})[h] = v
        for ep, batch in sorted(per_gainer.items()):
            self.client.push_nodes(ep, batch)
            self.keys_placed += len(batch)
            self._streamed.setdefault(ep, set()).update(batch)

    def _cutover(self, kind: str, endpoint: str) -> None:
        fault_point("rebalance.cutover")
        client = self.client
        with self._lock:
            self._check_abort()
            self.state = CUTOVER
            committed = client.ring.commit_transition()
            # post-commit bookkeeping inside the same critical
            # section: no seam between "the ring cut over" and "the
            # full ring / prober agree", so a crash can never observe
            # the halfway state
            if kind == "join":
                client._full_ring.add(endpoint)
            else:
                client._full_ring.remove(endpoint)
            self._pending = None
            self._streamed = {}
            self.state = IDLE
            self.completed += 1
        health = getattr(client, "_health", None)
        if kind == "join":
            if health is not None:
                health.track(endpoint)
        else:
            if health is not None:
                health.untrack(endpoint)
            client.forget_endpoint(endpoint)
        self.log(
            f"rebalance: {kind} {endpoint} committed epoch "
            f"{committed.epoch}"
        )

    def _abort(self, reason: str) -> bool:
        """Flag the abort and drop the staged epoch. The driving
        thread (if any) unwinds at its next ``_check_abort``; with no
        driving thread, ``recover()`` finishes the bookkeeping."""
        with self._lock:
            if self._pending is None:
                return False
            if self._abort_reason is None:
                self._abort_reason = reason
            self.client.ring.abort_transition()
        self.log(f"rebalance: aborting ({reason})")
        return True

    def _finish_abort(self, reason: str) -> None:
        """Roll-back bookkeeping: committed epoch stays authoritative;
        the keys already streamed become anti-entropy debt so a later
        plain backfill can square a half-copied shard."""
        with self._lock:
            pending, self._pending = self._pending, None
            streamed, self._streamed = self._streamed, {}
            self._abort_reason = None
            self.state = IDLE
            self.aborts += 1
        self.client.ring.abort_transition()
        for ep, keys in sorted(streamed.items()):
            if keys:
                self.client._record_missed(ep, sorted(keys))
        if pending is not None and pending[0] == "join":
            # the half-streamed shard never became a member: drop its
            # channel; breaker/metrics history is harmless to keep
            self.client._drop_channel(pending[1])
        self.log(f"rebalance: rolled back ({reason})")

    # ---------------------------------------------------- observability

    @property
    def in_transition(self) -> bool:
        return self.client.ring.in_transition or self._pending is not None

    def pressure(self) -> float:
        """Admission pressure while a transition epoch is open: high
        enough to shed writes (a transfer storm must not be amplified
        by user writes doubling into both epochs) while cheap reads
        keep flowing. Zero when idle — the signal costs nothing."""
        return self._pressure if self.in_transition else 0.0

    def watch_source(self) -> Tuple[bool, int]:
        """(transition open, progress) for the ``rebalance_stuck``
        watchdog: open + flat progress for stall_after_s = a wedge."""
        return self.in_transition, self.keys_streamed

    def status(self) -> dict:
        ring = self.client.ring
        return {
            "state": self.state,
            "epoch": ring.epoch,
            "inTransition": ring.in_transition,
            "pending": (
                {"kind": self._pending[0],
                 "endpoint": self._pending[1]}
                if self._pending else None
            ),
            "keysStreamed": self.keys_streamed,
            "keysPlaced": self.keys_placed,
            "segmentChunks": self.segment_chunks,
            "completed": self.completed,
            "aborts": self.aborts,
            "lastMovedFraction": round(self.last_moved_fraction, 6),
        }

    def _registry_samples(self) -> list:
        ring = self.client.ring
        return [
            ("khipu_rebalance_epoch", "gauge", {}, ring.epoch),
            ("khipu_rebalance_in_transition", "gauge", {},
             int(ring.in_transition)),
            ("khipu_rebalance_keys_streamed_total", "counter", {},
             self.keys_streamed),
            ("khipu_rebalance_keys_placed_total", "counter", {},
             self.keys_placed),
            ("khipu_rebalance_segment_chunks_total", "counter", {},
             self.segment_chunks),
            ("khipu_rebalance_completed_total", "counter", {},
             self.completed),
            ("khipu_rebalance_aborts_total", "counter", {},
             self.aborts),
            ("khipu_rebalance_moved_fraction", "gauge", {},
             round(self.last_moved_fraction, 6)),
        ]
