"""Health/membership prober: endpoints leave and re-join the ring.

Parity: the Akka cluster failure detector + MemberUp/MemberRemoved
events DistributedNodeStorage reacts to. A periodic Ping probe decides
dead/alive per endpoint with hysteresis (``down_after`` consecutive
misses to leave, ``up_after`` consecutive hits to re-join) so one
dropped heartbeat doesn't thrash the ring. Verdicts call the client's
mark_dead/mark_alive, which swap the ring snapshot atomically —
in-flight reads finish on the chains they already resolved, so a
rebalance never drops a read mid-flight.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from khipu_tpu.cluster.client import ShardedNodeClient


class HealthMonitor:
    """Probe loop over every configured endpoint (dead ones included —
    that is how they come back)."""

    def __init__(
        self,
        client: ShardedNodeClient,
        interval: float = 5.0,
        down_after: int = 2,
        up_after: int = 1,
        probe: Optional[Callable[[str], bool]] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.client = client
        self.interval = interval
        self.down_after = down_after
        self.up_after = up_after
        self.probe = probe or client.ping
        self.log = log or (lambda s: None)
        self.transitions = 0  # dead<->alive verdicts issued
        self._misses: Dict[str, int] = {}
        self._hits: Dict[str, int] = {}
        self._alive: Dict[str, bool] = {
            ep: True for ep in client.metrics
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        client._health = self
        # liveness verdicts were a private dict invisible to metrics;
        # export them as khipu_shard_up{endpoint=} (REPLACES by key —
        # the newest monitor owns the samples, same story as the
        # cluster client's collector)
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "cluster_health", self._registry_samples
            )
        except Exception:
            pass  # metrics are optional; the probe loop is not

    def _registry_samples(self) -> list:
        samples = [
            ("khipu_shard_up", "gauge", {"endpoint": ep},
             1 if alive else 0)
            for ep, alive in sorted(self._alive.items())
        ]
        samples.append((
            "khipu_shard_transitions_total", "counter", {},
            self.transitions,
        ))
        return samples

    # ------------------------------------------------------------ probes

    def alive(self, endpoint: str) -> bool:
        return self._alive.get(endpoint, False)

    def track(self, endpoint: str) -> None:
        """Start probing an endpoint that joined after construction
        (live rebalance cutover). Idempotent; the endpoint starts
        alive — it just proved itself by surviving the stream."""
        if endpoint not in self._alive:
            self._alive[endpoint] = True
            self._misses[endpoint] = 0
            self._hits[endpoint] = 0

    def untrack(self, endpoint: str) -> None:
        """Stop probing a retired endpoint (it left the membership on
        purpose — a dead-verdict for it would be noise)."""
        self._alive.pop(endpoint, None)
        self._misses.pop(endpoint, None)
        self._hits.pop(endpoint, None)

    def probe_once(self) -> Dict[str, bool]:
        """One probe round; returns the current verdict map."""
        for ep in list(self._alive):
            ok = self.probe(ep)
            if ok:
                self._misses[ep] = 0
                self._hits[ep] = self._hits.get(ep, 0) + 1
                if (
                    not self._alive[ep]
                    and self._hits[ep] >= self.up_after
                ):
                    self._alive[ep] = True
                    self.transitions += 1
                    self.client.mark_alive(ep)
                    self.log(f"cluster: {ep} re-joined the ring")
                    # anti-entropy: push the writes the endpoint missed
                    # while it was out of the ring (client.backfill)
                    backfill = getattr(self.client, "backfill", None)
                    if backfill is not None:
                        try:
                            pushed = backfill(ep)
                        except Exception:
                            pushed = -1  # debt re-recorded by backfill
                        if pushed:
                            self.log(
                                f"cluster: backfilled {pushed} missed "
                                f"keys onto {ep}"
                            )
            else:
                self._hits[ep] = 0
                self._misses[ep] = self._misses.get(ep, 0) + 1
                if (
                    self._alive[ep]
                    and self._misses[ep] >= self.down_after
                ):
                    self._alive[ep] = False
                    self.transitions += 1
                    self.client.mark_dead(ep)
                    self.log(f"cluster: {ep} marked dead")
        return dict(self._alive)

    # ------------------------------------------------------- background

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval):
                try:
                    self.probe_once()
                except Exception:
                    pass  # a probe crash must never kill the monitor

        self._thread = threading.Thread(
            target=loop, name="cluster-health", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
