"""ShardedNodeClient: replica-failover reads over the bridge shards.

Parity: DistributedNodeStorage.scala:13-57 — the reference resolves a
node hash to a cluster shard and lets Akka handle delivery, retry and
failover. Explicit here: the ring picks [primary, replicas...] per
key, the client walks that order with bounded exponential-backoff
retries and a per-endpoint circuit breaker (the Akka failure detector
role), verifies every returned value by content address before
admitting it, and falls back to a local store callback when the whole
replica set is down — a read NEVER returns wrong bytes and only
returns None when no copy is reachable anywhere.

Writes replicate: PutNodeData goes to every replica of each key so a
loopback cluster stays consistent when one shard is killed mid-run.

The transport is injectable (``channel_factory``); production uses
bridge.BridgeClient, tests plug fakes with scripted failures.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.chaos import fault_point, fault_value
from khipu_tpu.cluster.ring import HashRing
from khipu_tpu.observability.trace import span

# breaker states (CircuitBreaker pattern; Akka failure-detector role)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-endpoint breaker: ``failure_threshold`` consecutive failures
    open it; after ``reset_timeout`` one probe call is let through
    (half-open) — success closes, failure re-opens the full window."""

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False  # a half-open probe is in flight

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return CLOSED
            if self._clock() - self._opened_at >= self.reset_timeout:
                return HALF_OPEN
            return OPEN

    def allow(self) -> bool:
        """May a call go to this endpoint right now? Half-open admits
        exactly ONE probe until its outcome is recorded."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._clock() - self._opened_at < self.reset_timeout:
                return False
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            self._failures += 1
            if self._failures >= self.failure_threshold:
                # re-arm the full window (also on a failed probe)
                self._opened_at = self._clock()


class ShardMetrics:
    """Per-endpoint counters (NodeEntity.scala:28's served-read stats
    role), snapshotted into the khipu_metrics RPC."""

    def __init__(self) -> None:
        self.requests = 0  # RPC calls attempted (incl. retries)
        self.served = 0  # keys answered with verified bytes
        self.missing = 0  # keys the shard did not have
        self.corrupt = 0  # keys whose bytes failed the hash check
        self.failures = 0  # RPC errors (timeouts, resets, refusals)
        self.failovers = 0  # key-groups handed to the next replica
        self.replicated = 0  # keys write-replicated to this shard
        self.backfilled = 0  # keys re-replicated at re-join (anti-entropy)
        self.rebalanced = 0  # keys streamed onto this shard (rebalance)
        self.latency_ns = 0  # total RPC wall time

    def snapshot(self, breaker: CircuitBreaker, alive: bool) -> dict:
        return {
            "alive": alive,
            "breakerState": breaker.state,
            "requests": self.requests,
            "served": self.served,
            "missing": self.missing,
            "corrupt": self.corrupt,
            "failures": self.failures,
            "failovers": self.failovers,
            "replicated": self.replicated,
            "backfilled": self.backfilled,
            "rebalanced": self.rebalanced,
            "latencySeconds": round(self.latency_ns / 1e9, 6),
            "hitRate": round(
                self.served / max(1, self.served + self.missing), 4
            ),
        }


class ShardedNodeClient:
    """NodeDataSource read-through contract over N bridge endpoints.

    ``fetch(hashes) -> {hash: verified bytes}`` plugs directly into
    RemoteReadThroughNodeStorage, the regular-sync heal path and the
    fast-sync download pool. ``replicate(nodes)`` is the write side.
    """

    def __init__(
        self,
        endpoints: Sequence[str],
        replication: int = 2,
        vnodes: int = 64,
        local_get: Optional[Callable[[bytes], Optional[bytes]]] = None,
        max_retries: int = 2,
        backoff_base: float = 0.05,
        backoff_max: float = 1.0,
        breaker_failures: int = 5,
        breaker_reset: float = 30.0,
        channel_factory: Optional[Callable[[str], object]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rpc_deadline: Optional[float] = None,
        missed_cap: int = 100_000,
        jitter_seed: int = 0,
    ):
        if not endpoints:
            raise ValueError("cluster needs at least one endpoint")
        self.ring = HashRing(endpoints, replication, vnodes)
        # the CONFIGURED membership, never shrunk by health verdicts —
        # what a dead endpoint OWNS while it is out of the live ring
        # (the anti-entropy backfill's source of truth)
        self._full_ring = HashRing(endpoints, replication, vnodes)
        self.rpc_deadline = rpc_deadline
        self.local_get = local_get
        self.max_retries = max_retries
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._clock = clock
        self._sleep = sleep
        self.breaker_failures = breaker_failures
        self.breaker_reset = breaker_reset
        # retry-backoff jitter from a per-client seeded stream
        # (ClusterConfig.jitter_seed): chaos replay of a retry schedule
        # is bit-reproducible — module-level random would diverge per
        # run and break deterministic fault replay (KL003)
        self._jitter_rng = random.Random(jitter_seed)
        self._channel_factory = channel_factory or self._grpc_factory
        self._channels: Dict[str, object] = {}
        self._channel_lock = threading.Lock()
        self.breakers: Dict[str, CircuitBreaker] = {
            ep: CircuitBreaker(breaker_failures, breaker_reset, clock)
            for ep in endpoints
        }
        self.metrics: Dict[str, ShardMetrics] = {
            ep: ShardMetrics() for ep in endpoints
        }
        self.local_fallbacks = 0  # keys served by the local store
        self.unreachable = 0  # keys no copy could serve
        self._health = None  # attached by HealthMonitor
        self._rebalancer = None  # attached by Rebalancer
        # keys owed to an endpoint that could not take its replica
        # (dead at placement time, or the batch RPC failed) — drained
        # by ``backfill`` when the endpoint re-joins. Bounded: beyond
        # ``missed_cap`` total keys new debts are dropped and counted
        # (the endpoint then needs an offline re-sync, not a backfill)
        self.missed_cap = missed_cap
        self.missed_dropped = 0
        self._missed: Dict[str, Dict[bytes, None]] = {}
        self._missed_total = 0
        self._missed_lock = threading.Lock()
        # unified-registry pull source: the newest client owns the
        # process's cluster telemetry slot (replace-by-key — tests
        # build many short-lived clients)
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "cluster", self._registry_samples
            )
        except Exception:
            pass

    # -------------------------------------------------------- transport

    def _grpc_factory(self, endpoint: str):
        from khipu_tpu.bridge import BridgeClient

        return BridgeClient(endpoint, deadline=self.rpc_deadline)

    def _channel(self, endpoint: str):
        with self._channel_lock:
            ch = self._channels.get(endpoint)
            if ch is None:
                ch = self._channels[endpoint] = self._channel_factory(
                    endpoint
                )
            return ch

    def _drop_channel(self, endpoint: str) -> None:
        """Forget a (likely broken) channel so the next call redials."""
        with self._channel_lock:
            ch = self._channels.pop(endpoint, None)
        if ch is not None:
            try:
                ch.close()
            except Exception:
                pass

    def _call(self, endpoint: str, op: Callable[[object], object]):
        """One guarded RPC with bounded retry + expo backoff + jitter.
        Raises the last error after ``max_retries`` extra attempts."""
        breaker = self.breakers[endpoint]
        m = self.metrics[endpoint]
        last: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            if not breaker.allow():
                raise ShardUnavailable(f"{endpoint}: breaker open")
            m.requests += 1
            t0 = self._clock()
            try:
                # chaos seam: a `raise` rule (site "cluster.call:*" or
                # per-endpoint) is indistinguishable from an RPC error —
                # it feeds the same retry/backoff/breaker machinery
                fault_point(f"cluster.call:{endpoint}")
                with span(
                    "cluster.call", endpoint=endpoint, attempt=attempt
                ):
                    out = op(self._channel(endpoint))
            except Exception as e:  # grpc.RpcError or fake failures
                m.latency_ns += int((self._clock() - t0) * 1e9)
                m.failures += 1
                breaker.record_failure()
                self._drop_channel(endpoint)
                last = e
                if attempt < self.max_retries:
                    delay = min(
                        self.backoff_max,
                        self.backoff_base * (2**attempt),
                    )
                    self._sleep(
                        delay * (0.5 + self._jitter_rng.random() / 2)
                    )
                continue
            m.latency_ns += int((self._clock() - t0) * 1e9)
            breaker.record_success()
            return out
        raise last  # type: ignore[misc]

    # ------------------------------------------------------------ reads

    def fetch(self, hashes: List[bytes]) -> Dict[bytes, bytes]:
        """Read-through fetch: {hash: value} for every hash some healthy
        copy holds, every value content-address verified. Missing keys
        are simply absent — the caller's miss semantics apply."""
        remaining = list(dict.fromkeys(bytes(h) for h in hashes))
        result: Dict[bytes, bytes] = {}
        with span("cluster.fetch", keys=len(remaining)) as fetch_sp:
            # per-request shard selection: group keys by their replica
            # chain so one RPC serves each shard's share of the batch
            # read_chain = replicas_for outside a transition; mid-
            # rebalance it tries the NEXT epoch's owners first and
            # falls back to the committed owners, so a half-streamed
            # move can never make a key unreadable
            groups: Dict[tuple, List[bytes]] = {}
            for h in remaining:
                groups.setdefault(
                    tuple(self.ring.read_chain(h)), []
                ).append(h)
            for chain, keys in groups.items():
                want = keys
                for rank, endpoint in enumerate(chain):
                    if not want:
                        break
                    m = self.metrics[endpoint]
                    if rank > 0:
                        m.failovers += 1
                    try:
                        with span(
                            "cluster.replica", endpoint=endpoint,
                            rank=rank, keys=len(want),
                            failover=rank > 0,
                        ):
                            got = self._call(
                                endpoint,
                                lambda ch, w=tuple(want): (
                                    ch.get_node_data(list(w))
                                ),
                            )
                    except Exception:
                        continue  # next replica
                    still: List[bytes] = []
                    for h in want:
                        v = got.get(h)
                        if v is not None:
                            # data seam: `corrupt` rules bit-flip the
                            # fetched bytes — the admission check below
                            # MUST catch every one
                            v = fault_value("cluster.fetch.value", v)
                        if v is None:
                            m.missing += 1
                            still.append(h)
                        elif keccak256(v) != h:
                            m.corrupt += 1  # never admit wrong bytes
                            still.append(h)
                        else:
                            m.served += 1
                            result[h] = v
                    want = still
                for h in want:  # replica set exhausted: local fallback
                    v = self.local_get(h) if self.local_get else None
                    if v is not None and keccak256(v) == h:
                        self.local_fallbacks += 1
                        result[h] = v
                    else:
                        self.unreachable += 1
            fetch_sp.set_tag("served", len(result))
        return result

    # ----------------------------------------------------------- writes

    def replicate(self, nodes: Mapping[bytes, bytes]) -> int:
        """Write-replicate nodes to every replica of each key; returns
        the number of (key, endpoint) placements that succeeded. A dead
        replica is skipped (its breaker records the failure) — the
        read path's failover covers the gap until it heals, and the
        keys the skip left un-placed are remembered per FULL-ring owner
        so ``backfill`` squares the debt at re-join (anti-entropy)."""
        fault_point("cluster.replicate")
        alive = set(self.ring.members)
        per_endpoint: Dict[str, Dict[bytes, bytes]] = {}
        for h, v in nodes.items():
            hb = bytes(h)
            # write_chains = replicas_for outside a transition; mid-
            # rebalance it is the UNION of both epochs' owners, so
            # neither cutover nor rollback can lose a live write
            for endpoint in self.ring.write_chains(hb):
                per_endpoint.setdefault(endpoint, {})[hb] = bytes(v)
            # an out-of-ring CONFIGURED owner missed this write — it
            # comes back with a stale cache unless backfilled
            for endpoint in self._full_ring.replicas_for(hb):
                if endpoint not in alive:
                    self._record_missed(endpoint, (hb,))
        placed = 0
        for endpoint, batch in per_endpoint.items():
            try:
                self._call(
                    endpoint,
                    lambda ch, b=batch: ch.put_node_data(b),
                )
            except Exception:
                # the batch never landed: same debt as a dead owner
                self._record_missed(endpoint, batch)
                continue
            self.metrics[endpoint].replicated += len(batch)
            placed += len(batch)
        return placed

    # ---------------------------------------------------- anti-entropy

    def _record_missed(self, endpoint: str, keys) -> None:
        with self._missed_lock:
            bucket = self._missed.setdefault(endpoint, {})
            for h in keys:
                if h in bucket:
                    continue
                if self._missed_total >= self.missed_cap:
                    self.missed_dropped += 1
                    continue
                bucket[h] = None
                self._missed_total += 1

    def backfill(self, endpoint: str) -> int:
        """Anti-entropy at re-join (HealthMonitor dead->alive): push
        every key the endpoint missed while out of the ring. Values
        come from the local store first, then a cluster fetch; keys no
        copy can produce are dropped (nothing left to replicate).
        Returns keys re-replicated. Failed pushes re-enter the debt."""
        with self._missed_lock:
            bucket = self._missed.pop(endpoint, None)
            if bucket:
                self._missed_total -= len(bucket)
        if not bucket:
            return 0
        keys = list(bucket)
        placed = 0
        for start in range(0, len(keys), 384):
            chunk = keys[start : start + 384]
            batch: Dict[bytes, bytes] = {}
            missing: List[bytes] = []
            for h in chunk:
                v = self.local_get(h) if self.local_get else None
                if v is not None and keccak256(v) == h:
                    batch[h] = v
                else:
                    missing.append(h)
            if missing:
                batch.update(self.fetch(missing))
            if not batch:
                continue
            try:
                self._call(
                    endpoint,
                    lambda ch, b=batch: ch.put_node_data(b),
                )
            except Exception:
                self._record_missed(endpoint, batch)
                continue
            self.metrics[endpoint].backfilled += len(batch)
            placed += len(batch)
        return placed

    # ----------------------------------------------------- membership

    def mark_dead(self, endpoint: str) -> None:
        """Health verdict: take the endpoint out of placement. In-flight
        reads keep their (old-snapshot) replica chains — they fail over
        normally — new reads stop selecting it. An open rebalance
        transition is aborted FIRST (the staged plan assumed the dead
        member), so the committed epoch stays authoritative."""
        rb = self._rebalancer
        if rb is not None:
            rb.on_membership_event(endpoint, alive=False)
        self.ring.remove(endpoint)
        self._drop_channel(endpoint)

    def mark_alive(self, endpoint: str) -> None:
        if endpoint in self.metrics:
            rb = self._rebalancer
            if rb is not None:
                rb.on_membership_event(endpoint, alive=True)
            self.ring.add(endpoint)

    # ------------------------------------------------------- rebalance

    def attach_rebalancer(self, rebalancer) -> None:
        """The live-rebalance driver (cluster/rebalance.py) hooks
        membership verdicts so a shard dying mid-rebalance aborts the
        transition instead of wedging it."""
        self._rebalancer = rebalancer

    def admit_endpoint(self, endpoint: str) -> None:
        """Create the breaker/metrics slots a joining endpoint needs
        before any RPC can address it. Idempotent; does NOT add the
        endpoint to any ring — that is the rebalance cutover's job."""
        if endpoint not in self.breakers:
            self.breakers[endpoint] = CircuitBreaker(
                self.breaker_failures, self.breaker_reset, self._clock
            )
        if endpoint not in self.metrics:
            self.metrics[endpoint] = ShardMetrics()

    def forget_endpoint(self, endpoint: str) -> None:
        """Drop a retired endpoint's channel. Breaker/metrics history
        stays (counters are cumulative-by-contract); the rings were
        already updated by the rebalance cutover."""
        self._drop_channel(endpoint)

    def stream_node_data(self, endpoint: str, ranges, cursor: bytes,
                         count: int):
        """One StreamNodeData page from ``endpoint`` through the
        retry/breaker machinery: ``(done, next_cursor, pairs)``."""
        return self._call(
            endpoint,
            lambda ch: ch.stream_node_data(ranges, cursor, count),
        )

    def engine_info(self, endpoint: str):
        """The shard's storage-engine capability + segment manifest:
        ``(engine_name, [(topic, seq, size), ...])`` — the rebalance
        segment-ship negotiation probe."""
        return self._call(endpoint, lambda ch: ch.engine_info())

    def stream_segments(self, endpoint: str, topic: str, seq: int,
                        offset: int, max_bytes: int):
        """One raw segment chunk through the retry/breaker machinery:
        ``(raw, next_offset, done)``."""
        return self._call(
            endpoint,
            lambda ch: ch.stream_segments(topic, seq, offset, max_bytes),
        )

    def push_nodes(self, endpoint: str, nodes: Mapping[bytes, bytes]) -> int:
        """Rebalance write path: place a verified batch onto a gaining
        owner (server re-verifies by content address before admitting,
        same as the backfill path)."""
        admitted = self._call(
            endpoint, lambda ch, b=dict(nodes): ch.put_node_data(b)
        )
        self.metrics[endpoint].rebalanced += len(nodes)
        return admitted

    def ping(self, endpoint: str) -> bool:
        """Health probe primitive (bypasses retries: one shot)."""
        try:
            ch = self._channel(endpoint)
            ch.ping(b"hb")
        except Exception:
            self._drop_channel(endpoint)
            return False
        return True

    # ------------------------------------------------------ observability

    def metrics_snapshot(self) -> dict:
        """Everything khipu_metrics surfaces about the cluster."""
        alive = set(self.ring.members)
        rb = self._rebalancer
        return {
            "replication": self.ring.replication,
            "members": list(self.ring.members),
            "epoch": self.ring.epoch,
            "inTransition": self.ring.in_transition,
            "rebalance": rb.status() if rb is not None else None,
            "localFallbacks": self.local_fallbacks,
            "unreachable": self.unreachable,
            "missedKeys": self._missed_total,
            "missedDropped": self.missed_dropped,
            "shards": {
                ep: m.snapshot(self.breakers[ep], ep in alive)
                for ep, m in self.metrics.items()
            },
        }

    def _registry_samples(self) -> list:
        """The same counters as ``metrics_snapshot``, flattened into
        registry sample tuples — per-endpoint families labeled
        ``{endpoint=...}``, cluster-wide ones unlabeled."""
        alive = set(self.ring.members)
        out = [
            ("khipu_cluster_local_fallbacks_total", "counter", {},
             self.local_fallbacks),
            ("khipu_cluster_unreachable_total", "counter", {},
             self.unreachable),
            ("khipu_cluster_missed_keys", "gauge", {},
             self._missed_total),
            ("khipu_cluster_missed_dropped_total", "counter", {},
             self.missed_dropped),
            ("khipu_cluster_members", "gauge", {},
             len(self.ring.members)),
            ("khipu_cluster_epoch", "gauge", {}, self.ring.epoch),
        ]
        per_ep = (
            ("khipu_shard_requests_total", "counter", "requests"),
            ("khipu_shard_served_total", "counter", "served"),
            ("khipu_shard_missing_total", "counter", "missing"),
            ("khipu_shard_corrupt_total", "counter", "corrupt"),
            ("khipu_shard_failures_total", "counter", "failures"),
            ("khipu_shard_failovers_total", "counter", "failovers"),
            ("khipu_shard_replicated_total", "counter", "replicated"),
            ("khipu_shard_backfilled_total", "counter", "backfilled"),
            ("khipu_shard_rebalanced_total", "counter", "rebalanced"),
        )
        for ep, m in self.metrics.items():
            lb = {"endpoint": ep}
            for name, kind, attr in per_ep:
                out.append((name, kind, lb, getattr(m, attr)))
            out.append((
                "khipu_shard_latency_seconds_total", "counter", lb,
                round(m.latency_ns / 1e9, 6),
            ))
            out.append(
                ("khipu_shard_alive", "gauge", lb, int(ep in alive))
            )
        return out

    def collect_traces(self, probe_samples: int = 5) -> list:
        """Pull every live shard's span ring + clock estimate (the
        ``merged_chrome_trace`` input; observability/export.py). Shards
        whose channel lacks the trace RPCs — or that fail mid-pull —
        are skipped: a trace dump must never take the cluster down."""
        from khipu_tpu.observability.export import shard_timeline

        shards = []
        for ep in list(self.ring.members):
            try:
                shards.append(shard_timeline(
                    self._channel(ep), endpoint=ep,
                    probe_samples=probe_samples,
                ))
            except Exception:
                continue
        return shards

    def close(self) -> None:
        with self._channel_lock:
            channels, self._channels = dict(self._channels), {}
        for ch in channels.values():
            try:
                ch.close()
            except Exception:
                pass


class ShardUnavailable(Exception):
    """Raised by _call when the breaker refuses the endpoint."""
