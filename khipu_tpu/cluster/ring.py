"""Consistent-hash ring: node-hash -> ordered replica set of endpoints.

Parity: DistributedNodeStorage.scala:13-57 shards the node cache by
``hash % numberOfShards`` under Akka cluster sharding; a consistent
ring replaces the modulo so membership changes (a shard dying, a new
one joining) remap only ~1/N of the keyspace instead of all of it —
the property every sharded KV / parameter-server tier relies on for
cheap rebalance.

Each endpoint owns ``vnodes`` points on a 64-bit ring (points are
keccak-derived, so placement is deterministic across processes — every
client computes the same owner for a key with zero coordination).
Lookups walk clockwise from the key's point collecting the first
``replication`` DISTINCT endpoints: the primary plus failover replicas,
in deterministic preference order.

Membership changes swap an immutable snapshot under a lock; readers
never block, so a rebalance cannot drop an in-flight read.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Sequence, Tuple

from khipu_tpu.base.crypto.keccak import keccak256


def _point(data: bytes) -> int:
    """64-bit ring coordinate."""
    return int.from_bytes(keccak256(data)[:8], "big")


class HashRing:
    """Immutable-snapshot consistent-hash ring with virtual nodes."""

    def __init__(
        self,
        endpoints: Sequence[str] = (),
        replication: int = 2,
        vnodes: int = 64,
    ):
        if replication < 1:
            raise ValueError("replication must be >= 1")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.replication = replication
        self.vnodes = vnodes
        self._lock = threading.Lock()
        # snapshot: (sorted points, endpoint per point, member tuple)
        self._points: List[int] = []
        self._owners: List[str] = []
        self._members: Tuple[str, ...] = ()
        with self._lock:
            self._rebuild(tuple(dict.fromkeys(endpoints)))

    # ------------------------------------------------------- membership

    def _rebuild(self, members: Tuple[str, ...]) -> None:
        """Recompute the snapshot (caller holds the lock). Collisions on
        the 64-bit ring are vanishingly rare; last writer wins."""
        pairs: Dict[int, str] = {}
        for ep in members:
            for i in range(self.vnodes):
                pairs[_point(f"{ep}#{i}".encode())] = ep
        points = sorted(pairs)
        # one atomic swap: readers see either the old or the new ring
        self._points, self._owners, self._members = (
            points,
            [pairs[p] for p in points],
            members,
        )

    def add(self, endpoint: str) -> bool:
        """Join (or re-join) an endpoint; True if membership changed."""
        with self._lock:
            if endpoint in self._members:
                return False
            self._rebuild(self._members + (endpoint,))
            return True

    def remove(self, endpoint: str) -> bool:
        """Leave the ring; True if membership changed."""
        with self._lock:
            if endpoint not in self._members:
                return False
            self._rebuild(
                tuple(m for m in self._members if m != endpoint)
            )
            return True

    @property
    def members(self) -> Tuple[str, ...]:
        return self._members

    def __len__(self) -> int:
        return len(self._members)

    # ---------------------------------------------------------- lookups

    def replicas_for(self, key: bytes) -> List[str]:
        """The first ``replication`` distinct endpoints clockwise from
        the key's point: [primary, replica1, ...]. Fewer when the ring
        holds fewer members; empty on an empty ring."""
        points, owners = self._points, self._owners
        if not points:
            return []
        idx = bisect.bisect_right(points, _point(key))
        out: List[str] = []
        for i in range(len(points)):
            ep = owners[(idx + i) % len(points)]
            if ep not in out:
                out.append(ep)
                if len(out) == self.replication:
                    break
        return out

    def primary_for(self, key: bytes) -> str:
        owners = self.replicas_for(key)
        if not owners:
            raise LookupError("empty ring")
        return owners[0]
