"""Consistent-hash ring: node-hash -> ordered replica set of endpoints.

Parity: DistributedNodeStorage.scala:13-57 shards the node cache by
``hash % numberOfShards`` under Akka cluster sharding; a consistent
ring replaces the modulo so membership changes (a shard dying, a new
one joining) remap only ~1/N of the keyspace instead of all of it —
the property every sharded KV / parameter-server tier relies on for
cheap rebalance.

Each endpoint owns ``vnodes`` points on a 64-bit ring (points are
keccak-derived, so placement is deterministic across processes — every
client computes the same owner for a key with zero coordination).
Lookups walk clockwise from the key's point collecting the first
``replication`` DISTINCT endpoints: the primary plus failover replicas,
in deterministic preference order.

Membership changes swap an immutable snapshot under a lock; readers
never block, so a rebalance cannot drop an in-flight read.

Epoch-fenced transitions (live rebalance, cluster/rebalance.py): every
committed snapshot carries a monotonically increasing ``epoch``.
``begin_transition(members)`` stages the NEXT epoch alongside the
committed one without changing any committed ownership; while the
transition is open, ``write_chains`` returns the union of old and new
owners (writes land in both worlds) and ``read_chain`` tries the new
owners first and falls back to the old — so no read can miss a key
mid-movement regardless of how far the key streaming has progressed.
``commit_transition`` is the atomic cutover; ``abort_transition``
drops the staged epoch and leaves the committed ring exactly as it
was. A direct ``add``/``remove`` (health verdicts) while a transition
is open aborts it first — a membership change invalidates the staged
plan, and the rebalancer notices via the bumped ``transition_aborts``.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from khipu_tpu.base.crypto.keccak import keccak256

RING_BITS = 64
RING_SIZE = 1 << RING_BITS


def _point(data: bytes) -> int:
    """64-bit ring coordinate."""
    return int.from_bytes(keccak256(data)[:8], "big")


class RingSnapshot:
    """One immutable epoch of ring state: sorted vnode points, the
    endpoint owning each point, and the member tuple. Lookups on a
    snapshot are lock-free and stable — a rebalance plans against two
    snapshots knowing neither can change underneath it."""

    __slots__ = ("epoch", "members", "replication", "vnodes",
                 "points", "owners")

    def __init__(self, epoch: int, members: Tuple[str, ...],
                 replication: int, vnodes: int):
        self.epoch = epoch
        self.members = members
        self.replication = replication
        self.vnodes = vnodes
        # collisions on the 64-bit ring are vanishingly rare; last
        # writer wins (same tolerance as the pre-epoch ring)
        pairs: Dict[int, str] = {}
        for ep in members:
            for i in range(vnodes):
                pairs[_point(f"{ep}#{i}".encode())] = ep
        self.points = sorted(pairs)
        self.owners = [pairs[p] for p in self.points]

    def chain_at(self, point: int) -> List[str]:
        """Replica chain for a key whose ring coordinate is ``point``:
        first ``replication`` distinct endpoints clockwise. Short-
        circuits at ``len(members)`` distinct endpoints — with fewer
        members than replicas there is nothing more to find, so a
        1-member ring never walks all ``vnodes`` points."""
        points, owners = self.points, self.owners
        if not points:
            return []
        want = min(self.replication, len(self.members))
        idx = bisect.bisect_right(points, point)
        out: List[str] = []
        for i in range(len(points)):
            ep = owners[(idx + i) % len(points)]
            if ep not in out:
                out.append(ep)
                if len(out) == want:
                    break
        return out

    def replicas_for(self, key: bytes) -> List[str]:
        return self.chain_at(_point(key))


class HashRing:
    """Immutable-snapshot consistent-hash ring with virtual nodes."""

    def __init__(
        self,
        endpoints: Sequence[str] = (),
        replication: int = 2,
        vnodes: int = 64,
    ):
        if replication < 1:
            raise ValueError("replication must be >= 1")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.replication = replication
        self.vnodes = vnodes
        self._lock = threading.Lock()
        self.transition_aborts = 0  # implicit aborts via add/remove
        self._next: Optional[RingSnapshot] = None
        self._snap = RingSnapshot(
            1, tuple(dict.fromkeys(endpoints)), replication, vnodes
        )

    # ------------------------------------------------------- membership

    def add(self, endpoint: str) -> bool:
        """Join (or re-join) an endpoint; True if membership changed.
        Aborts any open transition first (the staged plan assumed a
        membership that no longer holds)."""
        with self._lock:
            self._drop_next_locked()
            if endpoint in self._snap.members:
                return False
            self._snap = RingSnapshot(
                self._snap.epoch + 1,
                self._snap.members + (endpoint,),
                self.replication, self.vnodes,
            )
            return True

    def remove(self, endpoint: str) -> bool:
        """Leave the ring; True if membership changed. Aborts any open
        transition first."""
        with self._lock:
            self._drop_next_locked()
            if endpoint not in self._snap.members:
                return False
            self._snap = RingSnapshot(
                self._snap.epoch + 1,
                tuple(m for m in self._snap.members if m != endpoint),
                self.replication, self.vnodes,
            )
            return True

    def _drop_next_locked(self) -> None:
        if self._next is not None:
            self._next = None
            self.transition_aborts += 1

    @property
    def members(self) -> Tuple[str, ...]:
        return self._snap.members

    @property
    def epoch(self) -> int:
        """The COMMITTED epoch — what reads are guaranteed against."""
        return self._snap.epoch

    def __len__(self) -> int:
        return len(self._snap.members)

    # ------------------------------------------------------ transitions

    @property
    def snapshot(self) -> RingSnapshot:
        return self._snap

    @property
    def next_snapshot(self) -> Optional[RingSnapshot]:
        return self._next

    @property
    def in_transition(self) -> bool:
        return self._next is not None

    def begin_transition(
        self, members: Sequence[str]
    ) -> Tuple[RingSnapshot, RingSnapshot]:
        """Stage the next epoch's membership without changing any
        committed ownership. Returns ``(old, new)`` snapshots the
        rebalancer plans against. Only one transition may be open."""
        with self._lock:
            if self._next is not None:
                raise RuntimeError("a ring transition is already open")
            new = RingSnapshot(
                self._snap.epoch + 1,
                tuple(dict.fromkeys(members)),
                self.replication, self.vnodes,
            )
            # set comparison: placement is order-insensitive, so a
            # reordered member list is still a no-op transition
            if set(new.members) == set(self._snap.members):
                raise ValueError("transition changes no membership")
            self._next = new
            return self._snap, new

    def commit_transition(self) -> RingSnapshot:
        """Atomic cutover: the staged epoch becomes the committed one.
        Readers see either entirely-old or entirely-new ownership —
        never a blend."""
        with self._lock:
            if self._next is None:
                raise RuntimeError("no ring transition is open")
            self._snap, self._next = self._next, None
            return self._snap

    def abort_transition(self) -> bool:
        """Drop the staged epoch; the committed ring is untouched.
        True if a transition was actually open."""
        with self._lock:
            if self._next is None:
                return False
            self._next = None
            return True

    # ---------------------------------------------------------- lookups

    def replicas_for(self, key: bytes) -> List[str]:
        """The first ``replication`` distinct endpoints clockwise from
        the key's point in the COMMITTED ring: [primary, replica1,
        ...]. Fewer when the ring holds fewer members; empty on an
        empty ring."""
        return self._snap.replicas_for(key)

    def primary_for(self, key: bytes) -> str:
        owners = self.replicas_for(key)
        if not owners:
            raise LookupError("empty ring")
        return owners[0]

    def read_chain(self, key: bytes) -> List[str]:
        """Replica chain for reads. Mid-transition: new-epoch owners
        first (they may already hold the streamed copy), then the old
        owners (they definitely hold everything the old epoch owned) —
        so a read NEVER misses a key because a rebalance is running.
        Outside a transition this is exactly ``replicas_for``."""
        snap, nxt = self._snap, self._next
        if nxt is None:
            return snap.replicas_for(key)
        pt = _point(key)
        out = nxt.chain_at(pt)
        for ep in snap.chain_at(pt):
            if ep not in out:
                out.append(ep)
        return out

    def write_chains(self, key: bytes) -> List[str]:
        """Replica set for writes. Mid-transition: the union of old and
        new owners — a write lands in both worlds, so neither commit
        nor abort of the transition can lose it. Outside a transition
        this is exactly ``replicas_for``."""
        snap, nxt = self._snap, self._next
        if nxt is None:
            return snap.replicas_for(key)
        pt = _point(key)
        out = snap.chain_at(pt)
        for ep in nxt.chain_at(pt):
            if ep not in out:
                out.append(ep)
        return out
