"""Node storages over NodeDataSource.

Parity: khipu-eth/.../storage/NodeStorage.scala:7 (unconfirmed ring,
never deletes from the source :16-19), ReadOnlyNodeStorage (buffering
wrapper for eth_call simulation), ArchiveNodeStorage (no prune).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional

from khipu_tpu.storage.cache import FIFOCache
from khipu_tpu.storage.unconfirmed import SimpleMapWithUnconfirmed


class NodeStorage:
    """hash -> node-rlp store with reorg ring + FIFO read cache.

    Deletes are swallowed: a content-addressed archive store never
    removes nodes (NodeStorage.scala:16-19)."""

    def __init__(self, source, depth: int = 20, cache_size: int = 1 << 20):
        self.source = source
        self._unconfirmed = SimpleMapWithUnconfirmed(source, depth)
        self._unconfirmed.set_buffering(False)  # regular-sync switch turns on
        self._cache: FIFOCache = FIFOCache(cache_size)
        # device-resident read-through (storage/device_mirror.py): when
        # the window commit targets the device mirror, freshly committed
        # nodes live ONLY there until the async spill stage writes them
        # here. Attached by the replay driver; None = host-only reads.
        # Never cached on hit: the mirror ring-evicts, and the spill
        # lands the durable copy in the host store shortly after.
        self.mirror = None

    def get(self, key: bytes) -> Optional[bytes]:
        v = self._cache.get(key)
        if v is not None:
            return v
        v = self._unconfirmed.get(key)
        if v is not None:
            self._cache.put(key, v)
            return v
        m = self.mirror
        if m is not None:
            return m.get(key)
        return None

    def put(self, key: bytes, value: bytes) -> None:
        self.update([], {key: value})

    def update(
        self, to_remove: Iterable[bytes], to_upsert: Mapping[bytes, bytes]
    ) -> None:
        for k, v in to_upsert.items():
            self._cache.put(bytes(k), bytes(v))
        # to_remove intentionally dropped (never delete from source)
        self._unconfirmed.update([], to_upsert)

    def switch_to_unconfirmed(self) -> None:
        self._unconfirmed.set_buffering(True)

    def clear_unconfirmed(self) -> None:
        # The FIFO cache is populated by update()/get() with unconfirmed
        # values; dropping the ring without evicting those keys would
        # keep serving nodes that were never durably written (and mask
        # MPTNodeMissingException after a reorg + restart). Evict only
        # the dropped keys — confirmed hot nodes stay cached. The trie
        # layer's decoded-node cache (mpt.py attaches _mpt_dcache to its
        # source, i.e. this object) reads through get() and can hold the
        # same unconfirmed nodes — evict there too.
        dcache = getattr(self, "_mpt_dcache", None)
        for key in self._unconfirmed.clear_unconfirmed():
            self._cache.remove(key)
            if dcache is not None:
                dcache.pop(key, None)

    def flush(self) -> None:
        self._unconfirmed.flush()

    @property
    def cache_hit_rate(self) -> float:
        return self._cache.hit_rate

    @property
    def cache_read_count(self) -> int:
        return self._cache.read_count


class ReadOnlyNodeStorage:
    """Buffers writes in memory; underlying storage is never touched.

    Used by simulateTransaction / eth_call (ReadOnlyNodeStorage.scala).
    """

    def __init__(self, inner):
        self.inner = inner
        self._buffer: Dict[bytes, bytes] = {}

    def get(self, key: bytes) -> Optional[bytes]:
        v = self._buffer.get(key)
        return v if v is not None else self.inner.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self._buffer[bytes(key)] = bytes(value)

    def update(self, to_remove, to_upsert) -> None:
        for k, v in to_upsert.items():
            self._buffer[bytes(k)] = bytes(v)
