"""Remote read-through node storage: the self-healing missing-node path.

Parity: storage/DistributedNodeStorage.scala:13-57 (read-through the
cluster-sharded NodeEntity cache) and the MPTNodeMissingException
recovery loop (SURVEY §5.3: Ledger.scala:69,511,542 +
RegularSyncService.scala:336-345 — fetch that exact node from a healthy
peer, store it, resume). The fetch callback is a peer pool's
GetNodeData in production, the gRPC bridge or another store in tests;
fetched values are content-address verified before being admitted.
"""

from __future__ import annotations

from typing import Callable, List, Mapping, Optional

from khipu_tpu.base.crypto.keccak import keccak256


class RemoteReadThroughNodeStorage:
    """Wraps a NodeStorage; on local miss, fetches by hash, verifies
    kec256(value) == hash, persists locally, serves the read.

    ``replicate_to`` (a cluster.ShardedNodeClient) additionally
    write-replicates every put onto the key's replica shards, so local
    commits keep the served cluster cache consistent."""

    def __init__(self, inner,
                 fetch: Callable[[List[bytes]], Mapping[bytes, bytes]],
                 replicate_to=None):
        self.inner = inner
        self.fetch = fetch
        self.replicate_to = replicate_to
        self.healed = 0  # nodes recovered from remote

    @classmethod
    def from_cluster(cls, inner, cluster, replicate_writes: bool = False):
        """Back the read-through by a sharded cluster client
        (cluster/client.py) — per-key shard selection, replica
        failover, breakers — instead of a single endpoint."""
        return cls(
            inner,
            cluster.fetch,
            replicate_to=cluster if replicate_writes else None,
        )

    def get(self, key: bytes) -> Optional[bytes]:
        v = self.inner.get(key)
        if v is not None:
            return v
        got = self.fetch([key])
        v = got.get(key)
        if v is None:
            return None
        if keccak256(v) != key:
            return None  # corrupt response: do not admit
        self.inner.put(key, v)
        self.healed += 1
        return v

    def put(self, key: bytes, value: bytes) -> None:
        self.inner.put(key, value)
        if self.replicate_to is not None:
            self.replicate_to.replicate({key: value})

    def update(self, to_remove, to_upsert) -> None:
        self.inner.update(to_remove, to_upsert)
        if self.replicate_to is not None and to_upsert:
            self.replicate_to.replicate(to_upsert)

    def __getattr__(self, name):
        return getattr(self.inner, name)
