"""SQLite-backed storage engine — the LMDB/RocksDB-role alternative.

Parity: khipu-lmdb / khipu-rocksdb (SURVEY §2.4): a second persistent
engine behind the same DataSource SPI, selected purely by
``db.engine = "sqlite"``. One database file per topic directory; WAL
mode for concurrent readers. The native append-log engine remains the
Kesque-role primary; this is the embedded-KV alternative the reference
keeps for operational flexibility.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Iterable, Mapping, Optional

from khipu_tpu.storage.datasource import (
    BlockDataSource,
    KeyValueDataSource,
    NodeDataSource,
)


class _SqliteTable:
    def __init__(self, data_dir: str, topic: str):
        os.makedirs(data_dir, exist_ok=True)
        self._path = os.path.join(data_dir, f"{topic}.sqlite")
        self._local = threading.local()
        self._all_conns = []  # every thread's connection, for close()
        self._conns_lock = threading.Lock()
        with self._conn() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS kv"
                " (k BLOB PRIMARY KEY, v BLOB NOT NULL)"
            )

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            # check_same_thread=False: each connection is still used by
            # exactly one thread for queries, but close() runs on the
            # shutdown thread — the default guard would make those
            # closes silently fail and pin -wal/-shm forever
            conn = sqlite3.connect(self._path, check_same_thread=False)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
            with self._conns_lock:
                # close + drop connections whose owner thread is gone —
                # thread-per-request servers would otherwise pin one fd
                # per request forever; weakrefs track thread liveness
                import weakref

                alive = []
                for c, tref in self._all_conns:
                    owner = tref()
                    if owner is not None and owner.is_alive():
                        alive.append((c, tref))
                    else:
                        try:
                            c.close()
                        except sqlite3.Error:
                            pass
                self._all_conns = alive
                self._all_conns.append(
                    (conn, weakref.ref(threading.current_thread()))
                )
        return conn

    def get(self, key: bytes) -> Optional[bytes]:
        row = self._conn().execute(
            "SELECT v FROM kv WHERE k = ?", (key,)
        ).fetchone()
        return row[0] if row else None

    def update(self, to_remove, to_upsert) -> None:
        conn = self._conn()
        with conn:
            conn.executemany(
                "DELETE FROM kv WHERE k = ?", [(bytes(k),) for k in to_remove]
            )
            conn.executemany(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?, ?)",
                [(bytes(k), bytes(v)) for k, v in to_upsert.items()],
            )

    @property
    def count(self) -> int:
        return self._conn().execute("SELECT COUNT(*) FROM kv").fetchone()[0]

    def keys(self) -> list:
        """All keys, sorted — the StreamNodeData iteration surface
        (live rebalance); bytes sort == SQLite BLOB ordering."""
        return [
            row[0] for row in self._conn().execute(
                "SELECT k FROM kv ORDER BY k"
            )
        ]

    def max_key8(self) -> int:
        row = self._conn().execute(
            "SELECT MAX(k) FROM kv WHERE LENGTH(k) = 8"
        ).fetchone()
        return int.from_bytes(row[0], "big") if row and row[0] else -1

    def close(self) -> None:
        # close EVERY thread's connection (RPC/bridge/peer workers each
        # opened their own) — sqlite allows cross-thread close and this
        # releases the -wal/-shm pins
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for conn, _tref in conns:
            try:
                conn.close()
            except sqlite3.Error:
                pass
        self._local.conn = None


class SqliteKeyValueDataSource(KeyValueDataSource):
    def __init__(self, data_dir: str, topic: str):
        super().__init__()
        self._table = _SqliteTable(data_dir, topic)

    def get(self, key: bytes) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            return self._table.get(bytes(key))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        self._table.update(to_remove, to_upsert)

    @property
    def count(self) -> int:
        return self._table.count

    def keys(self) -> list:
        return self._table.keys()

    def stop(self) -> None:
        self._table.close()


class SqliteNodeDataSource(SqliteKeyValueDataSource, NodeDataSource):
    """Content-addressed node store over sqlite. Removes are swallowed
    (archive semantics, NodeStorage.scala:16-19)."""

    def update(self, to_remove, to_upsert) -> None:
        self._table.update([], to_upsert)


class SqliteBlockDataSource(BlockDataSource):
    def __init__(self, data_dir: str, topic: str):
        super().__init__()
        self._table = _SqliteTable(data_dir, topic)
        self._best = self._table.max_key8()
        self._lock = threading.Lock()

    @staticmethod
    def _key(number: int) -> bytes:
        return int(number).to_bytes(8, "big")

    def get(self, number: int) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            return self._table.get(self._key(number))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        with self._lock:
            self._table.update(
                [self._key(n) for n in to_remove],
                {self._key(n): v for n, v in to_upsert.items()},
            )
            for n in to_upsert:
                if int(n) > self._best:
                    self._best = int(n)
            if to_remove:
                self._best = self._table.max_key8()

    @property
    def best_block_number(self) -> int:
        return self._best

    @property
    def count(self) -> int:
        return self._table.count

    def stop(self) -> None:
        self._table.close()
