"""Application state keys.

Parity: khipu-eth/.../storage/AppStateStorage.scala:8-15 — keys
BestBlockNumber / FastSyncDone / EstimatedHighestBlock /
SyncStartingBlock / LastPrunedBlock over a KeyValueDataSource.
"""

from __future__ import annotations

from typing import Optional


class AppStateStorage:
    BEST_BLOCK_NUMBER = b"BestBlockNumber"
    FAST_SYNC_DONE = b"FastSyncDone"
    ESTIMATED_HIGHEST_BLOCK = b"EstimatedHighestBlock"
    SYNC_STARTING_BLOCK = b"SyncStartingBlock"
    LAST_PRUNED_BLOCK = b"LastPrunedBlock"

    def __init__(self, source):
        self.source = source

    def _get_int(self, key: bytes, default: int = 0) -> int:
        v = self.source.get(key)
        return int.from_bytes(v, "big") if v else default

    def _put_int(self, key: bytes, value: int) -> None:
        self.source.put(key, int(value).to_bytes(8, "big"))

    @property
    def best_block_number(self) -> int:
        return self._get_int(self.BEST_BLOCK_NUMBER)

    @best_block_number.setter
    def best_block_number(self, n: int) -> None:
        self._put_int(self.BEST_BLOCK_NUMBER, n)

    @property
    def fast_sync_done(self) -> bool:
        return self.source.get(self.FAST_SYNC_DONE) == b"\x01"

    def mark_fast_sync_done(self) -> None:
        self.source.put(self.FAST_SYNC_DONE, b"\x01")

    @property
    def estimated_highest_block(self) -> int:
        return self._get_int(self.ESTIMATED_HIGHEST_BLOCK)

    @estimated_highest_block.setter
    def estimated_highest_block(self, n: int) -> None:
        self._put_int(self.ESTIMATED_HIGHEST_BLOCK, n)

    @property
    def sync_starting_block(self) -> int:
        return self._get_int(self.SYNC_STARTING_BLOCK)

    @sync_starting_block.setter
    def sync_starting_block(self, n: int) -> None:
        self._put_int(self.SYNC_STARTING_BLOCK, n)
