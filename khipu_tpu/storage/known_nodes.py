"""Persisted peer URIs (storage/KnownNodesStorage.scala)."""

from __future__ import annotations

from typing import List, Set

from khipu_tpu.base.rlp import rlp_decode, rlp_encode


class KnownNodesStorage:
    KEY = b"known-nodes"

    def __init__(self, source):
        self.source = source

    def get_known_nodes(self) -> Set[str]:
        raw = self.source.get(self.KEY)
        if raw is None:
            return set()
        return {uri.decode() for uri in rlp_decode(raw)}

    def update_known_nodes(
        self, to_add: Set[str] = frozenset(), to_remove: Set[str] = frozenset()
    ) -> Set[str]:
        nodes = (self.get_known_nodes() | set(to_add)) - set(to_remove)
        self.source.put(
            self.KEY, rlp_encode([uri.encode() for uri in sorted(nodes)])
        )
        return nodes
