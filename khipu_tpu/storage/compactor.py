"""Offline compaction: copy the nodes reachable from a pivot state root
into a fresh store generation — mark-and-sweep GC over the append-only
log (storage/KesqueCompactor.scala:32: NodeReader.processNode :72-92
walks the trie, NodeWriter :125 copies to the new file generation).

Works over any (source-store, target-store) pair with get/update, so it
serves the memory engine in tests and the native append-log engine in
production (where the payoff is reclaiming superseded log records).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from khipu_tpu.sync.fast_sync import (
    EVMCODE,
    STATE_NODE,
    STORAGE_NODE,
    _children_of,
)


@dataclass
class CompactionReport:
    state_nodes: int = 0
    storage_nodes: int = 0
    code_blobs: int = 0
    missing: int = 0
    corrupt: int = 0  # stored bytes whose keccak != key (verify_hashes)
    # segment-engine extensions (storage/kesque.py fills these in:
    # bytes the swap freed, and the post-compaction per-segment
    # live/garbage split feeding the khipu_kesque_* registry families)
    reclaimed_bytes: int = 0
    segment_stats: dict = field(default_factory=dict)

    @property
    def total(self) -> int:
        return self.state_nodes + self.storage_nodes + self.code_blobs


def compact(
    account_src,
    storage_src,
    evmcode_src,
    state_root: bytes,
    account_dst,
    storage_dst,
    evmcode_dst,
    batch: int = 1000,
    verify_hashes: bool = False,
) -> CompactionReport:
    """Walk the trie at ``state_root``; copy every reachable node/blob
    from the src stores into the dst stores. Returns counts
    (KesqueCompactor's NodeReader/NodeWriter roles).

    ``verify_hashes`` re-checks every value against its content address
    (all three stores are content-addressed) — the crash-recovery walk
    (sync/journal.py) uses it so a torn or bit-flipped record counts as
    ``corrupt`` instead of silently propagating."""
    if verify_hashes:
        from khipu_tpu.base.crypto.keccak import keccak256

    report = CompactionReport()
    pending: List[Tuple[int, bytes]] = [(STATE_NODE, state_root)]
    seen = {state_root}
    buffers: Dict[int, Dict[bytes, bytes]] = {
        STATE_NODE: {}, STORAGE_NODE: {}, EVMCODE: {},
    }
    srcs = {STATE_NODE: account_src, STORAGE_NODE: storage_src, EVMCODE: evmcode_src}
    dsts = {STATE_NODE: account_dst, STORAGE_NODE: storage_dst, EVMCODE: evmcode_dst}

    def flush(kind: int) -> None:
        if buffers[kind]:
            dsts[kind].update([], buffers[kind])
            buffers[kind].clear()

    while pending:
        kind, h = pending.pop()
        value = srcs[kind].get(h)
        if value is None:
            report.missing += 1
            continue
        if verify_hashes and keccak256(value) != h:
            report.corrupt += 1
            continue  # children unreadable from corrupt bytes
        buffers[kind][h] = value
        if kind == STATE_NODE:
            report.state_nodes += 1
        elif kind == STORAGE_NODE:
            report.storage_nodes += 1
        else:
            report.code_blobs += 1
        if len(buffers[kind]) >= batch:
            flush(kind)
        for child in _children_of(kind, value):
            if child[1] not in seen:
                seen.add(child[1])
                pending.append(child)
    for kind in buffers:
        flush(kind)
    return report


def verify_reachable(
    account_src, storage_src, evmcode_src, state_root: bytes,
    verify_hashes: bool = False,
) -> CompactionReport:
    """DataChecker role (tools/DataChecker.scala:122): walk the whole
    state trie at a block and assert every node is retrievable; the
    report's ``missing`` (and, with ``verify_hashes``, ``corrupt``)
    counts are the integrity verdict."""

    class _Null:
        def update(self, r, u):
            pass

    null = _Null()
    return compact(
        account_src, storage_src, evmcode_src, state_root,
        null, null, null, verify_hashes=verify_hashes,
    )
