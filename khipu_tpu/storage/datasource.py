"""DataSource SPI + in-memory engines.

Parity: khipu-storage/.../datasource/DataSource.scala:6 (count /
cacheHitRate / clock / stop over the SimpleMap get/put/update
contract), NodeDataSource.scala:5 (Hash -> bytes, content-addressed),
BlockDataSource.scala:3 (Long -> bytes + bestBlockNumber),
KeyValueDataSource.scala:3; EphemNodeDataSource (the reference's own
in-memory fake used by GenesisDataLoader and MptListValidator) is the
model for the Memory* engines here.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from khipu_tpu.chaos import fault_point
from khipu_tpu.storage.cache import Clock


class DataSource:
    """Common DataSource surface: metrics + lifecycle."""

    def __init__(self) -> None:
        self.clock = Clock()

    @property
    def count(self) -> int:
        raise NotImplementedError

    @property
    def cache_hit_rate(self) -> float:
        return 0.0

    @property
    def cache_read_count(self) -> int:
        return 0

    def stop(self) -> None:
        pass


class KeyValueDataSource(DataSource):
    """bytes -> bytes store."""

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, key: bytes, value: bytes) -> None:
        self.update([], {key: value})

    def remove(self, key: bytes) -> None:
        self.update([key], {})

    def update(
        self, to_remove: Iterable[bytes], to_upsert: Mapping[bytes, bytes]
    ) -> None:
        raise NotImplementedError


class NodeDataSource(KeyValueDataSource):
    """Content-addressed trie-node store: key == keccak256(value).

    Engines may therefore skip storing keys and recompute them from
    values (KesqueNodeDataSource.scala:61-63 does exactly this)."""


class BlockDataSource(DataSource):
    """block-number -> bytes append store tracking the best number."""

    def get(self, number: int) -> Optional[bytes]:
        raise NotImplementedError

    def put(self, number: int, value: bytes) -> None:
        self.update([], {number: value})

    def remove(self, number: int) -> None:
        self.update([number], {})

    def update(
        self, to_remove: Iterable[int], to_upsert: Mapping[int, bytes]
    ) -> None:
        raise NotImplementedError

    @property
    def best_block_number(self) -> int:
        raise NotImplementedError


class MemoryKeyValueDataSource(KeyValueDataSource):
    def __init__(self) -> None:
        super().__init__()
        self._map: Dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> Optional[bytes]:
        fault_point("storage.kv.get")
        t0 = self.clock.start()
        try:
            return self._map.get(bytes(key))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        fault_point("storage.kv.put")
        with self._lock:
            for k in to_remove:
                self._map.pop(bytes(k), None)
            for k, v in to_upsert.items():
                self._map[bytes(k)] = bytes(v)

    @property
    def count(self) -> int:
        return len(self._map)

    def keys(self) -> List[bytes]:
        return list(self._map.keys())


class MemoryNodeDataSource(MemoryKeyValueDataSource, NodeDataSource):
    """In-memory content-addressed node store (EphemNodeDataSource)."""

    def get(self, key: bytes) -> Optional[bytes]:
        fault_point("storage.node.get")
        t0 = self.clock.start()
        try:
            return self._map.get(bytes(key))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        fault_point("storage.node.put")
        with self._lock:
            for k in to_remove:
                self._map.pop(bytes(k), None)
            for k, v in to_upsert.items():
                self._map[bytes(k)] = bytes(v)


class MemoryBlockDataSource(BlockDataSource):
    def __init__(self) -> None:
        super().__init__()
        self._map: Dict[int, bytes] = {}
        self._best = -1
        self._lock = threading.Lock()

    def get(self, number: int) -> Optional[bytes]:
        fault_point("storage.block.get")
        t0 = self.clock.start()
        try:
            return self._map.get(int(number))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        fault_point("storage.block.put")
        with self._lock:
            for n in to_remove:
                self._map.pop(int(n), None)
            for n, v in to_upsert.items():
                self._map[int(n)] = bytes(v)
                if n > self._best:
                    self._best = int(n)
            if to_remove:
                self._best = max(self._map.keys(), default=-1)

    @property
    def best_block_number(self) -> int:
        return self._best

    @property
    def count(self) -> int:
        return len(self._map)


def verify_content_address(key: bytes, value: bytes) -> bool:
    """Short-key collision guard (KesqueNodeDataSource.scala:61-63)."""
    from khipu_tpu.base.crypto.keccak import keccak256

    return keccak256(value) == key
