"""Block-keyed storages + hash<->number mapping.

Parity: khipu-eth/.../storage/ BlockHeaderStorage / BlockBodyStorage /
ReceiptsStorage / TotalDifficultyStorage / BlockNumberStorage /
TransactionStorage (TxLocation) and BlockNumbers.scala:9 (two-way
number<->hash cache with unconfirmed ring).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from khipu_tpu.base.rlp import rlp_decode, rlp_encode


class BlockBytesStorage:
    """number -> bytes over a BlockDataSource."""

    def __init__(self, source):
        self.source = source

    def get(self, number: int) -> Optional[bytes]:
        return self.source.get(number)

    def put(self, number: int, value: bytes) -> None:
        self.source.put(number, value)

    def update(self, to_remove, to_upsert) -> None:
        self.source.update(to_remove, to_upsert)

    @property
    def best_block_number(self) -> int:
        return self.source.best_block_number


class BlockNumberStorage:
    """block-hash -> block-number (BlockNumberStorage.scala)."""

    def __init__(self, source):
        self.source = source

    def get(self, block_hash: bytes) -> Optional[int]:
        v = self.source.get(block_hash)
        return int.from_bytes(v, "big") if v is not None else None

    def put(self, block_hash: bytes, number: int) -> None:
        self.source.put(block_hash, int(number).to_bytes(8, "big"))

    def remove(self, block_hash: bytes) -> None:
        self.source.remove(block_hash)


class TotalDifficultyStorage(BlockBytesStorage):
    def get_td(self, number: int) -> Optional[int]:
        v = self.get(number)
        return int.from_bytes(v, "big") if v is not None else None

    def put_td(self, number: int, td: int) -> None:
        self.put(number, int(td).to_bytes((td.bit_length() + 7) // 8 or 1, "big"))


class TransactionStorage:
    """tx-hash -> TxLocation(blockNumber, index)
    (TransactionStorage.scala)."""

    def __init__(self, source):
        self.source = source

    def get(self, tx_hash: bytes) -> Optional[Tuple[int, int]]:
        v = self.source.get(tx_hash)
        if v is None:
            return None
        number, index = rlp_decode(v)
        return (
            int.from_bytes(number, "big"),
            int.from_bytes(index, "big"),
        )

    def put(self, tx_hash: bytes, block_number: int, index: int) -> None:
        enc = rlp_encode(
            [
                int(block_number).to_bytes(8, "big").lstrip(b"\x00") or b"",
                int(index).to_bytes(4, "big").lstrip(b"\x00") or b"",
            ]
        )
        self.source.put(tx_hash, enc)


class BlockNumbers:
    """RW-locked bidirectional number<->hash maps (BlockNumbers.scala:9)."""

    def __init__(
        self,
        block_number_storage: BlockNumberStorage,
        block_header_storage: Optional[BlockBytesStorage] = None,
    ):
        self._storage = block_number_storage
        self._headers = block_header_storage
        self._num_to_hash: Dict[int, bytes] = {}
        self._hash_to_num: Dict[bytes, int] = {}
        self._lock = threading.RLock()

    def number_of(self, block_hash: bytes) -> Optional[int]:
        # One critical section: the storage read and the map insert must
        # not interleave with remove(), or a reorg-orphaned mapping
        # would be resurrected.
        with self._lock:
            n = self._hash_to_num.get(block_hash)
            if n is not None:
                return n
            n = self._storage.get(block_hash)
            if n is not None:
                self._hash_to_num[block_hash] = n
                self._num_to_hash[n] = block_hash
        return n

    def hash_of(self, number: int) -> Optional[bytes]:
        with self._lock:
            h = self._num_to_hash.get(number)
        if h is not None:
            return h
        # Storage fallback (getHashByBlockNumber, BlockNumbers.scala):
        # after a restart the in-memory maps are empty; derive the hash
        # from the persisted header (hash == keccak256(header rlp)).
        if self._headers is None:
            return None
        header = self._headers.get(number)
        if header is None:
            return None
        from khipu_tpu.base.crypto.keccak import keccak256

        h = keccak256(header)
        # Trust the derived hash only while the hash->number record still
        # exists: after remove() (reorg orphaning) the stale header must
        # not resurrect the mapping. The storage re-check happens under
        # the lock so a concurrent remove() cannot interleave between the
        # verification and the map insert.
        with self._lock:
            if self._storage.get(h) != number:
                return None
            self._num_to_hash[number] = h
            self._hash_to_num[h] = number
        return h

    def put(self, block_hash: bytes, number: int) -> None:
        self._storage.put(block_hash, number)
        with self._lock:
            self._hash_to_num[block_hash] = number
            self._num_to_hash[number] = block_hash

    def remove(self, block_hash: bytes) -> None:
        self._storage.remove(block_hash)
        with self._lock:
            n = self._hash_to_num.pop(block_hash, None)
            if n is not None:
                self._num_to_hash.pop(n, None)
