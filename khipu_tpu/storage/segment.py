"""Append-only segment files: length-prefixed, CRC-framed records.

Parity: khipu-eth's Kesque engine stores every topic as a Kafka-style
log of framed records (KesqueDataSource.scala — topic files of
offset-addressed records); this module is the file layer under
storage/kesque.py. One ``Segment`` is one file of back-to-back frames:

    +----------+----------+------------------+
    | len u32  | crc u32  | payload (len B)  |
    +----------+----------+------------------+

``crc`` is CRC-32 over the payload. A frame is valid iff its header is
complete, ``len`` passes the sanity cap, the payload is fully present
and the CRC matches. The file layer knows nothing about keys or
values — payload semantics (node records, tombstones) live in
kesque.py.

Crash contract (docs/kesque.md, docs/recovery.md): appends are
positional writes at the committed end, chunked through the
``kesque.append`` chaos seam so an injected death tears a frame
mid-write exactly like a real power cut. ``Segment.open`` scans
forward from offset 0 and TRUNCATES the file back to the last valid
frame boundary — a torn tail can lose the in-flight suffix but can
never be served, and the window journal's recovery walk
(sync/journal.py ``verify_reachable(verify_hashes=True)``) then
classifies the lost records as ``missing`` and rolls the torn window
back bit-exact.

Reads are positional (``os.pread``) so concurrent readers never share
a file cursor with the appender.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, List, Tuple

from khipu_tpu.chaos import fault_point

FRAME_HEADER = 8  # u32 len + u32 crc32
_HDR = struct.Struct(">II")
# sanity cap: no single record (node RLP, code blob, block body) comes
# within orders of magnitude of this — a bigger length is torn bytes
MAX_FRAME_PAYLOAD = 1 << 30
# append chunk: each chunk write passes the kesque.append seam, so a
# seeded death can land at any 4 KiB boundary inside a frame
WRITE_CHUNK = 4096


class SegmentCorruptError(Exception):
    """A framed read failed its CRC/length check — torn or bit-flipped
    bytes reached a serving path (the open-time scan-back should have
    truncated them; mid-life corruption is a disk fault)."""


def frame(payload: bytes) -> bytes:
    """One encoded frame: header + payload."""
    if len(payload) > MAX_FRAME_PAYLOAD:
        raise ValueError(f"frame payload too large: {len(payload)}")
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def scan_frames(data: bytes, base: int = 0) -> Tuple[List[Tuple[int, bytes]], int]:
    """Scan ``data`` (the file bytes from offset ``base``) into
    ``([(absolute_offset, payload), ...], valid_end)`` where
    ``valid_end`` is the absolute offset just past the last VALID
    frame — the scan-back truncation point. Stops at the first torn,
    oversized or CRC-failing frame."""
    out: List[Tuple[int, bytes]] = []
    pos = 0
    n = len(data)
    while pos + FRAME_HEADER <= n:
        ln, crc = _HDR.unpack_from(data, pos)
        if ln > MAX_FRAME_PAYLOAD or pos + FRAME_HEADER + ln > n:
            break
        payload = data[pos + FRAME_HEADER : pos + FRAME_HEADER + ln]
        if zlib.crc32(payload) != crc:
            break
        out.append((base + pos, payload))
        pos += FRAME_HEADER + ln
    return out, base + pos


class Segment:
    """One append-only segment file. NOT thread-safe by itself — the
    owning KesqueStore serializes appends and index swaps under its
    lock; positional reads are safe against the appender by
    construction (``pread`` past ``end`` is never issued because the
    index only ever points inside the committed prefix)."""

    def __init__(self, path: str, seq: int):
        self.path = path
        self.seq = seq
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        self.end = os.fstat(self._fd).st_size  # committed end

    # ------------------------------------------------------------- open

    @classmethod
    def open(cls, path: str, seq: int) -> Tuple["Segment", int]:
        """Open an existing (or fresh) segment, scanning forward from
        offset 0 and truncating any torn tail. Returns
        ``(segment, truncated_bytes)``."""
        seg = cls(path, seq)
        size = seg.end
        if size == 0:
            return seg, 0
        data = os.pread(seg._fd, size, 0)
        _, valid_end = scan_frames(data)
        torn = size - valid_end
        if torn:
            os.ftruncate(seg._fd, valid_end)
            seg.end = valid_end
        return seg, torn

    # ----------------------------------------------------------- append

    def append(self, payload: bytes) -> Tuple[int, int]:
        """Append one framed record at the committed end; returns
        ``(offset, frame_bytes)``. The write is chunked through the
        ``kesque.append`` chaos seam: an injected death mid-loop leaves
        a torn frame past ``end`` for the open-time scan-back to
        truncate; an injected *raise* leaves ``end`` untouched, so the
        next append simply overwrites the torn bytes."""
        buf = frame(payload)
        off = self.end
        pos = off
        for i in range(0, len(buf), WRITE_CHUNK):
            fault_point("kesque.append")
            chunk = buf[i : i + WRITE_CHUNK]
            os.pwrite(self._fd, chunk, pos)
            pos += len(chunk)
        self.end = pos
        return off, len(buf)

    def append_many(self, payloads: List[bytes]) -> List[Tuple[int, int]]:
        """Append a batch of framed records as ONE sequential chunked
        write — the bulk-spill fast path (a window's whole mirror tile
        is a few hundred pwrites of WRITE_CHUNK, not one syscall per
        node). Returns ``[(offset, frame_bytes), ...]`` in order. Crash
        semantics are identical to per-record ``append``: ``end`` moves
        only after the last chunk, so a death mid-loop leaves complete
        leading frames (kept by the open-time scan) and one torn frame
        (truncated) — exactly the records that were durably written."""
        bufs = [frame(p) for p in payloads]
        locs: List[Tuple[int, int]] = []
        off = self.end
        for b in bufs:
            locs.append((off, len(b)))
            off += len(b)
        buf = b"".join(bufs)
        mv = memoryview(buf)
        pos = self.end
        for i in range(0, len(buf), WRITE_CHUNK):
            fault_point("kesque.append")
            chunk = mv[i : i + WRITE_CHUNK]
            os.pwrite(self._fd, chunk, pos)
            pos += len(chunk)
        self.end = pos
        return locs

    def append_raw(self, raw: bytes) -> int:
        """Append pre-framed bytes verbatim; returns the base offset.
        The segment-streamed ingest fast path: a shipped chunk is
        whole valid frames by contract (the caller has scanned and
        verified them), so re-framing would just re-CRC identical
        bytes. Crash semantics are identical to ``append_many`` —
        ``end`` moves only after the last chunk."""
        mv = memoryview(raw)
        off = self.end
        pos = off
        for i in range(0, len(raw), WRITE_CHUNK):
            fault_point("kesque.append")
            chunk = mv[i : i + WRITE_CHUNK]
            os.pwrite(self._fd, chunk, pos)
            pos += len(chunk)
        self.end = pos
        return off

    # ------------------------------------------------------------- read

    def read(self, offset: int) -> bytes:
        """Read the frame payload at ``offset`` (CRC-checked)."""
        hdr = os.pread(self._fd, FRAME_HEADER, offset)
        if len(hdr) < FRAME_HEADER:
            raise SegmentCorruptError(
                f"{self.path}@{offset}: truncated frame header"
            )
        ln, crc = _HDR.unpack(hdr)
        if ln > MAX_FRAME_PAYLOAD:
            raise SegmentCorruptError(
                f"{self.path}@{offset}: implausible frame length {ln}"
            )
        payload = os.pread(self._fd, ln, offset + FRAME_HEADER)
        if len(payload) < ln or zlib.crc32(payload) != crc:
            raise SegmentCorruptError(
                f"{self.path}@{offset}: frame failed CRC"
            )
        return payload

    def scan(self) -> Iterator[Tuple[int, bytes]]:
        """All valid frames, in append order."""
        data = os.pread(self._fd, self.end, 0)
        frames, _ = scan_frames(data)
        return iter(frames)

    def read_chunk(self, offset: int, max_bytes: int) -> Tuple[bytes, int, bool]:
        """A raw byte range of WHOLE frames starting at ``offset``:
        ``(raw, next_offset, done)``. The cut lands on a frame
        boundary so the receiver can parse the chunk standalone —
        the segment-streaming unit (fast-sync ingest, rebalance
        segment-ship). Never serves past the committed end. Always
        ships at least one frame, so one oversized record cannot
        wedge the stream."""
        end = self.end
        if offset >= end:
            return b"", end, True
        data = os.pread(self._fd, min(end - offset, max(max_bytes, FRAME_HEADER + 1)), offset)
        frames, valid_end = scan_frames(data, base=offset)
        if not frames:
            # the next frame alone exceeds max_bytes: read it whole
            hdr = os.pread(self._fd, FRAME_HEADER, offset)
            ln, _crc = _HDR.unpack(hdr)
            data = os.pread(self._fd, FRAME_HEADER + ln, offset)
            frames, valid_end = scan_frames(data, base=offset)
            if not frames:
                raise SegmentCorruptError(
                    f"{self.path}@{offset}: unreadable frame mid-log"
                )
        raw = data[: valid_end - offset]
        return raw, valid_end, valid_end >= end

    # -------------------------------------------------------- lifecycle

    def flush(self) -> None:
        os.fsync(self._fd)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def unlink(self) -> None:
        self.close()
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
