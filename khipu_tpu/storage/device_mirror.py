"""Device-resident word-major node mirror — the hot-read store's TPU
half.

Role parity: the reference's production node store keeps hot trie nodes
in a memory-mapped Kesque table so reads never touch the cold store
(khipu-kesque/.../KesqueNodeDataSource.scala:18, 4KB-fetch design).
On TPU the analogous asset is not host RAM but HBM *in the kernel's
native layout*: this mirror keeps admitted nodes as multi-rate-padded
u32 word-major tiles ``[tiles, nwords, 8, 128]`` with their claimed
content addresses resident alongside, so the two hot batch operations
run with ZERO per-call layout work (docs/roofline.md identifies the
batch-major -> word-major HBM transpose as the last gap between the
full Keccak path and the kernel bound):

  * :meth:`verify` — re-hash every resident node and compare against
    its claimed hash (the fast-sync snapshot verification, BASELINE
    config #5) in ONE dispatch per size class;
  * the #2 primary microbench (bench.py) — sustained content-address
    hashing over the resident tiles.

The layout cost is paid once at ADMIT (write) time on the host, which
is the store-ingest side where the reference also pays its layout
(Kesque packs records into its log format at write). Source of truth
stays the backing byte store; the mirror is an accelerator cache with
ring eviction, safe to drop at any time.

Capacity is fixed per size class at construction: one preallocated
device buffer per class, filled in place with donated jit updates
(stable shapes -> a handful of XLA compiles for the process lifetime).
Unfilled rows hold a synthetic padding row whose claimed digest is
self-consistent by construction, so verify needs no masking.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from khipu_tpu.observability.profiler import D2H, H2D, LEDGER
from khipu_tpu.observability.registry import REGISTRY
from khipu_tpu.observability.trace import span as _span
from khipu_tpu.ops.keccak_jnp import RATE

TILE = 8 * 128  # messages per kernel tile (keccak_pallas.TILE)

MIRROR_GAUGES = REGISTRY.gauge_group("khipu_mirror", {
    # ring evictions that overwrote a window row BEFORE the persist
    # stage spilled it to the host store (the row stays readable
    # through the session's staged encodings, but the bulk-tile spill
    # must fall back to host substitution for it — a sizing signal:
    # nonzero means mirror_capacity_rows is too small for the
    # configured pipeline depth)
    "unspilled_evictions": 0,
    # whole resident tiles fetched by the bulk spill read-back
    "spilled_tiles": 0,
}, help="device-mirror spill watermark state (storage/device_mirror.py)")


def _pack_word_major(padded_rows: np.ndarray) -> np.ndarray:
    """u8[N, nblocks*RATE] (N % TILE == 0) -> u32[tiles, nwords, 8, 128]
    — the kernel's native plane layout. Host-side, admit-time only."""
    n, width = padded_rows.shape
    nwords = width // 4
    words = (
        np.ascontiguousarray(padded_rows)
        .reshape(n, nwords, 4)
        .view("<u4")
        .reshape(n, nwords)
    )
    tiles = n // TILE
    return np.ascontiguousarray(
        words.reshape(tiles, 8, 128, nwords).transpose(0, 3, 1, 2)
    )


@lru_cache(maxsize=None)
def _class_kernels(nblocks: int, exact_len: Optional[int],
                   interpret: bool):
    """Process-wide jitted kernels for one (nblocks, exact_len) size
    class — hash runner, donated tile installers, verifier. Cached at
    module level (NOT per mirror instance) so a rebuilt mirror (tests,
    epoch restarts, one mirror per driver) reuses the XLA executables
    instead of paying a fresh multi-second compile per class."""
    import jax
    import jax.numpy as jnp
    from functools import partial

    width = exact_len if exact_len else nblocks * RATE
    nwords = width // 4

    if jax.default_backend() == "tpu":
        from khipu_tpu.ops.keccak_pallas import _build

        run = _build(
            nblocks, interpret,
            nwords_in=nwords if exact_len else None,
        )
    else:
        # CPU/test backend: XLA-compiled jnp sponge over the SAME
        # word-major plane layout (pallas interpret mode is orders
        # of magnitude too slow — same convention as trie/fused)
        from khipu_tpu.ops.keccak_jnp import hash_padded_u8

        full = nblocks * RATE

        @jax.jit
        def _run_jnp(planes):  # u32[t, nwords, 8, 128]
            t = planes.shape[0]
            words = planes.transpose(0, 2, 3, 1).reshape(
                t * TILE, nwords
            )
            u8 = jax.lax.bitcast_convert_type(
                words, jnp.uint8
            ).reshape(t * TILE, width)
            if exact_len is not None:  # fuse the multi-rate pad
                pad = jnp.zeros(
                    (t * TILE, full - width), dtype=jnp.uint8
                )
                u8 = jnp.concatenate([u8, pad], axis=1)
                u8 = u8.at[:, width].set(u8[:, width] ^ 0x01)
                u8 = u8.at[:, full - 1].set(u8[:, full - 1] ^ 0x80)
            digs = hash_padded_u8(u8, nblocks)  # u8[N, 32]
            dw = jax.lax.bitcast_convert_type(
                digs.reshape(t * TILE, 8, 4), jnp.uint32
            )
            return dw.reshape(t, 8, 128, 8).transpose(0, 3, 1, 2)

        run = _run_jnp

    # donated: the admit path updates the resident buffers in place
    # instead of copying the whole mirror per tile
    @partial(jax.jit, donate_argnums=(0, 1))
    def set_tile(resident, claimed, tile_idx, planes, digs):
        resident = jax.lax.dynamic_update_slice(
            resident, planes[None], (tile_idx, 0, 0, 0)
        )
        claimed = jax.lax.dynamic_update_slice(
            claimed, digs[None], (tile_idx, 0, 0, 0)
        )
        return resident, claimed

    # DEVICE-RESIDENT admit: encodings + claimed digests already live
    # on device (row-major u8, e.g. gathered from a FusedJob's output);
    # the word-major retile runs here instead of on the host, so the
    # window-commit admit path moves ZERO node bytes across the tunnel
    @partial(jax.jit, donate_argnums=(0, 1))
    def admit_device(resident, claimed, tile_idx, enc_u8, claim_u8):
        words = jax.lax.bitcast_convert_type(
            enc_u8.reshape(TILE, nwords, 4), jnp.uint32
        )  # [TILE, nwords] little-endian — matches _pack_word_major
        planes = words.reshape(8, 128, nwords).transpose(2, 0, 1)
        cw = jax.lax.bitcast_convert_type(
            claim_u8.reshape(TILE, 8, 4), jnp.uint32
        )  # [TILE, 8]
        claim = cw.reshape(8, 128, 8).transpose(2, 0, 1)
        resident = jax.lax.dynamic_update_slice(
            resident, planes[None], (tile_idx, 0, 0, 0)
        )
        claimed = jax.lax.dynamic_update_slice(
            claimed, claim[None], (tile_idx, 0, 0, 0)
        )
        return resident, claimed

    @jax.jit
    def verify(resident, claimed):
        digs = run(resident)
        bad = jnp.any(digs != claimed, axis=1)  # (tiles, 8, 128)
        return jnp.sum(bad.astype(jnp.int32))

    return run, set_tile, admit_device, verify


def _filler_row_u8_for(width: int, exact_len: Optional[int]) -> np.ndarray:
    filler = np.zeros(width, dtype=np.uint8)
    if exact_len is None:
        filler[0] ^= 0x01
        filler[-1] ^= 0x80
    return filler


@lru_cache(maxsize=None)
def _filler_for(nblocks: int, exact_len: Optional[int],
                interpret: bool) -> Tuple[bytes, bytes]:
    """(filler plane words u32[nwords], filler digest u32[8]) as raw
    bytes — the synthetic padding row and its self-consistent digest,
    computed once per class per process (one small device round-trip)."""
    import jax

    width = exact_len if exact_len else nblocks * RATE
    run = _class_kernels(nblocks, exact_len, interpret)[0]
    tile = np.broadcast_to(
        _filler_row_u8_for(width, exact_len), (TILE, width)
    ).astype(np.uint8)
    planes = _pack_word_major(tile)
    # amortized one-time cost: billed to its own phase so the lazy
    # first-admit build never pollutes a steady-state stage's totals
    with LEDGER.context(phase="init"):
        LEDGER.record("mirror.init", H2D, planes.nbytes)
        with LEDGER.transfer("mirror.init", D2H, TILE * 32):
            d = np.asarray(jax.device_get(run(planes)))  # (1, 8, 8, 128)
    return (
        planes[0, :, 0, 0].copy().tobytes(),
        d[0, :, 0, 0].copy().tobytes(),
    )


class _ClassMirror:
    """One size class (fixed rate-block count).

    Thread model: the window-commit collect stage admits, the persist
    stage rekeys, and RPC/readers fetch rows — concurrently. ``_lock``
    serializes buffer installs (which DONATE the resident arrays —
    a reader holding the old reference would see a deleted buffer)
    against row fetches; the bookkeeping dicts ride along under the
    same lock for a consistent row <-> key view."""

    def _filler_row_u8(self) -> np.ndarray:
        return _filler_row_u8_for(self.width, self.exact_len)

    def __init__(self, nblocks: int, capacity_rows: int, interpret: bool,
                 exact_len: Optional[int] = None):
        """``exact_len``: every row of this class is exactly that many
        bytes (a multiple of 4) — rows are stored UNPADDED and the
        kernel fuses the multi-rate padding in registers, ~18% less
        HBM read per hash than the generic padded layout. The generic
        class (exact_len None) stores padded rows and serves any
        length within its rate-block count."""
        import jax
        import jax.numpy as jnp

        if capacity_rows % TILE:
            raise ValueError("capacity_rows must be a multiple of 1024")
        if exact_len is not None and exact_len % 4:
            raise ValueError("exact_len must be a multiple of 4")
        self.nblocks = nblocks
        self.exact_len = exact_len
        self.width = exact_len if exact_len else nblocks * RATE
        self.nwords = self.width // 4
        self.capacity = capacity_rows
        self.tiles = capacity_rows // TILE
        self.fill = 0  # ring write pointer (rows)
        self.count = 0  # resident rows (<= capacity)
        self.rows: Dict[bytes, int] = {}  # hash -> row
        # placeholder-keyed rows of not-yet-published windows: the
        # device-resident commit admits under the window's placeholder
        # ALIASES (real hashes are unknown until the persist stage
        # fetches the mapping) and rekey() moves them into ``rows``.
        # Kept OUT of the content-address namespace on purpose: a
        # stale alias (crashed window, reused placeholder counter)
        # must never serve a get() by hash.
        self.alias_rows: Dict[bytes, int] = {}
        self.row_hash: List[Optional[bytes]] = [None] * capacity_rows
        self.lengths: Dict[bytes, int] = {}  # exact unpadded length
        # the SPILL WATERMARK: keys admitted from a window commit that
        # the persist stage has not yet written to the host store.
        # Ring eviction consults this set — overwriting an unspilled
        # row is counted (khipu_mirror_unspilled_evictions) because it
        # forces the spill back onto the host-substitution path
        self.unspilled: set = set()
        self._lock = threading.RLock()
        (self._run, self._set_tile, self._admit_device,
         self._verify) = _class_kernels(nblocks, exact_len, interpret)
        fw, fd = _filler_for(nblocks, exact_len, interpret)
        self._filler_words = np.frombuffer(fw, dtype="<u4").copy()
        filler_digest = np.frombuffer(fd, dtype="<u4").copy()

        # one-time per-class buffer materialization. Only the two small
        # filler arrays cross the tunnel — the broadcast to full mirror
        # size happens on device — so that is what the ledger records
        # (site AND phase kept separate from the per-tile admit path:
        # classes build lazily on first admit, which runs inside the
        # collect stage, and this setup cost must not bill there)
        with LEDGER.context(phase="init"), LEDGER.transfer(
            "mirror.init", H2D,
            self._filler_words.nbytes + filler_digest.nbytes,
        ):
            self.resident = jax.device_put(
                jnp.broadcast_to(
                    jnp.asarray(self._filler_words)[None, :, None, None],
                    (self.tiles, self.nwords, 8, 128),
                ).astype(jnp.uint32)
            )
            self.claimed = jax.device_put(
                jnp.broadcast_to(
                    jnp.asarray(filler_digest)[None, :, None, None],
                    (self.tiles, 8, 8, 128),
                ).astype(jnp.uint32)
            )

    def admit_tile(self, hashes: List[bytes], padded: np.ndarray,
                   lengths: List[int]) -> None:
        """Install one full tile (1024 rows; short batches are filled
        with the synthetic row by the caller)."""
        import jax
        import jax.numpy as jnp

        planes = _pack_word_major(padded)
        # claimed digests come from the CLAIMED hashes, not our kernel
        # (verify must catch a corrupt admit); filler rows claim their
        # own digest. A FULL tile of real rows needs no kernel call —
        # partial tiles (at most one per class per flush) hash once so
        # their filler rows self-claim
        if len(hashes) >= TILE:
            claim_rows = np.frombuffer(
                b"".join(hashes), dtype="<u4"
            ).reshape(TILE, 8).copy()
        else:
            # partial-tile tax: one extra device round-trip (planes up,
            # self-claim digests back) that full tiles never pay — the
            # ledger is what makes this visible per window
            LEDGER.record("mirror.claim", H2D, planes.nbytes)
            with LEDGER.transfer("mirror.claim", D2H, TILE * 32):
                digs = np.asarray(
                    jax.device_get(self._run(planes))
                )  # (1, 8, 8, 128)
            claim_rows = (
                digs[0].transpose(1, 2, 0).reshape(TILE, 8).copy()
            )  # row-major [row, word]
            if hashes:
                claim_rows[: len(hashes)] = np.frombuffer(
                    b"".join(hashes), dtype="<u4"
                ).reshape(len(hashes), 8)
        claim = claim_rows.reshape(8, 128, 8).transpose(2, 0, 1)[None]
        claim = np.ascontiguousarray(claim)

        with self._lock:
            tile_idx = self.fill // TILE
            # the resident-tile refresh: one word-major plane + its
            # claim tile cross host->device per admitted tile
            with LEDGER.transfer(
                "mirror.admit", H2D, planes[0].nbytes + claim[0].nbytes
            ):
                self.resident, self.claimed = self._set_tile(
                    self.resident, self.claimed, tile_idx,
                    jnp.asarray(planes[0]), jnp.asarray(claim[0]),
                )
            self._bookkeep_tile(hashes, lengths, self.rows)

    def _evict_row(self, row: int) -> None:
        # evict only if the mapping still points HERE: a duplicate
        # re-admit may have moved the hash to a newer row, whose
        # entry must survive this slot's overwrite
        old = self.row_hash[row]
        if old is None:
            return
        if self.rows.get(old) == row:
            del self.rows[old]
            self.lengths.pop(old, None)
            self.count -= 1
        elif self.alias_rows.get(old) == row:
            del self.alias_rows[old]
            self.lengths.pop(old, None)
            self.count -= 1
        else:
            return
        # spill-watermark check: overwriting a row the persist stage
        # has not spilled yet is legal (the session's staged encodings
        # still serve it) but costs the bulk spill its fast path
        if old in self.unspilled:
            self.unspilled.discard(old)
            MIRROR_GAUGES["unspilled_evictions"] += 1

    def _bookkeep_tile(self, keys, lengths,
                       target: Dict[bytes, int]) -> None:
        """Row <-> key accounting for one freshly installed tile
        starting at ``self.fill`` (lock held by caller)."""
        for r in range(TILE):
            row = self.fill + r
            self._evict_row(row)
            h = keys[r] if r < len(keys) else None
            self.row_hash[row] = h
            if h is not None:
                if h not in target:
                    self.count += 1  # re-admit of a resident key
                target[h] = row  # latest copy wins
                self.lengths[h] = int(lengths[r])
        self.fill = (self.fill + TILE) % self.capacity

    def admit_tile_device(self, keys: List[Optional[bytes]],
                          enc_dev, claim_dev, lengths,
                          alias: bool = True) -> None:
        """Install one tile whose encodings (u8[TILE, width]) and
        claimed digests (u8[TILE, 32]) ALREADY live on device — the
        window-commit path. No node bytes cross the tunnel; the
        word-major retile happens in the donated jit. ``alias`` keys
        go to the placeholder namespace (see ``alias_rows``)."""
        with self._lock, _span("mirror.admit_tile", rows=len(keys)):
            tile_idx = self.fill // TILE
            self.resident, self.claimed = self._admit_device(
                self.resident, self.claimed, tile_idx,
                enc_dev, claim_dev,
            )
            self._bookkeep_tile(
                keys, lengths, self.alias_rows if alias else self.rows
            )
            if alias:
                # below the spill watermark until persist reads them
                self.unspilled.update(
                    k for k in keys if k is not None
                )

    def rekey(self, mapping: Mapping[bytes, bytes]) -> int:
        """Move alias-keyed rows to their real content addresses once
        the persist stage has fetched the window's placeholder->digest
        mapping. Returns the number of rows promoted."""
        moved = 0
        with self._lock:
            for alias, real in mapping.items():
                row = self.alias_rows.pop(alias, None)
                if row is None:
                    continue
                if self.row_hash[row] != alias:
                    continue  # slot was ring-evicted since admit
                if real in self.rows:
                    self.count -= 1  # duplicate: newer copy wins below
                self.rows[real] = row
                self.row_hash[row] = real
                ln = self.lengths.pop(alias, None)
                if ln is not None:
                    self.lengths[real] = ln
                if alias in self.unspilled:
                    self.unspilled.discard(alias)
                    self.unspilled.add(real)
                moved += 1
        return moved

    def drop_aliases(self, aliases) -> None:
        """Forget alias rows without promoting them (torn window)."""
        with self._lock:
            for alias in aliases:
                row = self.alias_rows.pop(alias, None)
                if row is not None and self.row_hash[row] == alias:
                    self.row_hash[row] = None
                    self.count -= 1
                self.lengths.pop(alias, None)
                self.unspilled.discard(alias)

    def fetch_row(self, key: bytes) -> Optional[bytes]:
        """Read one row back by content address (unpadded). Lock held
        across the device fetch so a concurrent donated install can't
        delete the buffer under us."""
        import jax

        with self._lock:
            row = self.rows.get(key)
            if row is None:
                return None
            ln = self.lengths.get(key)
            if ln is None:
                return None
            t, r = divmod(row, TILE)
            i, j = divmod(r, 128)
            with LEDGER.transfer("mirror.get", D2H, self.nwords * 4):
                words = np.asarray(
                    # khipu-lint: ok KL004 fetch must finish under the install lock
                    jax.device_get(self.resident[t, :, i, j])
                ).astype("<u4")
            return words.tobytes()[:ln]

    def spill_rows(self, keys) -> Dict[bytes, bytes]:
        """Bulk read-back for the persist spill: ONE whole-tile array
        slice per resident tile covering the requested keys, instead
        of a device round-trip per node (``fetch_row``). Rows come
        back FINAL (the admitted encodings already carry real child
        digests), unpadded via the stored lengths. Keys not resident
        (ring-evicted before the spill) are simply absent — the
        caller substitutes those on the host. Fetched keys drop below
        the spill watermark."""
        import jax

        out: Dict[bytes, bytes] = {}
        with self._lock:
            by_tile: Dict[int, List[Tuple[bytes, int, int]]] = {}
            for key in keys:
                row = self.rows.get(key)
                if row is None:
                    row = self.alias_rows.get(key)
                if row is None:
                    continue
                ln = self.lengths.get(key)
                if not ln:
                    continue
                by_tile.setdefault(row // TILE, []).append(
                    (key, row % TILE, ln)
                )
            for t in sorted(by_tile):
                with LEDGER.transfer(
                    "mirror.spill", D2H, self.nwords * 4 * TILE
                ):
                    planes = np.asarray(
                        # khipu-lint: ok KL004 fetch must finish under the install lock
                        jax.device_get(self.resident[t])
                    )  # u32[nwords, 8, 128]
                MIRROR_GAUGES["spilled_tiles"] += 1
                # word-major -> row-major: row r of the tile lives at
                # [:, r // 128, r % 128] (same mapping as fetch_row)
                rows_u8 = np.ascontiguousarray(
                    planes.transpose(1, 2, 0).reshape(TILE, self.nwords)
                    .astype("<u4")
                ).view(np.uint8).reshape(TILE, self.width)
                for key, r, ln in by_tile[t]:
                    out[key] = rows_u8[r, :ln].tobytes()
                    self.unspilled.discard(key)
        return out

    def verify(self) -> int:
        import jax

        # lock held across the dispatch: a concurrent donated install
        # would delete the very buffers we are hashing
        with self._lock:
            with LEDGER.transfer("mirror.verify", D2H, 4):
                return int(
                    # khipu-lint: ok KL004 hash must read under the install lock
                    jax.device_get(
                        self._verify(self.resident, self.claimed)
                    )
                )


class DeviceNodeMirror:
    """Multi-class device mirror; admit in batches, verify in one
    dispatch per class. See module docstring."""

    def __init__(self, capacity_rows_per_class: int = 16 * TILE,
                 interpret: bool = False):
        self.capacity = capacity_rows_per_class
        self.interpret = interpret
        # keyed by (nblocks, exact_len-or-None): generic padded classes
        # serve arbitrary node lengths; exact classes store uniform-
        # length populations unpadded (in-kernel pad, less HBM/hash)
        self._classes: Dict[Tuple[int, Optional[int]], _ClassMirror] = {}
        # host staging until a whole tile per class is ready
        self._pending: Dict[int, List[Tuple[bytes, bytes]]] = {}

    def _class(self, nblocks: int,
               exact_len: Optional[int] = None) -> _ClassMirror:
        key = (nblocks, exact_len)
        cm = self._classes.get(key)
        if cm is None:
            cm = _ClassMirror(
                nblocks, self.capacity, self.interpret, exact_len
            )
            self._classes[key] = cm
        return cm

    def admit(self, items: Mapping[bytes, bytes]) -> None:
        """Stage nodes (hash -> encoding); full 1024-row tiles upload
        immediately, the remainder stays staged until flush()."""
        for h, enc in items.items():
            nb = len(enc) // RATE + 1
            self._pending.setdefault(nb, []).append((h, enc))
        for nb, pend in self._pending.items():
            while len(pend) >= TILE:
                self._install(nb, pend[:TILE])
                del pend[:TILE]

    def flush(self) -> None:
        """Upload partial tiles (padded out with synthetic rows)."""
        for nb, pend in self._pending.items():
            if pend:
                self._install(nb, pend)
                pend.clear()

    def admit_packed(self, hashes: List[bytes], rows: np.ndarray,
                     lengths: Optional[List[int]] = None,
                     exact: bool = False) -> None:
        """Bulk admit of one size class, N a multiple of 1024 — the
        vectorized ingest the snapshot-verify bench and bulk loaders
        use (per-row staging would dominate at millions of nodes).

        ``exact`` True: ``rows`` are RAW uniform-length encodings
        (length a multiple of 4) stored unpadded in an exact-length
        class — the kernel pads in registers. Otherwise ``rows`` are
        already multi-rate padded for their rate-block class."""
        n, width = rows.shape
        if n % TILE:
            raise ValueError("admit_packed wants whole 1024-row tiles")
        if exact:
            cm = self._class(width // RATE + 1, exact_len=width)
        else:
            if width % RATE:
                raise ValueError("padded rows must span whole blocks")
            cm = self._class(width // RATE)
        for start in range(0, n, TILE):
            chunk = hashes[start : start + TILE]
            cm.admit_tile(
                chunk,
                rows[start : start + TILE],
                (lengths[start : start + TILE] if lengths
                 else [width] * TILE),
            )

    def _install(self, nb: int, batch: List[Tuple[bytes, bytes]]) -> None:
        cm = self._class(nb)
        padded = np.broadcast_to(
            cm._filler_row_u8(), (TILE, cm.width)
        ).copy()
        hashes: List[bytes] = []
        lengths: List[int] = []
        for r, (h, enc) in enumerate(batch):
            padded[r, :] = 0
            padded[r, : len(enc)] = np.frombuffer(enc, dtype=np.uint8)
            padded[r, len(enc)] ^= 0x01
            padded[r, cm.width - 1] ^= 0x80
            hashes.append(h)
            lengths.append(len(enc))
        cm.admit_tile(hashes, padded, lengths)

    # ----------------------------------------------- device-side admit

    def admit_device(self, nblocks: int, keys: List[Optional[bytes]],
                     enc_dev, claim_dev, lengths: List[int],
                     alias: bool = True) -> None:
        """Admit rows whose padded encodings (u8[N, nblocks*RATE]) and
        claimed digests (u8[N, 32]) already live ON DEVICE, N a
        multiple of 1024. This is the window-commit ingest: gathers
        from a FusedJob's outputs feed straight in, zero node bytes
        over the tunnel. ``alias`` keys land in the placeholder
        namespace until :meth:`rekey` publishes them."""
        n = enc_dev.shape[0]
        if n % TILE:
            raise ValueError("admit_device wants whole 1024-row tiles")
        cm = self._class(nblocks)
        for start in range(0, n, TILE):
            cm.admit_tile_device(
                keys[start : start + TILE],
                enc_dev[start : start + TILE],
                claim_dev[start : start + TILE],
                lengths[start : start + TILE],
                alias=alias,
            )

    def rekey(self, mapping: Mapping[bytes, bytes]) -> int:
        """Promote alias-admitted rows to their real content addresses
        (persist stage, once the placeholder->digest mapping is on
        host). Returns rows promoted across all classes."""
        moved = 0
        for cm in list(self._classes.values()):
            if cm.alias_rows:
                moved += cm.rekey(mapping)
        return moved

    def drop_aliases(self, aliases) -> None:
        """Forget un-published alias rows (torn/abandoned window)."""
        for cm in list(self._classes.values()):
            if cm.alias_rows:
                cm.drop_aliases(aliases)

    def spill_rows(self, keys) -> Dict[bytes, bytes]:
        """Bulk-tile read-back of resident rows for the persist spill:
        one array-slice fetch per covered mirror tile per class (site
        ``mirror.spill``). Missing keys (evicted, never admitted) are
        absent from the result — the caller's host path covers them."""
        out: Dict[bytes, bytes] = {}
        remaining = list(keys)
        with _span("mirror.spill", rows=len(remaining)):
            for cm in list(self._classes.values()):
                if not remaining:
                    break
                got = cm.spill_rows(remaining)
                if got:
                    out.update(got)
                    remaining = [k for k in remaining if k not in out]
        return out

    @property
    def unspilled_count(self) -> int:
        return sum(
            len(cm.unspilled) for cm in list(self._classes.values())
        )

    # ------------------------------------------------------------ reads

    def contains(self, h: bytes) -> bool:
        for cm in list(self._classes.values()):
            if h in cm.rows:
                return True
        return any(h == ph for pend in self._pending.values()
                   for ph, _ in pend)

    def get(self, h: bytes) -> Optional[bytes]:
        """Read a node back from the device mirror (unpads via the
        stored exact length). Serves not-yet-spilled window nodes to
        the host read path (NodeStorage falls through here), so it
        must be safe against concurrent admits — each class fetch
        runs under that class's lock."""
        for cm in list(self._classes.values()):
            enc = cm.fetch_row(h)
            if enc is not None:
                return enc
        for pend in self._pending.values():
            for ph, enc in pend:
                if ph == h:
                    return enc
        return None

    # ------------------------------------------------------------ stats

    @property
    def resident_count(self) -> int:
        return sum(cm.count for cm in list(self._classes.values()))

    def verify(self) -> int:
        """Re-hash EVERY resident node on device and count content-
        address mismatches — one dispatch per size class, zero layout
        work (the tiles already live in kernel layout)."""
        return sum(cm.verify() for cm in list(self._classes.values()))


