"""Reorg buffer: ring of unconfirmed per-block write batches.

Parity: khipu-base/.../util/SimpleMapWithUnconfirmed.scala:3 +
KeyValueCircularArrayQueue (CircularArrayQueue.scala:207). Updates
enqueue whole per-block batches; only when the ring is full does the
OLDEST batch flush to the underlying source, so disk state trails the
chain tip by <= depth blocks (SURVEY §5.3: block-resolving-depth = 20).
A reorg within the window is handled by clear_unconfirmed() — buffered
batches are dropped without ever touching the source.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Iterable, List, Mapping, Optional, Tuple

Batch = Tuple[frozenset, Dict[bytes, bytes]]  # (removes, upserts)


class SimpleMapWithUnconfirmed:
    """Buffered view over a KeyValue/Node data source."""

    def __init__(self, source, depth: int = 20):
        self.source = source
        self.depth = depth
        self._queue: Deque[Batch] = deque()
        self._lock = threading.RLock()
        self._buffered = True

    # -- mode switches (Storages.swithToWithUnconfirmed / clearUnconfirmed)

    @property
    def buffering(self) -> bool:
        return self._buffered

    def set_buffering(self, on: bool) -> None:
        with self._lock:
            if not on:
                self.flush()
            self._buffered = on

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            for removes, upserts in reversed(self._queue):
                if key in upserts:
                    return upserts[key]
                if key in removes:
                    return None
        return self.source.get(key)

    def put(self, key: bytes, value: bytes) -> None:
        self.update([], {key: value})

    def update(
        self, to_remove: Iterable[bytes], to_upsert: Mapping[bytes, bytes]
    ) -> None:
        """One call == one block's batch (update:24-40)."""
        batch: Batch = (
            frozenset(bytes(k) for k in to_remove),
            {bytes(k): bytes(v) for k, v in to_upsert.items()},
        )
        with self._lock:
            if not self._buffered:
                self.source.update(*batch)
                return
            self._queue.append(batch)
            while len(self._queue) > self.depth:
                self.source.update(*self._queue.popleft())

    def flush(self) -> None:
        with self._lock:
            while self._queue:
                self.source.update(*self._queue.popleft())

    def clear_unconfirmed(self) -> List[bytes]:
        """Drop all buffered batches; returns the keys they touched so
        callers can invalidate read caches selectively."""
        with self._lock:
            dropped: List[bytes] = []
            for removes, upserts in self._queue:
                dropped.extend(removes)
                dropped.extend(upserts.keys())
            self._queue.clear()
            return dropped

    @property
    def pending_batches(self) -> int:
        return len(self._queue)
