"""Storage façade: assembles all typed storages from an engine config.

Parity: khipu-eth/.../storage/Storages.scala:6-81 (DefaultStorages:
account/storage/evmcode NodeStorages, header/body/receipts/td block
storages, blocknum, tx, appState; bestBlockNumber = min(bestBody,
bestReceipts) :40; swithToWithUnconfirmed:46 / clearUnconfirmed:63 fan
out to all) and ServiceBoard.scala:99-138 engine selection by
``db.engine`` — engines: ``memory`` | ``native`` (C++ append-log) |
``sqlite`` (embedded-KV alternative, LMDB/RocksDB role) | ``kesque``
(the paper's log-structured segment engine, storage/kesque.py —
KesqueDataSource.scala role, with segment streaming and compaction).
"""

from __future__ import annotations

from typing import Optional

from khipu_tpu.storage.app_state import AppStateStorage
from khipu_tpu.storage.block_storage import (
    BlockBytesStorage,
    BlockNumberStorage,
    BlockNumbers,
    TotalDifficultyStorage,
    TransactionStorage,
)
from khipu_tpu.storage.datasource import (
    MemoryBlockDataSource,
    MemoryKeyValueDataSource,
    MemoryNodeDataSource,
)
from khipu_tpu.storage.node_storage import NodeStorage


class Storages:
    def __init__(self, engine: str = "memory", data_dir: Optional[str] = None,
                 unconfirmed_depth: int = 20, cache_size: int = 1 << 20):
        self.engine = engine
        # set for engine == "kesque" only: the log-structured engine's
        # compaction/segment-streaming surface (storage/kesque.py)
        self.kesque_engine = None
        if engine == "memory":
            node_src = lambda topic: MemoryNodeDataSource()
            block_src = lambda topic: MemoryBlockDataSource()
            kv_src = lambda topic: MemoryKeyValueDataSource()
        elif engine == "native":
            if data_dir is None:
                raise ValueError("native engine requires data_dir")
            from khipu_tpu.native.store import (
                NativeBlockDataSource,
                NativeKeyValueDataSource,
                NativeNodeDataSource,
            )

            node_src = lambda topic: NativeNodeDataSource(data_dir, topic)
            block_src = lambda topic: NativeBlockDataSource(data_dir, topic)
            kv_src = lambda topic: NativeKeyValueDataSource(data_dir, topic)
        elif engine == "sqlite":
            if data_dir is None:
                raise ValueError("sqlite engine requires data_dir")
            from khipu_tpu.storage.sqlite_engine import (
                SqliteBlockDataSource,
                SqliteKeyValueDataSource,
                SqliteNodeDataSource,
            )

            node_src = lambda topic: SqliteNodeDataSource(data_dir, topic)
            block_src = lambda topic: SqliteBlockDataSource(data_dir, topic)
            kv_src = lambda topic: SqliteKeyValueDataSource(data_dir, topic)
        elif engine == "kesque":
            if data_dir is None:
                raise ValueError("kesque engine requires data_dir")
            from khipu_tpu.storage.kesque import KesqueEngine

            self.kesque_engine = KesqueEngine(data_dir)
            node_src = self.kesque_engine.node_source
            block_src = self.kesque_engine.block_source
            kv_src = self.kesque_engine.kv_source
        else:
            raise ValueError(f"unknown db.engine {engine!r}")

        # topic names match DbConfig.scala:11-21
        self.account_node_storage = NodeStorage(
            node_src("account"), unconfirmed_depth, cache_size)
        self.storage_node_storage = NodeStorage(
            node_src("storage"), unconfirmed_depth, cache_size)
        self.evmcode_storage = NodeStorage(
            node_src("evmcode"), unconfirmed_depth, cache_size)

        self.block_header_storage = BlockBytesStorage(block_src("header"))
        self.block_body_storage = BlockBytesStorage(block_src("body"))
        self.receipts_storage = BlockBytesStorage(block_src("receipts"))
        self.total_difficulty_storage = TotalDifficultyStorage(
            block_src("td"))
        self.block_number_storage = BlockNumberStorage(kv_src("blocknum"))
        self.block_numbers = BlockNumbers(
            self.block_number_storage, self.block_header_storage)
        self.transaction_storage = TransactionStorage(kv_src("tx"))
        self.app_state = AppStateStorage(kv_src("appstate"))
        # write-ahead window-commit journal records (sync/journal.py —
        # docs/recovery.md); same engine/durability as the block stores
        self.journal_source = kv_src("journal")
        self._window_journal = None

        self._node_storages = (
            self.account_node_storage,
            self.storage_node_storage,
            self.evmcode_storage,
        )

    @property
    def window_journal(self):
        """The crash-consistency WAL (lazy: sync/journal.py imports
        stay out of the storage layer's import graph)."""
        if self._window_journal is None:
            from khipu_tpu.sync.journal import WindowJournal

            self._window_journal = WindowJournal(self.journal_source)
        return self._window_journal

    @property
    def best_block_number(self) -> int:
        """min(bestBody, bestReceipts) — Storages.scala:40."""
        return min(
            self.block_body_storage.best_block_number,
            self.receipts_storage.best_block_number,
        )

    def attach_mirror(self, mirror) -> None:
        """Route trie-node read misses through the device mirror
        (device-resident window commit: nodes are readable from HBM
        before the async spill lands them in the host store). evmcode
        is excluded — code bytes never enter the fused hash path."""
        self.account_node_storage.mirror = mirror
        self.storage_node_storage.mirror = mirror

    def detach_mirror(self) -> None:
        """Drop the device read-through (recovery: the mirror is
        volatile, so crash verification must see host-durable state
        only — exactly what a real restart would see)."""
        self.account_node_storage.mirror = None
        self.storage_node_storage.mirror = None

    def switch_to_unconfirmed(self) -> None:
        for s in self._node_storages:
            s.switch_to_unconfirmed()

    def clear_unconfirmed(self) -> None:
        for s in self._node_storages:
            s.clear_unconfirmed()

    def get_node_any(self, h: bytes):
        """One node/code lookup across the three content-addressed
        stores — THE serving-side resolution, shared by the devp2p
        GetNodeData handler (network/host_service.py) and the gRPC
        bridge's served node cache (bridge.py) so the two endpoints
        cannot drift."""
        for store in (
            self.account_node_storage,
            self.storage_node_storage,
            self.evmcode_storage,
        ):
            v = store.get(h)
            if v is not None:
                return v
        return None

    def node_keys(self):
        """Sorted distinct keys across the three content-addressed
        node stores — the ``StreamNodeData`` iteration surface (live
        rebalance, cluster/rebalance.py). Serves durably-landed nodes
        only (the unconfirmed ring is by definition not yet part of
        the committed state a rebalance moves). Engines whose sources
        cannot enumerate raise, so a rebalance fails loudly instead of
        silently moving nothing."""
        out = set()
        for s in self._node_storages:
            keys = getattr(s.source, "keys", None)
            if keys is None:
                raise RuntimeError(
                    f"{type(s.source).__name__} cannot enumerate node "
                    "keys — live rebalance needs an enumerable node "
                    "store (memory or sqlite engine)"
                )
            out.update(bytes(k) for k in keys())
        return sorted(out)

    def storage_repair_report(self):
        """Open-time storage-layer repairs (the Kesque crash
        contract's torn-tail scan-back + index rebuilds), as report
        lines for journal recovery to surface. Empty for engines
        whose open path performs no repair."""
        if self.kesque_engine is None:
            return []
        return self.kesque_engine.repair_lines()

    def _all_sources(self):
        for s in self._node_storages:
            yield s.source
        yield self.block_header_storage.source
        yield self.block_body_storage.source
        yield self.receipts_storage.source
        yield self.total_difficulty_storage.source
        yield self.block_number_storage.source
        yield self.transaction_storage.source
        yield self.app_state.source
        yield self.journal_source

    def flush(self) -> None:
        for s in self._node_storages:
            s.flush()
        for src in self._all_sources():
            fl = getattr(src, "flush", None)
            if fl:
                fl()

    def stop(self) -> None:
        self.flush()
        for src in self._all_sources():
            stop = getattr(src, "stop", None)
            if stop:
                stop()
