"""Storage façade: assembles all typed storages from an engine config.

Parity: khipu-eth/.../storage/Storages.scala:6-81 (DefaultStorages:
account/storage/evmcode NodeStorages, header/body/receipts/td block
storages, blocknum, tx, appState; bestBlockNumber = min(bestBody,
bestReceipts) :40; swithToWithUnconfirmed:46 / clearUnconfirmed:63 fan
out to all) and ServiceBoard.scala:99-138 engine selection by
``db.engine`` — engines here: ``memory`` | ``native`` (C++ append-log).
"""

from __future__ import annotations

from typing import Optional

from khipu_tpu.storage.app_state import AppStateStorage
from khipu_tpu.storage.block_storage import (
    BlockBytesStorage,
    BlockNumberStorage,
    BlockNumbers,
    TotalDifficultyStorage,
    TransactionStorage,
)
from khipu_tpu.storage.datasource import (
    MemoryBlockDataSource,
    MemoryKeyValueDataSource,
    MemoryNodeDataSource,
)
from khipu_tpu.storage.node_storage import NodeStorage


class Storages:
    def __init__(self, engine: str = "memory", data_dir: Optional[str] = None,
                 unconfirmed_depth: int = 20, cache_size: int = 1 << 20):
        self.engine = engine
        if engine == "memory":
            account_src = MemoryNodeDataSource()
            storage_src = MemoryNodeDataSource()
            evmcode_src = MemoryNodeDataSource()
        elif engine == "native":
            if data_dir is None:
                raise ValueError("native engine requires data_dir")
            try:
                from khipu_tpu.native.store import NativeNodeDataSource
            except ImportError as e:
                raise NotImplementedError(
                    "db.engine='native' requires the C++ append-log store "
                    "(khipu_tpu/native/store.py) and a working g++"
                ) from e
            account_src = NativeNodeDataSource(data_dir, "account")
            storage_src = NativeNodeDataSource(data_dir, "storage")
            evmcode_src = NativeNodeDataSource(data_dir, "evmcode")
        else:
            raise ValueError(f"unknown db.engine {engine!r}")

        self.account_node_storage = NodeStorage(
            account_src, unconfirmed_depth, cache_size)
        self.storage_node_storage = NodeStorage(
            storage_src, unconfirmed_depth, cache_size)
        self.evmcode_storage = NodeStorage(
            evmcode_src, unconfirmed_depth, cache_size)

        self.block_header_storage = BlockBytesStorage(MemoryBlockDataSource())
        self.block_body_storage = BlockBytesStorage(MemoryBlockDataSource())
        self.receipts_storage = BlockBytesStorage(MemoryBlockDataSource())
        self.total_difficulty_storage = TotalDifficultyStorage(
            MemoryBlockDataSource())
        self.block_number_storage = BlockNumberStorage(
            MemoryKeyValueDataSource())
        self.block_numbers = BlockNumbers(
            self.block_number_storage, self.block_header_storage)
        self.transaction_storage = TransactionStorage(
            MemoryKeyValueDataSource())
        self.app_state = AppStateStorage(MemoryKeyValueDataSource())

        self._node_storages = (
            self.account_node_storage,
            self.storage_node_storage,
            self.evmcode_storage,
        )

    @property
    def best_block_number(self) -> int:
        """min(bestBody, bestReceipts) — Storages.scala:40."""
        return min(
            self.block_body_storage.best_block_number,
            self.receipts_storage.best_block_number,
        )

    def switch_to_unconfirmed(self) -> None:
        for s in self._node_storages:
            s.switch_to_unconfirmed()

    def clear_unconfirmed(self) -> None:
        for s in self._node_storages:
            s.clear_unconfirmed()

    def flush(self) -> None:
        for s in self._node_storages:
            s.flush()

    def stop(self) -> None:
        self.flush()
        for s in self._node_storages:
            stop = getattr(s.source, "stop", None)
            if stop:
                stop()
