"""Kesque reborn: the log-structured append-only storage engine.

Parity: khipu-eth's Kesque (kafka.kesque) engine — the paper's SECOND
research claim: a Kafka-log store tuned for blockchain access
patterns, where writes are sequential appends of whole batches and
reads are one positional fetch through an in-memory
hash -> (segment, offset) index (KesqueDataSource.scala,
KesqueNodeDataSource.scala:61-63 — node topics store VALUES only and
recompute keys by keccak on rebuild, exactly reproduced here).

Layout: ``<data_dir>/kesque/<topic>/<topic>-<seq>.kseg`` segment files
of CRC-framed records (storage/segment.py) plus a ``<topic>.kidx``
sidecar index checkpoint. Record payloads:

* node topics (content-addressed): ``0x4E ("N") + value`` — the key IS
  keccak256(value), never stored.
* kv/block topics: ``0x50 ("P") + u32 klen + key + value`` for a put,
  ``0x44 ("D") + u32 klen + key`` for a tombstone.

Why this wins for the persist stage: ``NodeStorage.update([], nodes)``
lands here as ONE ``append_batch`` — the whole mirror-tile spill of a
window (``DeviceNodeMirror.spill_rows``) becomes one sequential write
instead of per-node random puts (ledger site ``kesque.append``,
store-write class).

Crash contract (docs/kesque.md): segment opens scan back over torn
tails (segment.py); the sidecar index is CRC-framed and validated
against the repaired segment sizes — stale-optimistic sidecars (they
cover bytes the scan-back truncated) force a full rebuild, valid ones
are extended by scanning only the post-checkpoint tail. The chaos
seams ``kesque.append`` / ``kesque.roll`` / ``kesque.index`` /
``kesque.compact`` let the 120-seed kill sweep tear every one of those
steps; journal recovery (sync/journal.py) then proves the chain
recovers bit-exact.

Compaction (KesqueCompactor.scala role): ``KesqueEngine.compact``
reuses storage/compactor.py's reachability walk (``verify_hashes``)
to rewrite the live records of a pivot state root into fresh
segments, then swaps them in and unlinks the frozen generation.
Lock discipline (KL004): each store has ONE ``_lock`` guarding index
+ segment-table mutations and framed reads; the engine's
``_compact_lock`` serializes compactions and is always acquired
BEFORE any store ``_lock`` (``KesqueEngine._compact_lock ->
KesqueStore._lock``); nothing acquires them in reverse, and the
walk/copy phase holds neither continuously, so reads serve
throughout. A crash anywhere in compaction is safe by construction:
staged segments hold only duplicate content-addressed records until
the index swap, and the swap's effects (index entries, then file
unlinks) only ever drop bytes that were garbage or duplicated.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.chaos import fault_point
from khipu_tpu.native.keccak import keccak256_batch
from khipu_tpu.observability.profiler import HOST, LEDGER
from khipu_tpu.storage.datasource import (
    BlockDataSource,
    KeyValueDataSource,
    NodeDataSource,
)
from khipu_tpu.storage.segment import (
    FRAME_HEADER,
    Segment,
    SegmentCorruptError,
    scan_frames,
)

TAG_NODE = 0x4E  # "N": content-addressed, key recomputed on rebuild
TAG_PUT = 0x50  # "P": keyed put
TAG_DEL = 0x44  # "D": tombstone

NODE_TOPICS = ("account", "storage", "evmcode")

_U32 = struct.Struct(">I")
_IDX_MAGIC = b"KIDX2"
_IDX_SEG = struct.Struct(">IQQ")  # seq, end, garbage
_IDX_ENT = struct.Struct(">HIQI")  # klen, seq, off, rec_bytes

DEFAULT_SEGMENT_BYTES = 64 << 20


def encode_node_record(value: bytes) -> bytes:
    return bytes([TAG_NODE]) + value


def encode_put_record(key: bytes, value: bytes) -> bytes:
    return bytes([TAG_PUT]) + _U32.pack(len(key)) + key + value


def encode_del_record(key: bytes) -> bytes:
    return bytes([TAG_DEL]) + _U32.pack(len(key)) + key


def decode_record(payload: bytes) -> Tuple[int, Optional[bytes], bytes]:
    """``(tag, key_or_None, value)`` — node records return key=None
    (the caller recomputes it by content address when rebuilding)."""
    tag = payload[0]
    if tag == TAG_NODE:
        return tag, None, payload[1:]
    klen = _U32.unpack_from(payload, 1)[0]
    key = payload[5 : 5 + klen]
    if tag == TAG_DEL:
        return tag, key, b""
    return tag, key, payload[5 + klen :]


class KesqueStore:
    """One topic's segment log + in-memory index. Thread-safe: every
    index/segment-table mutation and framed read runs under ``_lock``
    (one lock, no nesting — KL004)."""

    def __init__(self, data_dir: str, topic: str,
                 content_addressed: bool,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.topic = topic
        self.content_addressed = content_addressed
        self.segment_bytes = max(1 << 12, segment_bytes)
        self.dir = os.path.join(data_dir, "kesque", topic)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # key -> (seq, offset, frame_bytes)
        self._index: Dict[bytes, Tuple[int, int, int]] = {}
        self._segments: Dict[int, Segment] = {}
        self._garbage: Dict[int, int] = {}  # seq -> superseded bytes
        self._next_seq = 0
        # open-time repair + rebuild provenance (crash-contract report)
        self.torn_bytes = 0
        self.rebuilt_index = False
        # stats (registry families + read-amplification)
        self.appended_bytes = 0
        self.appended_records = 0
        self.reclaimed_bytes = 0
        self.disk_read_bytes = 0
        self.value_bytes_returned = 0
        self._open_all()

    # --------------------------------------------------------- open/load

    def _seg_path(self, seq: int) -> str:
        return os.path.join(self.dir, f"{self.topic}-{seq:08d}.kseg")

    def _open_all(self) -> None:
        seqs = []
        for name in os.listdir(self.dir):
            if name.endswith(".kseg") and name.startswith(self.topic + "-"):
                try:
                    seqs.append(int(name[len(self.topic) + 1 : -5]))
                except ValueError:
                    continue
        for seq in sorted(seqs):
            seg, torn = Segment.open(self._seg_path(seq), seq)
            self.torn_bytes += torn
            self._segments[seq] = seg
            self._garbage.setdefault(seq, 0)
        self._next_seq = max(self._segments, default=-1) + 1
        if not self._load_sidecar():
            self.rebuilt_index = True
            self._index.clear()
            self._garbage = {seq: 0 for seq in self._segments}
            for seq in sorted(self._segments):
                self._apply_segment(self._segments[seq], 0)
        if not self._segments:
            self._roll_locked()

    def _apply_segment(self, seg: Segment, from_off: int) -> None:
        """Fold a segment's records (from ``from_off``) into the index,
        in append order — the rebuild-on-open path."""
        if from_off >= seg.end:
            return
        data = os.pread(seg._fd, seg.end - from_off, from_off)
        frames, _ = scan_frames(data, base=from_off)
        decoded = []
        node_values = []
        for off, payload in frames:
            tag, key, value = decode_record(payload)
            if tag == TAG_NODE:
                node_values.append(value)
            decoded.append((off, len(payload), tag, key, value))
        # content addresses recomputed in one native batch (one FFI
        # crossing for the whole segment, not one per record)
        node_keys = iter(keccak256_batch(node_values))
        for off, plen, tag, key, value in decoded:
            rec_bytes = FRAME_HEADER + plen
            if tag == TAG_NODE:
                key = next(node_keys)  # KesqueNodeDataSource.scala:61
            if tag == TAG_DEL:
                old = self._index.pop(key, None)
                if old is not None:
                    self._garbage[old[0]] = (
                        self._garbage.get(old[0], 0) + old[2]
                    )
                self._garbage[seg.seq] = (
                    self._garbage.get(seg.seq, 0) + rec_bytes
                )
                continue
            old = self._index.get(key)
            if old is not None:
                self._garbage[old[0]] = (
                    self._garbage.get(old[0], 0) + old[2]
                )
            self._index[key] = (seg.seq, off, rec_bytes)

    # ------------------------------------------------------ sidecar index

    @property
    def _sidecar_path(self) -> str:
        return os.path.join(self.dir, f"{self.topic}.kidx")

    def checkpoint(self) -> None:
        """Write the sidecar index: a CRC-framed snapshot of the index
        plus per-segment watermarks, atomically renamed into place.
        The ``kesque.index`` chaos seam sits before the rename — a
        death there leaves the previous sidecar intact."""
        from khipu_tpu.storage.segment import frame as _frame

        with self._lock:
            parts = [_IDX_MAGIC, _U32.pack(len(self._segments))]
            for seq in sorted(self._segments):
                seg = self._segments[seq]
                parts.append(_IDX_SEG.pack(
                    seq, seg.end, self._garbage.get(seq, 0)
                ))
            parts.append(struct.pack(">Q", len(self._index)))
            for key, (seq, off, rec) in self._index.items():
                parts.append(_IDX_ENT.pack(len(key), seq, off, rec))
                parts.append(key)
            payload = b"".join(parts)
        tmp = self._sidecar_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame(payload))
            f.flush()
            os.fsync(f.fileno())
        fault_point("kesque.index")
        os.replace(tmp, self._sidecar_path)

    def _load_sidecar(self) -> bool:
        """Load the sidecar if it is valid against the REPAIRED
        segments on disk; scan only post-checkpoint tails. Returns
        False (caller full-rebuilds) when the sidecar is absent,
        corrupt, stale-optimistic (covers truncated bytes) or refers
        to segments compaction has since unlinked."""
        try:
            with open(self._sidecar_path, "rb") as f:
                raw = f.read()
        except OSError:
            return False
        frames, _ = scan_frames(raw)
        if len(frames) != 1:
            return False
        payload = frames[0][1]
        if not payload.startswith(_IDX_MAGIC):
            return False
        try:
            pos = len(_IDX_MAGIC)
            nsegs = _U32.unpack_from(payload, pos)[0]
            pos += 4
            watermarks: Dict[int, int] = {}
            garbage: Dict[int, int] = {}
            for _ in range(nsegs):
                seq, end, garb = _IDX_SEG.unpack_from(payload, pos)
                pos += _IDX_SEG.size
                watermarks[seq] = end
                garbage[seq] = garb
            for seq, end in watermarks.items():
                seg = self._segments.get(seq)
                if seg is None or seg.end < end:
                    return False  # truncated/unlinked past the sidecar
            nent = struct.unpack_from(">Q", payload, pos)[0]
            pos += 8
            index: Dict[bytes, Tuple[int, int, int]] = {}
            for _ in range(nent):
                klen, seq, off, rec = _IDX_ENT.unpack_from(payload, pos)
                pos += _IDX_ENT.size
                key = payload[pos : pos + klen]
                pos += klen
                if seq not in watermarks or off + rec > watermarks[seq]:
                    return False
                index[key] = (seq, off, rec)
        except struct.error:
            return False
        self._index = index
        self._garbage = {seq: garbage.get(seq, 0) for seq in self._segments}
        # fold records appended after the checkpoint: covered-segment
        # tails, then whole segments the sidecar never saw, ascending
        # seq == append order (appends only ever hit the active seq)
        for seq in sorted(self._segments):
            self._apply_segment(
                self._segments[seq], watermarks.get(seq, 0)
            )
        return True

    # ----------------------------------------------------------- append

    def _roll_locked(self) -> Segment:
        """Open a fresh active segment (caller holds ``_lock`` or is
        init). The ``kesque.roll`` seam models a death between closing
        one segment and the first append of the next."""
        fault_point("kesque.roll")
        seq = self._next_seq
        self._next_seq += 1
        seg = Segment(self._seg_path(seq), seq)
        self._segments[seq] = seg
        self._garbage.setdefault(seq, 0)
        return seg

    def _active_locked(self) -> Segment:
        seq = max(self._segments)
        seg = self._segments[seq]
        if seg.end >= self.segment_bytes:
            seg = self._roll_locked()
        return seg

    def append_batch(self, to_remove: Iterable[bytes],
                     to_upsert: Mapping[bytes, bytes]) -> int:
        """THE write path: the whole batch — a window's entire
        mirror-tile spill — lands as one sequential run of back-to-back
        frames (``Segment.append_many``: chunked pwrites of the joined
        buffer, not one syscall per node). Returns bytes appended."""
        t0 = time.perf_counter()
        # (is_delete, key, payload) in append order: tombstones first,
        # matching the (removes, upserts) SPI argument order
        entries: List[Tuple[bool, bytes, bytes]] = []
        for key in to_remove:
            key = bytes(key)
            entries.append((True, key, encode_del_record(key)))
        for key, value in to_upsert.items():
            key, value = bytes(key), bytes(value)
            if self.content_addressed:
                payload = encode_node_record(value)
            else:
                payload = encode_put_record(key, value)
            entries.append((False, key, payload))
        if not entries:
            return 0
        nbytes = 0
        with self._lock:
            i = 0
            while i < len(entries):
                seg = self._active_locked()
                room = self.segment_bytes - seg.end
                group: List[Tuple[bool, bytes, bytes]] = []
                size = 0
                while i < len(entries):
                    fb = FRAME_HEADER + len(entries[i][2])
                    if group and size + fb > room:
                        break  # next group after a roll
                    group.append(entries[i])
                    size += fb
                    i += 1
                locs = seg.append_many([p for _, _, p in group])
                for (is_del, key, _p), (off, rec) in zip(group, locs):
                    nbytes += rec
                    if is_del:
                        self._garbage[seg.seq] = (
                            self._garbage.get(seg.seq, 0) + rec
                        )
                        old = self._index.pop(key, None)
                    else:
                        old = self._index.get(key)
                        self._index[key] = (seg.seq, off, rec)
                    if old is not None:
                        self._garbage[old[0]] = (
                            self._garbage.get(old[0], 0) + old[2]
                        )
            self.appended_bytes += nbytes
            self.appended_records += len(entries)
        LEDGER.record("kesque.append", HOST, nbytes,
                      duration=time.perf_counter() - t0)
        return nbytes

    def append_raw(self, raw: bytes,
                   entries: List[Tuple[bytes, int, int]]) -> None:
        """Splice a VERIFIED run of already-framed records into the
        log verbatim — the segment-streamed ingest fast path. ``raw``
        must be whole valid frames (the caller has scanned, decoded
        and content-addressed every one); ``entries`` is
        ``[(key, rel_off, rec_bytes), ...]`` addressing them relative
        to the chunk start. Shipping is byte-identical, so the frames
        are reused as written instead of being re-encoded and
        re-CRC'd one record at a time."""
        if not raw:
            return
        t0 = time.perf_counter()
        with self._lock:
            seg = self._active_locked()
            if seg.end and seg.end + len(raw) > self.segment_bytes:
                seg = self._roll_locked()
            base = seg.append_raw(raw)
            for key, rel, rec in entries:
                old = self._index.get(key)
                if old is not None:
                    self._garbage[old[0]] = (
                        self._garbage.get(old[0], 0) + old[2]
                    )
                self._index[key] = (seg.seq, base + rel, rec)
            self.appended_bytes += len(raw)
            self.appended_records += len(entries)
        LEDGER.record("kesque.append", HOST, len(raw),
                      duration=time.perf_counter() - t0)

    # ------------------------------------------------------------- reads

    def get(self, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        with self._lock:
            loc = self._index.get(key)
            if loc is None:
                return None
            seq, off, rec = loc
            payload = self._segments[seq].read(off)
            self.disk_read_bytes += rec
        _tag, _k, value = decode_record(payload)
        self.value_bytes_returned += len(value)
        return value

    def keys(self) -> List[bytes]:
        with self._lock:
            return sorted(self._index)

    def max_key8(self) -> int:
        with self._lock:
            best = -1
            for k in self._index:
                if len(k) == 8:
                    n = int.from_bytes(k, "big")
                    if n > best:
                        best = n
            return best

    @property
    def count(self) -> int:
        return len(self._index)

    @property
    def read_amplification(self) -> float:
        """Disk bytes fetched per value byte served — the serving-load
        number ``bench --ingest`` reports (frame headers + record tags
        are the only overhead of a positional Kesque read)."""
        if self.value_bytes_returned == 0:
            return 0.0
        return self.disk_read_bytes / self.value_bytes_returned

    # --------------------------------------------------------- streaming

    def segments(self) -> List[Tuple[int, int]]:
        """``[(seq, committed_size), ...]`` ascending — the shipping
        manifest (bridge ``EngineInfo``)."""
        with self._lock:
            return [
                (seq, self._segments[seq].end)
                for seq in sorted(self._segments)
            ]

    def read_chunk(self, seq: int, offset: int,
                   max_bytes: int) -> Tuple[bytes, int, bool]:
        """Raw whole-frame chunk of one segment (segment-ship unit)."""
        with self._lock:
            seg = self._segments.get(seq)
            if seg is None:
                # compacted away mid-stream: the puller restarts from
                # the fresh manifest (idempotent, content-addressed)
                raise KeyError(f"{self.topic} segment {seq} is gone")
            return seg.read_chunk(offset, max_bytes)

    # -------------------------------------------------------- compaction

    def freeze_for_compaction(self) -> Tuple[Tuple[int, ...], int]:
        """Roll the active segment and return the frozen generation:
        ``(seqs, total_bytes)``. Every record appended after this call
        lands in segments OUTSIDE the frozen set, so the swap can
        never drop concurrent writes."""
        with self._lock:
            frozen = tuple(sorted(self._segments))
            total = sum(self._segments[s].end for s in frozen)
            self._roll_locked()
            return frozen, total

    def new_compaction_segment(self) -> Segment:
        """A fresh, index-invisible segment for the compaction sink
        (unique seq from the same counter, so it can be adopted
        wholesale at swap time)."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        return Segment(self._seg_path(seq), seq)

    def swap_compacted(self, frozen: Tuple[int, ...],
                       staged: List[Segment],
                       staged_index: Dict[bytes, Tuple[int, int, int]],
                       ) -> int:
        """Adopt the staged generation and unlink the frozen one.
        Returns reclaimed bytes. Index rules: a staged entry wins only
        over a frozen location (a concurrent append into the post-
        freeze active segment is newer and kept); any key still
        pointing into the frozen set afterwards was unreachable from
        the pivot — dropped with its bytes."""
        frozen_set = set(frozen)
        with self._lock:
            for seg in staged:
                self._segments[seg.seq] = seg
                self._garbage.setdefault(seg.seq, 0)
            for key, loc in staged_index.items():
                cur = self._index.get(key)
                if cur is None or cur[0] in frozen_set:
                    self._index[key] = loc
            dropped = [
                k for k, loc in self._index.items()
                if loc[0] in frozen_set
            ]
            for k in dropped:
                del self._index[k]
            reclaimed = 0
            for seq in frozen:
                seg = self._segments.pop(seq, None)
                if seg is not None:
                    reclaimed += seg.end
                    seg.unlink()
                self._garbage.pop(seq, None)
            reclaimed -= sum(s.end for s in staged)
            self.reclaimed_bytes += max(0, reclaimed)
            return max(0, reclaimed)

    # ------------------------------------------------------------- stats

    def segment_stats(self) -> List[dict]:
        """Per-segment live/garbage split — the compaction report and
        ``khipu_kesque_*`` family source."""
        with self._lock:
            out = []
            for seq in sorted(self._segments):
                size = self._segments[seq].end
                garbage = min(size, self._garbage.get(seq, 0))
                out.append({
                    "seq": seq,
                    "bytes": size,
                    "garbage_bytes": garbage,
                    "live_bytes": size - garbage,
                })
            return out

    # --------------------------------------------------------- lifecycle

    def flush(self) -> None:
        """fsync the active segment — the durability barrier the
        window journal's flush-after-intent contract relies on."""
        with self._lock:
            if self._segments:
                self._segments[max(self._segments)].flush()

    def stop(self) -> None:
        self.checkpoint()
        with self._lock:
            for seg in self._segments.values():
                seg.close()


# --------------------------------------------------------------------
# DataSource adapters (the SPI Storages assembles)


class KesqueKeyValueDataSource(KeyValueDataSource):
    def __init__(self, store: KesqueStore):
        super().__init__()
        self._store = store

    def get(self, key: bytes) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            return self._store.get(key)
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        self._store.append_batch(to_remove, to_upsert)

    @property
    def count(self) -> int:
        return self._store.count

    def keys(self) -> List[bytes]:
        return self._store.keys()

    def flush(self) -> None:
        self._store.flush()

    def stop(self) -> None:
        self._store.stop()


class KesqueNodeDataSource(KesqueKeyValueDataSource, NodeDataSource):
    """Content-addressed node store over the segment log. Removes are
    swallowed (archive semantics, NodeStorage.scala:16-19); keys are
    never stored — rebuild recomputes them from values
    (KesqueNodeDataSource.scala:61-63)."""

    def update(self, to_remove, to_upsert) -> None:
        self._store.append_batch([], to_upsert)


class KesqueBlockDataSource(BlockDataSource):
    def __init__(self, store: KesqueStore):
        super().__init__()
        self._store = store
        self._best = store.max_key8()
        self._lock = threading.Lock()

    @staticmethod
    def _key(number: int) -> bytes:
        return int(number).to_bytes(8, "big")

    def get(self, number: int) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            return self._store.get(self._key(number))
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        with self._lock:
            self._store.append_batch(
                [self._key(n) for n in to_remove],
                {self._key(n): v for n, v in to_upsert.items()},
            )
            for n in to_upsert:
                if int(n) > self._best:
                    self._best = int(n)
            if to_remove:
                self._best = self._store.max_key8()

    @property
    def best_block_number(self) -> int:
        return self._best

    @property
    def count(self) -> int:
        return self._store.count

    def flush(self) -> None:
        self._store.flush()

    def stop(self) -> None:
        self._store.stop()


# --------------------------------------------------------------------
# Engine


class _CompactionSink:
    """The NodeWriter role: collects the reachability walk's live
    records into staged (index-invisible) segments of the target
    store. No store lock is held while writing — the files are private
    until ``swap_compacted`` adopts them."""

    def __init__(self, store: KesqueStore):
        self.store = store
        self.segments: List[Segment] = []
        self.index: Dict[bytes, Tuple[int, int, int]] = {}
        self.copied_bytes = 0

    def _active(self) -> Segment:
        if (not self.segments
                or self.segments[-1].end >= self.store.segment_bytes):
            self.segments.append(self.store.new_compaction_segment())
        return self.segments[-1]

    def update(self, to_remove, to_upsert) -> None:
        for key, value in to_upsert.items():
            seg = self._active()
            if self.store.content_addressed:
                payload = encode_node_record(value)
            else:
                payload = encode_put_record(bytes(key), value)
            off, rec = seg.append(payload)
            self.index[bytes(key)] = (seg.seq, off, rec)
            self.copied_bytes += rec


class KesqueEngine:
    """All of one node's Kesque topic stores + the compaction driver +
    the segment-shipping surface (fast-sync ingest, rebalance)."""

    name = "kesque"

    def __init__(self, data_dir: str,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES):
        self.data_dir = data_dir
        self.segment_bytes = segment_bytes
        self._stores: Dict[str, KesqueStore] = {}
        self._stores_lock = threading.Lock()
        self._compact_lock = threading.Lock()
        self.compactions = 0
        self.last_report: Optional[object] = None
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector("kesque", self._registry_samples)
        except Exception:
            pass

    # ----------------------------------------------------------- stores

    def store(self, topic: str) -> KesqueStore:
        with self._stores_lock:
            st = self._stores.get(topic)
            if st is None:
                st = KesqueStore(
                    self.data_dir, topic,
                    content_addressed=topic in NODE_TOPICS,
                    segment_bytes=self.segment_bytes,
                )
                self._stores[topic] = st
            return st

    def node_source(self, topic: str) -> KesqueNodeDataSource:
        return KesqueNodeDataSource(self.store(topic))

    def kv_source(self, topic: str) -> KesqueKeyValueDataSource:
        return KesqueKeyValueDataSource(self.store(topic))

    def block_source(self, topic: str) -> KesqueBlockDataSource:
        return KesqueBlockDataSource(self.store(topic))

    # ---------------------------------------------------- crash contract

    def repair_lines(self) -> List[str]:
        """Open-time torn-tail repairs + index rebuilds, one line per
        store — surfaced into the journal RecoveryReport so the crash
        contract's scan-back is visible in ``recover:`` output."""
        out = []
        for topic in sorted(self._stores):
            st = self._stores[topic]
            if st.torn_bytes:
                out.append(
                    f"kesque[{topic}]: torn segment tail truncated "
                    f"({st.torn_bytes} bytes scanned back)"
                )
            if st.rebuilt_index:
                out.append(
                    f"kesque[{topic}]: index rebuilt from segment scan "
                    f"({st.count} records)"
                )
        return out

    # -------------------------------------------------------- compaction

    def compact(self, state_root: bytes, batch: int = 1000) -> object:
        """Background-safe mark-and-sweep: rewrite the records
        reachable from ``state_root`` (hash-verified — a corrupt
        record is counted, never copied) into fresh segments of the
        three node topics, swap them in, unlink the frozen generation.
        Reads serve throughout: the walk holds no lock across reads
        and the swap is one short critical section per store."""
        from khipu_tpu.storage.compactor import compact as _compact

        with self._compact_lock:
            fault_point("kesque.compact")
            t0 = time.perf_counter()
            stores = {t: self.store(t) for t in NODE_TOPICS}
            frozen = {
                t: stores[t].freeze_for_compaction() for t in NODE_TOPICS
            }
            sinks = {t: _CompactionSink(stores[t]) for t in NODE_TOPICS}
            report = _compact(
                KesqueNodeDataSource(stores["account"]),
                KesqueNodeDataSource(stores["storage"]),
                KesqueNodeDataSource(stores["evmcode"]),
                state_root,
                sinks["account"], sinks["storage"], sinks["evmcode"],
                batch=batch, verify_hashes=True,
            )
            reclaimed = 0
            for t in NODE_TOPICS:
                reclaimed += stores[t].swap_compacted(
                    frozen[t][0], sinks[t].segments, sinks[t].index
                )
                stores[t].checkpoint()
            report.reclaimed_bytes = reclaimed
            report.segment_stats = self.segment_stats()
            self.compactions += 1
            self.last_report = report
            copied = sum(s.copied_bytes for s in sinks.values())
            LEDGER.record("kesque.compact", HOST, copied,
                          duration=time.perf_counter() - t0)
            return report

    # --------------------------------------------------------- streaming

    def list_segments(self, topics: Optional[Iterable[str]] = None
                      ) -> List[Tuple[str, int, int]]:
        """The shipping manifest: ``[(topic, seq, size), ...]`` over
        the node topics (the unit of bulk movement)."""
        out: List[Tuple[str, int, int]] = []
        for topic in (topics or NODE_TOPICS):
            for seq, size in self.store(topic).segments():
                out.append((topic, seq, size))
        return out

    def read_chunk(self, topic: str, seq: int, offset: int,
                   max_bytes: int) -> Tuple[bytes, int, bool]:
        return self.store(topic).read_chunk(seq, offset, max_bytes)

    def ingest_chunk(self, topic: str, raw: bytes) -> Tuple[int, int]:
        """Parse a shipped chunk and bulk-append its VERIFIED records:
        node records are admitted under their recomputed content
        address (a corrupt frame cannot forge a key — hashing IS the
        verification), anything else in a node topic is rejected.
        Returns ``(records, corrupt)``."""
        frames, end = scan_frames(raw)
        values: List[bytes] = []
        metas: List[Tuple[int, int]] = []
        corrupt = 0
        for off, payload in frames:
            if not payload:
                corrupt += 1
                continue
            tag, _key, value = decode_record(payload)
            if tag != TAG_NODE or not value:
                corrupt += 1  # only content-addressed records ship
                continue
            values.append(value)
            metas.append((off, FRAME_HEADER + len(payload)))
        # one native batch hash per chunk — the admission check IS the
        # content addressing, so this is the ingest hot loop
        keys = keccak256_batch(values)
        store = self.store(topic)
        if values and not corrupt and end == len(raw):
            # every frame verified as a node record: splice the chunk
            # into the log verbatim (no re-framing, no re-CRC)
            store.append_raw(raw, [
                (k, off, rec) for k, (off, rec) in zip(keys, metas)
            ])
        elif values:
            # mixed or short-scanned chunk: re-encode just the
            # verified records through the framing write path
            store.append_batch([], dict(zip(keys, values)))
        return len(values), corrupt

    # ------------------------------------------------------------- stats

    def segment_stats(self) -> Dict[str, List[dict]]:
        return {
            topic: self._stores[topic].segment_stats()
            for topic in sorted(self._stores)
        }

    def read_amplification(self) -> float:
        disk = sum(s.disk_read_bytes for s in self._stores.values())
        served = sum(
            s.value_bytes_returned for s in self._stores.values()
        )
        return disk / served if served else 0.0

    def _registry_samples(self) -> list:
        samples = []
        n_segs = 0
        live = garbage = appended = reclaimed = torn = entries = 0
        for st in list(self._stores.values()):
            for row in st.segment_stats():
                n_segs += 1
                live += row["live_bytes"]
                garbage += row["garbage_bytes"]
            appended += st.appended_bytes
            reclaimed += st.reclaimed_bytes
            torn += st.torn_bytes
            entries += st.count
        samples.extend([
            ("khipu_kesque_segments", "gauge", {}, n_segs),
            ("khipu_kesque_live_bytes", "gauge", {}, live),
            ("khipu_kesque_garbage_bytes", "gauge", {}, garbage),
            ("khipu_kesque_index_entries", "gauge", {}, entries),
            ("khipu_kesque_appended_bytes_total", "counter", {},
             appended),
            ("khipu_kesque_reclaimed_bytes_total", "counter", {},
             reclaimed),
            ("khipu_kesque_torn_bytes_total", "counter", {}, torn),
            ("khipu_kesque_compactions_total", "counter", {},
             self.compactions),
            ("khipu_kesque_read_amplification", "gauge", {},
             round(self.read_amplification(), 4)),
        ])
        return samples

    # --------------------------------------------------------- lifecycle

    def checkpoint(self) -> None:
        for st in list(self._stores.values()):
            st.checkpoint()

    def stop(self) -> None:
        for st in list(self._stores.values()):
            st.stop()
