"""FIFO cache with hit-rate counters and a nanosecond clock.

Parity: khipu-base/.../util/FIFOCache.scala:25 (hit/miss counters feed
DataSource.cacheHitRate) and util/Clock.scala:3 (per-source accumulated
read time, surfaced in the per-block perf line, Ledger.scala:447-448).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Generic, Optional, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class Clock:
    """Accumulates elapsed nanoseconds across timed sections."""

    __slots__ = ("_ns",)

    def __init__(self) -> None:
        self._ns = 0

    def start(self) -> int:
        return time.perf_counter_ns()

    def elapse(self, t0: int) -> None:
        self._ns += time.perf_counter_ns() - t0

    @property
    def elapsed_ns(self) -> int:
        return self._ns

    def reset(self) -> int:
        ns, self._ns = self._ns, 0
        return ns


class FIFOCache(Generic[K, V]):
    """Bounded FIFO cache; eviction order is insertion order."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map: "OrderedDict[K, V]" = OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    def get(self, key: K) -> Optional[V]:
        with self._lock:
            v = self._map.get(key)
            if v is None:
                self._misses += 1
            else:
                self._hits += 1
            return v

    def put(self, key: K, value: V) -> None:
        with self._lock:
            if key in self._map:
                self._map[key] = value
                return
            if len(self._map) >= self.capacity:
                self._map.popitem(last=False)
            self._map[key] = value

    def remove(self, key: K) -> None:
        with self._lock:
            self._map.pop(key, None)

    def __len__(self) -> int:
        return len(self._map)

    @property
    def hit_rate(self) -> float:
        n = self._hits + self._misses
        return self._hits / n if n else 0.0

    @property
    def read_count(self) -> int:
        return self._hits + self._misses

    def reset_counters(self) -> None:
        with self._lock:
            self._hits = 0
            self._misses = 0
