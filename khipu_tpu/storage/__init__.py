"""Storage subsystem — SPI traits, engines, façade.

Parity targets: khipu-storage (DataSource SPI, SURVEY §2.2),
khipu-eth/storage façade (§2.6), khipu-kesque role (§2.3; the native
C++ append-log engine lives in khipu_tpu/native).
"""

from khipu_tpu.storage.datasource import (
    BlockDataSource,
    DataSource,
    KeyValueDataSource,
    MemoryBlockDataSource,
    MemoryKeyValueDataSource,
    MemoryNodeDataSource,
    NodeDataSource,
)
from khipu_tpu.storage.cache import Clock, FIFOCache
from khipu_tpu.storage.unconfirmed import SimpleMapWithUnconfirmed
from khipu_tpu.storage.node_storage import NodeStorage, ReadOnlyNodeStorage
from khipu_tpu.storage.app_state import AppStateStorage
from khipu_tpu.storage.storages import Storages

__all__ = [
    "AppStateStorage",
    "BlockDataSource",
    "Clock",
    "DataSource",
    "FIFOCache",
    "KeyValueDataSource",
    "MemoryBlockDataSource",
    "MemoryKeyValueDataSource",
    "MemoryNodeDataSource",
    "NodeDataSource",
    "NodeStorage",
    "ReadOnlyNodeStorage",
    "SimpleMapWithUnconfirmed",
    "Storages",
]
