"""khipu_tpu — a TPU-native Ethereum execution/storage framework.

A ground-up rebuild of the capabilities of the reference client
(mahak/khipu, Scala/Akka): optimistic parallel transaction execution with
application-level race detection, a content-addressed trie-node storage
engine, and full-chain regular/fast sync — redesigned TPU-first:

* All Keccak-256 hashing of trie nodes runs as batched lane-parallel
  work on TPU (jax/XLA with a Pallas kernel on the hot path).
* Merkle-Patricia-Trie commits are level-synchronous bulk operations
  (one device batch per trie level) instead of node-at-a-time recursion.
* Multi-chip scale-out uses `jax.sharding.Mesh` + `shard_map` with XLA
  collectives over ICI, replacing the reference's Akka-cluster sharding.
* The EVM, ledger merge algebra, networking and storage SPI live host-side,
  mirroring the reference's layer map (SURVEY.md §1) with the same
  behavioral contracts (bit-exact state roots).
"""

__version__ = "0.1.0"
