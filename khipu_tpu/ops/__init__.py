"""Device compute kernels (the TPU hot path).

This package owns the work the reference does in its JVM hot loops
(khipu-base/.../crypto/hash/KeccakCore.scala sponge; the per-node
``kec256(rlp(node))`` in trie/Node.scala:111-112) — redesigned as
batched, lane-parallel array programs:

* keccak: Keccak-f[1600] over a whole batch of messages at once,
  64-bit lanes emulated as uint32 (hi, lo) pairs because the TPU VPU
  has no 64-bit integer ALU. jnp implementation (runs on any backend,
  XLA-fused) + a Pallas TPU kernel keeping the sponge state in VMEM.
"""

from khipu_tpu.ops.keccak import keccak256_batch  # noqa: F401
