"""Batched Keccak-256 dispatcher: Pallas TPU kernel with jnp fallback.

The public hashing entry point for the framework (trie commit, fast-sync
snapshot verify, content addressing). Replaces the reference's scalar
JVM sponge (khipu-base/.../crypto/hash/KeccakCore.scala) with batched
device execution; parity enforced against the scalar oracle in tests.
"""

from __future__ import annotations

from typing import List, Sequence

import jax

from khipu_tpu.ops.keccak_jnp import keccak256_batch_jnp


def _tpu_available() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def keccak256_batch(messages: Sequence[bytes], impl: str = "auto") -> List[bytes]:
    """Hash a batch of byte strings to 32-byte Keccak-256 digests.

    impl: "auto" (pallas on TPU, jnp elsewhere), "jnp", or "pallas".
    """
    if impl == "auto":
        impl = "pallas" if _tpu_available() else "jnp"
    if impl == "pallas":
        from khipu_tpu.ops.keccak_pallas import keccak256_batch_pallas

        return keccak256_batch_pallas(messages)
    if impl == "jnp":
        return keccak256_batch_jnp(messages)
    raise ValueError(f"unknown keccak impl {impl!r}")
