"""Batched Keccak-256 in pure jax.numpy (runs on TPU, CPU, anywhere).

Design (SURVEY.md §7.2 step 2): performance comes purely from batch
width — the sponge is bitwise-serial per message, so we hash B messages
simultaneously, one message per vector lane. 64-bit lanes are emulated
as (hi, lo) uint32 pairs: the TPU VPU has no 64-bit integer unit, and
all Keccak ops (xor/and/not/rotl) decompose exactly onto u32 pairs.

State layout: 25 lanes x 2 u32 halves, kept as Python lists of 25
arrays each of shape ``batch_shape`` — XLA sees 50 independent
elementwise dataflows and fuses the whole permutation.

Scalar oracle: khipu_tpu.base.crypto.keccak (tests assert bit-equality).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from khipu_tpu.base.crypto.keccak import ROTATION, ROUND_CONSTANTS
from khipu_tpu.observability.profiler import D2H, H2D, LEDGER

RATE = 136  # keccak-256 rate in bytes
LANES_PER_BLOCK = RATE // 8  # 17 u64 lanes absorbed per block

# (rc_lo, rc_hi) u32 pairs, static Python ints so they fold into the graph.
_RC32 = tuple((rc & 0xFFFFFFFF, rc >> 32) for rc in ROUND_CONSTANTS)


def _rotl64(lo, hi, n: int):
    """Rotate-left a u64 expressed as (lo, hi) u32 halves by static n."""
    n &= 63
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n < 32:
        return (
            (lo << n) | (hi >> (32 - n)),
            (hi << n) | (lo >> (32 - n)),
        )
    m = n - 32
    return (
        (hi << m) | (lo >> (32 - m)),
        (lo << m) | (hi >> (32 - m)),
    )


def _round(lo: List, hi: List, rc_lo, rc_hi) -> Tuple[List, List]:
    """One Keccak-f[1600] round over 25 (lo, hi) u32 lane arrays."""
    # theta
    c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    for x in range(5):
        r_lo, r_hi = _rotl64(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
        d_lo = c_lo[(x - 1) % 5] ^ r_lo
        d_hi = c_hi[(x - 1) % 5] ^ r_hi
        for y in range(5):
            lo[x + 5 * y] = lo[x + 5 * y] ^ d_lo
            hi[x + 5 * y] = hi[x + 5 * y] ^ d_hi
    # rho + pi
    b_lo: List = [None] * 25
    b_hi: List = [None] * 25
    for x in range(5):
        for y in range(5):
            r_lo, r_hi = _rotl64(lo[x + 5 * y], hi[x + 5 * y], ROTATION[x][y])
            idx = y + 5 * ((2 * x + 3 * y) % 5)
            b_lo[idx], b_hi[idx] = r_lo, r_hi
    # chi
    for x in range(5):
        for y in range(5):
            i0, i1, i2 = x + 5 * y, (x + 1) % 5 + 5 * y, (x + 2) % 5 + 5 * y
            lo[i0] = b_lo[i0] ^ (~b_lo[i1] & b_lo[i2])
            hi[i0] = b_hi[i0] ^ (~b_hi[i1] & b_hi[i2])
    # iota
    lo[0] = lo[0] ^ rc_lo
    hi[0] = hi[0] ^ rc_hi
    return lo, hi


_RC_LO_NP = np.asarray([p[0] for p in _RC32], np.uint32)
_RC_HI_NP = np.asarray([p[1] for p in _RC32], np.uint32)


def f1600(lo: List, hi: List, unroll: bool = False) -> Tuple[List, List]:
    """Keccak-f[1600]: 24 rounds via lax.fori_loop (or fully unrolled).

    The loop form keeps the traced graph ~24x smaller (fast compiles);
    rotation amounts stay static inside the body, only the round
    constant is a traced lookup. Constants are created per trace — a
    cached global would leak tracers between jit scopes.
    """
    if unroll:
        for rc_lo, rc_hi in _RC32:
            lo, hi = _round(lo, hi, jnp.uint32(rc_lo), jnp.uint32(rc_hi))
        return lo, hi

    rc_lo_arr = jnp.asarray(_RC_LO_NP)
    rc_hi_arr = jnp.asarray(_RC_HI_NP)

    def body(i, carry):
        clo, chi = carry
        nlo, nhi = _round(list(clo), list(chi), rc_lo_arr[i], rc_hi_arr[i])
        return tuple(nlo), tuple(nhi)

    flo, fhi = jax.lax.fori_loop(0, 24, body, (tuple(lo), tuple(hi)))
    return list(flo), list(fhi)


@functools.partial(jax.jit, static_argnames=("nblocks",))
def absorb(blocks: jax.Array, nblocks: int) -> jax.Array:
    """Absorb ``nblocks`` rate-blocks per message and squeeze 256 bits.

    blocks: uint32[nblocks, 34, B] — per block, 17 lanes x (lo, hi)
            interleaved as [lo0, hi0, lo1, hi1, ...], batch minor.
    returns: uint32[8, B] — digest words [lo0, hi0, .., lo3, hi3].
    """
    # Derive the zero state from the input (x ^ x) rather than
    # jnp.zeros: under shard_map the capacity lanes (17-24, never
    # absorbed) must carry the same varying-over-mesh-axis type as the
    # data lanes or the fori_loop carry fails vma typechecking; XLA
    # folds x^x to 0 so this costs nothing.
    zero = blocks[0, 0] ^ blocks[0, 0]
    lo = [zero] * 25
    hi = [zero] * 25
    for b in range(nblocks):
        for i in range(LANES_PER_BLOCK):
            lo[i] = lo[i] ^ blocks[b, 2 * i]
            hi[i] = hi[i] ^ blocks[b, 2 * i + 1]
        lo, hi = f1600(lo, hi)
    out = []
    for i in range(4):
        out.append(lo[i])
        out.append(hi[i])
    return jnp.stack(out)


def hash_padded_u8(padded_u8, nblocks: int):
    """Traceable batch hash of already multi-rate-padded byte rows:
    u8[N, nblocks*RATE] -> u8[N, 32]. THE shared jnp formulation for
    every fixpoint/sharded consumer (trie/fused.py,
    parallel/fused_sharded.py) — one place owns the bitcast/absorb
    packing."""
    n = padded_u8.shape[0]
    nwords = nblocks * 2 * LANES_PER_BLOCK
    w = jax.lax.bitcast_convert_type(
        padded_u8.reshape(n, nwords, 4), jnp.uint32
    )
    blocks = w.reshape(n, nblocks, 2 * LANES_PER_BLOCK).transpose(1, 2, 0)
    d = absorb(blocks, nblocks)  # [8, N]
    return jax.lax.bitcast_convert_type(d.T, jnp.uint8).reshape(n, 32)


def pad_to_blocks(messages: Sequence[bytes], nblocks: int) -> np.ndarray:
    """Host-side multi-rate padding + u32-lane packing.

    All messages must need exactly ``nblocks`` rate blocks
    (i.e. nblocks = len(m)//RATE + 1). Returns uint32[nblocks, 34, B].
    """
    batch = len(messages)
    buf32 = pad_to_words(messages, nblocks)
    # -> (nblocks, 34, B)
    return np.ascontiguousarray(
        buf32.reshape(batch, nblocks, 34).transpose(1, 2, 0)
    )


def pad_to_words(messages: Sequence[bytes], nblocks: int) -> np.ndarray:
    """Host-side multi-rate padding in the batch-major layout the
    device words path consumes directly: uint32[B, nblocks*34]. No
    host transpose — the word-major retile happens on device where it
    runs near HBM bandwidth."""
    batch = len(messages)
    buf = np.zeros((batch, nblocks * RATE), dtype=np.uint8)
    for j, m in enumerate(messages):
        if len(m) // RATE + 1 != nblocks:
            raise ValueError(
                f"message {j} needs {len(m)//RATE + 1} blocks, "
                f"class is {nblocks}"
            )
        buf[j, : len(m)] = np.frombuffer(m, dtype=np.uint8)
        buf[j, len(m)] ^= 0x01
        buf[j, nblocks * RATE - 1] ^= 0x80
    return buf.view("<u4")  # (B, nblocks*34)


def digests_to_bytes(words: np.ndarray) -> List[bytes]:
    """uint32[8, B] digest words -> list of 32-byte digests."""
    arr = np.asarray(words, dtype="<u4")  # (8, B)
    return [arr[:, j].tobytes() for j in range(arr.shape[1])]


def pad_batch_count(n: int, floor: int = 16) -> int:
    """Round a bucket's message count up to a power of two.

    Every distinct batch shape jit-specializes the absorb graph; trie
    commits produce arbitrary bucket sizes per block, so without this
    the compile count is unbounded (and each compile dwarfs hash time).
    """
    target = floor
    while target < n:
        target *= 2
    return target


def bucketed_batch(messages, target_count, run_bucket) -> List[bytes]:
    """Shared bucket/pad/scatter frame for every batched-hash backend.

    Buckets messages by rate-block class, pads each bucket with minimal-
    size filler messages up to ``target_count(nblocks, n)`` (bounding
    jit specializations), dispatches ``run_bucket(nblocks, msgs) ->
    digests`` (may return extra padding digests), and scatters results
    back into input order. Backends: jnp absorb (here), the Pallas tile
    kernel (ops.keccak_pallas), and the mesh-sharded absorb
    (parallel.keccak_sharded) — one frame, three dispatchers.
    """
    if not messages:
        return []
    buckets = {}
    for idx, m in enumerate(messages):
        buckets.setdefault(len(m) // RATE + 1, []).append(idx)
    out: List = [None] * len(messages)
    for nblocks, idxs in sorted(buckets.items()):
        msgs = [messages[i] for i in idxs]
        filler = b"\x00" * ((nblocks - 1) * RATE)
        msgs += [filler] * (target_count(nblocks, len(msgs)) - len(msgs))
        digests = run_bucket(nblocks, msgs)
        for i, digest in zip(idxs, digests):
            out[i] = digest
    return out


def keccak256_batch_jnp(messages: Sequence[bytes]) -> List[bytes]:
    """Hash a batch of variable-length messages, bucketing by block count."""

    def run_bucket(nblocks, msgs):
        blocks = pad_to_blocks(msgs, nblocks)
        with LEDGER.transfer("ops.keccak", H2D, blocks.nbytes):
            words = absorb(jnp.asarray(blocks), nblocks)
        with LEDGER.transfer("ops.keccak", D2H, int(words.size) * 4):
            got = jax.device_get(words)
        return digests_to_bytes(got)

    return bucketed_batch(
        messages, lambda nblocks, n: pad_batch_count(n), run_bucket
    )
