"""Pallas TPU kernel: batched Keccak-256 with the sponge state in VMEM.

One grid step hashes a tile of 8*128 = 1024 messages: each of the 50
u32 state half-lanes is an (8, 128) VPU-shaped tile, so every Keccak op
is a full-width elementwise VPU instruction and the 24-round permutation
never touches HBM. This is the TPU replacement for the reference's
scalar JVM sponge hot loop (khipu-base/.../crypto/hash/KeccakCore.scala
invoked per trie node at trie/Node.scala:111-112).

Kernel input layout: uint32[tiles, nwords, 8, 128] — word-major planes,
batch in the (sublane, lane) dims. Callers ship batch-major
uint32[N, nwords] (host-packed by keccak_jnp.pad_to_words, or generated
on device) and the retile to word-major runs on device near HBM
bandwidth; the multi-rate pad is fused into the kernel for fixed-size
classes. Output: uint32[tiles, 8, 8, 128] digest words.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from khipu_tpu.observability.profiler import D2H, H2D, LEDGER
from khipu_tpu.ops.keccak_jnp import (
    _RC32,
    _round,
    LANES_PER_BLOCK,
    RATE,
    pad_batch_count,
    pad_to_words,
)

TILE = 8 * 128  # messages per grid step


def _make_kernel(nblocks: int, nwords_in: int = None):
    """Sponge kernel over word-major planes.

    With ``nwords_in`` set, the input carries only the message words and
    the multi-rate padding is fused: pad words are per-size-class
    constants (0x01 right after the message, 0x80 in the last byte), so
    they xor into the state in registers instead of being materialized
    as an HBM concatenate (roofline attack plan item 2).
    """
    total_words = nblocks * 2 * LANES_PER_BLOCK
    if nwords_in is None:
        nwords_in = total_words
    pad_words = {}
    if nwords_in < total_words:
        pad_words[nwords_in] = 0x00000001
        last = total_words - 1
        pad_words[last] = pad_words.get(last, 0) | 0x80000000

    def kernel(blocks_ref, out_ref):
        zero = jnp.zeros((8, 128), jnp.uint32)
        lo: List = [zero] * 25
        hi: List = [zero] * 25
        for b in range(nblocks):
            base = b * 2 * LANES_PER_BLOCK
            for i in range(LANES_PER_BLOCK):
                for half, st in ((0, lo), (1, hi)):
                    w = base + 2 * i + half
                    if w < nwords_in:
                        st[i] = st[i] ^ blocks_ref[0, w]
                    if w in pad_words:
                        st[i] = st[i] ^ jnp.uint32(pad_words[w])
            for rc_lo, rc_hi in _RC32:
                lo, hi = _round(lo, hi, jnp.uint32(rc_lo), jnp.uint32(rc_hi))
        for k in range(4):
            out_ref[0, 2 * k] = lo[k]
            out_ref[0, 2 * k + 1] = hi[k]

    return kernel


def _build(nblocks: int, interpret: bool, nwords_in: int = None):
    """Compile the sponge for ``nblocks`` rate blocks. With
    ``nwords_in``, input planes carry only the message words and the
    pad is fused in-kernel. Normalizes the default BEFORE memoizing so
    `_build(n, i)` and `_build(n, i, nwords_in=full)` share one compile."""
    full = nblocks * 2 * LANES_PER_BLOCK
    if nwords_in is not None and nwords_in >= full:
        nwords_in = None
    return _build_cached(nblocks, interpret, nwords_in)


@functools.lru_cache(maxsize=32)
def _build_cached(nblocks: int, interpret: bool, nwords_in):
    nwords = (
        nwords_in
        if nwords_in is not None
        else nblocks * 2 * LANES_PER_BLOCK
    )

    @jax.jit
    def run(blocks):  # uint32[tiles, nwords, 8, 128]
        tiles = blocks.shape[0]
        return pl.pallas_call(
            _make_kernel(nblocks, nwords_in),
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((1, nwords, 8, 128), lambda i: (i, 0, 0, 0))
            ],
            out_specs=pl.BlockSpec((1, 8, 8, 128), lambda i: (i, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((tiles, 8, 8, 128), jnp.uint32),
            interpret=interpret,
        )(blocks)

    return run


@functools.lru_cache(maxsize=32)
def _build_from_bytes(nblocks: int, interpret: bool):
    """Fused device-side pack + hash for fixed-size padded messages.

    Takes uint8[N, nblocks*RATE] already multi-rate padded (host does the
    two xor bytes, vectorized); does the u8->u32 bitcast and the
    word-major retile on device, where they are cheap HBM shuffles, then
    runs the kernel. Avoids the multi-second host-side numpy transposes.
    """
    nwords = nblocks * 2 * LANES_PER_BLOCK
    run = _build(nblocks, interpret)

    @jax.jit
    def go(padded_u8):  # uint8[N, nblocks*RATE], N % TILE == 0
        n = padded_u8.shape[0]
        tiles = n // TILE
        w = jax.lax.bitcast_convert_type(
            padded_u8.reshape(n, nwords, 4), jnp.uint32
        )  # little-endian on TPU/x86 -> matches '<u4'
        tiled = w.reshape(tiles, 8, 128, nwords).transpose(0, 3, 1, 2)
        out = run(tiled)  # (tiles, 8, 8, 128)
        # back to digest-major: (N, 8) words -> bitcast to bytes
        d = out.transpose(0, 2, 3, 1).reshape(n, 8)
        return jax.lax.bitcast_convert_type(d, jnp.uint8).reshape(n, 32)

    return go


def _words_runner(nblocks: int, interpret: bool, nwords_in: int = None):
    """u32-native full path: batch-major words -> digest words.

    The byte-granular path (`_build_from_bytes`) costs ~4x the sponge
    itself in pure HBM relayout (u8 tiling is (32, 128); every
    reshape/bitcast across the u8/u32 boundary is a gather). Staying in
    u32 end to end, the only layout op left is the word-major tile
    transpose, which XLA runs near memory bandwidth. With ``nwords_in``
    the input carries message words only and the pad is fused
    in-kernel.
    """
    nwords = (
        nwords_in
        if nwords_in is not None
        else nblocks * 2 * LANES_PER_BLOCK
    )
    run = _build(nblocks, interpret, nwords_in=nwords_in)

    @jax.jit
    def go(words):  # uint32[N, nwords], N % TILE == 0
        n = words.shape[0]
        tiles = n // TILE
        tiled = words.reshape(tiles, 8, 128, nwords).transpose(0, 3, 1, 2)
        out = run(tiled)  # (tiles, 8, 8, 128)
        return out.transpose(0, 2, 3, 1).reshape(n, 8)  # digest words

    return go


@functools.lru_cache(maxsize=32)
def _build_from_words(nblocks: int, interpret: bool):
    """Already-padded batch-major words -> digest words."""
    return _words_runner(nblocks, interpret)


@functools.lru_cache(maxsize=32)
def _build_device_fixed_words(length: int, interpret: bool):
    """Device-resident full path for fixed-size messages given as u32
    words: retile + sponge with the multi-rate pad fused in-kernel (no
    HBM pad materialization at all). uint32[N, length//4] ->
    uint32[N, 8] digest words. Requires length % 4 == 0.
    """
    if length % 4:
        raise ValueError("u32 path requires length % 4 == 0")
    nblocks = length // RATE + 1
    return _words_runner(nblocks, interpret, nwords_in=length // 4)


@functools.lru_cache(maxsize=32)
def _build_device_fixed(length: int, interpret: bool):
    """Fully device-resident: pad + pack + hash uint8[N, length] on device.

    No host round-trip: use when the node bytes already live on device
    (or are generated there, as in the microbench). Returns uint8[N, 32].
    For length % 4 == 0 the words path (`_build_device_fixed_words`)
    avoids every u8-granular layout op; this wrapper only pays one
    bitcast at each edge.
    """
    nblocks = length // RATE + 1
    if length % 4 == 0:
        run_words = _build_device_fixed_words(length, interpret)

        @jax.jit
        def go(data_u8):  # uint8[N, length], N % TILE == 0
            n = data_u8.shape[0]
            words = jax.lax.bitcast_convert_type(
                data_u8.reshape(n, length // 4, 4), jnp.uint32
            )
            digest = run_words(words)
            return jax.lax.bitcast_convert_type(digest, jnp.uint8).reshape(
                n, 32
            )

        return go

    run_bytes = _build_from_bytes(nblocks, interpret)

    @jax.jit
    def go(data_u8):  # uint8[N, length], N % TILE == 0
        n = data_u8.shape[0]
        tail = np.zeros(nblocks * RATE - length, dtype=np.uint8)
        tail[0] ^= 0x01
        tail[-1] ^= 0x80
        pad = jnp.broadcast_to(jnp.asarray(tail), (n, tail.shape[0]))
        return run_bytes(jnp.concatenate([data_u8, pad], axis=1))

    return go


def keccak256_fixed(
    data: np.ndarray, interpret: bool = False
) -> np.ndarray:
    """Hash N equal-length messages: uint8[N, L] -> uint8[N, 32].

    The bulk-commit fast path (all dirty trie nodes of one size class in
    one device call). Pads on host (vectorized), ships batch-major u32
    words, retiles + hashes on device (no byte-granular device op).
    """
    n, length = data.shape
    nblocks = length // RATE + 1
    padded = np.zeros((n, nblocks * RATE), dtype=np.uint8)
    padded[:, :length] = data
    padded[:, length] ^= 0x01
    padded[:, nblocks * RATE - 1] ^= 0x80
    pad_rows = pad_batch_count(n, floor=TILE) - n
    if pad_rows:
        extra = np.zeros((pad_rows, nblocks * RATE), dtype=np.uint8)
        extra[:, length] ^= 0x01
        extra[:, nblocks * RATE - 1] ^= 0x80
        padded = np.concatenate([padded, extra], axis=0)
    with LEDGER.transfer("ops.keccak", H2D, padded.nbytes):
        out = _build_from_words(nblocks, interpret)(
            jnp.asarray(padded.view("<u4"))
        )
    with LEDGER.transfer("ops.keccak", D2H, int(out.size) * 4):
        got = jax.device_get(out)
    digest_words = np.asarray(got, dtype="<u4")[:n]
    return digest_words.view(np.uint8).reshape(n, 32)


def retile(blocks: np.ndarray) -> np.ndarray:
    """uint32[nblocks, 34, B] (B % 1024 == 0) -> [tiles, nblocks*34, 8, 128]."""
    nblocks, nwords_per_block, batch = blocks.shape
    tiles = batch // TILE
    # -> (B, nblocks*34)
    flat = blocks.reshape(nblocks * nwords_per_block, batch).T
    # -> (tiles, 8, 128, nwords) -> (tiles, nwords, 8, 128)
    return np.ascontiguousarray(
        flat.reshape(tiles, 8, 128, nblocks * nwords_per_block).transpose(0, 3, 1, 2)
    )


# Largest per-dispatch tile count: batches above this are CHUNKED into
# equal dispatches of exactly MAX_TILES tiles, so the set of compiled
# shapes per rate-block class is {1, 2, 4, 8, 16} tiles — a one-off
# compile budget instead of a new 10s+ XLA compile per batch size
# (bulk-build levels arrive in arbitrary sizes).
MAX_TILES = 16


def _pallas_target_count(nblocks: int, n: int) -> int:
    """Whole tiles, power-of-two tile count up to MAX_TILES, then whole
    multiples of MAX_TILES (bounds compiled shapes to {1,2,4,8,16})."""
    n_tiles_raw = (n + TILE - 1) // TILE
    if n_tiles_raw <= MAX_TILES:
        return pad_batch_count(n, floor=TILE)
    n_chunks = (n_tiles_raw + MAX_TILES - 1) // MAX_TILES
    return n_chunks * MAX_TILES * TILE


def keccak256_batch_pallas(
    messages: Sequence[bytes], interpret: bool = False
) -> List[bytes]:
    """Hash variable-length messages via the Pallas kernel.

    Buckets by rate-block count, zero-pads each bucket to a whole
    1024-message tile (padding digests discarded), chunks at MAX_TILES.
    """
    from khipu_tpu.ops.keccak_jnp import bucketed_batch

    def run_bucket(nblocks, msgs):
        packed = pad_to_words(msgs, nblocks)  # (B, nwords) batch-major
        run = _build_from_words(nblocks, interpret)
        rows_per_chunk = MAX_TILES * TILE
        chunks = []
        for start in range(0, packed.shape[0], rows_per_chunk):
            chunk = packed[start : start + rows_per_chunk]
            with LEDGER.transfer("ops.keccak", H2D, chunk.nbytes):
                words = run(jnp.asarray(chunk))
            with LEDGER.transfer("ops.keccak", D2H, int(words.size) * 4):
                chunks.append(np.asarray(jax.device_get(words), dtype="<u4"))
        arr = np.concatenate(chunks, axis=0)  # (B, 8) digest words
        return [arr[j].tobytes() for j in range(len(msgs))]

    return bucketed_batch(messages, _pallas_target_count, run_bucket)
