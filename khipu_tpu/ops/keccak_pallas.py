"""Pallas TPU kernel: batched Keccak-256 with the sponge state in VMEM.

One grid step hashes a tile of 8*128 = 1024 messages: each of the 50
u32 state half-lanes is an (8, 128) VPU-shaped tile, so every Keccak op
is a full-width elementwise VPU instruction and the 24-round permutation
never touches HBM. This is the TPU replacement for the reference's
scalar JVM sponge hot loop (khipu-base/.../crypto/hash/KeccakCore.scala
invoked per trie node at trie/Node.scala:111-112).

Input layout (host-packed by khipu_tpu.ops.keccak_jnp.pad_to_blocks and
retiled here): uint32[tiles, nblocks*34, 8, 128] — word-major, batch in
the (sublane, lane) dims. Output: uint32[tiles, 8, 8, 128] digest words.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from khipu_tpu.ops.keccak_jnp import (
    _RC32,
    _round,
    LANES_PER_BLOCK,
    RATE,
    pad_batch_count,
    pad_to_blocks,
)

TILE = 8 * 128  # messages per grid step


def _make_kernel(nblocks: int):
    def kernel(blocks_ref, out_ref):
        zero = jnp.zeros((8, 128), jnp.uint32)
        lo: List = [zero] * 25
        hi: List = [zero] * 25
        for b in range(nblocks):
            base = b * 2 * LANES_PER_BLOCK
            for i in range(LANES_PER_BLOCK):
                lo[i] = lo[i] ^ blocks_ref[0, base + 2 * i]
                hi[i] = hi[i] ^ blocks_ref[0, base + 2 * i + 1]
            for rc_lo, rc_hi in _RC32:
                lo, hi = _round(lo, hi, jnp.uint32(rc_lo), jnp.uint32(rc_hi))
        for k in range(4):
            out_ref[0, 2 * k] = lo[k]
            out_ref[0, 2 * k + 1] = hi[k]

    return kernel


@functools.lru_cache(maxsize=32)
def _build(nblocks: int, interpret: bool):
    nwords = nblocks * 2 * LANES_PER_BLOCK

    @jax.jit
    def run(blocks):  # uint32[tiles, nwords, 8, 128]
        tiles = blocks.shape[0]
        return pl.pallas_call(
            _make_kernel(nblocks),
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((1, nwords, 8, 128), lambda i: (i, 0, 0, 0))
            ],
            out_specs=pl.BlockSpec((1, 8, 8, 128), lambda i: (i, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((tiles, 8, 8, 128), jnp.uint32),
            interpret=interpret,
        )(blocks)

    return run


@functools.lru_cache(maxsize=32)
def _build_from_bytes(nblocks: int, interpret: bool):
    """Fused device-side pack + hash for fixed-size padded messages.

    Takes uint8[N, nblocks*RATE] already multi-rate padded (host does the
    two xor bytes, vectorized); does the u8->u32 bitcast and the
    word-major retile on device, where they are cheap HBM shuffles, then
    runs the kernel. Avoids the multi-second host-side numpy transposes.
    """
    nwords = nblocks * 2 * LANES_PER_BLOCK
    run = _build(nblocks, interpret)

    @jax.jit
    def go(padded_u8):  # uint8[N, nblocks*RATE], N % TILE == 0
        n = padded_u8.shape[0]
        tiles = n // TILE
        w = jax.lax.bitcast_convert_type(
            padded_u8.reshape(n, nwords, 4), jnp.uint32
        )  # little-endian on TPU/x86 -> matches '<u4'
        tiled = w.reshape(tiles, 8, 128, nwords).transpose(0, 3, 1, 2)
        out = run(tiled)  # (tiles, 8, 8, 128)
        # back to digest-major: (N, 8) words -> bitcast to bytes
        d = out.transpose(0, 2, 3, 1).reshape(n, 8)
        return jax.lax.bitcast_convert_type(d, jnp.uint8).reshape(n, 32)

    return go


@functools.lru_cache(maxsize=32)
def _build_device_fixed(length: int, interpret: bool):
    """Fully device-resident: pad + pack + hash uint8[N, length] on device.

    No host round-trip: use when the node bytes already live on device
    (or are generated there, as in the microbench). Returns uint8[N, 32].
    """
    nblocks = length // RATE + 1
    run_bytes = _build_from_bytes(nblocks, interpret)

    @jax.jit
    def go(data_u8):  # uint8[N, length], N % TILE == 0
        n = data_u8.shape[0]
        tail = np.zeros(nblocks * RATE - length, dtype=np.uint8)
        tail[0] ^= 0x01
        tail[-1] ^= 0x80
        pad = jnp.broadcast_to(jnp.asarray(tail), (n, tail.shape[0]))
        return run_bytes(jnp.concatenate([data_u8, pad], axis=1))

    return go


def keccak256_fixed(
    data: np.ndarray, interpret: bool = False
) -> np.ndarray:
    """Hash N equal-length messages: uint8[N, L] -> uint8[N, 32].

    The bulk-commit fast path (all dirty trie nodes of one size class in
    one device call). Pads on host (vectorized), packs and hashes on
    device.
    """
    n, length = data.shape
    nblocks = length // RATE + 1
    padded = np.zeros((n, nblocks * RATE), dtype=np.uint8)
    padded[:, :length] = data
    padded[:, length] ^= 0x01
    padded[:, nblocks * RATE - 1] ^= 0x80
    pad_rows = pad_batch_count(n, floor=TILE) - n
    if pad_rows:
        extra = np.zeros((pad_rows, nblocks * RATE), dtype=np.uint8)
        extra[:, length] ^= 0x01
        extra[:, nblocks * RATE - 1] ^= 0x80
        padded = np.concatenate([padded, extra], axis=0)
    out = _build_from_bytes(nblocks, interpret)(jnp.asarray(padded))
    return np.asarray(jax.device_get(out))[:n]


def retile(blocks: np.ndarray) -> np.ndarray:
    """uint32[nblocks, 34, B] (B % 1024 == 0) -> [tiles, nblocks*34, 8, 128]."""
    nblocks, nwords_per_block, batch = blocks.shape
    tiles = batch // TILE
    # -> (B, nblocks*34)
    flat = blocks.reshape(nblocks * nwords_per_block, batch).T
    # -> (tiles, 8, 128, nwords) -> (tiles, nwords, 8, 128)
    return np.ascontiguousarray(
        flat.reshape(tiles, 8, 128, nblocks * nwords_per_block).transpose(0, 3, 1, 2)
    )


# Largest per-dispatch tile count: batches above this are CHUNKED into
# equal dispatches of exactly MAX_TILES tiles, so the set of compiled
# shapes per rate-block class is {1, 2, 4, 8, 16} tiles — a one-off
# compile budget instead of a new 10s+ XLA compile per batch size
# (bulk-build levels arrive in arbitrary sizes).
MAX_TILES = 16


def _pallas_target_count(nblocks: int, n: int) -> int:
    """Whole tiles, power-of-two tile count up to MAX_TILES, then whole
    multiples of MAX_TILES (bounds compiled shapes to {1,2,4,8,16})."""
    n_tiles_raw = (n + TILE - 1) // TILE
    if n_tiles_raw <= MAX_TILES:
        return pad_batch_count(n, floor=TILE)
    n_chunks = (n_tiles_raw + MAX_TILES - 1) // MAX_TILES
    return n_chunks * MAX_TILES * TILE


def keccak256_batch_pallas(
    messages: Sequence[bytes], interpret: bool = False
) -> List[bytes]:
    """Hash variable-length messages via the Pallas kernel.

    Buckets by rate-block count, zero-pads each bucket to a whole
    1024-message tile (padding digests discarded), chunks at MAX_TILES.
    """
    from khipu_tpu.ops.keccak_jnp import bucketed_batch

    def run_bucket(nblocks, msgs):
        packed = pad_to_blocks(msgs, nblocks)
        tiled = retile(packed)
        run = _build(nblocks, interpret)
        chunks = []
        for start in range(0, tiled.shape[0], MAX_TILES):
            words = run(jnp.asarray(tiled[start : start + MAX_TILES]))
            chunks.append(np.asarray(jax.device_get(words), dtype="<u4"))
        arr = np.concatenate(chunks, axis=0)  # (tiles, 8, 8, 128)
        # invert retile: digest j is at [j//1024, :, (j%1024)//128, j%128]
        digests = []
        for pos in range(len(msgs)):
            t, r = divmod(pos, TILE)
            sub, lane = divmod(r, 128)
            digests.append(arr[t, :, sub, lane].tobytes())
        return digests

    return bucketed_batch(messages, _pallas_target_count, run_bucket)
