"""Pipelined sender recovery: prefetch thread + process-wide cache.

``recover_senders`` is a pure function of tx bytes (signing preimage +
v/r/s), so nothing forces it onto the block's critical path — yet the
driver paid it per block, and BENCH_r08 measured it at 0.444 of
foreground window time (native ECDSA recovery is ~230 us/signature;
it dwarfs everything else in the phase). Two independent fixes:

* **SenderPrefetcher** — a daemon thread that pulls blocks off the
  source iterator ahead of the driver, recovers their senders, and
  hands them over a bounded queue. On a multi-core host the recovery
  (a GIL-releasing native ctypes call) genuinely overlaps window N's
  execution; the driver's foreground ``senders`` phase collapses to a
  cache-hit sweep either way (the ``senders`` entry in
  ``phase_share_ceilings`` watches for it leaking back).
* **Process-wide sender cache** — an LRU keyed by
  ``(signing_preimage, v, r, s)``. The sender is a pure function of
  exactly that tuple, so the key is sound without computing the tx
  hash; re-imports, reorg replays, and the re-decode after a wire
  round-trip never pay recovery twice. (The per-OBJECT memo on
  SignedTransaction only survives as long as the decoded object —
  every re-decode used to start cold.)

``khipu_sender_prefetch_{hits,misses,...}`` gauges expose the cache's
behavior; flush_sender_cache() exists for tests and for benches that
want a deliberately cold first pass.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Iterable, Iterator, Optional

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    ecdsa_recover_batch,
    pubkey_to_address,
)
from khipu_tpu.base.rlp import rlp_encode
from khipu_tpu.evm.dataword import to_minimal_bytes

try:
    from khipu_tpu.observability.registry import REGISTRY

    PREFETCH_GAUGES = REGISTRY.gauge_group("khipu_sender_prefetch", {
        "hits": 0,  # senders served from the process-wide cache
        "misses": 0,  # senders that paid native ECDSA recovery
        "blocks": 0,  # blocks processed by recover_block_senders
        "evictions": 0,  # LRU entries dropped at capacity
    }, help="pipelined sender recovery (sync/prefetch.py)")
except Exception:  # pragma: no cover - stdlib-only fallback
    PREFETCH_GAUGES = {"hits": 0, "misses": 0, "blocks": 0, "evictions": 0}


# (signing_preimage, v, r, s) -> sender | None. The preimage rlp is
# needed for the signing hash anyway, so a hit costs one encode + one
# dict probe — no keccak, no curve math.
_CACHE: "OrderedDict[tuple, Optional[bytes]]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_ABSENT = object()


def flush_sender_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def sender_cache_len() -> int:
    with _CACHE_LOCK:
        return len(_CACHE)


def _signing_preimage(stx, chain_id: Optional[int]) -> bytes:
    fields = stx.tx._base_fields()
    if chain_id is not None:
        fields += [to_minimal_bytes(chain_id), b"", b""]
    return rlp_encode(fields)


def _batch_hash_wanted(flag: bool) -> bool:
    """Device-batched signing hashes only pay where the device wins:
    host keccak is native C, so CPU backends always hash scalar."""
    if not flag:
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def recover_block_senders(
    stxs, cache_entries: int = 65536, batch_hash: bool = False,
) -> None:
    """recover_senders with the process-wide cache in front: fill the
    per-object ``sender`` memo for every tx of a block, paying native
    recovery only for cache misses (one batched native call)."""
    todo = []
    hits = misses = 0
    for stx in stxs:
        if "sender" in stx.__dict__:
            continue
        recid, chain_id = stx._recid_chain_id()
        if recid is None:
            stx.__dict__["sender"] = None
            continue
        key = (_signing_preimage(stx, chain_id), stx.v, stx.r, stx.s)
        with _CACHE_LOCK:
            sender = _CACHE.get(key, _ABSENT)
            if sender is not _ABSENT:
                _CACHE.move_to_end(key)
        if sender is not _ABSENT:
            stx.__dict__["sender"] = sender
            hits += 1
        else:
            todo.append((stx, key, recid))
            misses += 1
    if todo:
        if _batch_hash_wanted(batch_hash):
            from khipu_tpu.ops.keccak import keccak256_batch

            hashes = keccak256_batch([key[0] for _, key, _ in todo])
        else:
            hashes = [keccak256(key[0]) for _, key, _ in todo]
        pubs = ecdsa_recover_batch([
            (h, recid, stx.r, stx.s)
            for h, (stx, _, recid) in zip(hashes, todo)
        ])
        evictions = 0
        with _CACHE_LOCK:
            for (stx, key, _), pub in zip(todo, pubs):
                sender = (
                    pubkey_to_address(pub) if pub is not None else None
                )
                stx.__dict__["sender"] = sender
                _CACHE[key] = sender
            while len(_CACHE) > cache_entries:
                _CACHE.popitem(last=False)
                evictions += 1
        if evictions:
            PREFETCH_GAUGES["evictions"] += evictions
    PREFETCH_GAUGES["hits"] += hits
    PREFETCH_GAUGES["misses"] += misses
    PREFETCH_GAUGES["blocks"] += 1


_DONE = object()


class _PrefetchError:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class SenderPrefetcher:
    """Wrap a block iterator: a daemon thread recovers each block's
    senders before the block reaches the consumer. Bounded queue
    (``depth`` blocks ahead); source exceptions propagate to the
    consumer at the position they occurred; ``close()`` detaches the
    thread on abnormal driver exit (it drains away on the sentinel)."""

    def __init__(
        self,
        blocks: Iterable,
        depth: int = 8,
        cache_entries: int = 65536,
        batch_hash: bool = False,
    ):
        self._source = iter(blocks)
        self._cache_entries = cache_entries
        self._batch_hash = batch_hash
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._closed = threading.Event()
        self.busy_seconds = 0.0
        self._thread = threading.Thread(
            target=self._run, name="khipu-sender-prefetch", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        try:
            for block in self._source:
                if self._closed.is_set():
                    return
                t0 = time.perf_counter()
                recover_block_senders(
                    block.body.transactions,
                    self._cache_entries,
                    self._batch_hash,
                )
                self.busy_seconds += time.perf_counter() - t0
                if not self._put(block):
                    return
            self._put(_DONE)
        # khipu-lint: ok KL002 not swallowed — the exception (including
        # InjectedDeath) crosses the queue as _PrefetchError and is
        # re-raised on the consumer thread at the exact iterator
        # position it occurred (__next__ raises item.exc), so
        # fail-stop semantics are preserved on the driver
        except BaseException as e:  # propagate through the queue
            self._put(_PrefetchError(e))

    def _put(self, item) -> bool:
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        item = self._q.get()
        if item is _DONE:
            raise StopIteration
        if isinstance(item, _PrefetchError):
            raise item.exc
        return item

    def close(self) -> None:
        """Stop the thread (abnormal exit: driver died mid-replay).
        Safe to call twice; the thread exits at its next queue/source
        step and is joined briefly (daemon — never blocks shutdown)."""
        self._closed.set()
        self._thread.join(timeout=2.0)
