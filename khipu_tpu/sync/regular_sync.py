"""Live regular sync: tip-following block import over real peers.

Parity: blockchain/sync/RegularSyncService.scala —
  bestPeer selection by total difficulty        :448-479
  requestHeaders / requestBodies batch fetch    :103-170
  branch resolution with backward header fetch,
  TD-compared reorg                             :171-269, 336-345
  missing-node retry inside the import loop     (kesque self-heal role)

The Akka actor round (one message per state transition) becomes an
explicit ``sync_once()`` step — callers loop it (``run(until)``), tests
drive it deterministically. Execution and persistence reuse the replay
driver's validated import path (ReplayDriver._execute_and_insert), so a
live-synced block passes exactly the gates a replayed one does.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.domain.block import Block, BlockBody
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.config import KhipuConfig
from khipu_tpu.network.messages import (
    BLOCK_BODIES,
    BLOCK_HEADERS,
    ETH_OFFSET,
    GET_BLOCK_BODIES,
    GET_BLOCK_HEADERS,
    GET_NODE_DATA,
    NEW_BLOCK,
    NEW_BLOCK_HASHES,
    NODE_DATA,
    TRANSACTIONS,
    GetBlockHeaders,
    decode_bodies,
    decode_headers,
    decode_new_block,
    decode_new_block_hashes,
    decode_transactions,
    encode_new_block,
    encode_new_block_hashes,
    encode_transactions,
)
from khipu_tpu.network.peer import Peer, PeerError, PeerManager
from khipu_tpu.observability.trace import span
from khipu_tpu.sync.replay import CollectorDied, ReplayDriver
from khipu_tpu.sync.reorg import ReorgManager, ReorgTooDeep
from khipu_tpu.trie.mpt import MPTNodeMissingException
from khipu_tpu.validators.roots import ommers_hash, transactions_root


class SyncAborted(Exception):
    pass


class RegularSyncService:
    """Pull loop: find the best-TD peer, fetch headers+bodies from our
    tip, import; resolve side branches by backward fetch + TD compare."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        manager: PeerManager,
        batch_size: int = 10,
        request_timeout: float = 5.0,
        log: Optional[Callable[[str], None]] = None,
        device_commit: bool = False,
        txpool=None,
        cluster=None,
        read_view=None,
    ):
        self.blockchain = blockchain
        self.config = config
        self.manager = manager
        self.batch_size = batch_size
        self.timeout = request_timeout
        self.log = log or (lambda s: None)
        self._driver = ReplayDriver(
            blockchain, config, device_commit=device_commit,
            read_view=read_view,
        )
        # journaled atomic chain switch (sync/reorg.py): TD-winning
        # side branches route through it instead of the old
        # unjournaled block-at-a-time rewind
        self.reorg = ReorgManager(
            blockchain, config, driver=self._driver, txpool=txpool,
            read_view=read_view, log=log,
        )
        # serializes chain mutation between the pull loop and the
        # NewBlock push handler (which runs on peer reader threads)
        self._import_lock = threading.Lock()
        self.imported = 0
        self.reorgs = 0
        self.healed_nodes = 0
        # pending-tx pool: every successful import removes the block's
        # txs (RegularSyncService.scala:419); gossiped txs land here
        self.txpool = txpool
        # NewBlockHashes announces, drained by sync_once (fetching from
        # inside the announcing peer's reader thread would deadlock on
        # its own reply)
        self._announced: List[tuple] = []
        self._announce_lock = threading.Lock()
        # sharded node-cache cluster (cluster/client.py): when set, the
        # heal path asks the replica shards BEFORE burning a peer
        # round-trip — the DistributedNodeStorage read the reference
        # does first (SURVEY §5.3)
        self.cluster = cluster
        self.cluster_healed = 0

    # ------------------------------------------------------------ fetches

    def _request_headers(
        self, peer: Peer, start, max_headers: int, reverse: bool = False
    ) -> List[BlockHeader]:
        # client-side span around the peer round-trip (no wire-format
        # change): fetch latency lands on the requesting thread's track
        with span("sync.fetch.headers", peer=peer.remote_pub[:8],
                  max_headers=max_headers):
            body = peer.request(
                ETH_OFFSET + GET_BLOCK_HEADERS,
                GetBlockHeaders(start, max_headers, 0, reverse).body(),
                ETH_OFFSET + BLOCK_HEADERS,
                timeout=self.timeout,
            )
        try:
            return decode_headers(body)
        except Exception as e:  # malformed reply IS the peer's fault
            raise PeerError(f"undecodable headers: {e}")

    def _request_bodies(
        self, peer: Peer, hashes: List[bytes]
    ) -> List[BlockBody]:
        with span("sync.fetch.bodies", peer=peer.remote_pub[:8],
                  count=len(hashes)):
            body = peer.request(
                ETH_OFFSET + GET_BLOCK_BODIES,
                list(hashes),
                ETH_OFFSET + BLOCK_BODIES,
                timeout=self.timeout,
            )
        try:
            return decode_bodies(body)
        except Exception as e:  # malformed reply IS the peer's fault
            raise PeerError(f"undecodable bodies: {e}")

    def _fetch_blocks(
        self, peer: Peer, headers: List[BlockHeader]
    ) -> List[Block]:
        """Bodies for ``headers``; every body is checked against its
        header's txRoot/ommersHash before assembly (a peer cannot hand
        us a mismatched body)."""
        blocks: List[Block] = []
        want = list(headers)
        while want:
            batch = want[: self.batch_size]
            bodies = self._request_bodies(peer, [h.hash for h in batch])
            if len(bodies) != len(batch):
                # BlockBodies carries no correlation and the serving
                # side silently SKIPS unknown hashes — a short reply
                # would shift every later header/body pair, so a count
                # mismatch ends the round (next round refetches fresh
                # headers; an honest mid-reorg peer recovers there)
                raise PeerError(
                    f"peer served {len(bodies)}/{len(batch)} bodies"
                )
            for header, body in zip(batch, bodies):
                if transactions_root(body.transactions) != header.transactions_root:
                    raise PeerError("body txRoot mismatch")
                if ommers_hash(body.ommers) != header.ommers_hash:
                    raise PeerError("body ommersHash mismatch")
                blocks.append(Block(header, body))
            want = want[len(bodies) :]
        return blocks

    # ------------------------------------------------------- branch logic

    def _resolve_branch(
        self, peer: Peer, headers: List[BlockHeader]
    ) -> Optional[List[BlockHeader]]:
        """Headers don't attach to our chain tip: walk the peer's chain
        backward (block_resolving_depth cap) until a header's parent is
        known to us, then decide the reorg by total difficulty
        (RegularSyncService.scala:171-269)."""
        chain = list(headers)
        depth_left = self.config.sync.block_resolving_depth
        while depth_left > 0:
            ancestor = self.blockchain.get_header_by_hash(
                chain[0].parent_hash
            )
            if ancestor is not None:
                return self._maybe_reorg(chain, ancestor)
            fetch = min(self.batch_size, depth_left)
            older = self._request_headers(
                peer, chain[0].parent_hash, fetch, reverse=True
            )
            if not older:
                return None
            # reverse fetch returns newest-first starting AT parent_hash
            older = list(reversed(older))
            if older[-1].hash != chain[0].parent_hash:
                return None  # peer served garbage
            chain = older + chain
            depth_left -= len(older)
        return None

    def _maybe_reorg(
        self, branch: List[BlockHeader], ancestor: BlockHeader
    ) -> Optional[List[BlockHeader]]:
        """Accept the branch iff its cumulative TD beats ours AND every
        branch header passes full validation against its parent
        (appendNewBlock TD rule, RegularSyncService.scala:336-345).
        Validating BEFORE any rollback means a peer cannot knock us off
        our tip with invented difficulty fields — the rollback itself
        happens only after the branch's bodies are also in hand
        (_sync_round)."""
        ancestor_td = self.blockchain.get_total_difficulty(ancestor.number)
        if ancestor_td is None:
            return None
        branch_td = ancestor_td + sum(h.difficulty for h in branch)
        our_best = self.blockchain.best_block_number
        our_td = self.blockchain.get_total_difficulty(our_best) or 0
        if branch_td <= our_td:
            self.log(
                f"side branch at #{ancestor.number} loses TD "
                f"({branch_td} <= {our_td}); keeping our chain"
            )
            return None
        parent = ancestor
        for h in branch:
            try:
                self._driver.header_validator.validate(h, parent)
            except Exception as e:
                raise PeerError(f"branch header #{h.number} invalid: {e}")
            parent = h
        return branch

    def _rollback_to(self, ancestor_number: int) -> None:
        """Remove our blocks above the common ancestor. Unjournaled
        primitive — live reorgs go through ReorgManager.switch; this
        stays for callers that rewind a chain they fully own. The walk
        must REACH the ancestor: a missing header mid-walk means best
        points above a hole, and silently moving best there (the old
        behavior) would canonize the gap."""
        n = self.blockchain.best_block_number
        while n > ancestor_number:
            header = self.blockchain.get_header_by_number(n)
            if header is None:
                raise SyncAborted(
                    f"rollback found no header at #{n} (walking "
                    f"{self.blockchain.best_block_number} -> "
                    f"{ancestor_number}): chain store has a hole"
                )
            self.blockchain.remove_block(header.hash)
            n -= 1
        self.blockchain.storages.app_state.best_block_number = ancestor_number
        self.reorgs += 1

    # ----------------------------------------------------------- healing

    def _heal_missing_node(self, peer: Peer, node_hash: bytes) -> None:
        """Fetch one trie node by hash and admit it (content-address
        verified) into the node stores — the read-through self-heal the
        kesque DistributedNodeStorage role performs (storage/remote.py),
        wired into the live import loop. The sharded cluster (replica
        failover + breakers, values pre-verified by the client) is
        consulted first; the announcing peer is the fallback when no
        shard holds the node."""
        with span("sync.heal", node=node_hash) as heal_sp:
            if self.cluster is not None:
                try:
                    got = self.cluster.fetch([node_hash])
                except Exception:
                    got = {}
                blob = got.get(node_hash)
                if blob is not None and keccak256(blob) == node_hash:
                    s = self.blockchain.storages
                    s.account_node_storage.put(node_hash, blob)
                    s.storage_node_storage.put(node_hash, blob)
                    self.healed_nodes += 1
                    self.cluster_healed += 1
                    heal_sp.set_tag("source", "cluster")
                    return
            body = peer.request(
                ETH_OFFSET + GET_NODE_DATA,
                [node_hash],
                ETH_OFFSET + NODE_DATA,
                timeout=self.timeout,
            )
            for blob in body:
                if keccak256(blob) == node_hash:
                    s = self.blockchain.storages
                    s.account_node_storage.put(node_hash, blob)
                    s.storage_node_storage.put(node_hash, blob)
                    self.healed_nodes += 1
                    heal_sp.set_tag("source", "peer")
                    return
            raise PeerError(
                f"peer could not heal node {node_hash.hex()[:16]}"
            )

    # -------------------------------------------------------------- steps

    def sync_once(self) -> int:
        """One pull round; returns the number of blocks imported."""
        peer = self.manager.best_peer()
        if peer is None or peer.status is None:
            return 0
        our_best = self.blockchain.best_block_number
        our_td = self.blockchain.get_total_difficulty(our_best) or 0
        # NOTE: no early TD gate — peer.status carries the HANDSHAKE-time
        # TD, stale the moment the peer advances. The reference keeps
        # asking its best peer on every resume tick and lets the header
        # response decide (RegularSyncService.ResumeRegularSyncTask);
        # TD only picks the peer and judges branches.
        try:
            # announce fetches share the round's PeerError handling: a
            # peer that times out answering its own announce gets
            # demoted, it must not kill the sync loop
            announced = self._drain_announces(peer)
            return announced + self._sync_round(peer, our_best, our_td)
        except PeerError as e:
            # wire/protocol failure (disconnect, timeout, mismatched
            # body, garbage headers): demote the peer; the loop carries
            # on with other peers
            self.log(f"peer failed mid-round: {e}")
            self.manager.blacklist.add(peer.remote_pub, duration=60.0)
            peer.disconnect()
            return 0
        except Exception as e:  # noqa: BLE001
            # a LOCAL failure (storage fault, import error that isn't
            # attributable to the wire) must not demote an honest peer
            # — but it must not kill the loop either (the reference's
            # actor restarts play this role). A branch that failed
            # AFTER rollback leaves us at the ancestor; later rounds
            # sync forward again from there.
            self.log(f"round failed locally: {e}")
            return 0

    def _sync_round(self, peer: Peer, our_best: int, our_td: int) -> int:
        headers = self._request_headers(peer, our_best + 1, self.batch_size)
        if not headers:
            if peer.status.total_difficulty <= our_td:
                return 0  # nothing new and no TD claim: at the tip
            # the peer claims higher TD but serves nothing past our tip:
            # its (heavier) chain is SHORTER than ours. Probe DOWNWARD
            # one height at a time — an empty reply only proves the peer
            # lacks the START height (the serving side bails on the
            # first missing header), so a coarser step would skip the
            # heights where its best/branch actually lives. Bounded by
            # the branch-resolving depth.
            headers = []
            probe = our_best
            floor = max(1, our_best - self.config.sync.block_resolving_depth)
            while probe >= floor and not headers:
                headers = self._request_headers(
                    peer, probe, self.batch_size, reverse=True
                )
                probe -= 1
            if not headers:
                return 0
            headers = list(reversed(headers))
            if headers[-1].hash == self.blockchain.get_hash_by_number(
                headers[-1].number
            ):
                return 0  # same chain after all — nothing to adopt

        tip = self.blockchain.get_hash_by_number(our_best)
        is_reorg = False
        if headers[0].parent_hash != tip:
            resolved = self._resolve_branch(peer, headers)
            if resolved is None:
                return 0
            headers = resolved
            is_reorg = True

        # bodies BEFORE any rollback: a reorg only touches our chain
        # once the replacement blocks are fully fetched and checked
        blocks = self._fetch_blocks(peer, headers)
        imported = 0
        with self._import_lock:  # excludes the NewBlock push handler
            # the tip may have MOVED while we fetched (a pushed block
            # imported by the handler): re-check under the lock
            cur_best = self.blockchain.best_block_number
            if is_reorg:
                ancestor_number = headers[0].number - 1
                anc = self.blockchain.get_header_by_number(ancestor_number)
                if anc is None or anc.hash != headers[0].parent_hash:
                    return 0  # chain changed under us; resolve next round
                # journaled atomic switch: fence -> intent -> rollback
                # -> adopt (windowed for long branches) -> finalize
                # (sync/reorg.py). Depth refusal escalates as PeerError:
                # a peer whose branch forks below the unconfirmed ring
                # gets demoted, we keep our chain.
                try:
                    done = self.reorg.switch(
                        ancestor_number, blocks,
                        # khipu-lint: ok KL004 one-shot cached probe, no lock taken inside
                        import_fn=lambda b: self._import_healing(peer, b),
                    )
                except ReorgTooDeep as e:
                    raise PeerError(str(e))
                self.reorgs += 1
                imported += done
                self.imported += done
                self.log(
                    f"reorg: switched at #{ancestor_number}, adopted "
                    f"{done} peer blocks"
                )
                blocks = []  # fully consumed by the switch
            else:
                # drop blocks a concurrent push already covered; if the
                # remainder no longer attaches, defer to the next round
                # (the TD rule decides between the competing tips)
                blocks = [
                    b for b in blocks if b.header.number > cur_best
                ]
                if blocks and blocks[0].header.parent_hash != (
                    self.blockchain.get_hash_by_number(
                        blocks[0].header.number - 1
                    )
                ):
                    return 0
            # bulk catch-up: a full fetched batch on the canonical
            # chain routes through the PIPELINED windowed replay
            # (seal/collect overlap, sync/replay.replay_windowed)
            # instead of block-at-a-time import; anything it didn't
            # take falls through to the healing per-block path below
            window = self.config.sync.commit_window_blocks
            if window > 1 and len(blocks) >= window:
                # the adaptive backend probe it can reach is one-shot,
                # process-cached (~ms), and must finish before any
                # window commits anyway — holding _import_lock across
                # it cannot deadlock (the probe takes no locks)
                # khipu-lint: ok KL004 one-shot cached probe, no lock taken inside
                done = self._import_windowed(blocks)
                if done:
                    if self.txpool is not None:
                        for b in blocks[:done]:
                            self.txpool.remove_mined(
                                b.body.transactions
                            )
                    imported += done
                    self.imported += done
                    blocks = blocks[done:]
            for block in blocks:
                # khipu-lint: ok KL004 one-shot cached probe, no lock taken inside
                self._import_healing(peer, block)
                if self.txpool is not None:
                    self.txpool.remove_mined(block.body.transactions)
                imported += 1
                self.imported += 1
        if imported:
            self.log(
                f"imported {imported} blocks, best now "
                f"#{self.blockchain.best_block_number}"
            )
        return imported

    def _import_healing(self, peer: Peer, block: Block) -> None:
        """Single-block validated import with the missing-node heal
        loop — the per-block live path, also handed to
        ReorgManager.switch for per-block branch adoption."""
        with span("import", block=block.header.number,
                  txs=len(block.body.transactions)):
            for attempt in range(3):
                try:
                    self._driver._execute_and_insert(block, _NullStats())
                    return
                except MPTNodeMissingException as e:
                    self._heal_missing_node(peer, e.hash)
            raise SyncAborted(
                f"block {block.header.number} kept failing after heals"
            )

    def _import_windowed(self, blocks: List[Block]) -> int:
        """Import a fetched batch through the windowed pipeline;
        returns how many LEADING blocks were persisted (windows commit
        front-to-back, so persisted blocks are always a prefix).

        Failure semantics: replay_windowed persists nothing of a window
        before its root checks pass, so on any fallback the per-block
        path can redo the remaining blocks safely. A WindowMismatch is
        BAD PEER DATA (a header whose state root the re-execution
        refutes) and escalates as PeerError — sync_once demotes the
        peer; a missing trie node (fast-sync leftover state) or a
        pre-Byzantium batch simply falls back to the healing loop."""
        from khipu_tpu.ledger.window import WindowMismatch

        before = self.blockchain.best_block_number
        try:
            self._driver.replay_windowed(
                iter(blocks), self.config.sync.commit_window_blocks
            )
        except WindowMismatch as e:
            raise PeerError(f"windowed import diverged: {e}")
        except MPTNodeMissingException as e:
            self.log(
                f"windowed import missing node {e.hash[:8].hex()}; "
                "healing per block"
            )
        except CollectorDied:
            # with graceful degradation OFF the operator asked for
            # fail-stop semantics: a dead collector means a torn window
            # may be on disk, and the per-block healing path must NOT
            # paper over it — surface the death so the round aborts and
            # startup recovery (sync/journal.py) settles the intent
            if not self.config.sync.degrade_on_collector_death:
                raise
            self.log("windowed import lost its collector; "
                     "healing per block")
        except Exception as e:  # noqa: BLE001
            self.log(f"windowed import fell back: {e}")
        return self.blockchain.best_block_number - before

    def run(self, until: Callable[[], bool], poll: float = 0.2,
            max_seconds: float = 60.0) -> None:
        """Loop sync_once until ``until()`` or timeout (test harness /
        node main-loop form)."""
        # monotonic: this deadline is pure elapsed-time bookkeeping —
        # wall-clock here would jump with NTP steps AND trip KL003
        deadline = time.monotonic() + max_seconds
        while not until():
            if time.monotonic() > deadline:
                raise SyncAborted("regular sync timed out")
            if self.sync_once() == 0:
                time.sleep(poll)

    # ------------------------------------------------------ propagation

    def install_new_block_handler(self) -> None:
        """Install the gossip consumers: peer-pushed NewBlock imports
        (handleNewBlockMsgs role), NewBlockHashes announces (queued —
        sync_once fetches them; fetching on the announcer's reader
        thread would deadlock on its own reply), and pending-tx gossip
        into the pool (SignedTransactions, CommonMessages.scala)."""
        installs = {
            ETH_OFFSET + NEW_BLOCK: self._on_new_block,
            # manager-level (future peers): announce without a source —
            # the drain falls back to the round's best peer
            ETH_OFFSET + NEW_BLOCK_HASHES: self._on_new_block_hashes,
            ETH_OFFSET + TRANSACTIONS: self._on_transactions,
        }
        self.manager.handlers.update(installs)
        for peer in self.manager.peers:
            peer.handlers.update(installs)
            # per-peer closure: record WHO announced, so the fetch goes
            # to the peer that actually has the block
            peer.handlers[ETH_OFFSET + NEW_BLOCK_HASHES] = (
                lambda body, p=peer: self._on_new_block_hashes(body, p)
            )

    def _on_transactions(self, body) -> None:
        if self.txpool is None:
            return None
        try:
            txs = decode_transactions(body)
        except Exception:
            return None
        from khipu_tpu.domain.transaction import recover_senders

        recover_senders(txs)
        for stx in txs:
            if stx.sender is not None:
                self.txpool.add(stx)
        return None

    def _on_new_block_hashes(self, body, source: Peer = None) -> None:
        try:
            pairs = decode_new_block_hashes(body)
        except Exception:
            return None
        with self._announce_lock:
            self._announced.extend(
                (h, n, source) for h, n in pairs
            )
            del self._announced[:-64]  # bounded backlog
        return None

    def _drain_announces(self, peer: Peer) -> int:
        """Fetch + import announced blocks we don't have yet (PV62
        NewBlockHashes consumer). Runs on the pull thread; fetches from
        the ANNOUNCING peer when known (it provably has the block —
        the best-TD peer may not have imported it yet), else from the
        round's peer."""
        with self._announce_lock:
            pairs, self._announced = self._announced, []
        before = self.imported
        for idx, (block_hash, number, source) in enumerate(pairs):
            if self.blockchain.get_header_by_hash(block_hash) is not None:
                continue
            if number != self.blockchain.best_block_number + 1:
                continue  # the pull round handles gaps/branches
            src = source if source is not None and source.alive else peer
            with span("announce", block=number,
                      from_announcer=source is not None):
                headers = self._request_headers(src, number, 1)
                if not headers or headers[0].hash != block_hash:
                    continue
                blocks = self._fetch_blocks(src, headers)
            if not self._import_lock.acquire(blocking=False):
                # a push import holds the lock: give the unprocessed
                # tail (this announce included) back to the backlog so
                # the next round retries it instead of dropping it —
                # prepended to keep announce order ahead of anything
                # that arrived meanwhile, same bounded-backlog cap
                with self._announce_lock:
                    self._announced[:0] = pairs[idx:]
                    del self._announced[:-64]
                break
            try:
                for block in blocks:
                    # khipu-lint: ok KL004 one-shot cached probe, no lock taken inside
                    self._on_new_block_locked(block)
            finally:
                self._import_lock.release()
        return self.imported - before

    def _on_new_block(self, body) -> None:
        # Runs on the pushing peer's reader thread: chain checks and the
        # import must hold the pull loop's lock — but NON-BLOCKING. The
        # pull loop heals missing nodes via peer.request WHILE holding
        # the lock; if this handler parked the reader thread waiting on
        # it, the heal reply could never be read (deadlock-by-timeout).
        # A dropped push is harmless: the pull loop catches up.
        try:
            block, _td = decode_new_block(body)
        except Exception:
            return None
        if not self._import_lock.acquire(blocking=False):
            return None
        try:
            # khipu-lint: ok KL004 one-shot cached probe, no lock taken inside
            self._on_new_block_locked(block)
        finally:
            self._import_lock.release()
        return None

    def _on_new_block_locked(self, block: Block) -> None:
        our_best = self.blockchain.best_block_number
        if block.header.number != our_best + 1:
            return  # ahead/behind: the pull loop catches up
        if block.header.parent_hash != (
            self.blockchain.get_hash_by_number(our_best)
        ):
            return  # side branch: the pull loop's TD rule decides
        try:
            with span("import", block=block.header.number, pushed=True):
                self._driver._execute_and_insert(block, _NullStats())
            self.imported += 1
            if self.txpool is not None:
                self.txpool.remove_mined(block.body.transactions)
            self.log(f"imported pushed block #{block.header.number}")
        except Exception as e:  # invalid push: pull loop decides
            self.log(f"pushed block rejected: {e}")


def broadcast_new_block(manager: PeerManager, block: Block, td: int) -> int:
    """Push a freshly sealed/imported block to every live peer
    (BroadcastNewBlocks role; miner + import tail call this). Returns
    the number of peers reached."""
    payload = encode_new_block(block, td)
    sent = 0
    for peer in list(manager.peers):
        if not peer.alive:
            continue
        try:
            peer.send(ETH_OFFSET + NEW_BLOCK, payload)
            sent += 1
        except Exception:
            pass
    return sent


def propagate_block(manager: PeerManager, block: Block, td: int) -> int:
    """Standard eth propagation split: the FULL block goes to
    ceil(sqrt(peers)) peers, the lightweight NewBlockHashes announce to
    the rest (they fetch on demand) — bandwidth-bounded flood, the
    shape the reference's BroadcastNewBlocks + NewBlockHashes pair
    implements. Returns peers reached."""
    import math

    peers = [p for p in list(manager.peers) if p.alive]
    if not peers:
        return 0
    n_full = max(1, math.isqrt(len(peers)))
    full_payload = encode_new_block(block, td)
    hash_payload = encode_new_block_hashes(
        [(block.hash, block.header.number)]
    )
    sent = 0
    for i, peer in enumerate(peers):
        try:
            if i < n_full:
                peer.send(ETH_OFFSET + NEW_BLOCK, full_payload)
            else:
                peer.send(ETH_OFFSET + NEW_BLOCK_HASHES, hash_payload)
            sent += 1
        except Exception:
            pass
    return sent


def broadcast_transactions(manager: PeerManager, stxs) -> int:
    """Gossip pending transactions to live peers (SignedTransactions,
    CommonMessages.scala; the reference's PendingTransactionsService
    pubsub role). A per-peer known-tx set (the reference's
    knownTransactions) suppresses re-sends: once T has been sent to P,
    later gossip ticks skip it, so a tx crosses each link a bounded
    number of times instead of re-flooding the mesh every hop."""
    stxs = list(stxs)
    if not stxs:
        return 0
    sent = 0
    for peer in list(manager.peers):
        if not peer.alive:
            continue
        # insertion-ordered dict: the trim really drops the OLDEST half
        known = peer.__dict__.setdefault("known_txs", {})
        fresh = [s for s in stxs if s.hash not in known]
        if not fresh:
            continue
        try:
            peer.send(ETH_OFFSET + TRANSACTIONS, encode_transactions(fresh))
            for s in fresh:
                known[s.hash] = None
            if len(known) > 16384:  # bounded memory per peer
                drop = len(known) - 8192
                for h in list(known)[:drop]:
                    del known[h]
            sent += 1
        except Exception:
            pass
    return sent


def gossip_pending(manager: PeerManager, pool, cursor: int) -> int:
    """Broadcast txs that arrived in the pool since ``cursor`` (the
    pool's arrival journal); returns the new cursor. The node main loop
    calls this each tick — local submissions (eth_sendRawTransaction /
    personal_sendTransaction) and peer-gossiped txs both propagate."""
    hashes, new_cursor = pool.arrivals_since(cursor)
    stxs = [pool.get(h) for h in hashes]
    broadcast_transactions(manager, [s for s in stxs if s is not None])
    return new_cursor


class _NullStats:
    """ReplayDriver stats sink for single-block imports."""

    blocks = txs = gas = parallel_txs = conflicts = 0
    fast_path_txs = residue_txs = mispredictions = 0

    def __setattr__(self, k, v):  # stats increments land here harmlessly
        object.__setattr__(self, k, v)
