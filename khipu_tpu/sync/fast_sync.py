"""Fast sync: typed node-hash queues, crash-resumable state download,
and batched content-address verification.

Parity: blockchain/sync/FastSyncService.scala:100 (SyncState :65-82
seeds the queue with StateMptNodeHash(target.stateRoot) :252; received
nodes are parsed and their children enqueued by type,
sync/package.scala:21-42; batched saves :898-918; periodic state
persist) and storage/FastSyncStateStorage.scala:24 (putSyncState :76 /
getSyncState :84 / purge :140 — crash-resume).

Networking is a callback: ``fetch(hashes) -> {hash: bytes}`` — a peer
pool in production, another Blockchain or store in tests. Every
received batch is content-address-verified through the batched device
hasher (ops.keccak — the same kernel config #5 benches), replacing the
per-node JVM kec256 at KesqueNodeDataSource.scala:61-63.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Set, Tuple

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.domain.account import (
    EMPTY_CODE_HASH,
    EMPTY_STORAGE_ROOT,
    Account,
)
from khipu_tpu.observability.trace import span

# Typed node hashes (sync/package.scala:21-42).
STATE_NODE = 0  # account-trie MPT node
STORAGE_NODE = 1  # contract-storage-trie MPT node
EVMCODE = 2  # code blob by code hash


@dataclass
class SyncState:
    """FastSyncService.SyncState (:65-82): resumable download state."""

    target_root: bytes
    pending: List[Tuple[int, bytes]] = field(default_factory=list)
    downloaded_nodes: int = 0

    def encode(self) -> bytes:
        return rlp_encode(
            [
                self.target_root,
                [[bytes([t]), h] for t, h in self.pending],
                self.downloaded_nodes.to_bytes(8, "big"),
            ]
        )

    @staticmethod
    def decode(data: bytes) -> "SyncState":
        root, pending, count = rlp_decode(data)
        return SyncState(
            target_root=root,
            pending=[(t[0], h) for t, h in pending],
            downloaded_nodes=int.from_bytes(count, "big"),
        )


class FastSyncStateStorage:
    """Persist/restore/purge the SyncState
    (FastSyncStateStorage.scala:24)."""

    KEY = b"fast-sync-state"

    def __init__(self, source):
        self.source = source

    def put_sync_state(self, state: SyncState) -> None:
        self.source.put(self.KEY, state.encode())

    def get_sync_state(self) -> Optional[SyncState]:
        raw = self.source.get(self.KEY)
        return SyncState.decode(raw) if raw is not None else None

    def purge(self) -> None:
        self.source.remove(self.KEY)


def _children_of(kind: int, encoded: bytes) -> List[Tuple[int, bytes]]:
    """Parse an MPT node and emit typed child work items
    (NodeDatasRequest.processResponse role)."""
    if kind == EVMCODE:
        return []
    node = rlp_decode(encoded)
    out: List[Tuple[int, bytes]] = []

    def ref_children(ref):
        if isinstance(ref, bytes) and len(ref) == 32:
            out.append((kind, ref))
        elif isinstance(ref, list):
            walk_node(ref)  # inline (<32B) child

    def walk_node(n):
        if len(n) == 17:  # branch
            for i in range(16):
                if n[i] != b"":
                    ref_children(n[i])
            if kind == STATE_NODE and n[16] != b"":
                leaf_value(n[16])
        elif len(n) == 2:
            from khipu_tpu.base.nibbles import hp_decode

            _, is_leaf = hp_decode(n[0])
            if is_leaf:
                if kind == STATE_NODE:
                    leaf_value(n[1])
            else:
                ref_children(n[1])
        return out

    def leaf_value(value: bytes):
        # account leaves reference a storage root + code hash
        acc = Account.decode(value)
        if acc.storage_root != EMPTY_STORAGE_ROOT:
            out.append((STORAGE_NODE, acc.storage_root))
        if acc.code_hash != EMPTY_CODE_HASH:
            out.append((EVMCODE, acc.code_hash))

    walk_node(node)
    return out


class StateSyncer:
    """Download a state trie to local storages via a fetch callback,
    with checkpoint/resume (SyncingHandler role, peers abstracted).

    Received batches are verified with the batched hasher before being
    saved; a corrupt node is rejected and stays pending.
    """

    def __init__(
        self,
        storages,
        state_storage: FastSyncStateStorage,
        fetch: Callable[[List[bytes]], Mapping[bytes, bytes]],
        batch_size: int = 100,  # nodes-per-request (application.conf)
        hasher=None,  # batch content-address check; None = host scalar
        checkpoint_every: int = 10,
        mirror=None,  # DeviceNodeMirror: admits verified state nodes
    ):
        self.storages = storages
        self.state_storage = state_storage
        self.fetch = fetch
        self.batch_size = batch_size
        self.hasher = hasher
        self.checkpoint_every = checkpoint_every
        # device mirror (storage/device_mirror.py): verified nodes are
        # admitted in the kernel's word-major layout at download time,
        # so the post-sync whole-snapshot re-verification (config #5)
        # runs on resident tiles with zero layout work
        self.mirror = mirror

    def _verify(self, hashes: List[bytes], values: List[bytes]) -> List[bool]:
        with span(
            "fastsync.verify", nodes=len(hashes),
            device=self.hasher is not None,
        ):
            if self.hasher is None:
                return [keccak256(v) == h for h, v in zip(hashes, values)]
            digests = self.hasher(values)
            return [d == h for d, h in zip(digests, hashes)]

    def start(self, target_root: bytes) -> SyncState:
        """Begin (or resume) syncing toward target_root; runs to
        completion (the peer-request loop is the fetch callback's
        concern). Returns the final state."""
        state = self.state_storage.get_sync_state()
        if state is None or state.target_root != target_root:
            state = SyncState(
                target_root=target_root,
                pending=[(STATE_NODE, target_root)],
            )
        batches_done = 0
        seen: Set[bytes] = set()
        while state.pending:
            batch = state.pending[: self.batch_size]
            state.pending = state.pending[self.batch_size :]
            want = [h for _, h in batch]
            with span("fastsync.fetch", batch=batches_done,
                      nodes=len(want)):
                got = self.fetch(want)
            missing: List[Tuple[int, bytes]] = []
            hashes, values, kinds = [], [], []
            for kind, h in batch:
                v = got.get(h)
                if v is None:
                    missing.append((kind, h))
                else:
                    hashes.append(h)
                    values.append(v)
                    kinds.append(kind)
            ok = self._verify(hashes, values) if hashes else []
            node_batch: Dict[bytes, bytes] = {}
            storage_batch: Dict[bytes, bytes] = {}
            code_batch: Dict[bytes, bytes] = {}
            for kind, h, v, good in zip(kinds, hashes, values, ok):
                if not good:
                    missing.append((kind, h))  # corrupt: retry later
                    continue
                if kind == STATE_NODE:
                    node_batch[h] = v
                elif kind == STORAGE_NODE:
                    storage_batch[h] = v
                else:
                    code_batch[h] = v
                for child in _children_of(kind, v):
                    if child[1] not in seen:
                        seen.add(child[1])
                        state.pending.append(child)
                state.downloaded_nodes += 1
            # batched saves (saveAccountNodes :898-918)
            if node_batch:
                self.storages.account_node_storage.update([], node_batch)
            if storage_batch:
                self.storages.storage_node_storage.update([], storage_batch)
            if code_batch:
                self.storages.evmcode_storage.update([], code_batch)
            if self.mirror is not None:
                if node_batch:
                    self.mirror.admit(node_batch)
                if storage_batch:
                    self.mirror.admit(storage_batch)
            state.pending.extend(missing)
            if missing and not (node_batch or storage_batch or code_batch):
                raise RuntimeError(
                    f"no progress: {len(missing)} nodes unavailable"
                )
            batches_done += 1
            if batches_done % self.checkpoint_every == 0:
                self.state_storage.put_sync_state(state)
        if self.mirror is not None:
            # re-verification of every RESIDENT node on word-major
            # tiles: one dispatch per size class, zero layout work.
            # Covers the whole snapshot when the mirror's per-class
            # capacity >= the snapshot's node count (the bench sizes it
            # so); a smaller mirror ring-evicts and this verifies the
            # retained tail — per-batch download verification above
            # covered every node either way. BEFORE purge: a failure
            # must leave the resumable checkpoint intact, not force a
            # full re-download.
            self.mirror.flush()
            bad = self.mirror.verify()
            if bad:
                raise RuntimeError(
                    f"device-mirror verify: {bad} of "
                    f"{self.mirror.resident_count} resident nodes "
                    "failed content-address check"
                )
        self.state_storage.purge()
        self.storages.app_state.mark_fast_sync_done()
        return state


@dataclass
class SegmentIngestReport:
    """What the segment-streamed ingest moved and proved."""

    segments: int = 0
    records: int = 0
    bytes: int = 0
    corrupt_frames: int = 0
    verified_nodes: int = 0  # post-ingest reachability walk
    missing: int = 0
    corrupt_nodes: int = 0


def segment_snapshot_ingest(
    storages,
    list_segments: Callable[[], List[Tuple[str, int, int]]],
    fetch_chunk: Callable[[str, int, int, int], Tuple[bytes, int, bool]],
    target_root: Optional[bytes] = None,
    workers: int = 4,
    chunk_bytes: int = 1 << 20,
) -> SegmentIngestReport:
    """The Kesque bulk-ingest path: stream whole VERIFIED segments in
    parallel instead of walking the trie node-by-node (StateSyncer).

    Why it wins ≥3×: the per-node loop pays one fetch round-trip per
    ``batch_size`` nodes AND must parse every node to discover its
    children before it can even request them — the trie walk serializes
    discovery. Segment streaming needs zero discovery (the source's
    segment manifest IS the work list), ships megabyte chunks, and
    lands each chunk as one sequential ``append_batch``. Verification
    is not skipped — it is free: every shipped record is admitted under
    its recomputed keccak, so a corrupt frame simply cannot land under
    a valid key (the same content-address argument as
    KesqueNodeDataSource.scala:61-63), and the optional
    ``target_root`` walk re-proves reachability exactly like crash
    recovery does.

    ``list_segments() -> [(topic, seq, size), ...]`` and
    ``fetch_chunk(topic, seq, offset, max_bytes) -> (raw, next, done)``
    abstract the wire (BridgeClient.engine_info / stream_segments in
    production, a local engine in tests). Requires a kesque-backed
    ``storages`` (segments are the unit of movement — there is nothing
    to bulk-append into otherwise)."""
    import time as _time
    from concurrent.futures import ThreadPoolExecutor

    from khipu_tpu.chaos import fault_point
    from khipu_tpu.observability.profiler import HOST, LEDGER

    engine = getattr(storages, "kesque_engine", None)
    if engine is None:
        raise RuntimeError(
            "segment ingest requires Storages(engine='kesque')"
        )
    report = SegmentIngestReport()
    manifest = list_segments()

    def pull(item: Tuple[str, int, int]) -> Tuple[int, int, int]:
        topic, seq, _size = item
        records = nbytes = corrupt = 0
        offset, done = 0, False
        while not done:
            fault_point("kesque.ingest")
            t0 = _time.perf_counter()
            raw, offset, done = fetch_chunk(topic, seq, offset,
                                            chunk_bytes)
            if not raw:
                break
            n, bad = engine.ingest_chunk(topic, raw)
            records += n
            corrupt += bad
            nbytes += len(raw)
            LEDGER.record("kesque.ingest", HOST, len(raw),
                          duration=_time.perf_counter() - t0)
        return records, nbytes, corrupt

    with span("fastsync.segment_ingest", segments=len(manifest),
              workers=workers):
        with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
            for records, nbytes, corrupt in pool.map(pull, manifest):
                report.segments += 1
                report.records += records
                report.bytes += nbytes
                report.corrupt_frames += corrupt

    if target_root is not None:
        from khipu_tpu.storage.compactor import verify_reachable

        walk = verify_reachable(
            storages.account_node_storage,
            storages.storage_node_storage,
            storages.evmcode_storage,
            target_root, verify_hashes=True,
        )
        report.verified_nodes = walk.total
        report.missing = walk.missing
        report.corrupt_nodes = walk.corrupt
        if walk.missing or walk.corrupt:
            raise RuntimeError(
                f"segment ingest incomplete: {walk.missing} missing / "
                f"{walk.corrupt} corrupt nodes reachable from target "
                "root"
            )
        storages.app_state.mark_fast_sync_done()
    return report
