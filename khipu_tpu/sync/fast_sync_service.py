"""Fast-sync orchestration: pivot choice + multi-peer download scheduler.

Parity: blockchain/sync/FastSyncService.scala —
  pivot selection: ask every handshaked peer for its best header, take
  the MEDIAN best number minus ``pivot_block_offset`` (requires
  ``min_peers_to_choose_pivot`` peers)                    :184-273
  download scheduler: bounded-concurrency node requests spread across
  the peer pool; a stalling/failing peer is blacklisted and its
  work is redistributed                                   :537-667
  block-data backfill to the pivot (headers/bodies/receipts stored
  WITHOUT execution — the state arrives as the downloaded trie)

The queue/verify/persist half lives in sync/fast_sync.py (StateSyncer);
this module supplies its ``fetch`` callback from real peers and drives
the whole flow end to end.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Mapping, Optional

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import BlockBody
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.receipt import Receipt, encode_receipts
from khipu_tpu.network.messages import (
    BLOCK_BODIES,
    BLOCK_HEADERS,
    ETH_OFFSET,
    GET_BLOCK_BODIES,
    GET_BLOCK_HEADERS,
    GET_NODE_DATA,
    GET_RECEIPTS,
    NODE_DATA,
    RECEIPTS,
    GetBlockHeaders,
    decode_bodies,
    decode_headers,
)
from khipu_tpu.network.peer import Peer, PeerError, PeerManager
from khipu_tpu.sync.fast_sync import FastSyncStateStorage, StateSyncer, SyncState
from khipu_tpu.validators.roots import (
    ommers_hash,
    receipts_root,
    transactions_root,
)


class FastSyncError(Exception):
    pass


class PeerFetchPool:
    """Spread node-data requests across live peers with bounded
    concurrency; timeout -> blacklist + redistribute
    (processDownload:537-667 role)."""

    def __init__(
        self,
        manager: PeerManager,
        nodes_per_request: int = 50,
        timeout: float = 5.0,
        max_rounds: int = 5,
        log: Optional[Callable[[str], None]] = None,
        cluster=None,
    ):
        self.manager = manager
        self.per_request = nodes_per_request
        self.timeout = timeout
        self.max_rounds = max_rounds
        self.log = log or (lambda s: None)
        self.blacklisted = 0
        self._rr = 0  # rotating start so small fetches still spread
        # sharded node-cache cluster: consulted before the peer pool —
        # a shard read is one verified RPC vs. a devp2p round-trip, and
        # the client's replica failover/breakers absorb dead shards
        self.cluster = cluster
        self.cluster_served = 0

    def _live_peers(self) -> List[Peer]:
        return [
            p for p in self.manager.peers
            if p.alive
            and not self.manager.blacklist.is_blacklisted(p.remote_pub)
        ]

    def fetch_nodes(self, hashes: List[bytes]) -> Mapping[bytes, bytes]:
        """StateSyncer fetch callback: every returned value is keyed by
        its CONTENT hash (NodeData replies carry no correlation)."""
        results: Dict[bytes, bytes] = {}
        pending = list(hashes)
        if self.cluster is not None and pending:
            try:
                got = self.cluster.fetch(pending)
            except Exception:
                got = {}
            results.update(got)  # values verified by the client
            self.cluster_served += len(got)
            pending = [h for h in pending if h not in results]
        for _ in range(self.max_rounds):
            if not pending:
                break
            peers = self._live_peers()
            if not peers:
                raise FastSyncError("no live peers for node download")
            start = self._rr % len(peers)
            self._rr += 1
            peers = peers[start:] + peers[:start]
            chunks = [
                pending[i : i + self.per_request]
                for i in range(0, len(pending), self.per_request)
            ]
            lock = threading.Lock()
            got_any = [False]

            def worker(peer: Peer, mine: List[List[bytes]]) -> None:
                for chunk in mine:
                    try:
                        body = peer.request(
                            ETH_OFFSET + GET_NODE_DATA,
                            list(chunk),
                            ETH_OFFSET + NODE_DATA,
                            timeout=self.timeout,
                        )
                    except PeerError:
                        # stalling / dead peer: blacklist, abandon its
                        # remaining chunks (requeued by the outer round)
                        self.manager.blacklist.add(
                            peer.remote_pub, duration=600.0
                        )
                        peer.disconnect()
                        self.blacklisted += 1
                        self.log(
                            "blacklisted stalling peer "
                            f"{peer.remote_pub[:4].hex()}"
                        )
                        return
                    with lock:
                        for blob in body:
                            results[keccak256(bytes(blob))] = bytes(blob)
                            got_any[0] = True

            # round-robin chunk assignment across the live pool
            assign: Dict[int, List[List[bytes]]] = {
                i: [] for i in range(len(peers))
            }
            for i, chunk in enumerate(chunks):
                assign[i % len(peers)].append(chunk)
            threads = [
                threading.Thread(
                    target=worker, args=(peers[i], assign[i]), daemon=True
                )
                for i in range(len(peers))
                if assign[i]
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            pending = [h for h in pending if h not in results]
            if pending and not got_any[0] and not self._live_peers():
                break
        return results


class FastSyncService:
    """choose pivot -> download state via the peer pool -> backfill
    block data -> hand off at the pivot."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        manager: PeerManager,
        hasher=None,
        log: Optional[Callable[[str], None]] = None,
        cluster=None,
    ):
        self.blockchain = blockchain
        self.config = config
        self.manager = manager
        self.hasher = hasher
        self.log = log or (lambda s: None)
        sync = config.sync
        self.min_peers = sync.min_peers_to_choose_pivot
        self.pivot_offset = sync.pivot_block_offset
        self.pool = PeerFetchPool(
            manager,
            nodes_per_request=sync.nodes_per_request,
            timeout=sync.peer_request_timeout,
            log=self.log,
            cluster=cluster,
        )

    # -------------------------------------------------------------- pivot

    def _best_header_of(self, peer: Peer) -> Optional[BlockHeader]:
        try:
            body = peer.request(
                ETH_OFFSET + GET_BLOCK_HEADERS,
                GetBlockHeaders(peer.status.best_hash, 1).body(),
                ETH_OFFSET + BLOCK_HEADERS,
                timeout=self.pool.timeout,
            )
            headers = decode_headers(body)
            return headers[0] if headers else None
        except PeerError:
            return None

    def choose_pivot(self) -> BlockHeader:
        """Median best number over >= min_peers peers, minus the offset
        (FastSyncService.scala:184-273)."""
        peers = [p for p in self.pool._live_peers() if p.status is not None]
        if len(peers) < self.min_peers:
            raise FastSyncError(
                f"need {self.min_peers} peers to choose a pivot, "
                f"have {len(peers)}"
            )
        bests: List[int] = []
        for p in peers:
            h = self._best_header_of(p)
            if h is not None:
                bests.append(h.number)
        if len(bests) < self.min_peers:
            raise FastSyncError(
                f"only {len(bests)}/{self.min_peers} peers answered the "
                "pivot probe"
            )
        bests.sort()
        median = bests[len(bests) // 2]
        pivot_number = max(1, median - self.pivot_offset)
        header = self._fetch_header_by_number(pivot_number)
        if header is None:
            raise FastSyncError(f"no peer served pivot header {pivot_number}")
        self.log(
            f"pivot = #{pivot_number} (median best {median} - "
            f"{self.pivot_offset}), root {header.state_root.hex()[:16]}"
        )
        return header

    def _fetch_header_by_number(self, n: int) -> Optional[BlockHeader]:
        for peer in self.pool._live_peers():
            try:
                body = peer.request(
                    ETH_OFFSET + GET_BLOCK_HEADERS,
                    GetBlockHeaders(n, 1).body(),
                    ETH_OFFSET + BLOCK_HEADERS,
                    timeout=self.pool.timeout,
                )
                headers = decode_headers(body)
                if headers and headers[0].number == n:
                    return headers[0]
            except PeerError:
                continue
        return None

    # ----------------------------------------------------------- backfill

    def _backfill_blocks(self, pivot: BlockHeader) -> None:
        """Headers/bodies/receipts genesis..pivot, stored WITHOUT
        execution (the state trie arrived separately); every link is
        validated: parent hashes, tx/ommers roots, receipts roots."""
        s = self.blockchain.storages
        expected_parent = self.blockchain.get_hash_by_number(0)
        td = self.blockchain.get_total_difficulty(0) or 0
        n = 1
        batch = 20
        while n <= pivot.number:
            count = min(batch, pivot.number - n + 1)
            headers = self._headers_range(n, count)
            hashes = [h.hash for h in headers]
            bodies = self._bodies_of(hashes)
            receipts = self._receipts_of(hashes)
            for h, body, rcpts in zip(headers, bodies, receipts):
                if h.parent_hash != expected_parent:
                    raise FastSyncError(
                        f"backfill: broken parent link at #{h.number}"
                    )
                if transactions_root(body.transactions) != h.transactions_root:
                    raise FastSyncError(f"backfill: bad txRoot at #{h.number}")
                if ommers_hash(body.ommers) != h.ommers_hash:
                    raise FastSyncError(
                        f"backfill: bad ommersHash at #{h.number}"
                    )
                if receipts_root(rcpts) != h.receipts_root:
                    raise FastSyncError(
                        f"backfill: bad receiptsRoot at #{h.number}"
                    )
                td += h.difficulty
                s.block_header_storage.put(h.number, h.encode())
                s.block_body_storage.put(h.number, body.encode())
                s.receipts_storage.put(h.number, encode_receipts(rcpts))
                s.total_difficulty_storage.put_td(h.number, td)
                s.block_numbers.put(h.hash, h.number)
                for i, tx in enumerate(body.transactions):
                    s.transaction_storage.put(tx.hash, h.number, i)
                expected_parent = h.hash
            n += count
        s.app_state.best_block_number = pivot.number

    def _headers_range(self, start: int, count: int) -> List[BlockHeader]:
        for peer in self.pool._live_peers():
            try:
                body = peer.request(
                    ETH_OFFSET + GET_BLOCK_HEADERS,
                    GetBlockHeaders(start, count).body(),
                    ETH_OFFSET + BLOCK_HEADERS,
                    timeout=self.pool.timeout,
                )
                headers = decode_headers(body)
                if len(headers) == count:
                    return headers
            except PeerError:
                continue
        raise FastSyncError(f"no peer served headers [{start}..+{count})")

    def _bodies_of(self, hashes: List[bytes]) -> List[BlockBody]:
        # EXACT counts only: replies carry no correlation and servers
        # skip unknown hashes, so a short reply would silently shift
        # every later header/body pair — try the next peer instead
        out: List[BlockBody] = []
        want = list(hashes)
        while want:
            chunk = want[:20]
            served = False
            for peer in self.pool._live_peers():
                try:
                    body = peer.request(
                        ETH_OFFSET + GET_BLOCK_BODIES,
                        chunk,
                        ETH_OFFSET + BLOCK_BODIES,
                        timeout=self.pool.timeout,
                    )
                except PeerError:
                    continue
                got = decode_bodies(body)
                if len(got) == len(chunk):
                    out.extend(got)
                    want = want[len(chunk) :]
                    served = True
                    break
            if not served:
                raise FastSyncError("no peer served the full body chunk")
        return out

    def _receipts_of(self, hashes: List[bytes]) -> List[List[Receipt]]:
        from khipu_tpu.domain.receipt import decode_receipts
        from khipu_tpu.base.rlp import rlp_encode

        out: List[List[Receipt]] = []
        want = list(hashes)
        while want:
            chunk = want[:5]
            served = False
            for peer in self.pool._live_peers():
                try:
                    body = peer.request(
                        ETH_OFFSET + GET_RECEIPTS,
                        chunk,
                        ETH_OFFSET + RECEIPTS,
                        timeout=self.pool.timeout,
                    )
                except PeerError:
                    continue
                if len(body) == len(chunk):
                    out.extend(
                        decode_receipts(rlp_encode(item)) for item in body
                    )
                    want = want[len(chunk) :]
                    served = True
                    break
            if not served:
                raise FastSyncError("no peer served the full receipt chunk")
        return out

    # ------------------------------------------------------------- driver

    def run(self) -> SyncState:
        """Full fast sync: pivot -> state download -> block backfill.
        After this, regular sync takes over from the pivot."""
        pivot = self.choose_pivot()
        syncer = StateSyncer(
            self.blockchain.storages,
            FastSyncStateStorage(self.blockchain.storages.app_state.source),
            self.pool.fetch_nodes,
            batch_size=self.config.sync.nodes_per_request,
            hasher=self.hasher,
        )
        state = syncer.start(pivot.state_root)
        self.log(
            f"state download complete: {state.downloaded_nodes} nodes "
            f"({self.pool.blacklisted} peers blacklisted)"
        )
        self._backfill_blocks(pivot)
        self.log(f"backfilled block data to pivot #{pivot.number}")
        return state
