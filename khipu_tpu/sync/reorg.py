"""Crash-safe, serving-safe chain reorganization.

Every deep-pipeline PR widened the window between "executed" and
"durable" — a 4-stage collector, a device mirror of placeholder
aliases, a ReadView overlay serving executed-but-not-yet-durable
reads — and all of it assumed a monotonic chain. The ReorgManager is
where a TD-winning side branch crosses that machinery: one journaled,
fenced, atomic switch instead of regular_sync's old unjournaled
block-at-a-time rewind.

The switch runs five phases (chaos seams in parentheses; see
docs/recovery.md for the crash-point table):

1. FENCE — invalidate the serving overlay above the fork point
   (``ReadView.invalidate_above``), settle any in-flight window
   intents a dead collector left behind (journal recovery pass, which
   also detaches the volatile device mirror), and drop unpublished
   placeholder aliases from the mirror. After the fence, nothing
   above the ancestor is visible to readers or half-owned by a
   background stage.
2. INTENT (``reorg.intent``) — stage the adopted branch's full block
   RLP in the window-commit journal and fsync a reorg-intent record
   (sync/journal.py). From here a kill anywhere resolves to exactly
   the old chain or exactly the new one.
3. ROLLBACK (``reorg.rollback``, per block) — remove the old blocks
   tip-down, verifying the walk reaches the ancestor.
4. ADOPT (``reorg.adopt``, per block) — import the branch through the
   same validated paths live sync uses: the windowed pipeline for
   long branches, per-block (with the caller's heal hook) otherwise.
5. FINALIZE (``reorg.finalize``) — commit-mark the intent, emit
   ``removed: true`` filter entries for logs in orphaned blocks,
   drop adopted txs from the pool, and recycle orphaned-only txs
   back into it through the standard replacement rules (geth parity).

A reorg deeper than ``db.unconfirmed_depth`` is refused
(``ReorgTooDeep`` — regular_sync demotes the peer) instead of walking
off the pruned unconfirmed ring.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from khipu_tpu.chaos import fault_point
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.observability.journey import JOURNEY
from khipu_tpu.observability.trace import span


class ReorgTooDeep(RuntimeError):
    """The branch forks below the unconfirmed ring — refuse it."""


class ReorgManager:
    """Owns the atomic chain switch; one per sync service/driver."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        driver=None,
        txpool=None,
        read_view=None,
        log: Optional[Callable[[str], None]] = None,
    ):
        self.blockchain = blockchain
        self.config = config
        if driver is None:
            from khipu_tpu.sync.replay import ReplayDriver

            driver = ReplayDriver(blockchain, config)
        self.driver = driver
        self.txpool = txpool
        self.read_view = read_view
        self.log = log or (lambda s: None)
        # counters are read by scrape/watchdog threads while the
        # switch mutates them on the import thread
        self._lock = threading.Lock()
        self.switches = 0
        self.refused = 0
        self.last_depth = 0
        self.orphaned_blocks = 0
        self.recycled_txs = 0
        # reorg observers: fn(ancestor_number, removed_hits) — the
        # filter manager's note_reorg hangs here (jsonrpc/filters.py)
        self._listeners: List[Callable] = []
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector("reorg", self._registry_samples)
        except Exception:  # pragma: no cover
            pass

    # -------------------------------------------------------- observability

    def _registry_samples(self) -> list:
        with self._lock:
            return [
                ("khipu_reorg_total", "counter", {}, self.switches),
                ("khipu_reorg_refused_total", "counter", {},
                 self.refused),
                ("khipu_reorg_depth", "gauge", {}, self.last_depth),
                ("khipu_reorg_orphaned_blocks_total", "counter", {},
                 self.orphaned_blocks),
                ("khipu_reorg_recycled_txs_total", "counter", {},
                 self.recycled_txs),
            ]

    def watch_source(self) -> int:
        """Cumulative switch count — the watchdog's ``reorg_storm``
        detector samples this (observability/telemetry.py)."""
        with self._lock:
            return self.switches

    def add_listener(self, fn: Callable) -> None:
        """Register ``fn(ancestor_number, removed_hits)`` to run at
        finalize (after the chain is switched, before control returns
        to the import loop)."""
        self._listeners.append(fn)

    # -------------------------------------------------------------- switch

    def switch(self, ancestor_number: int, blocks: List[Block],
               import_fn: Optional[Callable[[Block], None]] = None) -> int:
        """Atomically replace (ancestor, best] with ``blocks``.

        The caller has already decided the branch wins (TD rule) and
        validated its headers; the caller holds whatever lock excludes
        concurrent imports. ``import_fn`` overrides the per-block
        import (regular_sync passes its node-healing wrapper).
        Returns the number of adopted blocks."""
        bc = self.blockchain
        best = bc.best_block_number
        depth = best - ancestor_number
        max_depth = self.config.db.unconfirmed_depth
        if depth > max_depth:
            with self._lock:
                self.refused += 1
            raise ReorgTooDeep(
                f"reorg depth {depth} exceeds unconfirmed_depth "
                f"{max_depth}: refusing to walk off the pruned ring"
            )
        blocks = list(blocks)
        if not blocks or blocks[0].header.number != ancestor_number + 1:
            raise ValueError("adopted branch must start at ancestor+1")
        anc_header = bc.get_header_by_number(ancestor_number)
        if (anc_header is None
                or anc_header.hash != blocks[0].header.parent_hash):
            raise ValueError(
                "adopted branch does not attach to the ancestor"
            )

        with span("reorg.switch", ancestor=ancestor_number, depth=depth,
                  adopted=len(blocks)):
            self._fence(ancestor_number)
            old_blocks = self._collect_old(ancestor_number, best)
            # orphaned log hits and orphaned-only txs BEFORE removal,
            # while bodies/receipts are still readable; the hits go to
            # listeners at finalize, the txs ride in the intent record
            # so a mid-switch death can still recycle them
            removed_hits = self._removed_hits(old_blocks)
            orphans = self._orphan_txs(old_blocks, blocks)
            if JOURNEY.enabled:
                # every tx on the losing branch gets its retraction
                # page (PINNED — tail retention outlives the ring);
                # re-inclusion is stamped at finalize once the branch
                # actually won
                for b in old_blocks:
                    for stx in b.body.transactions:
                        JOURNEY.record(stx.hash, "reorg.retract",
                                       ancestor=ancestor_number,
                                       block=b.header.number)

            journal = bc.storages.window_journal
            fault_point("reorg.intent")
            seq = journal.log_reorg_intent(
                ancestor_number, anc_header.hash,
                [b.hash for b in old_blocks], blocks,
                orphan_txs=orphans,
            )
            try:
                self._rollback(ancestor_number, old_blocks)
                self._adopt(blocks, import_fn)
                fault_point("reorg.finalize")
            except Exception:
                # a LOCAL failure mid-switch (InjectedDeath is a
                # BaseException and falls through raw, like SIGKILL):
                # the intent is durable, so settle the torn switch the
                # same way a restart would — the node lands at exactly
                # the old chain or the new one — then surface the error
                from khipu_tpu.sync.journal import recover

                recover(bc, log=self.log, config=self.config,
                        txpool=self.txpool)
                raise
            journal.log_commit(seq)
            journal.prune()
            self._finalize(ancestor_number, old_blocks, orphans,
                           blocks, removed_hits)
        return len(blocks)

    # -------------------------------------------------------------- phases

    def _fence(self, ancestor_number: int) -> None:
        """Nothing above the ancestor stays visible to readers or
        half-owned by a background stage."""
        if self.read_view is not None:
            self.read_view.invalidate_above(ancestor_number)
        s = self.blockchain.storages
        journal = s.window_journal
        journal.prune()
        if journal.pending():
            # in-flight windows left by a dead/aborted collector:
            # settle them through the standard recovery pass (which
            # also detaches the volatile device mirror)
            from khipu_tpu.sync.journal import recover

            recover(self.blockchain, log=self.log, config=self.config)
        else:
            # committed windows have rekeyed their aliases; anything
            # still alias-keyed belongs to a window that will never
            # publish — forget those rows rather than let a stale
            # placeholder satisfy a read-through
            mirror = getattr(s.account_node_storage, "mirror", None)
            if mirror is not None:
                drop = getattr(mirror, "drop_aliases", None)
                aliases = []
                for cm in getattr(mirror, "_classes", {}).values():
                    aliases.extend(getattr(cm, "alias_rows", {}).keys())
                if drop is not None and aliases:
                    drop(aliases)

    def _collect_old(self, ancestor_number: int, best: int) -> List[Block]:
        out = []
        for n in range(ancestor_number + 1, best + 1):
            block = self.blockchain.get_block_by_number(n)
            if block is None:
                raise RuntimeError(
                    f"canonical chain has no block at #{n} below best "
                    f"#{best}: refusing to reorg across a hole"
                )
            out.append(block)
        return out

    def _removed_hits(self, old_blocks: List[Block]) -> list:
        """Every log in the orphaned blocks as a ``removed: true``
        LogHit (filter parity: clients un-apply state they derived
        from logs the reorg retracted)."""
        from khipu_tpu.jsonrpc.filters import LogHit

        hits = []
        for block in old_blocks:
            receipts = self.blockchain.get_receipts(block.number)
            if receipts is None:
                continue
            log_index = 0
            for tx_index, receipt in enumerate(receipts):
                for log in receipt.logs:
                    hits.append(LogHit(
                        address=log.address,
                        topics=tuple(log.topics),
                        data=log.data,
                        block_number=block.number,
                        block_hash=block.hash,
                        tx_hash=block.body.transactions[tx_index].hash,
                        tx_index=tx_index,
                        log_index=log_index,
                        removed=True,
                    ))
                    log_index += 1
        return hits

    def _rollback(self, ancestor_number: int,
                  old_blocks: List[Block]) -> None:
        """Remove the old blocks tip-down. The walk is hash-exact
        (every block was just read from the canonical chain) and must
        reach the ancestor — a hole would strand best above it.

        The best pointer drops to the ancestor BEFORE any removal:
        concurrent readers resolve state through the best header, and
        the ancestor's is the one header guaranteed present throughout
        the rollback. (Recovery reads the intent record, not the best
        pointer, to find the torn span — moving best first costs it
        nothing.)"""
        bc = self.blockchain
        bc.storages.app_state.best_block_number = ancestor_number
        for block in reversed(old_blocks):
            fault_point("reorg.rollback")
            bc.remove_block(block.hash)
            if bc.get_header_by_number(block.number) is not None:
                raise RuntimeError(
                    f"rollback failed to remove block #{block.number}"
                )

    def _adopt(self, blocks: List[Block],
               import_fn: Optional[Callable[[Block], None]]) -> None:
        """Import the branch through the validated live-sync paths: a
        long branch takes the windowed pipeline (the journal interleaves
        its window intents after the reorg intent — recovery settles
        them in seq order), the rest goes per-block."""
        bc = self.blockchain
        window = self.config.sync.commit_window_blocks
        done = 0
        if window > 1 and len(blocks) >= window:
            fault_point("reorg.adopt")
            before = bc.best_block_number
            self.driver.replay_windowed(iter(blocks), window)
            done = bc.best_block_number - before
        from khipu_tpu.sync.replay import ReplayStats

        stats = ReplayStats()
        for block in blocks[done:]:
            fault_point("reorg.adopt")
            if import_fn is not None:
                import_fn(block)
            else:
                self.driver._execute_and_insert(block, stats)

    def _orphan_txs(self, old_blocks: List[Block],
                    adopted: List[Block]) -> list:
        """Txs mined ONLY on the losing branch, senders recovered —
        the recycling candidates."""
        from khipu_tpu.domain.transaction import recover_senders

        adopted_tx_hashes = {
            tx.hash for b in adopted for tx in b.body.transactions
        }
        orphans = [
            tx for b in old_blocks for tx in b.body.transactions
            if tx.hash not in adopted_tx_hashes
        ]
        recover_senders(orphans)
        return orphans

    def _finalize(self, ancestor_number: int, old_blocks: List[Block],
                  orphans: list, adopted: List[Block],
                  removed_hits: list) -> None:
        recycled = 0
        if JOURNEY.enabled:
            # re-inclusion pages: a retracted tx that was mined again
            # on the winning branch closes the retract->reinclude arc
            retracted = {
                stx.hash for b in old_blocks
                for stx in b.body.transactions
            }
            for b in adopted:
                for stx in b.body.transactions:
                    if stx.hash in retracted:
                        JOURNEY.record(stx.hash, "reorg.reinclude",
                                       via="mined",
                                       block=b.header.number)
        if self.txpool is not None:
            for b in adopted:
                # adopted-branch txs leave the pool, same as every
                # other import path
                self.txpool.remove_mined(b.body.transactions)
            # orphan recycling: txs mined only on the losing branch
            # re-enter through the pool's standard replacement rules —
            # a pooled same-(sender,nonce) tx with a higher gas price
            # keeps its slot
            for stx in orphans:
                if stx.sender is None:
                    continue
                try:
                    if self.txpool.add(stx):
                        recycled += 1
                        if JOURNEY.enabled:
                            # pool residence IS the re-inclusion state
                            # for orphaned-only txs (awaiting re-mining)
                            JOURNEY.record(stx.hash, "reorg.reinclude",
                                           via="pool")
                except ValueError:
                    pass
        for fn in list(self._listeners):
            try:
                fn(ancestor_number, removed_hits)
            except Exception as e:  # a broken observer can't undo a switch
                self.log(f"reorg listener failed: {e}")
        with self._lock:
            self.switches += 1
            self.last_depth = len(old_blocks)
            self.orphaned_blocks += len(old_blocks)
            self.recycled_txs += recycled
        self.log(
            f"reorg: ancestor #{ancestor_number}, orphaned "
            f"{len(old_blocks)} blocks, adopted {len(adopted)}, "
            f"recycled {recycled} txs"
        )
