"""Cost-model-adaptive commit: pick the commit path the backend is
actually fast at (docs/roofline.md "The adaptive commit rule").

The device-resident commit (storage/device_mirror.py) is a bet: that
d2d gathers and the fused fixpoint beat the host memcpy + scalar keccak
they replaced. BENCH_r07 shows the bet losing 20x on a 1-core CPU
backend — there "device" memory IS host RAM, so every d2d gather is a
memcpy with dispatch overhead on top, and the fused fixpoint re-hashes
``rounds x padded_rows`` where the host path hashes each node once.
This module closes the loop the cost model (observability/costmodel.py)
opened: measure, decide, and keep deciding.

Two instruments, one controller:

* ``probe_backend()`` — a one-shot, process-cached measurement per
  backend platform: time a jit d2d gather against the same-shape host
  fancy-index memcpy. Device commit only engages when d2d wins by
  ``adaptive_d2d_margin`` — on real HBM it wins by orders of
  magnitude; where device memory is host RAM it cannot, by
  construction, clear the margin. The probe's upload is billed to the
  ledger site ``adaptive.probe`` (KL001).
* ``AdaptiveCommitController`` — an EWMA over each window's seal-stage
  cost per hash, one series per mode, with a Schmitt trigger between
  them: flip device -> host when the device EWMA exceeds
  ``adaptive_flip_ratio`` x the host estimate, flip back only below
  ``adaptive_flip_back_ratio`` x, and never flip before
  ``adaptive_dwell_windows`` windows have passed in the current mode
  (the hysteresis band + dwell kill oscillation). The host estimate
  starts from a calibrated scalar-keccak floor and is replaced by the
  measured host EWMA once host windows run. ``device_mirror_commit``
  stays the CAP: the controller only ever downgrades device -> host.

The controller also turns the ``seal.upload`` roofline verdict into a
``pipeline_depth`` recommendation: a bytes-bound upload overlaps with
more windows in flight (raise depth toward ``adaptive_depth_max``,
GPipe-style), a fixed-overhead upload does not (lower it and stop
paying queue memory for overlap that cannot happen).

Every decision is exported as the ``khipu_adaptive_*`` registry family
and a ``window.adapt`` flight-recorder event. Both commit paths
produce byte-identical state roots, so adaptive timing nondeterminism
never touches replay bit-exactness — only which hardware does the
hashing.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from khipu_tpu.observability.costmodel import classify, subphase_floors
from khipu_tpu.observability.profiler import H2D, LEDGER
from khipu_tpu.observability.registry import REGISTRY
from khipu_tpu.observability.trace import event

__all__ = [
    "ADAPTIVE_GAUGES",
    "ProbeResult",
    "probe_backend",
    "AdaptiveCommitController",
]

ADAPTIVE_GAUGES = REGISTRY.gauge_group("khipu_adaptive", {
    # 1 while the controller holds the device-mirror commit path
    "device_mode": 0,
    # mode changes, ever (the initial probe downgrade counts)
    "flips_total": 0,
    "windows_observed": 0,
    # backend probe readout (bytes/s; 0 until a probe ran)
    "probe_d2d_bytes_per_s": 0,
    "probe_memcpy_bytes_per_s": 0,
    # current pipeline_depth recommendation (0 = no opinion yet)
    "depth_hint": 0,
    # per-hash seal-stage EWMAs the Schmitt trigger compares (seconds)
    "ewma_device_hash_s": 0.0,
    "ewma_host_hash_s": 0.0,
    # flips wanted by the ratio but suppressed by the dwell window
    "flap_suppressed_total": 0,
}, help="cost-model-adaptive commit controller (sync/adaptive.py)")

# probe workload: ~0.5 MB gathered through ~2k rows — big enough that
# a real tunnel/HBM difference dominates the clock, small enough to be
# noise at startup
_PROBE_ROWS = 2048
_PROBE_COLS = 256
_PROBE_REPS = 3

# one probe per backend platform per process — jit warmup is the
# expensive part and the answer cannot change under our feet
_PROBE_CACHE: Dict[str, "ProbeResult"] = {}


class ProbeResult:
    """One backend's gather-vs-memcpy measurement."""

    __slots__ = ("platform", "d2d_bytes_per_s", "memcpy_bytes_per_s",
                 "device_ok")

    def __init__(self, platform: str, d2d_bytes_per_s: float,
                 memcpy_bytes_per_s: float, device_ok: bool):
        self.platform = platform
        self.d2d_bytes_per_s = d2d_bytes_per_s
        self.memcpy_bytes_per_s = memcpy_bytes_per_s
        self.device_ok = device_ok

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"<Probe {self.platform} d2d={self.d2d_bytes_per_s:.3g}B/s "
            f"memcpy={self.memcpy_bytes_per_s:.3g}B/s "
            f"ok={self.device_ok}>"
        )


def _measure_probe(margin: float) -> ProbeResult:
    import jax
    import jax.numpy as jnp
    import numpy as np

    platform = jax.default_backend()
    rng = np.random.default_rng(0)  # KL003: seeded, replay-stable
    host = rng.integers(0, 256, size=(_PROBE_ROWS, _PROBE_COLS),
                        dtype=np.uint8)
    idx = rng.permutation(_PROBE_ROWS).astype(np.int32)

    gather = jax.jit(lambda a, i: a[i])
    with LEDGER.transfer("adaptive.probe", H2D,
                         host.nbytes + idx.nbytes):
        dev = jnp.asarray(host)
        idx_dev = jnp.asarray(idx)
    # the gather stays on device — its bytes never cross the boundary
    # (the H2D upload above is the only crossing, already ledgered)
    # khipu-lint: ok KL001 device-resident gather, no host<->device bytes
    gather(dev, idx_dev).block_until_ready()  # warm: compile + paths

    t0 = time.perf_counter()
    for _ in range(_PROBE_REPS):
        # khipu-lint: ok KL001 device-resident gather, no host<->device bytes
        gather(dev, idx_dev).block_until_ready()
    d2d_s = (time.perf_counter() - t0) / _PROBE_REPS

    host[idx]  # warm the host path too (page faults, cache)
    t0 = time.perf_counter()
    for _ in range(_PROBE_REPS):
        host[idx]
    memcpy_s = (time.perf_counter() - t0) / _PROBE_REPS

    nbytes = host.nbytes
    d2d_rate = nbytes / d2d_s if d2d_s > 0 else 0.0
    memcpy_rate = nbytes / memcpy_s if memcpy_s > 0 else 0.0
    # where device memory is host RAM the gather can never clear the
    # margin; real HBM clears it by orders of magnitude
    ok = d2d_rate >= margin * memcpy_rate > 0
    return ProbeResult(platform, d2d_rate, memcpy_rate, ok)


def probe_backend(margin: float = 1.5) -> ProbeResult:
    """Measure (once per backend platform) whether d2d gathers beat the
    host memcpy they would replace by ``margin``. A backend without a
    working jax reports ``device_ok=False`` — the host path needs no
    device."""
    try:
        import jax

        platform = jax.default_backend()
    except Exception:
        return ProbeResult("none", 0.0, 0.0, False)
    cached = _PROBE_CACHE.get(platform)
    if cached is not None:
        return cached
    try:
        result = _measure_probe(margin)
    except Exception:
        result = ProbeResult(platform, 0.0, 0.0, False)
    _PROBE_CACHE[platform] = result
    ADAPTIVE_GAUGES["probe_d2d_bytes_per_s"] = int(result.d2d_bytes_per_s)
    ADAPTIVE_GAUGES["probe_memcpy_bytes_per_s"] = int(
        result.memcpy_bytes_per_s
    )
    return result


def exec_device_allowed(sync_cfg) -> bool:
    """Gate for the execute-stage device dispatch (ledger/batch_*.py
    -> trie/fused.fused_exec_validate): the sync config must opt in
    (``exec_device``) AND the one-shot backend probe must show real
    device memory — d2d beating host memcpy by the same margin the
    adaptive commit controller demands. Where device memory is host
    RAM (CPU jax), shipping row tiles out just adds a tunnel tax to a
    numpy pass, so the probe keeps the host path authoritative."""
    if not getattr(sync_cfg, "exec_device", False):
        return False
    if not getattr(sync_cfg, "adaptive_probe", True):
        return True  # explicit cap with probing disabled: honor it
    return probe_backend(sync_cfg.adaptive_d2d_margin).device_ok


def _calibrate_host_hash_s(samples: int = 256) -> float:
    """Seconds per scalar host keccak — the host estimate the trigger
    compares against until measured host windows replace it."""
    from khipu_tpu.base.crypto.keccak import keccak256

    msg = b"\x5a" * 128  # a typical branch-node encoding size
    keccak256(msg)  # bind the implementation outside the clock
    t0 = time.perf_counter()
    for _ in range(samples):
        keccak256(msg)
    return (time.perf_counter() - t0) / samples


class AdaptiveCommitController:
    """Per-committer mode controller. All methods run on the seal-stage
    thread (one window at a time), so plain attributes suffice."""

    def __init__(self, sync_cfg, device_cap: bool = True):
        self.cfg = sync_cfg
        # the config is the CAP: adaptive only downgrades device->host
        self.device_cap = bool(device_cap)
        self.device_mode = self.device_cap
        self.windows = 0
        self.flips = 0
        self.flaps_suppressed = 0
        self._dwell = 0  # windows spent in the current mode
        self._ewma: Dict[str, Optional[float]] = {
            "device": None, "host": None,
        }
        self.host_floor_s = _calibrate_host_hash_s()
        self.depth_hint: Optional[int] = None
        self.probe: Optional[ProbeResult] = None
        if self.device_cap and sync_cfg.adaptive_probe:
            self.probe = probe_backend(sync_cfg.adaptive_d2d_margin)
            if not self.probe.device_ok:
                self._flip(False, "probe", ratio=0.0)
        self._export()

    # ------------------------------------------------------ observations

    def mode(self) -> str:
        return "device" if self.device_mode else "host"

    def observe_window(self, mode: str, hashes: int,
                       seal_seconds: float) -> None:
        """One window's seal-stage verdict: ``hashes`` nodes resolved in
        ``seal_seconds`` under ``mode``. Updates that mode's EWMA, then
        re-runs the Schmitt trigger."""
        self.windows += 1
        self._dwell += 1
        if hashes > 0 and seal_seconds > 0:
            per_hash = seal_seconds / hashes
            prev = self._ewma.get(mode)
            alpha = self.cfg.adaptive_ewma_alpha
            self._ewma[mode] = (
                per_hash if prev is None
                else alpha * per_hash + (1.0 - alpha) * prev
            )
        self._decide()
        self._export()

    def note_upload(self, upload_bytes: int,
                    upload_seconds: float) -> None:
        """Roofline-classify the window's ``seal.upload`` and move the
        pipeline-depth recommendation: bytes-bound uploads overlap with
        deeper pipelines; fixed-overhead ones do not."""
        if upload_seconds <= 0:
            return
        verdict = classify(
            upload_seconds, subphase_floors(upload_bytes, 0, 0)
        )
        prev = self.depth_hint
        base = prev if prev is not None else self.cfg.pipeline_depth
        if verdict["bound"] == "bytes-bound":
            hint = min(self.cfg.adaptive_depth_max, base + 1)
        elif verdict["bound"] == "fixed-overhead":
            hint = max(1, base - 1)
        else:
            hint = base
        self.depth_hint = hint
        ADAPTIVE_GAUGES["depth_hint"] = hint
        if hint != prev:
            event("window.adapt", kind="depth", depth_hint=hint,
                  bound=verdict["bound"], upload_bytes=upload_bytes)

    # --------------------------------------------------------- decisions

    def _host_estimate(self) -> float:
        measured = self._ewma.get("host")
        return measured if measured is not None else self.host_floor_s

    def _decide(self) -> None:
        if not self.device_cap:
            return
        host_est = self._host_estimate()
        dev = self._ewma.get("device")
        if host_est <= 0 or dev is None:
            return
        ratio = dev / host_est
        if self.device_mode and ratio > self.cfg.adaptive_flip_ratio:
            if self._dwell >= self.cfg.adaptive_dwell_windows:
                self._flip(False, "ewma", ratio=ratio)
            else:
                self.flaps_suppressed += 1
                ADAPTIVE_GAUGES["flap_suppressed_total"] = (
                    self.flaps_suppressed
                )
        elif (not self.device_mode
              and ratio < self.cfg.adaptive_flip_back_ratio
              and (self.probe is None or self.probe.device_ok)):
            if self._dwell >= self.cfg.adaptive_dwell_windows:
                self._flip(True, "ewma", ratio=ratio)
            else:
                self.flaps_suppressed += 1
                ADAPTIVE_GAUGES["flap_suppressed_total"] = (
                    self.flaps_suppressed
                )

    def _flip(self, device_mode: bool, reason: str,
              ratio: float) -> None:
        self.device_mode = device_mode
        self.flips += 1
        self._dwell = 0
        event("window.adapt", kind="mode", mode=self.mode(),
              reason=reason, ratio=round(ratio, 4),
              window=self.windows)

    def _export(self) -> None:
        ADAPTIVE_GAUGES["device_mode"] = int(self.device_mode)
        ADAPTIVE_GAUGES["flips_total"] = self.flips
        ADAPTIVE_GAUGES["windows_observed"] = self.windows
        ADAPTIVE_GAUGES["flap_suppressed_total"] = self.flaps_suppressed
        dev = self._ewma.get("device")
        host = self._ewma.get("host")
        ADAPTIVE_GAUGES["ewma_device_hash_s"] = (
            round(dev, 9) if dev is not None else 0.0
        )
        ADAPTIVE_GAUGES["ewma_host_hash_s"] = (
            round(host, 9) if host is not None else 0.0
        )
