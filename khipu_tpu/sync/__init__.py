"""Sync drivers: chain building + regular-sync replay
(blockchain/sync/RegularSyncService.scala role, networking-free)."""

from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.sync.replay import ReplayDriver

__all__ = ["ChainBuilder", "ReplayDriver"]
