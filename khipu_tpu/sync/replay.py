"""Regular-sync replay driver: feed blocks through execution, gate every
root, keep the per-block perf line.

Parity: blockchain/sync/RegularSyncService.scala:43 —
executeAndInsertBlocks:381 (serial fold), executeAndInsertBlock:405
(validate -> execute -> save), and the one-line per-block perf report
:429 (tx/s, mgas/s, parallel %, cache hit %). Networking is replaced by
a block source (another Blockchain, or decoded RLP blocks); the
north-star replay metric (blocks/s) is measured here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional

from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.domain.transaction import recover_senders
from khipu_tpu.ledger.ledger import execute_block
from khipu_tpu.validators.validators import (
    BlockHeaderValidator,
    BlockValidator,
    OmmersValidator,
)


@dataclass
class ReplayStats:
    blocks: int = 0
    txs: int = 0
    gas: int = 0
    seconds: float = 0.0
    parallel_txs: int = 0
    conflicts: int = 0
    # per-phase wall-clock split (seconds): senders / validate / execute
    # / commit / seal / collect / save — the breakdown that names the
    # next bottleneck instead of guessing it
    phases: dict = field(default_factory=dict)

    @property
    def blocks_per_s(self) -> float:
        return self.blocks / self.seconds if self.seconds else 0.0

    def phase_line(self) -> dict:
        return {k: round(v, 3) for k, v in self.phases.items()}


class ReplayDriver:
    """Executes a stream of blocks against a target chain DB."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        log: Optional[Callable[[str], None]] = None,
        validate_headers: bool = True,
        device_commit: bool = False,
    ):
        self.blockchain = blockchain
        self.config = config
        self.log = log
        self.header_validator = BlockHeaderValidator(
            config.blockchain,
            difficulty_fn=lambda h, p: calc_difficulty(
                h.unix_timestamp, p, config.blockchain
            ),
        )
        self.validate_headers = validate_headers
        # windowed-session epoch: blocks between committer resets (see
        # replay_windowed) — bounds session memory on long replays
        self.session_epoch_blocks = 512
        # route dirty-node hashing of every block commit through the
        # batched device path (Pallas on TPU); save_block's persisted-
        # root == header.state_root check gates it per block
        if device_commit:
            from khipu_tpu.trie.bulk import device_hasher

            self.hasher = device_hasher
        else:
            self.hasher = None

    def replay(self, blocks: Iterable[Block]) -> ReplayStats:
        """executeAndInsertBlocks: serial fold with full validation."""
        window = self.config.sync.commit_window_blocks
        if window > 1:
            return self.replay_windowed(blocks, window)
        stats = ReplayStats()
        t_start = time.perf_counter()
        for block in blocks:
            self._execute_and_insert(block, stats)
        stats.seconds = time.perf_counter() - t_start
        return stats

    def replay_windowed(
        self, blocks: Iterable[Block], window_size: int
    ) -> ReplayStats:
        """Window-batched PIPELINED replay: execute W blocks against one
        open deferred session, seal the window (pack + async device
        dispatch of the fused fixpoint), then execute the NEXT window's
        transactions on the host while the device resolves the previous
        one — the double-buffering that hides the device round-trip
        behind host execution (SURVEY §7.4-5; the reference overlaps
        execution with persistence the same way via its actor mailbox,
        RegularSyncService.scala:381). Root checks happen at collect —
        one window later than the serial path, with identical failure
        semantics (nothing of a window persists before its roots pass).
        """
        from collections import deque

        from khipu_tpu.evm.config import for_block
        from khipu_tpu.ledger.window import WindowCommitter
        from khipu_tpu.trie.bulk import host_hasher

        stats = ReplayStats()
        ph = stats.phases
        for k in ("senders", "validate", "execute", "commit", "seal",
                  "collect", "save"):
            ph[k] = 0.0
        t_start = time.perf_counter()
        hasher = self.hasher or host_hasher
        blocks = iter(blocks)
        try:
            first = next(blocks)
        except StopIteration:
            return stats

        parent = self.blockchain.get_header_by_number(first.number - 1)
        window_headers = {}
        window_headers_full = {}
        window_blocks = {}

        def block_hash_of(n: int):
            h = window_headers.get(n)
            return h if h else self.blockchain.get_hash_by_number(n)

        def make_committer(parent_root: bytes) -> WindowCommitter:
            return WindowCommitter(
                self.blockchain.storages,
                parent_root,
                hasher=hasher,
                account_start_nonce=(
                    self.config.blockchain.account_start_nonce
                ),
                get_block_hash=block_hash_of,
                # device mode: one-dispatch fixpoint finalize — the
                # per-level hasher loop would pay O(levels) tunnel
                # round-trips per window (docs/roofline.md)
                fused=self.hasher is not None,
            )

        committer = make_committer(parent.state_root)
        in_flight: deque = deque()  # (WindowJob, [(block, result)])
        # epoch reset: every N blocks the session committer is rebuilt
        # from the last VALIDATED root, dropping the resolved-
        # placeholder map and all retained refs — with the per-collect
        # staged prune this bounds replay memory to O(epoch), not
        # O(chain) (the reference's analog is its bounded node cache +
        # persisted store)
        epoch = self.session_epoch_blocks
        blocks_since_reset = 0

        def collect_one():
            job, results = in_flight.popleft()
            t0 = time.perf_counter()
            committer.collect(job)  # raises WindowMismatch on divergence
            ph["collect"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            for block, result in results:
                td = (
                    self.blockchain.get_total_difficulty(block.number - 1)
                    or 0
                ) + block.header.difficulty
                # world=None: the window already persisted the nodes
                self.blockchain.save_block(
                    block, result.receipts, td, world=None
                )
                stats.blocks += 1
                stats.txs += result.stats.tx_count
                stats.gas += result.gas_used
                stats.parallel_txs += result.stats.parallel_count
                stats.conflicts += result.stats.conflict_count
            ph["save"] += time.perf_counter() - t0
            if self.log is not None:
                self.log(
                    f"Committed window [{results[0][0].number}.."
                    f"{results[-1][0].number}] ({len(results)} blocks) "
                    "in one batched device pass"
                )

        results_cur: List = []
        prev = parent
        import itertools

        for block in itertools.chain((first,), blocks):
            header = block.header
            t0 = time.perf_counter()
            # batch-recover + cache every sender in one native call
            recover_senders(block.body.transactions)
            ph["senders"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            if self.validate_headers:
                self.header_validator.validate(header, prev)
            BlockValidator.validate_body(block)
            OmmersValidator.validate(
                self.blockchain, block,
                header_lookup=window_headers_full.get,
                block_lookup=window_blocks.get,
                header_validator=(
                    self.header_validator
                    if self.validate_headers else None
                ),
            )
            config = for_block(header.number, self.config.blockchain)
            if not config.byzantium:
                raise ValueError(
                    "window commits need Byzantium receipts "
                    "(pre-Byzantium receipts embed per-tx roots)"
                )
            ph["validate"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            result = execute_block(
                block,
                b"",  # the open session IS the parent state
                committer.make_world,
                self.config,
                validate=True,
                check_root=False,  # deferred to window finalize
            )
            ph["execute"] += time.perf_counter() - t0
            t0 = time.perf_counter()
            committer.commit_block(result.world, header)
            ph["commit"] += time.perf_counter() - t0
            window_headers[header.number] = header.hash
            window_headers_full[header.number] = header
            window_blocks[header.number] = block
            results_cur.append((block, result))
            prev = header
            if len(results_cur) >= window_size:
                # the PREVIOUS window must be collected before seal:
                # seal substitutes its resolved hashes into this one
                while in_flight:
                    collect_one()
                blocks_since_reset += len(results_cur)
                t0 = time.perf_counter()
                in_flight.append((committer.seal(), results_cur))
                ph["seal"] += time.perf_counter() - t0
                results_cur = []
                if blocks_since_reset >= epoch:
                    # collect the just-sealed window, then restart the
                    # session from its validated root (memory bound)
                    while in_flight:
                        collect_one()
                    committer = make_committer(prev.state_root)
                    blocks_since_reset = 0
                    # header/body maps: ommers reach back 6 ancestors,
                    # BLOCKHASH 256 — prune beyond that
                    for d, keep in (
                        (window_headers, 260),
                        (window_headers_full, 8),
                        (window_blocks, 8),
                    ):
                        for n in sorted(d)[:-keep]:
                            del d[n]
        while in_flight:
            collect_one()
        if results_cur:
            t0 = time.perf_counter()
            job = committer.seal()
            ph["seal"] += time.perf_counter() - t0
            in_flight.append((job, results_cur))
            collect_one()
        stats.seconds = time.perf_counter() - t_start
        return stats

    def _execute_and_insert(self, block: Block, stats: ReplayStats) -> None:
        header = block.header
        parent = self.blockchain.get_header_by_number(header.number - 1)
        if parent is None:
            raise ValueError(f"no parent for block {header.number}")
        if self.validate_headers:
            self.header_validator.validate(header, parent)
        BlockValidator.validate_body(block)
        OmmersValidator.validate(
            self.blockchain, block,
            header_validator=(
                self.header_validator if self.validate_headers else None
            ),
        )

        t0 = time.perf_counter()
        result = execute_block(
            block,
            parent.state_root,
            self.blockchain.get_world_state,
            self.config,
            validate=True,
            hasher=self.hasher,  # root check + persist share one flush
        )
        td = (
            self.blockchain.get_total_difficulty(parent.number) or 0
        ) + header.difficulty
        self.blockchain.save_block(
            block, result.receipts, td, result.world, hasher=self.hasher
        )
        dt = time.perf_counter() - t0

        stats.blocks += 1
        stats.txs += result.stats.tx_count
        stats.gas += result.gas_used
        stats.parallel_txs += result.stats.parallel_count
        stats.conflicts += result.stats.conflict_count

        if self.log is not None:
            # RegularSyncService.scala:429 one-line format
            ntx = result.stats.tx_count
            self.log(
                f"Executed #{header.number} ({block.hash[:4].hex()}) "
                f"{ntx} txs in {dt * 1000:.1f}ms, "
                f"{ntx / dt if dt else 0:.1f} tx/s, "
                f"{result.gas_used / dt / 1e6 if dt else 0:.2f} mgas/s, "
                f"parallel {result.stats.parallel_rate * 100:.0f}%, "
                f"cache hit "
                f"{self.blockchain.storages.account_node_storage.cache_hit_rate * 100:.0f}%"
            )
