"""Regular-sync replay driver: feed blocks through execution, gate every
root, keep the per-block perf line.

Parity: blockchain/sync/RegularSyncService.scala:43 —
executeAndInsertBlocks:381 (serial fold), executeAndInsertBlock:405
(validate -> execute -> save), and the one-line per-block perf report
:429 (tx/s, mgas/s, parallel %, cache hit %). Networking is replaced by
a block source (another Blockchain, or decoded RLP blocks); the
north-star replay metric (blocks/s) is measured here.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional

from khipu_tpu.chaos import InjectedDeath, fault_point
from khipu_tpu.chaos import apply_config as apply_fault_config
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.domain.transaction import recover_senders
from khipu_tpu.ledger.ledger import execute_block
from khipu_tpu.observability.profiler import HOST, LEDGER
from khipu_tpu.observability.registry import REGISTRY
from khipu_tpu.observability.trace import (
    Tracer,
    apply_config,
    event,
    span,
    use_tracer,
)
from khipu_tpu.observability.trace import tracer as _default_tracer
from khipu_tpu.validators.validators import (
    BlockHeaderValidator,
    BlockValidator,
    OmmersValidator,
)

# live window-pipeline gauges served by the khipu_metrics RPC
# (jsonrpc/eth_service.py), registered as khipu_pipeline_* in the
# unified registry. The GaugeGroup keeps dict-style writes — a gauge
# set is one attribute store, so the collector thread and the driver
# both update them in place exactly as the plain dict allowed.
PIPELINE_GAUGES = REGISTRY.gauge_group("khipu_pipeline", {
    "depth": 0,  # configured pipeline_depth of the last run
    "in_flight": 0,  # windows sealed but not yet collected
    "windows_sealed": 0,
    "windows_collected": 0,
    "occupancy": 0.0,  # driver/collector overlap fraction, last run
    "driver_stall_s": 0.0,  # driver seconds blocked on backpressure
    "collector_busy_s": 0.0,  # background collect+save busy seconds
    "collector_deaths": 0,  # dead workers detected by liveness checks
    "sync_fallback_windows": 0,  # windows committed synchronously after
    # a collector death (graceful degradation — docs/recovery.md)
}, help="window-pipeline state (sync/replay.py)")


class CollectorDied(RuntimeError):
    """The background collector thread is no longer alive but never
    recorded a failure — a simulated (chaos ``die``) or real
    (interpreter-level) death mid-job. Detected by the timed liveness
    checks in submit/drain instead of hanging on the condition
    variable forever."""


@dataclass
class ReplayStats:
    blocks: int = 0
    txs: int = 0
    gas: int = 0
    seconds: float = 0.0
    parallel_txs: int = 0
    conflicts: int = 0
    # per-phase wall-clock split (seconds): senders / validate / execute
    # / commit / seal / collect / save — the breakdown that names the
    # next bottleneck instead of guessing it. Under the deep pipeline
    # `collect`/`save` are DRIVER-THREAD STALL (backpressure + drains);
    # the background collector's busy time lands in `collect_bg` /
    # `save_bg` (it overlaps execute, so adding it to wall clock would
    # double-count)
    phases: dict = field(default_factory=dict)
    # fraction of the collector's busy time that overlapped driver work
    # (1.0 = collect/save fully hidden behind execution)
    pipeline_occupancy: float = 0.0

    @property
    def blocks_per_s(self) -> float:
        return self.blocks / self.seconds if self.seconds else 0.0

    def phase_line(self) -> dict:
        return {k: round(v, 3) for k, v in self.phases.items()}


class _WindowCollector:
    """Bounded background collector: root checks + live-node/code
    persistence + block saves run HERE while the driver executes the
    next window's transactions. ``submit`` enqueues one collect+save
    closure and blocks only while ``depth`` jobs are already queued or
    running (backpressure); ``drain`` blocks until the pipeline is
    empty. Jobs run strictly FIFO on one thread — block saves chain
    total difficulty, and window N+1's encodings resolve through
    window N's published hashes (ledger/window.collect docstring).

    Failure semantics: the FIRST exception (typically WindowMismatch)
    aborts the pipeline — queued jobs are dropped WITHOUT persisting
    anything and the original exception object re-raises on the driver
    thread at its next submit/drain, so a mismatch still names the
    failing block number."""

    def __init__(self, depth: int, join_timeout: float = 60.0,
                 liveness_poll: float = 0.1):
        self.depth = max(1, depth)
        self.busy_seconds = 0.0
        self.join_timeout = join_timeout
        # backpressure/drain waits wake at this period to re-check the
        # worker is still alive — a dead thread can never notify, so an
        # untimed wait would hang the driver forever
        self.liveness_poll = liveness_poll
        self._cv = threading.Condition()
        self._q: deque = deque()
        self._active = False
        self._current: Optional[Callable[[], None]] = None
        self._failure: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="window-collector", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------- driver side

    def _check_liveness(self) -> None:
        """Call under ``_cv``. A worker that exited without recording a
        failure and without being closed died mid-job (chaos ``die`` or
        a real interpreter-level death) — raise instead of waiting on
        notifies that will never come."""
        if (self._failure is None and not self._closed
                and not self._thread.is_alive()):
            raise CollectorDied(
                "window-collector thread died mid-job "
                f"({len(self._q)} queued, active={self._active})"
            )

    def submit(self, fn: Callable[[], None]) -> float:
        """Queue one job; returns driver seconds stalled on
        backpressure. Re-raises the collector's failure, if any;
        raises CollectorDied when the worker is gone."""
        t0 = time.perf_counter()
        with self._cv:
            self._check_liveness()
            while (self._failure is None and not self._closed
                   and len(self._q) + self._active >= self.depth):
                self._cv.wait(timeout=self.liveness_poll)
                self._check_liveness()
            if self._failure is not None:
                raise self._failure
            if self._closed:
                raise RuntimeError("collector is closed")
            self._q.append(fn)
            PIPELINE_GAUGES["windows_sealed"] += 1
            PIPELINE_GAUGES["in_flight"] = len(self._q) + self._active
            self._cv.notify_all()
        return time.perf_counter() - t0

    def drain(self) -> float:
        """Wait until every queued job has completed; returns driver
        seconds stalled. Re-raises the collector's failure, if any;
        raises CollectorDied when the worker is gone."""
        t0 = time.perf_counter()
        with self._cv:
            self._check_liveness()
            while self._failure is None and (self._q or self._active):
                self._cv.wait(timeout=self.liveness_poll)
                self._check_liveness()
            if self._failure is not None:
                raise self._failure
        return time.perf_counter() - t0

    def take_pending(self) -> List[Callable[[], None]]:
        """After CollectorDied: the dead worker's unfinished jobs in
        FIFO order — the partially-executed current job FIRST (jobs are
        idempotent: node puts are content-addressed, block saves
        overwrite by number, stats apply only at job end). Marks the
        collector closed; the caller runs these synchronously."""
        with self._cv:
            fns: List[Callable[[], None]] = []
            if self._active and self._current is not None:
                fns.append(self._current)
            fns.extend(self._q)
            self._q.clear()
            self._closed = True
            PIPELINE_GAUGES["in_flight"] = 0
            self._cv.notify_all()
        return fns

    def close(self) -> None:
        """Stop the worker (after finishing anything queued) and join.
        Safe to call twice. Raises if the worker is still alive after
        ``join_timeout`` — a wedged job must not be silently abandoned
        with the pipeline's windows unaccounted for."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=self.join_timeout)
        if self._thread.is_alive():
            raise RuntimeError(
                "window-collector failed to stop within "
                f"{self.join_timeout:.0f}s — a wedged job is still "
                "holding the pipeline (its windows are NOT committed)"
            )

    def kill(self) -> None:
        """Abort: drop queued jobs WITHOUT running them (nothing else
        persists) and join. The driver calls this when IT failed —
        windows sealed after the failing block must not be committed.
        Already unwinding, so a wedged worker is logged loudly instead
        of raised over the original failure."""
        with self._cv:
            self._q.clear()
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=self.join_timeout)
        if self._thread.is_alive():
            import sys

            print(
                "WARNING: window-collector did not stop within "
                f"{self.join_timeout:.0f}s of kill(); abandoning the "
                "wedged daemon thread",
                file=sys.stderr,
            )

    # ------------------------------------------------------- worker side

    def _run(self) -> None:
        while True:
            with self._cv:
                while (not self._q and not self._closed
                       and self._failure is None):
                    self._cv.wait()
                if self._failure is not None or (
                    self._closed and not self._q
                ):
                    return
                fn = self._q.popleft()
                self._current = fn
                self._active = True
                PIPELINE_GAUGES["in_flight"] = len(self._q) + 1
            t0 = time.perf_counter()
            try:
                fn()
            except InjectedDeath:
                # simulated process death (chaos `die`): no failure
                # record, no notify — the thread just stops with the
                # job half done, exactly like a SIGKILL. The driver's
                # liveness checks raise CollectorDied; _current stays
                # set so take_pending can re-run the torn job.
                return
            # khipu-lint: ok KL002 InjectedDeath is handled by the
            # dedicated handler above (thread stops, SIGKILL
            # semantics); everything else is RECORDED as _failure and
            # re-raised on the driver by submit()/drain() — fail-stop
            # is preserved, not swallowed
            except BaseException as exc:  # surfaces on the driver
                with self._cv:
                    self._failure = exc
                    self._active = False
                    self._current = None
                    self._q.clear()  # abort: NOTHING else persists
                    PIPELINE_GAUGES["in_flight"] = 0
                    self._cv.notify_all()
                return
            dt = time.perf_counter() - t0
            with self._cv:
                self.busy_seconds += dt
                self._active = False
                self._current = None
                PIPELINE_GAUGES["windows_collected"] += 1
                PIPELINE_GAUGES["in_flight"] = len(self._q)
                PIPELINE_GAUGES["collector_busy_s"] = self.busy_seconds
                self._cv.notify_all()


class ReplayDriver:
    """Executes a stream of blocks against a target chain DB."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        log: Optional[Callable[[str], None]] = None,
        validate_headers: bool = True,
        device_commit: bool = False,
        tracer: Optional[Tracer] = None,
        read_view=None,
    ):
        self.blockchain = blockchain
        self.config = config
        # serving-plane read view (serving/readview.py): committed
        # blocks publish their account diffs into it on the driver
        # thread, durable windows retire them on the collector thread,
        # and a pipeline abort invalidates everything above the
        # committed best — RPC reads stay monotonic mid-pipeline
        self.read_view = read_view
        # per-driver recorder: a driver handed its own Tracer (e.g. the
        # bridge server's — bridge.py) records there; the default stays
        # the module-global instance so single-driver processes and the
        # existing khipu_traces surface are unchanged
        self.tracer = tracer if tracer is not None else _default_tracer
        apply_config(config.observability, self.tracer)
        apply_fault_config(getattr(config, "faults", None))
        self.log = log
        self.header_validator = BlockHeaderValidator(
            config.blockchain,
            difficulty_fn=lambda h, p: calc_difficulty(
                h.unix_timestamp, p, config.blockchain
            ),
        )
        self.validate_headers = validate_headers
        # windowed-session epoch: blocks between committer resets (see
        # replay_windowed) — bounds session memory on long replays
        self.session_epoch_blocks = 512
        # route dirty-node hashing of every block commit through the
        # batched device path (Pallas on TPU); save_block's persisted-
        # root == header.state_root check gates it per block
        if device_commit:
            from khipu_tpu.trie.bulk import device_hasher

            self.hasher = device_hasher
        else:
            self.hasher = None

    def recover(self):
        """Crash-recovery startup pass (sync/journal.py): settle every
        pending window-commit intent — repair complete windows, roll
        back partial ones. Returns a RecoveryReport."""
        from khipu_tpu.sync.journal import recover

        return recover(self.blockchain, log=self.log)

    def replay(self, blocks: Iterable[Block]) -> ReplayStats:
        """executeAndInsertBlocks: serial fold with full validation."""
        window = self.config.sync.commit_window_blocks
        if window > 1:
            return self.replay_windowed(blocks, window)
        stats = ReplayStats()
        t_start = time.perf_counter()
        with use_tracer(self.tracer):
            for block in blocks:
                self._execute_and_insert(block, stats)
        stats.seconds = time.perf_counter() - t_start
        return stats

    def replay_windowed(
        self, blocks: Iterable[Block], window_size: int
    ) -> ReplayStats:
        """Window-batched PIPELINED replay: runs with THIS driver's
        tracer active on the calling thread (collector jobs re-activate
        it on theirs — the tracer rides the closure like ``seal_tok``),
        so concurrent drivers in one process record to disjoint rings.
        See ``_replay_windowed`` for the pipeline itself."""
        with use_tracer(self.tracer):
            return self._replay_windowed(blocks, window_size)

    def _replay_windowed(
        self, blocks: Iterable[Block], window_size: int
    ) -> ReplayStats:
        """Window-batched PIPELINED replay: execute W blocks against one
        open deferred session, seal the window (pack + async device
        dispatch of the fused fixpoint), then execute the NEXT window's
        transactions on the host while the device resolves the previous
        one — the double-buffering that hides the device round-trip
        behind host execution (SURVEY §7.4-5; the reference overlaps
        execution with persistence the same way via its actor mailbox,
        RegularSyncService.scala:381). Root checks happen at collect —
        up to ``pipeline_depth`` windows later than the serial path, on
        a background collector thread, with identical failure semantics
        (nothing of a window persists before its roots pass; a
        WindowMismatch drains the pipeline and re-raises here with the
        failing block number — docs/window_pipeline.md).
        """
        from khipu_tpu.evm.config import for_block
        from khipu_tpu.ledger.window import WindowCommitter
        from khipu_tpu.trie.bulk import host_hasher

        stats = ReplayStats()
        ph = stats.phases
        for k in ("senders", "validate", "execute", "commit", "seal",
                  "collect", "save", "collect_bg", "save_bg"):
            ph[k] = 0.0
        t_start = time.perf_counter()
        hasher = self.hasher or host_hasher
        blocks = iter(blocks)
        try:
            first = next(blocks)
        except StopIteration:
            return stats

        parent = self.blockchain.get_header_by_number(first.number - 1)
        window_headers = {}
        window_headers_full = {}
        window_blocks = {}

        def block_hash_of(n: int):
            h = window_headers.get(n)
            return h if h else self.blockchain.get_hash_by_number(n)

        def make_committer(parent_root: bytes) -> WindowCommitter:
            return WindowCommitter(
                self.blockchain.storages,
                parent_root,
                hasher=hasher,
                account_start_nonce=(
                    self.config.blockchain.account_start_nonce
                ),
                get_block_hash=block_hash_of,
                # device mode: one-dispatch fixpoint finalize — the
                # per-level hasher loop would pay O(levels) tunnel
                # round-trips per window (docs/roofline.md)
                fused=self.hasher is not None,
                on_block_committed=(
                    self.read_view.publish_block
                    if self.read_view is not None else None
                ),
            )

        committer = make_committer(parent.state_root)
        depth = max(1, self.config.sync.pipeline_depth)
        collector = _WindowCollector(
            depth, join_timeout=self.config.sync.collector_join_timeout
        )
        PIPELINE_GAUGES["depth"] = depth
        # crash consistency: WAL intent before each background job, a
        # commit mark after its best-number advance (docs/recovery.md)
        journal = (
            self.blockchain.storages.window_journal
            if self.config.sync.commit_journal else None
        )
        window_parent_root = parent.state_root
        # graceful degradation: a dead collector thread (CollectorDied
        # from the liveness checks) switches the driver to synchronous
        # commits instead of aborting — unless config says abort
        sync_degraded = False
        degrade_on_death = self.config.sync.degrade_on_collector_death

        def _degrade() -> None:
            nonlocal sync_degraded
            sync_degraded = True
            PIPELINE_GAUGES["collector_deaths"] += 1
            event("pipeline.degrade", reason="collector-died")
            if self.log is not None:
                self.log(
                    "window-collector thread died; degrading to "
                    "synchronous window commits (jobs are idempotent "
                    "— re-running the torn one)"
                )
            for fn in collector.take_pending():
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                fn()

        def submit_job(run_fn) -> float:
            if sync_degraded:
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                run_fn()
                if journal is not None:
                    journal.prune()
                return 0.0
            try:
                return collector.submit(run_fn)
            except CollectorDied:
                if not degrade_on_death:
                    raise
                _degrade()
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                run_fn()
                return 0.0

        def drain_pipeline() -> float:
            # with the pipeline empty every intent is settled: drop the
            # committed prefix so the journal stays O(pipeline_depth),
            # not O(chain)
            if sync_degraded:
                if journal is not None:
                    journal.prune()
                return 0.0
            try:
                stall = collector.drain()
            except CollectorDied:
                if not degrade_on_death:
                    raise
                _degrade()
                return 0.0
            if journal is not None:
                journal.prune()
            return stall
        # epoch reset: every N blocks the session committer is rebuilt
        # from the last VALIDATED root, dropping the resolved-
        # placeholder map and all retained refs — with the per-collect
        # staged prune this bounds replay memory to O(epoch), not
        # O(chain) (the reference's analog is its bounded node cache +
        # persisted store)
        epoch = self.session_epoch_blocks
        blocks_since_reset = 0

        def make_collect_job(cm: WindowCommitter, job, results, seal_tok,
                             intent_seq):
            # runs ON THE COLLECTOR THREAD, strictly FIFO. ``seal_tok``
            # (the driver's window.seal span id) rides the closure across
            # the queue so the trace links the collector's spans to the
            # seal that produced them (the cross-thread parent edge —
            # flow arrows in the Chrome dump)
            lo, hi = results[0][0].number, results[-1][0].number
            tr = self.tracer

            def run():
                # the driver's tracer rides the closure: the collector
                # thread has no thread-local binding of its own, and
                # falling back to the module default would split one
                # driver's trace across two rings
                with use_tracer(tr):
                    _run()

            def _run():
                # chaos seams: a rule at any of the collector.* sites
                # models a failure/death at that phase of the job
                # (docs/recovery.md crash-point table)
                fault_point("collector.collect")
                t0 = time.perf_counter()
                with span("window.collect", parent=seal_tok,
                          block_lo=lo, block_hi=hi), \
                        LEDGER.context(window=lo, phase="collect"):
                    cm.collect(job)  # raises WindowMismatch on divergence
                t1 = time.perf_counter()
                fault_point("collector.persist")
                blocks = txs = gas = ptxs = confl = 0
                with span("window.persist", parent=seal_tok,
                          block_lo=lo, block_hi=hi, blocks=len(results)), \
                        LEDGER.context(window=lo, phase="persist"):
                    for block, result in results:
                        td = (
                            self.blockchain.get_total_difficulty(
                                block.number - 1
                            )
                            or 0
                        ) + block.header.difficulty
                        # world=None: the window already persisted the
                        # nodes
                        t_save = time.perf_counter()
                        self.blockchain.save_block(
                            block, result.receipts, td, world=None
                        )
                        # host-side persistence: classification traffic
                        # for window_report, never a device crossing
                        LEDGER.record(
                            "block.save", HOST, 0,
                            duration=time.perf_counter() - t_save,
                        )
                        fault_point("collector.save")
                        blocks += 1
                        txs += result.stats.tx_count
                        gas += result.gas_used
                        ptxs += result.stats.parallel_count
                        confl += result.stats.conflict_count
                    # the commit mark is the job's LAST mutation, and
                    # it is persistence work: keeping it inside the
                    # persist span keeps span-recomputed occupancy in
                    # agreement with the busy-seconds gauge
                    if intent_seq is not None:
                        fault_point("collector.commit")
                        journal.log_commit(intent_seq)
                    if self.log is not None:
                        self.log(
                            f"Committed window [{lo}..{hi}] "
                            f"({len(results)} blocks) in one batched "
                            "device pass"
                        )
                    # stats land ONLY here, after the commit mark: a
                    # torn job re-run after a collector death stays
                    # idempotent — no double counting (nothing below
                    # can raise before they apply)
                    stats.blocks += blocks
                    stats.txs += txs
                    stats.gas += gas
                    stats.parallel_txs += ptxs
                    stats.conflicts += confl
                    LEDGER.note_blocks(blocks)
                # the window is durable (best advanced, commit mark
                # down): the committed store now serves same-or-newer
                # state, so the read-view overlay can let go of it
                if self.read_view is not None:
                    self.read_view.retire_through(hi)
                t2 = time.perf_counter()
                ph["collect_bg"] += t1 - t0
                ph["save_bg"] += t2 - t1

            return run

        def seal_and_submit() -> None:
            nonlocal results_cur, window_parent_root
            lo = results_cur[0][0].number
            hi = results_cur[-1][0].number
            t0 = time.perf_counter()
            intent_seq = None
            LEDGER.note_window(lo, lo, hi)
            with span("window.seal", block_lo=lo, block_hi=hi) as seal_sp, \
                    LEDGER.context(window=lo, phase="seal"):
                job = committer.seal()
                if journal is not None:
                    # WAL barrier: the intent is durable BEFORE the job
                    # can run (submit enqueues it strictly afterwards).
                    # It is part of sealing — inside the span, so the
                    # driver phase accounting sees the journal cost.
                    intent_seq = journal.log_intent(
                        lo, hi, window_parent_root,
                        [b.header.state_root for b, _ in results_cur],
                    )
            ph["seal"] += time.perf_counter() - t0
            run_fn = make_collect_job(
                committer, job, results_cur, seal_sp.token, intent_seq
            )
            with span("pipeline.stall", block_lo=lo, block_hi=hi,
                      kind="submit"):
                ph["collect"] += submit_job(run_fn)
            window_parent_root = results_cur[-1][0].header.state_root
            results_cur = []

        results_cur: List = []
        prev = parent
        import itertools

        try:
            for block in itertools.chain((first,), blocks):
                header = block.header
                with span(
                    "window.build",
                    block=header.number,
                    txs=len(block.body.transactions),
                ):
                    t0 = time.perf_counter()
                    # batch-recover + cache every sender in one native
                    # call
                    recover_senders(block.body.transactions)
                    ph["senders"] += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    if self.validate_headers:
                        self.header_validator.validate(header, prev)
                    BlockValidator.validate_body(block)
                    OmmersValidator.validate(
                        self.blockchain, block,
                        header_lookup=window_headers_full.get,
                        block_lookup=window_blocks.get,
                        header_validator=(
                            self.header_validator
                            if self.validate_headers else None
                        ),
                    )
                    config = for_block(
                        header.number, self.config.blockchain
                    )
                    if not config.byzantium:
                        raise ValueError(
                            "window commits need Byzantium receipts "
                            "(pre-Byzantium receipts embed per-tx roots)"
                        )
                    ph["validate"] += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    result = execute_block(
                        block,
                        b"",  # the open session IS the parent state
                        committer.make_world,
                        self.config,
                        validate=True,
                        check_root=False,  # deferred to window finalize
                    )
                    ph["execute"] += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    committer.commit_block(result.world, header)
                    ph["commit"] += time.perf_counter() - t0
                window_headers[header.number] = header.hash
                window_headers_full[header.number] = header
                window_blocks[header.number] = block
                results_cur.append((block, result))
                prev = header
                if len(results_cur) >= window_size:
                    # NO barrier before seal: cross-window refs resolve
                    # from the in-flight jobs' device digests (resolved-
                    # input tiles); the only wait is submit backpressure
                    # once pipeline_depth windows are queued
                    blocks_since_reset += len(results_cur)
                    seal_and_submit()
                    if blocks_since_reset >= epoch:
                        # drain the pipeline, then restart the session from
                        # the last validated root (memory bound)
                        with span("pipeline.stall", kind="epoch-drain"):
                            stalled = drain_pipeline()
                        ph["collect"] += stalled
                        committer = make_committer(prev.state_root)
                        blocks_since_reset = 0
                        # header/body maps: ommers reach back 6 ancestors,
                        # BLOCKHASH 256 — prune beyond that
                        for d, keep in (
                            (window_headers, 260),
                            (window_headers_full, 8),
                            (window_blocks, 8),
                        ):
                            for n in sorted(d)[:-keep]:
                                del d[n]
            if results_cur:
                seal_and_submit()
            with span("pipeline.stall", kind="final-drain"):
                stalled = drain_pipeline()
            ph["collect"] += stalled
        except BaseException:
            # a driver-side failure (validation, execution, or a
            # re-raised collector failure) aborts the pipeline:
            # queued windows are dropped WITHOUT persisting
            collector.kill()
            # un-durable overlay state must die with the windows that
            # produced it — reads fall back to the committed store
            # (never a torn window)
            if self.read_view is not None:
                self.read_view.invalidate_above(
                    self.blockchain.best_block_number
                )
            raise
        collector.close()
        stats.seconds = time.perf_counter() - t_start
        # overlap fraction: collector busy seconds NOT spent with the
        # driver blocked on it ((C - stall)/C) — 1.0 means collect+save
        # were fully hidden behind host execution
        stall = ph["collect"] + ph["save"]
        busy = collector.busy_seconds
        occ = (
            max(0.0, min(1.0, (busy - stall) / busy)) if busy > 0 else 0.0
        )
        stats.pipeline_occupancy = occ
        PIPELINE_GAUGES["occupancy"] = round(occ, 4)
        PIPELINE_GAUGES["driver_stall_s"] = round(stall, 3)
        return stats

    def _execute_and_insert(self, block: Block, stats: ReplayStats) -> None:
        header = block.header
        parent = self.blockchain.get_header_by_number(header.number - 1)
        if parent is None:
            raise ValueError(f"no parent for block {header.number}")
        if self.validate_headers:
            self.header_validator.validate(header, parent)
        BlockValidator.validate_body(block)
        OmmersValidator.validate(
            self.blockchain, block,
            header_validator=(
                self.header_validator if self.validate_headers else None
            ),
        )

        t0 = time.perf_counter()
        result = execute_block(
            block,
            parent.state_root,
            self.blockchain.get_world_state,
            self.config,
            validate=True,
            hasher=self.hasher,  # root check + persist share one flush
        )
        td = (
            self.blockchain.get_total_difficulty(parent.number) or 0
        ) + header.difficulty
        self.blockchain.save_block(
            block, result.receipts, td, result.world, hasher=self.hasher
        )
        dt = time.perf_counter() - t0

        stats.blocks += 1
        stats.txs += result.stats.tx_count
        stats.gas += result.gas_used
        stats.parallel_txs += result.stats.parallel_count
        stats.conflicts += result.stats.conflict_count

        if self.log is not None:
            # RegularSyncService.scala:429 one-line format
            ntx = result.stats.tx_count
            self.log(
                f"Executed #{header.number} ({block.hash[:4].hex()}) "
                f"{ntx} txs in {dt * 1000:.1f}ms, "
                f"{ntx / dt if dt else 0:.1f} tx/s, "
                f"{result.gas_used / dt / 1e6 if dt else 0:.2f} mgas/s, "
                f"parallel {result.stats.parallel_rate * 100:.0f}%, "
                f"cache hit "
                f"{self.blockchain.storages.account_node_storage.cache_hit_rate * 100:.0f}%"
            )
