"""Regular-sync replay driver: feed blocks through execution, gate every
root, keep the per-block perf line.

Parity: blockchain/sync/RegularSyncService.scala:43 —
executeAndInsertBlocks:381 (serial fold), executeAndInsertBlock:405
(validate -> execute -> save), and the one-line per-block perf report
:429 (tx/s, mgas/s, parallel %, cache hit %). Networking is replaced by
a block source (another Blockchain, or decoded RLP blocks); the
north-star replay metric (blocks/s) is measured here.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional

from khipu_tpu.chaos import InjectedDeath, fault_point
from khipu_tpu.chaos import apply_config as apply_fault_config
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.ledger.ledger import execute_block
from khipu_tpu.sync.prefetch import recover_block_senders
from khipu_tpu.observability.journey import JOURNEY, current_node
from khipu_tpu.observability.profiler import HOST, LEDGER
from khipu_tpu.observability.registry import REGISTRY
from khipu_tpu.observability.trace import (
    Tracer,
    apply_config,
    event,
    span,
    use_tracer,
)
from khipu_tpu.observability.trace import tracer as _default_tracer
from khipu_tpu.validators.validators import (
    BlockHeaderValidator,
    BlockValidator,
    OmmersValidator,
)

# live window-pipeline gauges served by the khipu_metrics RPC
# (jsonrpc/eth_service.py), registered as khipu_pipeline_* in the
# unified registry. The GaugeGroup keeps dict-style writes — a gauge
# set is one attribute store, so the collector thread and the driver
# both update them in place exactly as the plain dict allowed.
PIPELINE_GAUGES = REGISTRY.gauge_group("khipu_pipeline", {
    "depth": 0,  # configured pipeline_depth of the last run
    "in_flight": 0,  # windows sealed but not yet fully saved
    "windows_sealed": 0,
    "windows_collected": 0,
    "occupancy": 0.0,  # driver/collector overlap fraction, last run
    "driver_stall_s": 0.0,  # driver seconds blocked on backpressure
    "collector_busy_s": 0.0,  # background stage busy seconds (all)
    "collector_deaths": 0,  # dead workers detected by liveness checks
    "sync_fallback_windows": 0,  # windows committed synchronously after
    # a collector death (graceful degradation — docs/recovery.md)
    # per-stage occupancy/depth of the staged collector pipeline
    # (seal -> collect -> persist -> save; docs/window_pipeline.md)
    "stage_seal_depth": 0,
    "stage_collect_depth": 0,
    "stage_persist_depth": 0,
    "stage_save_depth": 0,
    "stage_seal_busy_s": 0.0,
    "stage_collect_busy_s": 0.0,
    "stage_persist_busy_s": 0.0,
    "stage_save_busy_s": 0.0,
}, help="window-pipeline state (sync/replay.py)")


class CollectorDied(RuntimeError):
    """The background collector thread is no longer alive but never
    recorded a failure — a simulated (chaos ``die``) or real
    (interpreter-level) death mid-job. Detected by the timed liveness
    checks in submit/drain instead of hanging on the condition
    variable forever."""


@dataclass
class ReplayStats:
    blocks: int = 0
    txs: int = 0
    gas: int = 0
    seconds: float = 0.0
    parallel_txs: int = 0
    conflicts: int = 0
    # execute-stage split (ledger/schedule.py): txs through the
    # vectorized fast path vs the serial residue, and scheduled
    # attempts discarded by the post-hoc footprint check
    fast_path_txs: int = 0
    residue_txs: int = 0
    mispredictions: int = 0
    # per-phase wall-clock split (seconds): senders / validate / execute
    # / commit / seal / collect / save — the breakdown that names the
    # next bottleneck instead of guessing it. Under the deep pipeline
    # `seal` is the driver's cheap close-out + journal fsync and
    # `collect`/`save` are DRIVER-THREAD STALL (backpressure + drains);
    # the staged collector's busy time lands in `seal_bg` (pack +
    # dispatch build + upload) / `collect_bg` (root checks + mirror
    # admit) / `persist_bg` (async host spill) / `save_bg` (block
    # saves) — those overlap execute, so adding them to wall clock
    # would double-count
    phases: dict = field(default_factory=dict)
    # fraction of the collector's busy time that overlapped driver work
    # (1.0 = collect/save fully hidden behind execution)
    pipeline_occupancy: float = 0.0
    # persist-stage store traffic (WindowCommitter always-on counters):
    # node bytes + keys landed in the host store and the seconds the
    # store writes took — bench.py derives persist_bytes_per_sec from
    # these on every replay metric line
    persist_bytes: int = 0
    persist_store_seconds: float = 0.0

    @property
    def blocks_per_s(self) -> float:
        return self.blocks / self.seconds if self.seconds else 0.0

    @property
    def persist_bytes_per_sec(self) -> float:
        """Persist-stage store throughput (bytes landed per second of
        store-write time — the number the Kesque engine moves)."""
        if self.persist_store_seconds <= 0.0:
            return 0.0
        return self.persist_bytes / self.persist_store_seconds

    @property
    def fast_path_coverage(self) -> float:
        """Fraction of executed txs the vectorized fast path carried —
        the scheduler's headline number (1.0 = every tx predicted and
        batched; the mixed-contract fixture pins it BELOW 0.5 to prove
        the residue carries real traffic)."""
        return self.fast_path_txs / self.txs if self.txs else 0.0

    def phase_line(self) -> dict:
        return {k: round(v, 3) for k, v in self.phases.items()}


def _timed_prefetch_pull(prefetcher, ph):
    """Pull blocks off the prefetch queue, billing the wait: it is the
    part of sender recovery the background thread failed to hide, so
    without it the driver phases would no longer tile the wall clock
    (pipeline.stall is a DRIVER_PHASES member; ph["senders"] keeps the
    bench attribution honest)."""
    it = iter(prefetcher)
    while True:
        t0 = time.perf_counter()
        with span("pipeline.stall", kind="prefetch"):
            try:
                block = next(it)
            except StopIteration:
                return
        ph["senders"] += time.perf_counter() - t0
        yield block


class _WindowCollector:
    """Staged background collector pipeline: each window job flows
    through up to four bounded FIFO stages on dedicated threads —
    **seal** (the pack scan + fused dispatch build + upload, off the
    driver; window N+1 packs while window N's upload is in flight —
    the double buffering), **collect** (root checks + d2d mirror
    admit), **persist** (async host spill of the window's nodes),
    **save** (block storage) — while the driver executes the next
    window's transactions. ``submit``
    enqueues one job (a single callable, or a tuple of per-stage
    callables) and blocks only while ``depth`` jobs already occupy the
    first stage (backpressure); stage hand-offs are bounded the same
    way; ``drain`` blocks until every stage is empty. Within a stage
    jobs run strictly FIFO — block saves chain total difficulty, and
    window N+1's encodings resolve through window N's published hashes
    (ledger/window.persist docstring) — and a job cannot overtake
    another across stages because hand-off order preserves queue order.

    Failure semantics: the FIRST exception (typically WindowMismatch)
    aborts the whole pipeline — queued jobs at EVERY stage are dropped
    WITHOUT persisting anything and the original exception object
    re-raises on the driver thread at its next submit/drain, so a
    mismatch still names the failing block number."""

    STAGES = ("seal", "collect", "persist", "save")

    def __init__(self, depth: int, join_timeout: float = 60.0,
                 liveness_poll: float = 0.1):
        self.depth = max(1, depth)
        self.join_timeout = join_timeout
        # backpressure/drain waits wake at this period to re-check the
        # workers are still alive — a dead thread can never notify, so
        # an untimed wait would hang the driver forever
        self.liveness_poll = liveness_poll
        self._cv = threading.Condition()
        k = len(self.STAGES)
        self._qs: List[deque] = [deque() for _ in range(k)]
        self._active: List[bool] = [False] * k
        self._current: List[Optional[tuple]] = [None] * k
        self._done: List[bool] = [False] * k  # normal thread exit
        self.stage_busy: List[float] = [0.0] * k
        self._failure: Optional[BaseException] = None
        self._closed = False
        self._inflight = 0  # jobs submitted but not fully completed
        self._threads = [
            threading.Thread(
                target=self._run, args=(i,),
                name=f"window-{name}", daemon=True,
            )
            for i, name in enumerate(self.STAGES)
        ]
        for t in self._threads:
            t.start()

    @property
    def _thread(self) -> threading.Thread:
        """The first-stage thread — the legacy single-worker handle
        (tests and external liveness probes join/poll it)."""
        return self._threads[0]

    @property
    def busy_seconds(self) -> float:
        return sum(self.stage_busy)

    # ------------------------------------------------------- driver side

    def _update_gauges(self) -> None:
        """Call under ``_cv``."""
        PIPELINE_GAUGES["in_flight"] = self._inflight
        for i, name in enumerate(self.STAGES):
            PIPELINE_GAUGES[f"stage_{name}_depth"] = (
                len(self._qs[i]) + (1 if self._active[i] else 0)
            )
            PIPELINE_GAUGES[f"stage_{name}_busy_s"] = round(
                self.stage_busy[i], 3
            )

    def _check_liveness(self) -> None:
        """Call under ``_cv``. A stage worker that exited without
        recording a failure, without being closed, died mid-job (chaos
        ``die`` or a real interpreter-level death) — raise instead of
        waiting on notifies that will never come."""
        if self._failure is not None or self._closed:
            return
        for i, t in enumerate(self._threads):
            if not self._done[i] and not t.is_alive():
                raise CollectorDied(
                    f"window-{self.STAGES[i]} stage thread died mid-"
                    f"job ({sum(len(q) for q in self._qs)} queued, "
                    f"active={self._active})"
                )

    def submit(self, fns) -> float:
        """Queue one job: a bare callable (runs entirely on the first
        stage) or a tuple of per-stage callables — stage i runs
        ``fns[i]`` then hands the job to stage i+1; the job completes
        at its last callable. Returns driver seconds stalled on
        first-stage backpressure. Re-raises the collector's failure,
        if any; raises CollectorDied when a worker is gone."""
        fns = (fns,) if callable(fns) else tuple(fns)
        t0 = time.perf_counter()
        with self._cv:
            self._check_liveness()
            while (self._failure is None and not self._closed
                   and len(self._qs[0]) + self._active[0] >= self.depth):
                self._cv.wait(timeout=self.liveness_poll)
                self._check_liveness()
            if self._failure is not None:
                raise self._failure
            if self._closed:
                raise RuntimeError("collector is closed")
            self._qs[0].append(fns)
            self._inflight += 1
            PIPELINE_GAUGES["windows_sealed"] += 1
            self._update_gauges()
            self._cv.notify_all()
        return time.perf_counter() - t0

    def drain(self) -> float:
        """Wait until every submitted job has fully completed (all
        stages); returns driver seconds stalled. Re-raises the
        collector's failure, if any; raises CollectorDied when a
        worker is gone."""
        t0 = time.perf_counter()
        with self._cv:
            self._check_liveness()
            while self._failure is None and self._inflight:
                self._cv.wait(timeout=self.liveness_poll)
                self._check_liveness()
            if self._failure is not None:
                raise self._failure
        return time.perf_counter() - t0

    def take_pending(self) -> List[Callable[[], None]]:
        """After CollectorDied: every unfinished job in FIFO order —
        deepest stage first (those windows are oldest), each stage's
        partially-executed current job ahead of its queue (jobs are
        idempotent: node puts are content-addressed, block saves
        overwrite by number, stats apply only at job end). A job with
        several stages left comes back as one closure running them in
        order; a job with ONE stage left comes back as that bare
        callable. Marks the collector closed; the caller runs these
        synchronously."""
        with self._cv:
            out: List[Callable[[], None]] = []
            for i in range(len(self.STAGES) - 1, -1, -1):
                entries: List[tuple] = []
                if self._active[i] and self._current[i] is not None:
                    entries.append(self._current[i])
                entries.extend(self._qs[i])
                self._qs[i].clear()
                out.extend(self._resume(fns, i) for fns in entries)
            self._closed = True
            self._inflight = 0
            self._update_gauges()
            self._cv.notify_all()
        return out

    @staticmethod
    def _resume(fns: tuple, i: int) -> Callable[[], None]:
        rest = fns[i:]
        if len(rest) == 1:
            return rest[0]

        def run_rest():
            for fn in rest:
                fn()

        return run_rest

    def close(self) -> None:
        """Stop the workers (after finishing anything queued) and join.
        Safe to call twice. Raises if any worker is still alive after
        ``join_timeout`` — a wedged job must not be silently abandoned
        with the pipeline's windows unaccounted for."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        deadline = time.monotonic() + self.join_timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._threads):
            raise RuntimeError(
                "window-collector failed to stop within "
                f"{self.join_timeout:.0f}s — a wedged job is still "
                "holding the pipeline (its windows are NOT committed)"
            )

    def kill(self) -> None:
        """Abort: drop queued jobs at every stage WITHOUT running them
        (nothing else persists) and join. The driver calls this when IT
        failed — windows sealed after the failing block must not be
        committed. Already unwinding, so a wedged worker is logged
        loudly instead of raised over the original failure."""
        with self._cv:
            for q in self._qs:
                q.clear()
            self._closed = True
            self._inflight = 0
            self._update_gauges()
            self._cv.notify_all()
        deadline = time.monotonic() + self.join_timeout
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._threads):
            import sys

            print(
                "WARNING: window-collector did not stop within "
                f"{self.join_timeout:.0f}s of kill(); abandoning the "
                "wedged daemon thread",
                file=sys.stderr,
            )

    # ------------------------------------------------------- worker side

    def _exit_ready(self, i: int) -> bool:
        """Call under ``_cv``: stage ``i`` may exit once the collector
        is closed and its upstream can never forward again — exited
        normally, or died mid-job (its torn job is take_pending's to
        re-run, never forwarded)."""
        if not self._closed:
            return False
        if i == 0:
            return True
        return self._done[i - 1] or not self._threads[i - 1].is_alive()

    def _run(self, i: int) -> None:
        q = self._qs[i]
        while True:
            with self._cv:
                while (not q and self._failure is None
                       and not self._exit_ready(i)):
                    # timed: an upstream death is silent (no notify)
                    self._cv.wait(timeout=0.5)
                if self._failure is not None or (
                    not q and self._exit_ready(i)
                ):
                    self._done[i] = True
                    self._cv.notify_all()
                    return
                fns = q.popleft()
                self._current[i] = fns
                self._active[i] = True
                self._update_gauges()
            t0 = time.perf_counter()
            try:
                fns[i]()
            except InjectedDeath:
                # simulated process death (chaos `die`): no failure
                # record, no notify — the thread just stops with the
                # job half done, exactly like a SIGKILL. The driver's
                # liveness checks raise CollectorDied; _current stays
                # set so take_pending can re-run the torn job.
                return
            # khipu-lint: ok KL002 InjectedDeath is handled by the
            # dedicated handler above (thread stops, SIGKILL
            # semantics); everything else is RECORDED as _failure and
            # re-raised on the driver by submit()/drain() — fail-stop
            # is preserved, not swallowed
            except BaseException as exc:  # surfaces on the driver
                with self._cv:
                    self._failure = exc
                    self._active[i] = False
                    self._current[i] = None
                    for qq in self._qs:
                        qq.clear()  # abort: NOTHING else persists
                    self._inflight = 0
                    self._update_gauges()
                    self._cv.notify_all()
                return
            dt = time.perf_counter() - t0
            forward = len(fns) > i + 1 and i + 1 < len(self._qs)
            with self._cv:
                self.stage_busy[i] += dt
                PIPELINE_GAUGES["collector_busy_s"] = round(
                    self.busy_seconds, 3
                )
                if forward:
                    # bounded hand-off: wait while downstream is full
                    # (close() still forwards — queued work must
                    # complete; only kill()/failure drop it)
                    while (len(self._qs[i + 1]) >= self.depth
                           and self._failure is None
                           and not self._closed):
                        self._cv.wait(timeout=self.liveness_poll)
                    if self._failure is None:
                        self._qs[i + 1].append(fns)
                else:
                    self._inflight = max(0, self._inflight - 1)
                    PIPELINE_GAUGES["windows_collected"] += 1
                self._active[i] = False
                self._current[i] = None
                self._update_gauges()
                self._cv.notify_all()


class ReplayDriver:
    """Executes a stream of blocks against a target chain DB."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        log: Optional[Callable[[str], None]] = None,
        validate_headers: bool = True,
        device_commit: bool = False,
        tracer: Optional[Tracer] = None,
        read_view=None,
    ):
        self.blockchain = blockchain
        self.config = config
        # serving-plane read view (serving/readview.py): committed
        # blocks publish their account diffs into it on the driver
        # thread, durable windows retire them on the collector thread,
        # and a pipeline abort invalidates everything above the
        # committed best — RPC reads stay monotonic mid-pipeline
        self.read_view = read_view
        # per-driver recorder: a driver handed its own Tracer (e.g. the
        # bridge server's — bridge.py) records there; the default stays
        # the module-global instance so single-driver processes and the
        # existing khipu_traces surface are unchanged
        self.tracer = tracer if tracer is not None else _default_tracer
        apply_config(config.observability, self.tracer)
        apply_fault_config(getattr(config, "faults", None))
        self.log = log
        self.header_validator = BlockHeaderValidator(
            config.blockchain,
            difficulty_fn=lambda h, p: calc_difficulty(
                h.unix_timestamp, p, config.blockchain
            ),
        )
        self.validate_headers = validate_headers
        # windowed-session epoch: blocks between committer resets (see
        # replay_windowed) — bounds session memory on long replays
        self.session_epoch_blocks = 512
        # route dirty-node hashing of every block commit through the
        # batched device path (Pallas on TPU); save_block's persisted-
        # root == header.state_root check gates it per block
        if device_commit:
            from khipu_tpu.trie.bulk import device_hasher

            self.hasher = device_hasher
        else:
            self.hasher = None
        # lazy per-driver device mirror (the window-commit target when
        # sync.device_mirror_commit is on); built on first windowed
        # replay so chaos configs that never reach a fused dispatch
        # pay no device setup
        self._mirror = None

    def recover(self):
        """Crash-recovery startup pass (sync/journal.py): settle every
        pending window-commit intent — repair complete windows, roll
        back partial ones, complete or abandon torn chain switches.
        Returns a RecoveryReport."""
        from khipu_tpu.sync.journal import recover

        return recover(self.blockchain, log=self.log, config=self.config)

    def replay(self, blocks: Iterable[Block]) -> ReplayStats:
        """executeAndInsertBlocks: serial fold with full validation."""
        window = self.config.sync.commit_window_blocks
        if window > 1:
            return self.replay_windowed(blocks, window)
        stats = ReplayStats()
        t_start = time.perf_counter()
        sync = self.config.sync
        prefetcher = None
        if sync.sender_prefetch:
            from khipu_tpu.sync.prefetch import SenderPrefetcher

            prefetcher = SenderPrefetcher(
                blocks,
                depth=sync.sender_prefetch_depth,
                cache_entries=sync.sender_cache_entries,
                batch_hash=sync.sender_batch_hash,
            )
            blocks = prefetcher
        try:
            with use_tracer(self.tracer):
                for block in blocks:
                    self._execute_and_insert(block, stats)
        finally:
            if prefetcher is not None:
                prefetcher.close()
        stats.seconds = time.perf_counter() - t_start
        return stats

    def replay_windowed(
        self, blocks: Iterable[Block], window_size: int
    ) -> ReplayStats:
        """Window-batched PIPELINED replay: runs with THIS driver's
        tracer active on the calling thread (collector jobs re-activate
        it on theirs — the tracer rides the closure like ``seal_tok``),
        so concurrent drivers in one process record to disjoint rings.
        See ``_replay_windowed`` for the pipeline itself."""
        with use_tracer(self.tracer):
            return self._replay_windowed(blocks, window_size)

    def _replay_windowed(
        self, blocks: Iterable[Block], window_size: int
    ) -> ReplayStats:
        """Window-batched PIPELINED replay: execute W blocks against one
        open deferred session, seal the window (pack + async device
        dispatch of the fused fixpoint), then execute the NEXT window's
        transactions on the host while the device resolves the previous
        one — the double-buffering that hides the device round-trip
        behind host execution (SURVEY §7.4-5; the reference overlaps
        execution with persistence the same way via its actor mailbox,
        RegularSyncService.scala:381). Root checks happen at collect —
        up to ``pipeline_depth`` windows later than the serial path, on
        a background collector thread, with identical failure semantics
        (nothing of a window persists before its roots pass; a
        WindowMismatch drains the pipeline and re-raises here with the
        failing block number — docs/window_pipeline.md).
        """
        from khipu_tpu.evm.config import for_block
        from khipu_tpu.ledger.window import WindowCommitter
        from khipu_tpu.trie.bulk import host_hasher

        stats = ReplayStats()
        ph = stats.phases
        for k in ("senders", "validate", "execute", "commit", "seal",
                  "collect", "save", "seal_bg", "collect_bg",
                  "persist_bg", "save_bg", "senders_bg"):
            ph[k] = 0.0
        t_start = time.perf_counter()
        hasher = self.hasher or host_hasher
        # pipelined sender recovery (sync/prefetch.py): the prefetch
        # thread recovers window N+1's senders while this thread
        # executes window N; its busy time lands in senders_bg and the
        # driver's foreground "senders" phase becomes a cache sweep
        sync = self.config.sync
        prefetcher = None
        if sync.sender_prefetch:
            from khipu_tpu.sync.prefetch import SenderPrefetcher

            prefetcher = SenderPrefetcher(
                blocks,
                depth=sync.sender_prefetch_depth,
                cache_entries=sync.sender_cache_entries,
                batch_hash=sync.sender_batch_hash,
            )
            # the driver's wait on the prefetch queue is sender
            # recovery leaking back onto the critical path (the
            # thread can't keep ahead) — bill it to pipeline.stall so
            # the driver phases still tile the wall clock, and to the
            # senders phase so the bench attributes it honestly
            blocks = _timed_prefetch_pull(prefetcher, ph)
        blocks = iter(blocks)
        try:
            first = next(blocks)
        except StopIteration:
            if prefetcher is not None:
                prefetcher.close()
            return stats

        parent = self.blockchain.get_header_by_number(first.number - 1)
        window_headers = {}
        window_headers_full = {}
        window_blocks = {}

        def block_hash_of(n: int):
            h = window_headers.get(n)
            return h if h else self.blockchain.get_hash_by_number(n)

        # device-resident commit (docs/window_pipeline.md): on the
        # fused device path the store's mirror becomes the commit
        # target — collect admits windows d2d and the host spill runs
        # async on the persist stage; NodeStorage read-through serves
        # not-yet-spilled nodes. One mirror per driver, reused across
        # epochs/replays (its XLA kernels are process-cached anyway)
        mirror = None
        if (self.hasher is not None
                and self.config.sync.device_mirror_commit):
            mirror = self._mirror
            if mirror is None:
                from khipu_tpu.storage.device_mirror import (
                    DeviceNodeMirror,
                )

                mirror = self._mirror = DeviceNodeMirror(
                    self.config.sync.mirror_capacity_rows
                )
            self.blockchain.storages.attach_mirror(mirror)

        # cost-model-adaptive commit (sync/adaptive.py): ONE controller
        # per replay — it outlives epoch committer rebuilds so the
        # EWMA keeps its history. device_cap mirrors whether this
        # driver could use the fused device path at all; the probe
        # (when enabled) downgrades to host before window 0 on
        # backends whose "device" memory is host RAM
        adaptive = None
        if self.config.sync.adaptive_commit and self.hasher is not None:
            from khipu_tpu.sync.adaptive import AdaptiveCommitController

            # the probe's calibration upload is seal-path machinery —
            # bill it to the seal phase so bench --diff attributes it
            # there instead of to an unattributed "?" row
            with LEDGER.context(window=0, phase="seal"):
                adaptive = AdaptiveCommitController(
                    self.config.sync, device_cap=True
                )

        def make_committer(parent_root: bytes) -> WindowCommitter:
            return WindowCommitter(
                self.blockchain.storages,
                parent_root,
                hasher=hasher,
                account_start_nonce=(
                    self.config.blockchain.account_start_nonce
                ),
                get_block_hash=block_hash_of,
                # device mode: one-dispatch fixpoint finalize — the
                # per-level hasher loop would pay O(levels) tunnel
                # round-trips per window (docs/roofline.md)
                fused=self.hasher is not None,
                on_block_committed=(
                    self.read_view.publish_block
                    if self.read_view is not None else None
                ),
                mirror=mirror,
                adaptive=adaptive,
            )

        committer = make_committer(parent.state_root)
        depth = max(1, self.config.sync.pipeline_depth)
        collector = _WindowCollector(
            depth, join_timeout=self.config.sync.collector_join_timeout
        )
        PIPELINE_GAUGES["depth"] = depth
        # crash consistency: WAL intent before each background job, a
        # commit mark after its best-number advance (docs/recovery.md)
        journal = (
            self.blockchain.storages.window_journal
            if self.config.sync.commit_journal else None
        )
        window_parent_root = parent.state_root
        # graceful degradation: a dead collector thread (CollectorDied
        # from the liveness checks) switches the driver to synchronous
        # commits instead of aborting — unless config says abort
        sync_degraded = False
        degrade_on_death = self.config.sync.degrade_on_collector_death

        def _degrade() -> None:
            nonlocal sync_degraded
            sync_degraded = True
            PIPELINE_GAUGES["collector_deaths"] += 1
            event("pipeline.degrade", reason="collector-died")
            if self.log is not None:
                self.log(
                    "window-collector thread died; degrading to "
                    "synchronous window commits (jobs are idempotent "
                    "— re-running the torn one)"
                )
            for fn in collector.take_pending():
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                fn()

        def submit_job(run_fns) -> float:
            if sync_degraded:
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                for fn in run_fns:
                    fn()
                if journal is not None:
                    journal.prune()
                return 0.0
            try:
                return collector.submit(run_fns)
            except CollectorDied:
                if not degrade_on_death:
                    raise
                _degrade()
                PIPELINE_GAUGES["sync_fallback_windows"] += 1
                for fn in run_fns:
                    fn()
                return 0.0

        def drain_pipeline() -> float:
            # with the pipeline empty every intent is settled: drop the
            # committed prefix so the journal stays O(pipeline_depth),
            # not O(chain)
            if sync_degraded:
                if journal is not None:
                    journal.prune()
                return 0.0
            try:
                stall = collector.drain()
            except CollectorDied:
                if not degrade_on_death:
                    raise
                _degrade()
                return 0.0
            if journal is not None:
                journal.prune()
            return stall
        # epoch reset: every N blocks the session committer is rebuilt
        # from the last VALIDATED root, dropping the resolved-
        # placeholder map and all retained refs — with the per-collect
        # staged prune this bounds replay memory to O(epoch), not
        # O(chain) (the reference's analog is its bounded node cache +
        # persisted store)
        epoch = self.session_epoch_blocks
        blocks_since_reset = 0

        def make_stage_jobs(cm: WindowCommitter, job, results, seal_tok,
                            intent_seq):
            # the four per-stage closures one window job flows
            # through, each ON ITS OWN COLLECTOR STAGE THREAD,
            # strictly FIFO within a stage. ``seal_tok`` (the driver's
            # window.seal span id) rides the closures across the
            # queues so the trace links the stages' spans to the seal
            # that produced them (the cross-thread parent edge — flow
            # arrows in the Chrome dump). The driver's tracer rides
            # the same way: stage threads have no thread-local binding
            # of their own, and falling back to the module default
            # would split one driver's trace across two rings.
            lo, hi = results[0][0].number, results[-1][0].number
            tr = self.tracer

            def seal_fn():
                # the OFF-DRIVER seal tail: pack scan + dispatch build
                # + upload, running while the driver executes the next
                # window (and while the previous window's upload is in
                # flight — the double buffering). The journal intent
                # was fsynced on the DRIVER before this job existed,
                # and pack mutates memory only, so the crash contract
                # is unchanged: persist is still the first durable
                # mutation. The LEDGER phase stays "seal" so the
                # per-window cost model and bench --diff keep
                # attributing the sub-phases to the seal family.
                with use_tracer(tr):
                    fault_point("collector.seal")
                    t0 = time.perf_counter()
                    with span("window.pack", parent=seal_tok,
                              block_lo=lo, block_hi=hi), \
                            LEDGER.context(window=lo, phase="seal"):
                        cm.pack_and_dispatch(job)
                    ph["seal_bg"] += time.perf_counter() - t0

            def collect_fn():
                # chaos seams: a rule at any of the collector.* sites
                # models a failure/death at that phase of the job
                # (docs/recovery.md crash-point table)
                with use_tracer(tr):
                    fault_point("collector.collect")
                    t0 = time.perf_counter()
                    with span("window.collect", parent=seal_tok,
                              block_lo=lo, block_hi=hi), \
                            LEDGER.context(window=lo, phase="collect"):
                        # root checks fetch ONLY the per-block root
                        # digests (32 B x blocks d2h); the window's
                        # live nodes land in the device mirror d2d
                        cm.collect_roots(job)  # raises WindowMismatch
                        cm.admit_mirror(job)
                    ph["collect_bg"] += time.perf_counter() - t0

            def persist_fn():
                with use_tracer(tr):
                    fault_point("collector.persist")
                    t0 = time.perf_counter()
                    with span("window.persist", parent=seal_tok,
                              block_lo=lo, block_hi=hi,
                              live=len(job.live)), \
                            LEDGER.context(window=lo, phase="persist"):
                        # the bulk d2h (full mapping) + host spill,
                        # now OFF the collect critical path
                        cm.persist(job)
                    ph["persist_bg"] += time.perf_counter() - t0

            def save_fn():
                with use_tracer(tr):
                    t0 = time.perf_counter()
                    blocks = txs = gas = ptxs = confl = 0
                    fast = residue = mispred = 0
                    with span("window.save", parent=seal_tok,
                              block_lo=lo, block_hi=hi,
                              blocks=len(results)), \
                            LEDGER.context(window=lo, phase="save"):
                        for block, result in results:
                            td = (
                                self.blockchain.get_total_difficulty(
                                    block.number - 1
                                )
                                or 0
                            ) + block.header.difficulty
                            # world=None: the window already persisted
                            # the nodes
                            t_save = time.perf_counter()
                            self.blockchain.save_block(
                                block, result.receipts, td, world=None
                            )
                            # host-side persistence: classification
                            # traffic for window_report, never a
                            # device crossing
                            LEDGER.record(
                                "block.save", HOST, 0,
                                duration=time.perf_counter() - t_save,
                            )
                            fault_point("collector.save")
                            blocks += 1
                            txs += result.stats.tx_count
                            gas += result.gas_used
                            ptxs += result.stats.parallel_count
                            confl += result.stats.conflict_count
                            fast += result.stats.fast_path_txs
                            residue += result.stats.residue_txs
                            mispred += result.stats.mispredicted_txs
                        # the commit mark is the job's LAST mutation:
                        # a window is durable only after persist+save
                        # — the journal's crash-consistency contract
                        # holds at every stage boundary
                        if intent_seq is not None:
                            fault_point("collector.commit")
                            journal.log_commit(intent_seq)
                        if JOURNEY.enabled:
                            # persist+save done, commit mark down: the
                            # crash-survivable point — the passport's
                            # durable page (feeds the durable-latency
                            # histogram with this ring's trace id)
                            for b, _r in results:
                                for stx in b.body.transactions:
                                    JOURNEY.record(
                                        stx.hash, "durable",
                                        block=b.header.number,
                                    )
                        if self.log is not None:
                            self.log(
                                f"Committed window [{lo}..{hi}] "
                                f"({len(results)} blocks) in one "
                                "batched device pass"
                            )
                        # stats land ONLY here, after the commit mark:
                        # a torn job re-run after a collector death
                        # stays idempotent — no double counting
                        # (nothing below can raise before they apply)
                        stats.blocks += blocks
                        stats.txs += txs
                        stats.gas += gas
                        stats.parallel_txs += ptxs
                        stats.conflicts += confl
                        stats.fast_path_txs += fast
                        stats.residue_txs += residue
                        stats.mispredictions += mispred
                        LEDGER.note_blocks(blocks)
                    # the window is durable (best advanced, commit
                    # mark down): the committed store now serves
                    # same-or-newer state, so the read-view overlay
                    # can let go of it
                    if self.read_view is not None:
                        self.read_view.retire_through(hi)
                    ph["save_bg"] += time.perf_counter() - t0

            return (seal_fn, collect_fn, persist_fn, save_fn)

        def seal_and_submit() -> None:
            nonlocal results_cur, window_parent_root
            lo = results_cur[0][0].number
            hi = results_cur[-1][0].number
            t0 = time.perf_counter()
            intent_seq = None
            LEDGER.note_window(lo, lo, hi)
            with span("window.seal", block_lo=lo, block_hi=hi) as seal_sp, \
                    LEDGER.context(window=lo, phase="seal"):
                job = committer.seal()
                if JOURNEY.enabled:
                    for b, _r in results_cur:
                        for stx in b.body.transactions:
                            JOURNEY.record(stx.hash, "seal",
                                           window_lo=lo, window_hi=hi)
                if journal is not None:
                    # WAL barrier: the intent is durable BEFORE the job
                    # can run (submit enqueues it strictly afterwards).
                    # It is part of sealing — inside the span, so the
                    # driver phase accounting sees the journal cost.
                    _j0 = time.perf_counter()
                    with span("seal.journal", block_lo=lo, block_hi=hi):
                        intent_seq = journal.log_intent(
                            lo, hi, window_parent_root,
                            [b.header.state_root for b, _ in results_cur],
                        )
                    # host-side classification event so the window
                    # report's seal row decomposes WAL cost too
                    LEDGER.record(
                        "seal.journal", HOST, 0,
                        duration=time.perf_counter() - _j0,
                    )
                    if JOURNEY.enabled:
                        # the WAL intent is fsynced: from here a crash
                        # replays the window forward — the passport's
                        # journal-intent page
                        for b, _r in results_cur:
                            for stx in b.body.transactions:
                                JOURNEY.record(stx.hash,
                                               "journal.intent",
                                               seq=intent_seq)
                # stage-job closure build stays inside the span (it
                # is part of sealing, and an unbilled sliver here
                # loses GIL slices to the stage threads — see the
                # bookkeeping note in the build loop)
                run_fns = make_stage_jobs(
                    committer, job, results_cur, seal_sp.token,
                    intent_seq,
                )
            ph["seal"] += time.perf_counter() - t0
            with span("pipeline.stall", block_lo=lo, block_hi=hi,
                      kind="submit"):
                ph["collect"] += submit_job(run_fns)
                # adaptive depth: the controller's seal.upload
                # roofline verdict sizes how many windows may queue
                # ahead of the seal stage (bytes-bound uploads
                # overlap, fixed-overhead ones don't) — applied
                # between windows, never mid-submit
                if adaptive is not None and adaptive.depth_hint:
                    new_depth = max(1, adaptive.depth_hint)
                    if new_depth != collector.depth:
                        collector.depth = new_depth
                        PIPELINE_GAUGES["depth"] = new_depth
                window_parent_root = (
                    results_cur[-1][0].header.state_root
                )
                results_cur = []

        results_cur: List = []
        prev = parent
        import itertools

        try:
            for block in itertools.chain((first,), blocks):
                header = block.header
                with span(
                    "window.build",
                    block=header.number,
                    txs=len(block.body.transactions),
                ):
                    t0 = time.perf_counter()
                    # cache-fronted recovery (sync/prefetch.py): a
                    # no-op sweep when the prefetch thread already
                    # filled the per-object memos; one batched native
                    # call for anything it missed. The dedicated span
                    # feeds the "senders" phase-share ceiling
                    with span("senders", block=header.number):
                        recover_block_senders(
                            block.body.transactions,
                            sync.sender_cache_entries,
                            sync.sender_batch_hash,
                        )
                    ph["senders"] += time.perf_counter() - t0
                    if JOURNEY.enabled:
                        # passport ingress for imported txs: FIRST
                        # sighting wins, so an RPC-submitted tx keeps
                        # its rpc ingress and a reorg re-import keeps
                        # the original stamp
                        for stx in block.body.transactions:
                            JOURNEY.record(stx.hash, "ingress",
                                           source="import",
                                           block=header.number)
                    t0 = time.perf_counter()
                    if self.validate_headers:
                        self.header_validator.validate(header, prev)
                    BlockValidator.validate_body(block)
                    OmmersValidator.validate(
                        self.blockchain, block,
                        header_lookup=window_headers_full.get,
                        block_lookup=window_blocks.get,
                        header_validator=(
                            self.header_validator
                            if self.validate_headers else None
                        ),
                    )
                    config = for_block(
                        header.number, self.config.blockchain
                    )
                    if not config.byzantium:
                        raise ValueError(
                            "window commits need Byzantium receipts "
                            "(pre-Byzantium receipts embed per-tx roots)"
                        )
                    ph["validate"] += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    with span("execute", block=header.number,
                              txs=len(block.body.transactions)):
                        result = execute_block(
                            block,
                            b"",  # the open session IS the parent state
                            committer.make_world,
                            self.config,
                            validate=True,
                            check_root=False,  # deferred to finalize
                        )
                    ph["execute"] += time.perf_counter() - t0
                    t0 = time.perf_counter()
                    committer.commit_block(
                        result.world, header,
                        txs=(
                            [stx.hash
                             for stx in block.body.transactions]
                            if JOURNEY.enabled else None
                        ),
                    )
                    ph["commit"] += time.perf_counter() - t0
                    # window bookkeeping stays INSIDE the span: each
                    # statement outside a driver phase is a chance to
                    # lose a GIL slice to a collector stage thread,
                    # unbilled — the wall-clock tiling gate
                    # (driver_total_s vs wall_s) holds only if the
                    # driver's inter-span slivers stay negligible
                    window_headers[header.number] = header.hash
                    window_headers_full[header.number] = header
                    window_blocks[header.number] = block
                    results_cur.append((block, result))
                    prev = header
                if len(results_cur) >= window_size:
                    # NO barrier before seal: cross-window refs resolve
                    # from the in-flight jobs' device digests (resolved-
                    # input tiles); the only wait is submit backpressure
                    # once pipeline_depth windows are queued
                    blocks_since_reset += len(results_cur)
                    seal_and_submit()
                    if blocks_since_reset >= epoch:
                        # drain the pipeline, then restart the session from
                        # the last validated root (memory bound)
                        with span("pipeline.stall", kind="epoch-drain"):
                            stalled = drain_pipeline()
                        ph["collect"] += stalled
                        # bank the retiring committer's persist-stage
                        # counters before the rebuild drops them
                        stats.persist_bytes += committer.persist_bytes
                        stats.persist_store_seconds += (
                            committer.persist_seconds
                        )
                        committer = make_committer(prev.state_root)
                        blocks_since_reset = 0
                        # header/body maps: ommers reach back 6 ancestors,
                        # BLOCKHASH 256 — prune beyond that
                        for d, keep in (
                            (window_headers, 260),
                            (window_headers_full, 8),
                            (window_blocks, 8),
                        ):
                            for n in sorted(d)[:-keep]:
                                del d[n]
            if results_cur:
                seal_and_submit()
            with span("pipeline.stall", kind="final-drain"):
                stalled = drain_pipeline()
            ph["collect"] += stalled
        except BaseException:
            # a driver-side failure (validation, execution, or a
            # re-raised collector failure) aborts the pipeline:
            # queued windows are dropped WITHOUT persisting
            if prefetcher is not None:
                prefetcher.close()
            collector.kill()
            # un-durable overlay state must die with the windows that
            # produced it — reads fall back to the committed store
            # (never a torn window)
            if self.read_view is not None:
                self.read_view.invalidate_above(
                    self.blockchain.best_block_number
                )
            raise
        collector.close()
        if prefetcher is not None:
            prefetcher.close()
            # overlapped sender recovery: background busy time, kept
            # out of the foreground wall-clock phases (like *_bg)
            ph["senders_bg"] += prefetcher.busy_seconds
        # every window is durable: free the last in-flight fused jobs'
        # device buffers (earlier retirees were freed at later seals)
        committer.drain_retired()
        stats.persist_bytes += committer.persist_bytes
        stats.persist_store_seconds += committer.persist_seconds
        stats.seconds = time.perf_counter() - t_start
        # overlap fraction: collector busy seconds NOT spent with the
        # driver blocked on it ((C - stall)/C) — 1.0 means collect+save
        # were fully hidden behind host execution
        stall = ph["collect"] + ph["save"]
        busy = collector.busy_seconds
        occ = (
            max(0.0, min(1.0, (busy - stall) / busy)) if busy > 0 else 0.0
        )
        stats.pipeline_occupancy = occ
        PIPELINE_GAUGES["occupancy"] = round(occ, 4)
        PIPELINE_GAUGES["driver_stall_s"] = round(stall, 3)
        return stats

    def _execute_and_insert(self, block: Block, stats: ReplayStats) -> None:
        header = block.header
        parent = self.blockchain.get_header_by_number(header.number - 1)
        if parent is None:
            raise ValueError(f"no parent for block {header.number}")
        # passport stamps for the per-block import path (live sync,
        # reorg adopt). A replica's tail re-execution runs under
        # use_node("replica:...") and stamps ONLY its own visibility
        # page (serving/replica.py) — ingress/durable belong to the
        # primary plane
        journeys = JOURNEY.enabled and current_node() == "primary"
        if journeys:
            for stx in block.body.transactions:
                JOURNEY.record(stx.hash, "ingress", source="import",
                               block=header.number)
        if self.validate_headers:
            self.header_validator.validate(header, parent)
        BlockValidator.validate_body(block)
        OmmersValidator.validate(
            self.blockchain, block,
            header_validator=(
                self.header_validator if self.validate_headers else None
            ),
        )

        t0 = time.perf_counter()
        result = execute_block(
            block,
            parent.state_root,
            self.blockchain.get_world_state,
            self.config,
            validate=True,
            hasher=self.hasher,  # root check + persist share one flush
        )
        td = (
            self.blockchain.get_total_difficulty(parent.number) or 0
        ) + header.difficulty
        self.blockchain.save_block(
            block, result.receipts, td, result.world, hasher=self.hasher
        )
        if journeys:
            for stx in block.body.transactions:
                JOURNEY.record(stx.hash, "durable",
                               block=header.number)
        dt = time.perf_counter() - t0

        stats.blocks += 1
        stats.txs += result.stats.tx_count
        stats.gas += result.gas_used
        stats.parallel_txs += result.stats.parallel_count
        stats.conflicts += result.stats.conflict_count
        stats.fast_path_txs += result.stats.fast_path_txs
        stats.residue_txs += result.stats.residue_txs
        stats.mispredictions += result.stats.mispredicted_txs

        if self.log is not None:
            # RegularSyncService.scala:429 one-line format
            ntx = result.stats.tx_count
            self.log(
                f"Executed #{header.number} ({block.hash[:4].hex()}) "
                f"{ntx} txs in {dt * 1000:.1f}ms, "
                f"{ntx / dt if dt else 0:.1f} tx/s, "
                f"{result.gas_used / dt / 1e6 if dt else 0:.2f} mgas/s, "
                f"parallel {result.stats.parallel_rate * 100:.0f}%, "
                f"cache hit "
                f"{self.blockchain.storages.account_node_storage.cache_hit_rate * 100:.0f}%"
            )
