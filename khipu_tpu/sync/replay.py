"""Regular-sync replay driver: feed blocks through execution, gate every
root, keep the per-block perf line.

Parity: blockchain/sync/RegularSyncService.scala:43 —
executeAndInsertBlocks:381 (serial fold), executeAndInsertBlock:405
(validate -> execute -> save), and the one-line per-block perf report
:429 (tx/s, mgas/s, parallel %, cache hit %). Networking is replaced by
a block source (another Blockchain, or decoded RLP blocks); the
north-star replay metric (blocks/s) is measured here.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, List, Optional

from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.ledger.ledger import execute_block
from khipu_tpu.validators.validators import (
    BlockHeaderValidator,
    BlockValidator,
)


@dataclass
class ReplayStats:
    blocks: int = 0
    txs: int = 0
    gas: int = 0
    seconds: float = 0.0
    parallel_txs: int = 0
    conflicts: int = 0

    @property
    def blocks_per_s(self) -> float:
        return self.blocks / self.seconds if self.seconds else 0.0


class ReplayDriver:
    """Executes a stream of blocks against a target chain DB."""

    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        log: Optional[Callable[[str], None]] = None,
        validate_headers: bool = True,
        device_commit: bool = False,
    ):
        self.blockchain = blockchain
        self.config = config
        self.log = log
        self.header_validator = BlockHeaderValidator(
            config.blockchain,
            difficulty_fn=lambda h, p: calc_difficulty(
                h.unix_timestamp, p, config.blockchain
            ),
        )
        self.validate_headers = validate_headers
        # route dirty-node hashing of every block commit through the
        # batched device path (Pallas on TPU); save_block's persisted-
        # root == header.state_root check gates it per block
        if device_commit:
            from khipu_tpu.trie.bulk import device_hasher

            self.hasher = device_hasher
        else:
            self.hasher = None

    def replay(self, blocks: Iterable[Block]) -> ReplayStats:
        """executeAndInsertBlocks: serial fold with full validation."""
        stats = ReplayStats()
        t_start = time.perf_counter()
        for block in blocks:
            self._execute_and_insert(block, stats)
        stats.seconds = time.perf_counter() - t_start
        return stats

    def _execute_and_insert(self, block: Block, stats: ReplayStats) -> None:
        header = block.header
        parent = self.blockchain.get_header_by_number(header.number - 1)
        if parent is None:
            raise ValueError(f"no parent for block {header.number}")
        if self.validate_headers:
            self.header_validator.validate(header, parent)
        BlockValidator.validate_body(block)

        t0 = time.perf_counter()
        result = execute_block(
            block,
            parent.state_root,
            self.blockchain.get_world_state,
            self.config,
            validate=True,
        )
        td = (
            self.blockchain.get_total_difficulty(parent.number) or 0
        ) + header.difficulty
        self.blockchain.save_block(
            block, result.receipts, td, result.world, hasher=self.hasher
        )
        dt = time.perf_counter() - t0

        stats.blocks += 1
        stats.txs += result.stats.tx_count
        stats.gas += result.gas_used
        stats.parallel_txs += result.stats.parallel_count
        stats.conflicts += result.stats.conflict_count

        if self.log is not None:
            # RegularSyncService.scala:429 one-line format
            ntx = result.stats.tx_count
            self.log(
                f"Executed #{header.number} ({block.hash[:4].hex()}) "
                f"{ntx} txs in {dt * 1000:.1f}ms, "
                f"{ntx / dt if dt else 0:.1f} tx/s, "
                f"{result.gas_used / dt / 1e6 if dt else 0:.2f} mgas/s, "
                f"parallel {result.stats.parallel_rate * 100:.0f}%, "
                f"cache hit "
                f"{self.blockchain.storages.account_node_storage.cache_hit_rate * 100:.0f}%"
            )
