"""Write-ahead window-commit journal + crash recovery.

The deep pipeline (sync/replay.py) moved root checks, node/code
persistence and block saves onto a background collector thread. A
process death mid-job leaves node storage, block storage and
``AppStateStorage.best_block_number`` mutually inconsistent — and
before this module nothing on startup detected or repaired that.

Protocol (two records per window, over the ``journal`` KV topic):

* INTENT — written and flushed BEFORE the background job's first
  mutation (the driver writes it at submit, the job runs strictly
  after): ``[b"I", seq, lo, hi, parent_root, [expected_root, ...]]``
  under key ``b"J" + seq``. The expected roots are the header state
  roots the collector will verify — recovery re-verifies against the
  same values.
* COMMIT — ``b"\\x01"`` under key ``b"C" + seq``, written after the
  window's last ``save_block`` advanced ``best_block_number``.

A crash between the two leaves a pending intent. ``recover()`` scans
them in order and, per window, either REPAIRS (every block present
with the expected root, td/body/receipts stored, and the state trie at
the window's last root fully reachable with every node's bytes
matching its content address — node puts are content-addressed and
idempotent, so a partially re-persisted window that verifies is simply
complete) or ROLLS BACK (removes the window's partial block records
and resets ``best_block_number`` to the last fully-committed window;
orphaned trie nodes are harmless — content-addressed, unreferenced,
reclaimed by the compactor). Once one window rolls back every later
pending window rolls back too: its parent chain is gone.

A chain REORG (sync/reorg.py) journals a third record shape in the
same seq stream:

* REORG-INTENT — ``[b"R", seq, ancestor_number, ancestor_hash,
  [old_hash, ...], [adopted_hash, ...], [orphan_tx_rlp, ...]]`` under
  ``b"J" + seq``, with the adopted branch's FULL block RLP staged
  under ``b"RB" + seq + number`` and flushed BEFORE the intent.
  Staging first makes the switch atomic: once the intent is durable,
  recovery can always re-execute the adopted branch from the (still
  durable) ancestor state, so a kill anywhere inside the switch
  resolves to exactly the old chain (abandon: nothing was removed
  yet) or exactly the new one (roll forward: strip everything above
  the ancestor, re-execute the staged blocks). The orphan txs — mined
  on the losing branch only — ride in the record because the rollback
  removes their bodies: an in-process recovery handed a txpool can
  still recycle them after a mid-switch death.

Crash points and their outcomes are enumerated in docs/recovery.md;
tests/test_chaos.py and tests/test_reorg.py provoke them with the
chaos harness.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Union

from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.observability.journey import JOURNEY

_INTENT_PREFIX = b"J"
_COMMIT_PREFIX = b"C"
_REORG_BLOCK_PREFIX = b"RB"  # staged adopted-branch block RLP
_HEAD_KEY = b"head"  # next seq to assign
_TAIL_KEY = b"tail"  # lowest seq not yet pruned


def _seq_key(prefix: bytes, seq: int) -> bytes:
    return prefix + int(seq).to_bytes(8, "big")


def _int_bytes(n: int) -> bytes:
    return int(n).to_bytes(8, "big").lstrip(b"\x00") or b"\x00"


def _block_key(seq: int, number: int) -> bytes:
    return (_REORG_BLOCK_PREFIX + int(seq).to_bytes(8, "big")
            + int(number).to_bytes(8, "big"))


@dataclass
class IntentRecord:
    seq: int
    lo: int
    hi: int
    parent_root: bytes
    roots: List[bytes]  # expected header state roots, lo..hi


@dataclass
class ReorgRecord:
    seq: int
    ancestor_number: int
    ancestor_hash: bytes
    old_hashes: List[bytes]  # ancestor+1 .. old tip (the chain we leave)
    adopted_hashes: List[bytes]  # ancestor+1 .. new tip (staged branch)
    # txs mined ONLY on the losing branch (their bodies do not survive
    # the rollback): recovery recycles these into a provided txpool
    orphan_tx_rlp: List[bytes] = field(default_factory=list)

    @property
    def old_top(self) -> int:
        return self.ancestor_number + len(self.old_hashes)

    @property
    def new_top(self) -> int:
        return self.ancestor_number + len(self.adopted_hashes)

    def orphan_txs(self) -> list:
        from khipu_tpu.domain.transaction import SignedTransaction

        out = []
        for raw in self.orphan_tx_rlp:
            try:
                out.append(SignedTransaction.decode(raw))
            except Exception:
                pass  # a torn tx row loses one orphan, not the switch
        return out


class WindowJournal:
    """The WAL over one KeyValueDataSource (``Storages.journal_source``
    — every engine gives it the same durability as the block stores;
    ``flush`` after the intent is the fsync barrier where the engine
    has one)."""

    def __init__(self, source):
        self.source = source
        self._lock = threading.Lock()
        # registry pull collector, replace-by-key: the newest journal
        # (tests build hundreds) owns the khipu_journal_depth sample
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "journal",
                lambda: [("khipu_journal_depth", "gauge", {},
                          self.depth)],
            )
        except Exception:  # pragma: no cover
            pass

    # ----------------------------------------------------------- pointers

    def _get_int(self, key: bytes, default: int = 0) -> int:
        v = self.source.get(key)
        return int.from_bytes(v, "big") if v else default

    def _flush(self) -> None:
        fl = getattr(self.source, "flush", None)
        if fl:
            fl()

    # ------------------------------------------------------------ writing

    def log_intent(self, lo: int, hi: int, parent_root: bytes,
                   expected_roots: List[bytes]) -> int:
        """Durable BEFORE the caller mutates anything; returns the seq
        for the matching ``log_commit``. Record first, head second: a
        crash between the two orphans a record whose job never started
        — recovery's tail..head scan correctly ignores it."""
        if len(expected_roots) != hi - lo + 1:
            raise ValueError("one expected root per block of the window")
        with self._lock:
            seq = self._get_int(_HEAD_KEY)
            self.source.put(
                _seq_key(_INTENT_PREFIX, seq),
                rlp_encode([
                    b"I", _int_bytes(seq), _int_bytes(lo), _int_bytes(hi),
                    bytes(parent_root),
                    [bytes(r) for r in expected_roots],
                ]),
            )
            self.source.put(_HEAD_KEY, int(seq + 1).to_bytes(8, "big"))
            self._flush()
        return seq

    def log_reorg_intent(self, ancestor_number: int, ancestor_hash: bytes,
                         old_hashes: List[bytes], adopted_blocks,
                         orphan_txs=()) -> int:
        """Stage the adopted branch + fsync the reorg intent; durable
        BEFORE the switch removes anything. Staging goes first (own
        flush barrier): an intent that promises a branch recovery
        cannot read would be a torn switch with no winning side. A
        crash between the two leaves orphan staged rows under a seq
        the head never covered — bounded garbage, ignored by the scan
        and overwritten when the seq is eventually assigned."""
        if len(adopted_blocks) == 0:
            raise ValueError("a reorg adopts at least one block")
        first = adopted_blocks[0].number
        if first != ancestor_number + 1:
            raise ValueError(
                f"adopted branch starts at #{first}, expected "
                f"#{ancestor_number + 1}"
            )
        with self._lock:
            seq = self._get_int(_HEAD_KEY)
            for b in adopted_blocks:
                self.source.put(_block_key(seq, b.number), b.encode())
            self._flush()
            self.source.put(
                _seq_key(_INTENT_PREFIX, seq),
                rlp_encode([
                    b"R", _int_bytes(seq), _int_bytes(ancestor_number),
                    bytes(ancestor_hash),
                    [bytes(h) for h in old_hashes],
                    [bytes(b.hash) for b in adopted_blocks],
                    [stx.encode() for stx in orphan_txs],
                ]),
            )
            self.source.put(_HEAD_KEY, int(seq + 1).to_bytes(8, "big"))
            self._flush()
        return seq

    def staged_blocks(self, rec: "ReorgRecord"):
        """Decode the adopted branch staged for ``rec`` (roll-forward
        input). None if any staged row is missing — impossible after a
        durable intent (staging flushes first) but recovery treats it
        as roll-back-only rather than crash."""
        from khipu_tpu.domain.block import Block

        out = []
        with self._lock:
            for i in range(len(rec.adopted_hashes)):
                raw = self.source.get(
                    _block_key(rec.seq, rec.ancestor_number + 1 + i)
                )
                if raw is None:
                    return None
                out.append(Block.decode(raw))
        return out

    def log_commit(self, seq: int) -> None:
        """The window's blocks are saved and best advanced — or
        recovery settled the intent (repair OR rollback); either way
        the intent needs no further attention."""
        with self._lock:
            self.source.put(_seq_key(_COMMIT_PREFIX, seq), b"\x01")
            self._flush()

    # ------------------------------------------------------------ reading

    def pending(self) -> List[Union[IntentRecord, "ReorgRecord"]]:
        """Intents without a commit mark, ascending — the windows (or
        chain switches) a crash may have left half-persisted."""
        out: List[Union[IntentRecord, ReorgRecord]] = []
        with self._lock:
            tail = self._get_int(_TAIL_KEY)
            head = self._get_int(_HEAD_KEY)
            for seq in range(tail, head):
                raw = self.source.get(_seq_key(_INTENT_PREFIX, seq))
                if raw is None:
                    continue
                if self.source.get(_seq_key(_COMMIT_PREFIX, seq)):
                    continue
                out.append(self._decode(raw))
        return out

    @staticmethod
    def _decode(raw: bytes) -> Union[IntentRecord, "ReorgRecord"]:
        fields = rlp_decode(raw)
        tag = fields[0]
        if tag == b"I":
            _, seq, lo, hi, parent_root, roots = fields
            return IntentRecord(
                seq=int.from_bytes(seq, "big"),
                lo=int.from_bytes(lo, "big"),
                hi=int.from_bytes(hi, "big"),
                parent_root=parent_root,
                roots=list(roots),
            )
        if tag == b"R":
            _, seq, anc_n, anc_h, old, adopted = fields[:6]
            orphans = list(fields[6]) if len(fields) > 6 else []
            return ReorgRecord(
                seq=int.from_bytes(seq, "big"),
                ancestor_number=int.from_bytes(anc_n, "big"),
                ancestor_hash=anc_h,
                old_hashes=list(old),
                adopted_hashes=list(adopted),
                orphan_tx_rlp=orphans,
            )
        raise ValueError(f"bad journal record tag {tag!r}")

    def prune(self) -> int:
        """Drop the settled prefix (intent+commit pairs below the first
        pending intent); returns records removed. Bounds the journal to
        O(in-flight windows)."""
        removed = 0
        with self._lock:
            tail = self._get_int(_TAIL_KEY)
            head = self._get_int(_HEAD_KEY)
            seq = tail
            while seq < head:
                ik = _seq_key(_INTENT_PREFIX, seq)
                raw = self.source.get(ik)
                if (raw is not None
                        and not self.source.get(
                            _seq_key(_COMMIT_PREFIX, seq))):
                    break  # first pending — stop
                if raw is not None:
                    try:
                        rec = self._decode(raw)
                    except ValueError:
                        rec = None
                    if isinstance(rec, ReorgRecord):
                        # a settled switch's staged branch goes with it
                        for i in range(len(rec.adopted_hashes)):
                            self.source.remove(_block_key(
                                seq, rec.ancestor_number + 1 + i
                            ))
                self.source.remove(ik)
                self.source.remove(_seq_key(_COMMIT_PREFIX, seq))
                removed += 1
                seq += 1
            if seq != tail:
                self.source.put(_TAIL_KEY, int(seq).to_bytes(8, "big"))
        return removed

    @property
    def depth(self) -> int:
        """Live record span (head - tail) — a journal-health gauge."""
        with self._lock:
            return self._get_int(_HEAD_KEY) - self._get_int(_TAIL_KEY)


# ------------------------------------------------------------- recovery


@dataclass
class RecoveryReport:
    scanned: int = 0  # pending intents found
    repaired: int = 0  # windows verified complete; mark restored
    rolled_back: int = 0  # windows undone
    blocks_removed: int = 0
    missing_nodes: int = 0  # state-walk misses across failed verifies
    corrupt_nodes: int = 0  # content-address mismatches found
    reorgs_completed: int = 0  # torn switches rolled FORWARD to new tip
    reorgs_abandoned: int = 0  # switches killed before any removal
    best_before: int = 0
    best_after: int = 0
    actions: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return self.scanned == 0


def recover(blockchain, log: Optional[Callable[[str], None]] = None,
            config=None, txpool=None) -> RecoveryReport:
    """The startup pass (ReplayDriver.recover / ServiceBoard.__init__):
    settle every pending intent — repair complete windows, roll back
    partial ones, complete or abandon torn chain switches, leave
    ``best_block_number`` on the last block whose state fully verifies.
    Idempotent: a crash DURING recovery re-enters the same scan.

    ``config`` (a KhipuConfig) enables reorg roll-forward: a torn
    switch re-executes its staged branch from the ancestor state.
    Without one (legacy callers) the node settles at the ancestor —
    still a consistent chain prefix, finished on the next start.

    ``txpool`` (in-process recovery, e.g. ReorgManager's mid-switch
    failure path): orphan txs staged in a settled reorg intent are
    recycled into it through the pool's replacement rules. Boot-time
    recovery passes None — a restarted process has no pool to
    protect.

    Torn segment tails (kesque engine, docs/kesque.md): the storage
    layer's OWN open-time repair runs before this pass ever sees the
    stores — ``Segment.open`` scans back over any frame torn by a
    death inside ``kesque.append``/``kesque.roll`` and truncates to
    the last valid boundary, and a sidecar index that covers the
    truncated bytes (a ``kesque.index`` death) is discarded for a
    full rebuild. What recovery sees is therefore a PREFIX of the
    appended records; ``_verify_window``'s hash-verified reachability
    walk then classifies any record lost off the tail as ``missing``
    and rolls the torn window back — the same verdict a torn sqlite
    write would get. The repairs themselves are surfaced as
    ``storage:`` action lines via ``storages.storage_repair_report``
    so the scan-back is visible in recovery output."""
    storages = blockchain.storages
    # the device mirror is volatile: recovery verification must see
    # exactly what a real restart would see — host-durable state only.
    # (In-process crash tests would otherwise "recover" through HBM.)
    detach = getattr(storages, "detach_mirror", None)
    if detach is not None:
        detach()
    journal = storages.window_journal
    report = RecoveryReport(best_before=storages.app_state.best_block_number)
    # open-time storage repairs (kesque torn-tail scan-back / index
    # rebuild) happened when the engine opened; put them on the record
    repairs = getattr(storages, "storage_repair_report", None)
    if repairs is not None:
        for line in repairs():
            report.actions.append(f"storage: {line}")
    pending = journal.pending()
    report.scanned = len(pending)
    emit = log or (lambda s: None)
    rollback_floor: Optional[int] = None  # first rolled-back lo

    for rec in pending:
        if isinstance(rec, ReorgRecord):
            outcome = _settle_reorg(
                blockchain, rec, journal, report, config, rollback_floor,
                txpool=txpool,
            )
            journal.log_commit(rec.seq)
            if outcome == "rolled_forward":
                # the chain was rebuilt through the adopted branch:
                # later pending window intents (journaled by the
                # crashed windowed adoption) verify against the
                # re-executed blocks
                rollback_floor = None
            continue
        verified = False
        if rollback_floor is None:
            verified = _verify_window(blockchain, rec, report)
        if verified:
            journal.log_commit(rec.seq)
            report.repaired += 1
            report.actions.append(
                f"window [{rec.lo}..{rec.hi}] verified complete; "
                "commit mark restored"
            )
        else:
            removed = _rollback_window(blockchain, rec)
            journal.log_commit(rec.seq)  # settled by rollback
            report.rolled_back += 1
            report.blocks_removed += removed
            if rollback_floor is None:
                rollback_floor = rec.lo
            report.actions.append(
                f"window [{rec.lo}..{rec.hi}] rolled back "
                f"({removed} partial block records removed)"
            )

    if rollback_floor is not None:
        # best falls back to the last fully-committed window; the block
        # sources already recomputed their best on remove
        app_best = storages.app_state.best_block_number
        new_best = min(app_best, rollback_floor - 1,
                       max(0, storages.best_block_number))
        storages.app_state.best_block_number = max(0, new_best)
        report.actions.append(
            f"best block rolled back {app_best} -> "
            f"{storages.app_state.best_block_number}"
        )
    journal.prune()
    report.best_after = storages.app_state.best_block_number
    for line in report.actions:
        emit(f"recover: {line}")
    return report


def _verify_window(blockchain, rec: IntentRecord,
                   report: RecoveryReport) -> bool:
    """Is the window FULLY persisted? Every block record present under
    its expected root, and the state trie at the window's last root
    reachable end-to-end with every node content-address clean."""
    from khipu_tpu.storage.compactor import verify_reachable

    s = blockchain.storages
    for i, n in enumerate(range(rec.lo, rec.hi + 1)):
        header = blockchain.get_header_by_number(n)
        if header is None or header.state_root != rec.roots[i]:
            return False
        if (s.block_body_storage.get(n) is None
                or s.receipts_storage.get(n) is None
                or s.total_difficulty_storage.get(n) is None
                or s.block_numbers.hash_of(n) != header.hash):
            return False
    walk = verify_reachable(
        s.account_node_storage, s.storage_node_storage,
        s.evmcode_storage, rec.roots[-1], verify_hashes=True,
    )
    report.missing_nodes += walk.missing
    report.corrupt_nodes += walk.corrupt
    return walk.missing == 0 and walk.corrupt == 0


def _rollback_window(blockchain, rec: IntentRecord) -> int:
    """Remove whatever block records the dead job managed to write.
    Deliberately NOT Blockchain.remove_block: that needs a decodable
    header+body pair, and a torn window may have either half missing."""
    from khipu_tpu.domain.block import BlockBody

    s = blockchain.storages
    removed = 0
    for n in range(rec.lo, rec.hi + 1):
        header_raw = s.block_header_storage.get(n)
        body_raw = s.block_body_storage.get(n)
        if header_raw is None and body_raw is None \
                and s.receipts_storage.get(n) is None:
            continue
        removed += 1
        if body_raw is not None:
            try:
                for tx in BlockBody.decode(body_raw).transactions:
                    s.transaction_storage.source.remove(tx.hash)
                    if JOURNEY.enabled:
                        # recovery truth on the passport: the tx's
                        # half-committed window never reached the
                        # commit mark — its journey ends before
                        # durable and resumes when the re-import
                        # stamps fresh pages
                        JOURNEY.record(tx.hash, "journal.rollback",
                                       block=n)
            except Exception:
                pass  # a torn body still gets its by-number records cut
        if header_raw is not None:
            h = s.block_numbers.hash_of(n)
            if h is not None:
                s.block_numbers.remove(h)
        s.block_header_storage.source.remove(n)
        s.block_body_storage.source.remove(n)
        s.receipts_storage.source.remove(n)
        s.total_difficulty_storage.source.remove(n)
    return removed


def _recycle_orphans(txpool, rec: ReorgRecord, report) -> None:
    """Re-enter the losing branch's orphan txs through the pool's
    standard replacement rules (a pooled higher-bid same-slot tx keeps
    its place)."""
    if txpool is None or not rec.orphan_tx_rlp:
        return
    recycled = 0
    for stx in rec.orphan_txs():
        if stx.sender is None:
            continue
        try:
            if txpool.add(stx):
                recycled += 1
        except ValueError:
            pass
    if recycled:
        report.actions.append(
            f"reorg at #{rec.ancestor_number}: {recycled} orphaned "
            f"txs recycled into the pool"
        )


def _settle_reorg(blockchain, rec: ReorgRecord, journal, report,
                  config, rollback_floor, txpool=None) -> str:
    """Resolve one pending reorg intent to a whole chain.

    ABANDON when the old chain is untouched (the kill hit after the
    intent fsync but before the rollback removed anything): the node
    is already at exactly the old chain — nothing to do.

    ROLL FORWARD otherwise: the switch is torn (old blocks partially
    removed, adopted blocks partially saved, or any mix). Strip
    everything above the ancestor and re-execute the staged branch
    from the durable ancestor state — the node lands at exactly the
    new chain. Re-execution goes through the same validated import
    path as live sync, so the recovered chain is bit-exact vs a fresh
    replay of the winning branch."""
    s = blockchain.storages
    anc = rec.ancestor_number

    # intactness is judged by block PRESENCE, not the best pointer:
    # the switch drops best to the ancestor before it removes anything
    # (serving safety — sync/reorg.py _rollback), so a kill there
    # leaves best low with the old chain untouched. Restore best.
    intact = s.app_state.best_block_number in (rec.old_top, anc)
    if intact:
        for i, h in enumerate(rec.old_hashes):
            n = anc + 1 + i
            if (s.block_numbers.hash_of(n) != h
                    or s.block_header_storage.get(n) is None
                    or s.block_body_storage.get(n) is None):
                intact = False
                break
    if intact:
        s.app_state.best_block_number = rec.old_top
        report.reorgs_abandoned += 1
        report.actions.append(
            f"reorg at #{anc} abandoned: old chain intact through "
            f"#{rec.old_top}"
        )
        return "abandoned"

    # mirror-image fast path: the kill hit AFTER adoption finished
    # (pre-finalize) — if the new chain is fully present and its tip
    # state verifies end-to-end, completing is just the commit mark
    if _new_chain_complete(blockchain, rec, report):
        report.reorgs_completed += 1
        report.actions.append(
            f"reorg at #{anc} completed in place: adopted chain "
            f"verified through #{rec.new_top}"
        )
        _recycle_orphans(txpool, rec, report)
        return "rolled_forward"

    top = max(rec.old_top, rec.new_top,
              s.app_state.best_block_number,
              max(0, s.best_block_number))
    removed = _remove_above(blockchain, anc, top)
    s.app_state.best_block_number = anc
    report.blocks_removed += removed

    blocks = journal.staged_blocks(rec)
    # roll-forward needs a config (gas schedule, chain id) and an
    # ancestor whose state a prior window rollback did not take out;
    # failing either, the ancestor prefix is the consistent stop
    if (config is None or blocks is None
            or (rollback_floor is not None and rollback_floor <= anc)):
        report.rolled_back += 1
        report.actions.append(
            f"reorg at #{anc} rolled back to ancestor "
            f"({removed} block records removed; no roll-forward "
            f"{'config' if config is None else 'state'})"
        )
        # at the ancestor NEITHER branch's txs are mined
        _recycle_orphans(txpool, rec, report)
        return "rolled_back"

    from khipu_tpu.sync.replay import ReplayDriver, ReplayStats

    driver = ReplayDriver(blockchain, config)
    stats = ReplayStats()
    for b in blocks:
        driver._execute_and_insert(b, stats)
    report.reorgs_completed += 1
    report.actions.append(
        f"reorg at #{anc} rolled forward: {removed} torn block records "
        f"removed, {len(blocks)} adopted blocks re-executed to "
        f"#{rec.new_top}"
    )
    _recycle_orphans(txpool, rec, report)
    return "rolled_forward"


def _new_chain_complete(blockchain, rec: ReorgRecord, report) -> bool:
    """Every adopted block at its number with full records, best at
    the new tip, and the tip state reachable with clean content
    addresses — same bar _verify_window holds torn windows to."""
    from khipu_tpu.storage.compactor import verify_reachable

    s = blockchain.storages
    if s.app_state.best_block_number != rec.new_top:
        return False
    for i, h in enumerate(rec.adopted_hashes):
        n = rec.ancestor_number + 1 + i
        if (s.block_numbers.hash_of(n) != h
                or s.block_header_storage.get(n) is None
                or s.block_body_storage.get(n) is None
                or s.receipts_storage.get(n) is None
                or s.total_difficulty_storage.get(n) is None):
            return False
    tip = blockchain.get_header_by_number(rec.new_top)
    walk = verify_reachable(
        s.account_node_storage, s.storage_node_storage,
        s.evmcode_storage, tip.state_root, verify_hashes=True,
    )
    report.missing_nodes += walk.missing
    report.corrupt_nodes += walk.corrupt
    return walk.missing == 0 and walk.corrupt == 0


def _remove_above(blockchain, ancestor: int, top: int) -> int:
    """Raw by-number removal of every block record in
    (ancestor, top] — old-chain remnants and partially-adopted blocks
    alike. NOT Blockchain.remove_block: a torn switch may have either
    half of any record missing."""
    from khipu_tpu.domain.block import BlockBody

    s = blockchain.storages
    removed = 0
    for n in range(ancestor + 1, top + 1):
        header_raw = s.block_header_storage.get(n)
        body_raw = s.block_body_storage.get(n)
        if (header_raw is None and body_raw is None
                and s.receipts_storage.get(n) is None):
            continue
        removed += 1
        if body_raw is not None:
            try:
                for tx in BlockBody.decode(body_raw).transactions:
                    s.transaction_storage.source.remove(tx.hash)
                    if JOURNEY.enabled:
                        JOURNEY.record(tx.hash, "journal.rollback",
                                       block=n)
            except Exception:
                pass  # a torn body still gets its by-number records cut
        h = s.block_numbers.hash_of(n)
        if h is not None:
            s.block_numbers.remove(h)
        s.block_header_storage.source.remove(n)
        s.block_body_storage.source.remove(n)
        s.receipts_storage.source.remove(n)
        s.total_difficulty_storage.source.remove(n)
    return removed
