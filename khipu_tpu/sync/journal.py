"""Write-ahead window-commit journal + crash recovery.

The deep pipeline (sync/replay.py) moved root checks, node/code
persistence and block saves onto a background collector thread. A
process death mid-job leaves node storage, block storage and
``AppStateStorage.best_block_number`` mutually inconsistent — and
before this module nothing on startup detected or repaired that.

Protocol (two records per window, over the ``journal`` KV topic):

* INTENT — written and flushed BEFORE the background job's first
  mutation (the driver writes it at submit, the job runs strictly
  after): ``[b"I", seq, lo, hi, parent_root, [expected_root, ...]]``
  under key ``b"J" + seq``. The expected roots are the header state
  roots the collector will verify — recovery re-verifies against the
  same values.
* COMMIT — ``b"\\x01"`` under key ``b"C" + seq``, written after the
  window's last ``save_block`` advanced ``best_block_number``.

A crash between the two leaves a pending intent. ``recover()`` scans
them in order and, per window, either REPAIRS (every block present
with the expected root, td/body/receipts stored, and the state trie at
the window's last root fully reachable with every node's bytes
matching its content address — node puts are content-addressed and
idempotent, so a partially re-persisted window that verifies is simply
complete) or ROLLS BACK (removes the window's partial block records
and resets ``best_block_number`` to the last fully-committed window;
orphaned trie nodes are harmless — content-addressed, unreferenced,
reclaimed by the compactor). Once one window rolls back every later
pending window rolls back too: its parent chain is gone.

Crash points and their outcomes are enumerated in docs/recovery.md;
tests/test_chaos.py provokes them with the chaos harness.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from khipu_tpu.base.rlp import rlp_decode, rlp_encode

_INTENT_PREFIX = b"J"
_COMMIT_PREFIX = b"C"
_HEAD_KEY = b"head"  # next seq to assign
_TAIL_KEY = b"tail"  # lowest seq not yet pruned


def _seq_key(prefix: bytes, seq: int) -> bytes:
    return prefix + int(seq).to_bytes(8, "big")


def _int_bytes(n: int) -> bytes:
    return int(n).to_bytes(8, "big").lstrip(b"\x00") or b"\x00"


@dataclass
class IntentRecord:
    seq: int
    lo: int
    hi: int
    parent_root: bytes
    roots: List[bytes]  # expected header state roots, lo..hi


class WindowJournal:
    """The WAL over one KeyValueDataSource (``Storages.journal_source``
    — every engine gives it the same durability as the block stores;
    ``flush`` after the intent is the fsync barrier where the engine
    has one)."""

    def __init__(self, source):
        self.source = source
        self._lock = threading.Lock()
        # registry pull collector, replace-by-key: the newest journal
        # (tests build hundreds) owns the khipu_journal_depth sample
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "journal",
                lambda: [("khipu_journal_depth", "gauge", {},
                          self.depth)],
            )
        except Exception:  # pragma: no cover
            pass

    # ----------------------------------------------------------- pointers

    def _get_int(self, key: bytes, default: int = 0) -> int:
        v = self.source.get(key)
        return int.from_bytes(v, "big") if v else default

    def _flush(self) -> None:
        fl = getattr(self.source, "flush", None)
        if fl:
            fl()

    # ------------------------------------------------------------ writing

    def log_intent(self, lo: int, hi: int, parent_root: bytes,
                   expected_roots: List[bytes]) -> int:
        """Durable BEFORE the caller mutates anything; returns the seq
        for the matching ``log_commit``. Record first, head second: a
        crash between the two orphans a record whose job never started
        — recovery's tail..head scan correctly ignores it."""
        if len(expected_roots) != hi - lo + 1:
            raise ValueError("one expected root per block of the window")
        with self._lock:
            seq = self._get_int(_HEAD_KEY)
            self.source.put(
                _seq_key(_INTENT_PREFIX, seq),
                rlp_encode([
                    b"I", _int_bytes(seq), _int_bytes(lo), _int_bytes(hi),
                    bytes(parent_root),
                    [bytes(r) for r in expected_roots],
                ]),
            )
            self.source.put(_HEAD_KEY, int(seq + 1).to_bytes(8, "big"))
            self._flush()
        return seq

    def log_commit(self, seq: int) -> None:
        """The window's blocks are saved and best advanced — or
        recovery settled the intent (repair OR rollback); either way
        the intent needs no further attention."""
        with self._lock:
            self.source.put(_seq_key(_COMMIT_PREFIX, seq), b"\x01")
            self._flush()

    # ------------------------------------------------------------ reading

    def pending(self) -> List[IntentRecord]:
        """Intents without a commit mark, ascending — the windows a
        crash may have left half-persisted."""
        out: List[IntentRecord] = []
        with self._lock:
            tail = self._get_int(_TAIL_KEY)
            head = self._get_int(_HEAD_KEY)
            for seq in range(tail, head):
                raw = self.source.get(_seq_key(_INTENT_PREFIX, seq))
                if raw is None:
                    continue
                if self.source.get(_seq_key(_COMMIT_PREFIX, seq)):
                    continue
                out.append(self._decode(raw))
        return out

    @staticmethod
    def _decode(raw: bytes) -> IntentRecord:
        tag, seq, lo, hi, parent_root, roots = rlp_decode(raw)
        if tag != b"I":
            raise ValueError(f"bad journal record tag {tag!r}")
        return IntentRecord(
            seq=int.from_bytes(seq, "big"),
            lo=int.from_bytes(lo, "big"),
            hi=int.from_bytes(hi, "big"),
            parent_root=parent_root,
            roots=list(roots),
        )

    def prune(self) -> int:
        """Drop the settled prefix (intent+commit pairs below the first
        pending intent); returns records removed. Bounds the journal to
        O(in-flight windows)."""
        removed = 0
        with self._lock:
            tail = self._get_int(_TAIL_KEY)
            head = self._get_int(_HEAD_KEY)
            seq = tail
            while seq < head:
                ik = _seq_key(_INTENT_PREFIX, seq)
                if (self.source.get(ik) is not None
                        and not self.source.get(
                            _seq_key(_COMMIT_PREFIX, seq))):
                    break  # first pending — stop
                self.source.remove(ik)
                self.source.remove(_seq_key(_COMMIT_PREFIX, seq))
                removed += 1
                seq += 1
            if seq != tail:
                self.source.put(_TAIL_KEY, int(seq).to_bytes(8, "big"))
        return removed

    @property
    def depth(self) -> int:
        """Live record span (head - tail) — a journal-health gauge."""
        with self._lock:
            return self._get_int(_HEAD_KEY) - self._get_int(_TAIL_KEY)


# ------------------------------------------------------------- recovery


@dataclass
class RecoveryReport:
    scanned: int = 0  # pending intents found
    repaired: int = 0  # windows verified complete; mark restored
    rolled_back: int = 0  # windows undone
    blocks_removed: int = 0
    missing_nodes: int = 0  # state-walk misses across failed verifies
    corrupt_nodes: int = 0  # content-address mismatches found
    best_before: int = 0
    best_after: int = 0
    actions: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return self.scanned == 0


def recover(blockchain, log: Optional[Callable[[str], None]] = None
            ) -> RecoveryReport:
    """The startup pass (ReplayDriver.recover / ServiceBoard.__init__):
    settle every pending intent — repair complete windows, roll back
    partial ones, leave ``best_block_number`` on the last window whose
    state fully verifies. Idempotent: a crash DURING recovery re-enters
    the same scan."""
    storages = blockchain.storages
    # the device mirror is volatile: recovery verification must see
    # exactly what a real restart would see — host-durable state only.
    # (In-process crash tests would otherwise "recover" through HBM.)
    detach = getattr(storages, "detach_mirror", None)
    if detach is not None:
        detach()
    journal = storages.window_journal
    report = RecoveryReport(best_before=storages.app_state.best_block_number)
    pending = journal.pending()
    report.scanned = len(pending)
    emit = log or (lambda s: None)
    rollback_floor: Optional[int] = None  # first rolled-back lo

    for rec in pending:
        verified = False
        if rollback_floor is None:
            verified = _verify_window(blockchain, rec, report)
        if verified:
            journal.log_commit(rec.seq)
            report.repaired += 1
            report.actions.append(
                f"window [{rec.lo}..{rec.hi}] verified complete; "
                "commit mark restored"
            )
        else:
            removed = _rollback_window(blockchain, rec)
            journal.log_commit(rec.seq)  # settled by rollback
            report.rolled_back += 1
            report.blocks_removed += removed
            if rollback_floor is None:
                rollback_floor = rec.lo
            report.actions.append(
                f"window [{rec.lo}..{rec.hi}] rolled back "
                f"({removed} partial block records removed)"
            )

    if rollback_floor is not None:
        # best falls back to the last fully-committed window; the block
        # sources already recomputed their best on remove
        app_best = storages.app_state.best_block_number
        new_best = min(app_best, rollback_floor - 1,
                       max(0, storages.best_block_number))
        storages.app_state.best_block_number = max(0, new_best)
        report.actions.append(
            f"best block rolled back {app_best} -> "
            f"{storages.app_state.best_block_number}"
        )
    journal.prune()
    report.best_after = storages.app_state.best_block_number
    for line in report.actions:
        emit(f"recover: {line}")
    return report


def _verify_window(blockchain, rec: IntentRecord,
                   report: RecoveryReport) -> bool:
    """Is the window FULLY persisted? Every block record present under
    its expected root, and the state trie at the window's last root
    reachable end-to-end with every node content-address clean."""
    from khipu_tpu.storage.compactor import verify_reachable

    s = blockchain.storages
    for i, n in enumerate(range(rec.lo, rec.hi + 1)):
        header = blockchain.get_header_by_number(n)
        if header is None or header.state_root != rec.roots[i]:
            return False
        if (s.block_body_storage.get(n) is None
                or s.receipts_storage.get(n) is None
                or s.total_difficulty_storage.get(n) is None
                or s.block_numbers.hash_of(n) != header.hash):
            return False
    walk = verify_reachable(
        s.account_node_storage, s.storage_node_storage,
        s.evmcode_storage, rec.roots[-1], verify_hashes=True,
    )
    report.missing_nodes += walk.missing
    report.corrupt_nodes += walk.corrupt
    return walk.missing == 0 and walk.corrupt == 0


def _rollback_window(blockchain, rec: IntentRecord) -> int:
    """Remove whatever block records the dead job managed to write.
    Deliberately NOT Blockchain.remove_block: that needs a decodable
    header+body pair, and a torn window may have either half missing."""
    from khipu_tpu.domain.block import BlockBody

    s = blockchain.storages
    removed = 0
    for n in range(rec.lo, rec.hi + 1):
        header_raw = s.block_header_storage.get(n)
        body_raw = s.block_body_storage.get(n)
        if header_raw is None and body_raw is None \
                and s.receipts_storage.get(n) is None:
            continue
        removed += 1
        if body_raw is not None:
            try:
                for tx in BlockBody.decode(body_raw).transactions:
                    s.transaction_storage.source.remove(tx.hash)
            except Exception:
                pass  # a torn body still gets its by-number records cut
        if header_raw is not None:
            h = s.block_numbers.hash_of(n)
            if h is not None:
                s.block_numbers.remove(h)
        s.block_header_storage.source.remove(n)
        s.block_body_storage.source.remove(n)
        s.receipts_storage.source.remove(n)
        s.total_difficulty_storage.source.remove(n)
    return removed
