"""Fixture-chain construction: execute txs to derive consensus-true
headers, producing a chain the replay driver can verify bit-exactly.

Role of the reference's mining/BlockGenerator.scala:31 (prepareBlock —
execute the txs, take the resulting roots/gas into the new header),
minus PoW sealing. Used by tests and the replay benchmark to provision
chains offline (no network in this environment).
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block, BlockBody
from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import SignedTransaction
from khipu_tpu.domain.difficulty import calc_difficulty
from khipu_tpu.ledger.bloom import bloom_union
from khipu_tpu.ledger.ledger import execute_block
from khipu_tpu.validators.roots import (
    ommers_hash,
    receipts_root,
    transactions_root,
)


class ChainBuilder:
    """Appends consensus-valid blocks by executing their transactions
    (BlockGenerator/prepareBlock role)."""

    def __init__(self, blockchain: Blockchain, config: KhipuConfig,
                 genesis: GenesisSpec):
        self.blockchain = blockchain
        self.config = config
        self.genesis = blockchain.load_genesis(genesis)
        self.head = self.genesis

    @classmethod
    def from_head(cls, blockchain: Blockchain,
                  config: KhipuConfig) -> "ChainBuilder":
        """Attach to an already-initialized chain at its current head
        (the miner's entry point — no genesis loading)."""
        b = cls.__new__(cls)
        b.blockchain = blockchain
        b.config = config
        b.genesis = blockchain.get_block_by_number(0)
        b.head = blockchain.get_block_by_number(
            blockchain.best_block_number
        )
        return b

    def add_block(
        self,
        txs: Sequence[SignedTransaction] = (),
        coinbase: Optional[bytes] = None,
        timestamp: Optional[int] = None,
        extra_data: bytes = b"",
        ommers: Sequence[BlockHeader] = (),
    ) -> Block:
        parent = self.head.header
        ts = (
            timestamp
            if timestamp is not None
            else parent.unix_timestamp + 13
        )
        header = BlockHeader(
            parent_hash=parent.hash,
            ommers_hash=ommers_hash(tuple(ommers)),
            beneficiary=coinbase or parent.beneficiary,
            state_root=b"\x00" * 32,  # filled after execution
            transactions_root=transactions_root(txs),
            receipts_root=b"\x00" * 32,
            logs_bloom=b"\x00" * 256,
            # consensus-true difficulty so replay can validate headers
            difficulty=calc_difficulty(
                ts, parent, self.config.blockchain
            ),
            number=parent.number + 1,
            gas_limit=parent.gas_limit,
            gas_used=0,
            unix_timestamp=ts,
            extra_data=extra_data,
        )
        draft = Block(header, BlockBody(tuple(txs), tuple(ommers)))
        result = execute_block(
            draft,
            parent.state_root,
            self.blockchain.get_world_state,
            self.config,
            validate=False,
        )
        sealed = Block(
            BlockHeader(
                parent_hash=header.parent_hash,
                ommers_hash=header.ommers_hash,
                beneficiary=header.beneficiary,
                state_root=result.world.root_hash,
                transactions_root=header.transactions_root,
                receipts_root=receipts_root(result.receipts),
                logs_bloom=bloom_union(
                    r.logs_bloom for r in result.receipts
                ),
                difficulty=header.difficulty,
                number=header.number,
                gas_limit=header.gas_limit,
                gas_used=result.gas_used,
                unix_timestamp=header.unix_timestamp,
                extra_data=header.extra_data,
            ),
            draft.body,
        )
        td = (self.blockchain.get_total_difficulty(parent.number) or 0) + (
            sealed.header.difficulty
        )
        self.blockchain.save_block(
            sealed, result.receipts, td, result.world
        )
        self.head = sealed
        return sealed
