"""Gameday invariant checkers (docs/gameday.md).

The reusable half of the gameday harness: every checker takes live
objects (blockchains, replicas, the fleet router, a rebalancer) plus
the run's observations and returns an ``InvariantResult`` — named,
machine-checkable, and identical whether it gates the headline
``bench.py --gameday`` run, one cell of the pairwise hazard matrix
(tests/test_gameday.py), or an ad-hoc chaos experiment.

The invariant set is the paper's operational contract under
composition:

* ``ryw``          — zero read-your-writes violations across failover
  AND retraction (the loadgen's built-in checker is the witness).
* ``retraction``   — a reorg-retracted block is retracted from EVERY
  serving replica's view, and each replica's chain is a hash-exact
  prefix of the primary's canonical chain.
* ``token_floor``  — consistent-read tokens anchor to the canonical
  chain; a token whose anchor was retracted re-anchors monotonically
  DOWN to the fork ancestor, never to a phantom height above it.
* ``epoch``        — the shard ring lands at exactly the old or the
  new epoch (never a torn intermediate) once recovery has run.
* ``roots``        — final state roots and header hashes are
  bit-exact against a fresh serial replay of the same blocks.
* ``admission_p99``— p99 latency of ADMITTED requests stays within
  budget (default 5x the unloaded floor): overload sheds, it does not
  queue into the latency tail.

``record_run`` aggregates per-run outcomes into the module's
``khipu_gameday_*`` registry families so a gameday leaves the same
metrics audit trail as every other subsystem.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "InvariantResult",
    "InvariantReport",
    "check_ryw",
    "check_retraction",
    "check_token_floor",
    "check_epoch",
    "check_roots_bit_exact",
    "check_admission_p99",
    "record_run",
    "gameday_stats",
]


@dataclass(frozen=True)
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""

    def __bool__(self) -> bool:
        return self.ok


class InvariantReport:
    """Collects results; ``ok`` only when every check passed. ``raise_
    if_failed`` is the gate half (bench exits non-zero), ``failures``
    the test half (assert not report.failures)."""

    def __init__(self):
        self.results: List[InvariantResult] = []

    def add(self, result: InvariantResult) -> InvariantResult:
        self.results.append(result)
        return result

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    @property
    def failures(self) -> List[InvariantResult]:
        return [r for r in self.results if not r.ok]

    def summary(self) -> Dict[str, bool]:
        return {r.name: r.ok for r in self.results}

    def raise_if_failed(self) -> None:
        if not self.ok:
            lines = "; ".join(
                f"{r.name}: {r.detail or 'failed'}" for r in self.failures
            )
            raise AssertionError(f"gameday invariants violated — {lines}")


# ------------------------------------------------------------- checkers


def check_ryw(violations: Sequence) -> InvariantResult:
    """Zero read-your-writes violations. ``violations`` is
    ``LoadReport.violations`` — the loadgen's per-client monotonicity
    and pending-visibility checker already spans failover and
    retraction, so an empty list IS the invariant."""
    return InvariantResult(
        "ryw", len(violations) == 0,
        "" if not violations else f"{len(violations)} violation(s): "
        f"{violations[:3]}",
    )


def check_retraction(primary_bc, replicas: Iterable,
                     retracted: Sequence[Tuple[int, bytes]],
                     ) -> InvariantResult:
    """Every (number, old_hash) the fork battle retracted must be gone
    from every serving replica, and each replica's chain must be a
    hash-exact prefix of the primary's canonical chain (a replica that
    kept a phantom block would serve reads no canonical node ever
    could). Dead replicas are skipped — they serve nothing."""
    problems: List[str] = []
    for rep in replicas:
        if not rep.alive():
            continue
        bc = rep.blockchain
        for number, old_hash in retracted:
            header = bc.get_header_by_number(number)
            if header is not None and header.hash == old_hash:
                problems.append(
                    f"{rep.name}: retracted block {number} still served"
                )
        top = min(bc.best_block_number, primary_bc.best_block_number)
        for number in range(top + 1):
            mine = bc.get_header_by_number(number)
            theirs = primary_bc.get_header_by_number(number)
            if mine is None or theirs is None or mine.hash != theirs.hash:
                problems.append(
                    f"{rep.name}: diverges from primary at {number}"
                )
                break
    return InvariantResult(
        "retraction", not problems, "; ".join(problems[:4]),
    )


def check_token_floor(router, retracted: Sequence[Tuple[int, bytes]],
                      ancestor: Optional[int]) -> InvariantResult:
    """Tokens anchor honestly after the fork battle: a freshly minted
    primary token must sit ON the canonical chain, and a token bearing
    a retracted (number, hash) must floor at or below the fork
    ancestor — the strongest honest promise left once its block is
    gone. Asserting via the router's own ``_token_floor`` checks the
    exact code path every routed read takes."""
    from khipu_tpu.serving.router import ReadToken

    bc = router.primary.service.blockchain
    tok = ReadToken.decode(router._mint(None))
    if tok is None:
        return InvariantResult("token_floor", False, "mint undecodable")
    header = bc.get_header_by_number(tok.number)
    if header is None or (tok.block_hash
                          and header.hash != tok.block_hash):
        return InvariantResult(
            "token_floor", False,
            f"minted token anchors off-chain at {tok.number}",
        )
    for number, old_hash in retracted:
        stale = ReadToken(router.chain_id, number, old_hash)
        floor = router._token_floor(stale)
        limit = ancestor if ancestor is not None else bc.best_block_number
        if floor is None or floor > min(number, limit):
            return InvariantResult(
                "token_floor", False,
                f"retracted token @{number} floored at {floor}, "
                f"ancestor {ancestor}",
            )
    return InvariantResult("token_floor", True)


def check_epoch(rebalancer, old_epoch: int,
                new_epoch: int) -> InvariantResult:
    """Exactly-old-or-new: after recovery the committed ring epoch is
    one of the two legal landing points and no transition is still
    staged — a torn intermediate epoch means a reader could see a
    placement neither plan ever promised."""
    status = rebalancer.status()
    epoch = status["epoch"]
    if rebalancer.in_transition:
        return InvariantResult(
            "epoch", False, f"still in transition at epoch {epoch}",
        )
    ok = epoch in (old_epoch, new_epoch)
    return InvariantResult(
        "epoch", ok,
        "" if ok else
        f"epoch {epoch} is neither old {old_epoch} nor new {new_epoch}",
    )


def check_roots_bit_exact(bc, reference_bc) -> InvariantResult:
    """Final convergence: same best number, and every header's hash
    AND state root bit-exact against a fresh serial replay
    (``reference_bc``) of the canonical blocks. This is the invariant
    that catches a hazard corrupting state while every serving-plane
    check still passes."""
    best, ref_best = bc.best_block_number, reference_bc.best_block_number
    if best != ref_best:
        return InvariantResult(
            "roots", False, f"best {best} != reference {ref_best}",
        )
    for number in range(best + 1):
        mine = bc.get_header_by_number(number)
        ref = reference_bc.get_header_by_number(number)
        if mine is None or ref is None:
            return InvariantResult(
                "roots", False, f"missing header at {number}",
            )
        if mine.hash != ref.hash:
            return InvariantResult(
                "roots", False, f"hash mismatch at {number}",
            )
        if mine.state_root != ref.state_root:
            return InvariantResult(
                "roots", False, f"state root mismatch at {number}",
            )
    return InvariantResult("roots", True)


def check_admission_p99(p99_ms: float, floor_p99_ms: float,
                        budget: float = 5.0) -> InvariantResult:
    """Admitted-request p99 within ``budget`` x the unloaded floor.
    Overload is survived by SHEDDING (-32005), so what the admission
    controller lets through must still be fast."""
    limit = floor_p99_ms * budget
    ok = p99_ms <= limit
    return InvariantResult(
        "admission_p99", ok,
        "" if ok else
        f"p99 {p99_ms:.2f}ms > {budget:.1f}x floor "
        f"({floor_p99_ms:.2f}ms -> limit {limit:.2f}ms)",
    )


# --------------------------------------------------- registry families


class _GamedayStats:
    def __init__(self):
        self._lock = threading.Lock()
        self.runs = 0
        self.events_by_kind: Dict[str, int] = {}
        self.checks_by_invariant: Dict[str, int] = {}
        self.failures_by_invariant: Dict[str, int] = {}
        self.last_p99_ms = 0.0

    def record(self, events_by_kind: Dict[str, int],
               report: InvariantReport,
               p99_ms: Optional[float] = None) -> None:
        with self._lock:
            self.runs += 1
            for kind, n in events_by_kind.items():
                self.events_by_kind[kind] = (
                    self.events_by_kind.get(kind, 0) + n
                )
            for r in report.results:
                self.checks_by_invariant[r.name] = (
                    self.checks_by_invariant.get(r.name, 0) + 1
                )
                if not r.ok:
                    self.failures_by_invariant[r.name] = (
                        self.failures_by_invariant.get(r.name, 0) + 1
                    )
            if p99_ms is not None:
                self.last_p99_ms = float(p99_ms)

    def samples(self) -> list:
        with self._lock:
            out = [
                ("khipu_gameday_runs_total", "counter", {}, self.runs),
                ("khipu_gameday_last_p99_ms", "gauge", {},
                 self.last_p99_ms),
            ]
            for kind, n in sorted(self.events_by_kind.items()):
                out.append((
                    "khipu_gameday_events_total", "counter",
                    {"kind": kind}, n,
                ))
            for name, n in sorted(self.checks_by_invariant.items()):
                out.append((
                    "khipu_gameday_invariant_checks_total", "counter",
                    {"invariant": name}, n,
                ))
                out.append((
                    "khipu_gameday_invariant_failures_total", "counter",
                    {"invariant": name},
                    self.failures_by_invariant.get(name, 0),
                ))
            return out


_STATS = _GamedayStats()


def record_run(events_by_kind: Dict[str, int], report: InvariantReport,
               p99_ms: Optional[float] = None) -> None:
    """Fold one completed gameday run into the khipu_gameday_*
    registry families."""
    _STATS.record(events_by_kind, report, p99_ms)


def gameday_stats() -> _GamedayStats:
    return _STATS


try:
    from khipu_tpu.observability.registry import REGISTRY

    REGISTRY.register_collector("gameday", _STATS.samples)
except Exception:  # pragma: no cover - registry is stdlib-only
    pass
