"""Deterministic fault-injection harness (docs/recovery.md)."""

from khipu_tpu.chaos.plan import (
    FaultLog,
    FaultPlan,
    FaultRule,
    InjectedDeath,
    InjectedFault,
    active,
    apply_config,
    fault_log,
    fault_point,
    fault_value,
    install,
    uninstall,
)

__all__ = [
    "FaultLog",
    "FaultPlan",
    "FaultRule",
    "InjectedDeath",
    "InjectedFault",
    "active",
    "apply_config",
    "fault_log",
    "fault_point",
    "fault_value",
    "install",
    "uninstall",
]
