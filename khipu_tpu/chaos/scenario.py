"""Deterministic gameday scenario engine (docs/gameday.md).

A *scenario* is a scripted hazard timeline composed from the existing
chaos seams — the same ``FaultRule`` machinery the single-hazard
sweeps use — plus hook events for hazards that are actions rather
than faults (a shard join, a fork battle). The load-bearing design
decision is that events are keyed to **progress milestones (block
heights), not wall-clock**: the driver calls ``engine.step(height)``
from its import loop, and an event fires the first time progress
reaches its ``at_height``. Two runs with the same seed therefore see
the same event schedule at the same points in the workload's life, no
matter how fast the host is — wall-clock timelines cannot compose
replayably, milestone timelines can.

Composition is ONE seed end to end: the scenario derives any stagger
or parameter jitter from ``derive(seed, salt, mod)`` (keccak-keyed,
the ``FaultPlan._rng`` convention), and seam events arm rules onto a
single shared ``FaultPlan`` via ``plan.extend`` — per-(rule, site) RNG
independence (chaos/plan.py) guarantees that arming hazard B cannot
shift hazard A's draws.

Watchdog correlation: every fire updates the module-level *current
event id* (``current_event_id()``), which ``Watchdog._trip`` stamps
onto ``khipu_watchdog_trips_total`` as a ``scenario`` label — a trip
during a gameday run is attributable to the hazard that preceded it.

Determinism contract, precisely: ``Scenario.schedule()`` — the
(event id, height, kind, site) list — is a pure function of the
scenario's construction inputs, and ``ScenarioEngine.step`` fires
events in schedule order. What a seam event's armed rule then *hits*
depends on workload progress, which the gameday drivers keep
deterministic by stepping from a single import loop.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from khipu_tpu.chaos.plan import (
    FaultPlan,
    FaultRule,
    InjectedDeath,
    known_seam,
)
from khipu_tpu.observability.trace import event as _trace_event

__all__ = [
    "SEAM_KINDS",
    "HOOK_KINDS",
    "ScenarioEvent",
    "Scenario",
    "ScenarioEngine",
    "derive",
    "current_event_id",
    "clear_current_event",
    "quiet_deaths",
]

# Event kinds that arm a FaultRule on the shared plan. ``die`` models
# a process death at the seam (collector stage, replica tail thread),
# ``raise`` a persistent/transient failure (a dead shard endpoint),
# ``latency``/``corrupt`` the slow-disk and bit-flip hazards.
SEAM_KINDS = ("die", "raise", "latency", "corrupt")

# Action events dispatched to engine hooks: not faults but the
# operational maneuvers the faults compose against.
HOOK_KINDS = ("join", "fork", "call")


def derive(seed: int, salt: str, mod: int) -> int:
    """Deterministic parameter derivation: keccak-keyed like
    ``FaultPlan._rng`` so every scenario knob is a pure function of
    (seed, salt) — no ambient RNG, no wall clock."""
    from khipu_tpu.base.crypto.keccak import keccak256

    digest = keccak256(f"{seed}:{salt}".encode())
    return int.from_bytes(digest[:8], "big") % max(1, int(mod))


@dataclass(frozen=True)
class ScenarioEvent:
    """One timeline entry.

    ``kind`` in SEAM_KINDS arms ``FaultRule(site, kind, ...)`` on the
    shared plan when progress reaches ``at_height``; ``kind`` in
    HOOK_KINDS invokes the engine hook registered under that kind.
    ``params`` for seam kinds: ``after_hits`` (let N more hits of the
    site pass before the rule arms, default 0), ``times`` (fire budget,
    default 1; None = unlimited), ``prob``, ``latency_s``. For hook
    kinds ``params`` flows to the hook verbatim.
    """

    event_id: str
    at_height: int
    kind: str
    site: str = ""
    params: Mapping = field(default_factory=dict)

    def __post_init__(self):
        if self.kind not in SEAM_KINDS and self.kind not in HOOK_KINDS:
            raise ValueError(f"unknown scenario event kind {self.kind!r}")
        if self.kind in SEAM_KINDS:
            if not self.site:
                raise ValueError(f"{self.event_id}: seam event needs a site")
            if not known_seam(self.site):
                raise ValueError(
                    f"{self.event_id}: {self.site!r} is not a registered "
                    "chaos seam (chaos.plan.KNOWN_SEAMS)"
                )
        if self.at_height < 0:
            raise ValueError(f"{self.event_id}: negative at_height")

    def rule(self, armed_after: int) -> FaultRule:
        """The FaultRule this seam event arms, given the site's hit
        count at arm time."""
        p = self.params
        return FaultRule(
            site=self.site,
            kind=self.kind,
            prob=float(p.get("prob", 1.0)),
            after=armed_after + int(p.get("after_hits", 0)),
            times=p.get("times", 1),
            latency_s=float(p.get("latency_s", 0.01)),
        )


class Scenario:
    """An ordered, validated hazard timeline under one seed.

    Events fire in ``(at_height, insertion order)`` — the stable sort
    makes ``schedule()`` (the determinism pin) a pure function of the
    constructor arguments.
    """

    def __init__(self, seed: int, events: List[ScenarioEvent]):
        self.seed = int(seed)
        ids = [e.event_id for e in events]
        if len(set(ids)) != len(ids):
            dupes = sorted({i for i in ids if ids.count(i) > 1})
            raise ValueError(f"duplicate scenario event ids: {dupes}")
        self.events: Tuple[ScenarioEvent, ...] = tuple(
            sorted(events, key=lambda e: e.at_height)
        )

    def schedule(self) -> List[Tuple[str, int, str, str]]:
        """The full (event_id, at_height, kind, site) timeline — what
        'same seed => identical event schedule' pins."""
        return [
            (e.event_id, e.at_height, e.kind, e.site)
            for e in self.events
        ]


# ----------------------------------------------------- current event id

# The most recent scenario event fired, for hazard attribution
# (sticky until the next fire or clear_current_event). A module global
# rather than a thread-local on purpose: the watchdog trips on ITS
# thread for hazards injected from the driver's thread.
_current_lock = threading.Lock()
_current_event: Optional[str] = None


def current_event_id() -> Optional[str]:
    with _current_lock:
        return _current_event


def clear_current_event() -> None:
    global _current_event
    with _current_lock:
        _current_event = None


def _set_current_event(event_id: str) -> None:
    global _current_event
    with _current_lock:
        _current_event = event_id


class ScenarioEngine:
    """Fires a Scenario against a live run.

    The driver calls ``step(height)`` at each progress milestone (the
    gameday bench steps between import windows); every event whose
    ``at_height`` has been reached fires exactly once, in schedule
    order. Seam events ``plan.extend`` a rule armed after the site's
    CURRENT hit count (plus the event's ``after_hits``), hook events
    call the registered hook with the event.
    """

    def __init__(self, scenario: Scenario, plan: FaultPlan,
                 hooks: Optional[Dict[str, Callable]] = None):
        self.scenario = scenario
        self.plan = plan
        self.hooks: Dict[str, Callable] = dict(hooks or {})
        self._pending: List[ScenarioEvent] = list(scenario.events)
        self._lock = threading.Lock()
        # (event_id, fired_at_height) in fire order
        self.fired: List[Tuple[str, int]] = []
        self.events_by_kind: Dict[str, int] = {}
        missing = sorted({
            e.kind for e in self._pending
            if e.kind in HOOK_KINDS and e.kind not in self.hooks
        })
        if missing:
            raise ValueError(f"no hook registered for kinds: {missing}")

    def step(self, height: int) -> List[ScenarioEvent]:
        """Fire every due event; returns them in fire order."""
        due: List[ScenarioEvent] = []
        with self._lock:
            while self._pending and self._pending[0].at_height <= height:
                due.append(self._pending.pop(0))
        for ev in due:
            _set_current_event(ev.event_id)
            with self._lock:
                self.fired.append((ev.event_id, height))
                self.events_by_kind[ev.kind] = (
                    self.events_by_kind.get(ev.kind, 0) + 1
                )
            _trace_event(
                f"scenario.{ev.kind}", id=ev.event_id,
                height=height, site=ev.site,
            )
            if ev.kind in SEAM_KINDS:
                self.plan.extend([ev.rule(self.plan.hits(ev.site))])
            else:
                self.hooks[ev.kind](ev)
        return due

    def done(self) -> bool:
        with self._lock:
            return not self._pending

    def remaining(self) -> int:
        with self._lock:
            return len(self._pending)


class quiet_deaths:
    """Context manager: while active, a thread dying of
    ``InjectedDeath`` does so silently (the SIGKILL model from
    chaos/plan.py — a killed process prints no traceback) instead of
    spamming stderr through ``threading.excepthook``. Any other
    exception still reaches the previous hook."""

    def __enter__(self):
        self._prev = threading.excepthook

        def hook(args, _prev=self._prev):
            if args.exc_type is InjectedDeath:
                return
            _prev(args)

        threading.excepthook = hook
        return self

    def __exit__(self, *exc):
        threading.excepthook = self._prev
        return False
