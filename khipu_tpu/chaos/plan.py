"""Deterministic, seeded fault injection behind zero-cost seams.

The retry/breaker machinery (cluster/client.py), the deep pipeline's
failure semantics (sync/replay.py) and the content-address admission
checks (bridge.py, cluster fetch) had only ever been exercised by
happy-path unit tests. This module provokes the failure modes ON
PURPOSE: hot paths call ``fault_point("site")`` / ``fault_value("site",
v)`` seams which, with no plan installed, cost one module attribute
load and one ``is None`` branch (the ``_NULL_SPAN`` cost model from
observability/trace.py — behavior is bit-exact identical to an
uninstrumented build). With a ``FaultPlan`` installed, rules matched
against the site fire deterministically: every random draw comes from
a per-(rule, site) RNG derived from ``(seed, rule index, site)`` and
is consumed in per-site hit order, so the same seed over the same
workload fires the same faults at the same hits, run after run.

Fault taxonomy (docs/recovery.md):

* ``raise``   — raise ``InjectedFault`` (an ``Exception``): transport
  errors, store failures. Exercises retries, breakers, failover and
  the pipeline's abort path.
* ``latency`` — sleep ``latency_s``: slow shards, slow disks.
  Exercises deadlines and backpressure.
* ``corrupt`` — flip ONE bit of the value passing through a
  ``fault_value`` seam: wire/disk corruption. Content-address
  verification MUST catch every one — a silent acceptance is a bug.
* ``die``     — raise ``InjectedDeath`` (a ``BaseException``, so
  ordinary ``except Exception`` recovery cannot swallow it): simulated
  process death mid-job. The window collector treats it as a SIGKILL —
  the thread stops silently, leaving partial state for recovery.

Every fired fault is recorded in the plan's ``fired`` log, the module
``fault_log`` ring (surfaced by khipu_metrics) and, when the tracer is
enabled, as a ``chaos.fault`` event in the PR-3 flight recorder.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from khipu_tpu.observability.trace import event as _trace_event

__all__ = [
    "InjectedFault",
    "InjectedDeath",
    "FaultRule",
    "FaultPlan",
    "FaultLog",
    "fault_log",
    "fault_point",
    "fault_value",
    "install",
    "uninstall",
    "active",
    "apply_config",
    "merge_plans",
    "KNOWN_SEAMS",
    "known_seam",
]

KINDS = ("raise", "latency", "corrupt", "die")

# Canonical registry of every fault seam in the tree — the chaos
# analog of profiler.KNOWN_SITES. A seam name ending in ``*`` is a
# prefix pattern for parameterised seams (``cluster.call:{endpoint}``).
# tests/test_gameday.py's seam audit walks every ``fault_point`` /
# ``fault_value`` call in khipu_tpu/ and fails if a seam is missing
# here OR referenced by no test, so a new seam cannot silently ship
# unregistered or unexercised.
KNOWN_SEAMS = frozenset({
    # ledger / window collector stage boundaries (sync/replay.py,
    # ledger/window.py, ledger/batch_*.py)
    "ledger.batch",
    "collector.seal", "collector.pack", "collector.collect",
    "collector.persist", "collector.save", "collector.commit",
    "collector.spill",
    # storage datasources (storage/datasource.py)
    "storage.kv.get", "storage.kv.put",
    "storage.node.get", "storage.node.put",
    "storage.block.get", "storage.block.put",
    # log-structured store (storage/kesque.py, storage/segment.py,
    # sync/fast_sync.py)
    "kesque.append", "kesque.roll", "kesque.index",
    "kesque.compact", "kesque.ingest",
    # bridge RPC plane (bridge.py)
    "bridge.node.value", "bridge.segment.raw",
    "bridge.call.*", "bridge.serve.*",
    # reorg two-phase switch (sync/reorg.py)
    "reorg.intent", "reorg.rollback", "reorg.adopt", "reorg.finalize",
    # shard cluster (cluster/client.py, cluster/rebalance.py)
    "cluster.call:*", "cluster.fetch.value", "cluster.replicate",
    "rebalance.plan", "rebalance.stream", "rebalance.cutover",
    "rebalance.retire",
    # serving plane (serving/replica.py, serving/fleet.py)
    "replica.tail", "fleet.route",
    # fused device dispatch (trie/fused.py)
    "fused.dispatch", "fused.collect",
})


def known_seam(site: str) -> bool:
    """True when ``site`` is registered in ``KNOWN_SEAMS`` exactly or
    via a ``prefix*`` pattern."""
    if site in KNOWN_SEAMS:
        return True
    return any(
        p.endswith("*") and site.startswith(p[:-1]) for p in KNOWN_SEAMS
    )


class InjectedFault(Exception):
    """A deliberate failure from a ``raise`` rule. An ordinary
    Exception: retry/breaker/failover paths handle it like any
    transport or store error."""


class InjectedDeath(BaseException):
    """Simulated process death from a ``die`` rule. Deliberately NOT an
    Exception so generic recovery cannot catch it — the component that
    models the death (the collector thread) handles it explicitly; for
    everything else it propagates like a kill signal."""


@dataclass(frozen=True)
class FaultRule:
    """One injection rule. ``site`` matches a seam name exactly, or as
    a prefix when it ends with ``*`` (``"cluster.call:*"``). The rule
    arms after ``after`` hits of the site, fires with probability
    ``prob`` per hit, and at most ``times`` times total (None =
    unlimited)."""

    site: str
    kind: str  # raise | latency | corrupt | die
    prob: float = 1.0
    after: int = 0
    times: Optional[int] = None
    latency_s: float = 0.01

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site


class FaultLog:
    """Bounded ring + counters of fired faults (the CompileEventLog
    shape from observability/recorder.py), surfaced by khipu_metrics
    whether or not the tracer ring is enabled."""

    def __init__(self, capacity: int = 4096):
        from collections import deque

        self._ring = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.counts: Dict[str, int] = {k: 0 for k in KINDS}
        self.by_site: Dict[str, int] = {}

    def record(self, site: str, kind: str, hit: int, rule_index: int):
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + 1
            self.by_site[site] = self.by_site.get(site, 0) + 1
            self._ring.append(
                {"site": site, "kind": kind, "hit": hit,
                 "rule": rule_index}
            )
        _trace_event("chaos.fault", site=site, kind=kind, hit=hit)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "fired": sum(self.counts.values()),
                "byKind": dict(self.counts),
                "bySite": dict(self.by_site),
            }

    def recent(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.counts = {k: 0 for k in KINDS}
            self.by_site = {}


fault_log = FaultLog()


def _fault_samples() -> list:
    """Registry collector: fired-fault counters as khipu_chaos_* —
    total unlabeled, per-kind and per-site labeled families."""
    snap = fault_log.snapshot()
    out = [("khipu_chaos_faults_fired_total", "counter", {},
            snap["fired"])]
    for kind, n in sorted(snap["byKind"].items()):
        out.append(("khipu_chaos_faults_by_kind_total", "counter",
                    {"kind": kind}, n))
    for site, n in sorted(snap["bySite"].items()):
        out.append(("khipu_chaos_faults_by_site_total", "counter",
                    {"site": site}, n))
    return out


try:
    from khipu_tpu.observability.registry import REGISTRY

    REGISTRY.register_collector("chaos", _fault_samples)
except Exception:  # pragma: no cover - registry is stdlib-only
    pass


class FaultPlan:
    """A seeded set of rules evaluated at every seam hit.

    Determinism contract: per-site hit counters advance on every hit;
    each (rule, site) pair draws from its OWN ``random.Random`` seeded
    from ``keccak256(f"{key_seed}:{key_index}:{site}")`` — independent
    of dict order, thread interleaving across DIFFERENT sites, and of
    any other rule. Replaying the same workload with the same seed
    fires the same (site, hit, kind) sequence.

    A rule's RNG key is ``(seed, position)`` as seen by the plan that
    ORIGINALLY carried the rule — ``merge_plans`` preserves the parts'
    keys, so a rule's draw stream never changes just because another
    plan's rules were concatenated in front of it (the aliasing bug
    that naive ``FaultPlan(seed, a.rules + b.rules)`` composition has).
    """

    def __init__(self, seed: int = 0, rules: Optional[List[FaultRule]] = None,
                 sleep=time.sleep):
        self.seed = int(seed)
        self.rules: Tuple[FaultRule, ...] = tuple(rules or ())
        self._sleep = sleep
        self._lock = threading.Lock()
        self._hits: Dict[str, int] = {}
        self._fire_counts: Dict[int, int] = {}
        self._rngs: Dict[Tuple[int, str], object] = {}
        # per-rule RNG key: (origin seed, origin position). Stable
        # across merge_plans/extend — THE per-(rule, site) independence
        # anchor.
        self._rule_keys: List[Tuple[int, int]] = [
            (self.seed, i) for i in range(len(self.rules))
        ]
        # next origin position for rules this plan mints itself
        self._next_own = len(self.rules)
        # every fired fault, in fire order: (site, hit, kind, rule idx)
        self.fired: List[Tuple[str, int, str, int]] = []

    # ----------------------------------------------------------- plumbing

    def _rng(self, rule_index: int, site: str):
        import random

        from khipu_tpu.base.crypto.keccak import keccak256

        key = (rule_index, site)
        rng = self._rngs.get(key)
        if rng is None:
            kseed, kidx = self._rule_keys[rule_index]
            digest = keccak256(
                f"{kseed}:{kidx}:{site}".encode()
            )
            rng = self._rngs[key] = random.Random(
                int.from_bytes(digest[:8], "big")
            )
        return rng

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def extend(self, rules: List[FaultRule]) -> None:
        """Append rules at runtime (the scenario engine arms hazards at
        progress milestones this way). New rules key their RNG streams
        from this plan's own ``(seed, next position)`` sequence, so a
        plan built up by ``extend`` draws identically to one
        constructed with every rule up front."""
        rules = tuple(rules)
        with self._lock:
            for _ in rules:
                self._rule_keys.append((self.seed, self._next_own))
                self._next_own += 1
            self.rules = self.rules + rules

    # --------------------------------------------------------------- fire

    def fire(self, site: str, value: Optional[bytes] = None):
        """Evaluate every rule against one seam hit; returns ``value``
        (possibly corrupted). Raising kinds raise after the fire is
        logged, so the record survives the exception."""
        actions = []
        with self._lock:
            hit = self._hits[site] = self._hits.get(site, 0) + 1
            for i, rule in enumerate(self.rules):
                if not rule.matches(site):
                    continue
                if hit <= rule.after:
                    continue
                if (rule.times is not None
                        and self._fire_counts.get(i, 0) >= rule.times):
                    continue
                if rule.prob < 1.0:
                    # draw consumed in per-site hit order — the
                    # determinism invariant
                    if self._rng(i, site).random() >= rule.prob:
                        continue
                self._fire_counts[i] = self._fire_counts.get(i, 0) + 1
                self.fired.append((site, hit, rule.kind, i))
                actions.append((i, rule, hit))
        for i, rule, hit in actions:
            fault_log.record(site, rule.kind, hit, i)
            if rule.kind == "latency":
                self._sleep(rule.latency_s)
            elif rule.kind == "corrupt":
                if isinstance(value, (bytes, bytearray)) and len(value):
                    rng = self._rng(i, site)
                    flipped = bytearray(value)
                    pos = rng.randrange(len(flipped))
                    flipped[pos] ^= 1 << rng.randrange(8)
                    value = bytes(flipped)
            elif rule.kind == "raise":
                raise InjectedFault(
                    f"injected fault at {site} (hit {hit}, rule {i})"
                )
            else:  # die
                raise InjectedDeath(
                    f"injected death at {site} (hit {hit}, rule {i})"
                )
        return value


def merge_plans(*plans: FaultPlan, sleep=None) -> FaultPlan:
    """Compose plans into ONE installable plan whose injection
    schedule is the union of the parts'.

    Each rule keeps the RNG key ``(origin seed, origin position)`` it
    had in the plan it came from, so its per-site draw stream — and
    therefore every probabilistic fire decision — is bit-identical to
    what it would have been running its part alone over the same
    workload. Naive composition (``FaultPlan(seed, a.rules + b.rules)``)
    re-indexes b's rules and re-seeds them under a's seed, aliasing
    their streams onto different draws.

    Merge BEFORE installing: hit counters, fire counts and the
    ``fired`` log start fresh on the merged plan. The merged plan's
    own ``seed`` (used by later ``extend`` calls) is the first part's.
    """
    if not plans:
        return FaultPlan()
    merged = FaultPlan(
        seed=plans[0].seed, sleep=sleep or plans[0]._sleep
    )
    rules: List[FaultRule] = []
    keys: List[Tuple[int, int]] = []
    for p in plans:
        rules.extend(p.rules)
        keys.extend(p._rule_keys)
    merged.rules = tuple(rules)
    merged._rule_keys = keys
    merged._next_own = 1 + max(
        (idx for (s, idx) in keys if s == merged.seed), default=-1
    )
    return merged


# THE installed plan. ``None`` (the default) keeps both seams below at
# one attribute load + branch — the zero-cost-disabled contract.
_PLAN: Optional[FaultPlan] = None


def fault_point(site: str) -> None:
    """Control seam: may raise, sleep, or do nothing."""
    plan = _PLAN
    if plan is not None:
        plan.fire(site)


def fault_value(site: str, value):
    """Data seam: the value flows THROUGH the harness, which may
    corrupt it (or raise/sleep). Identity when no plan is installed."""
    plan = _PLAN
    if plan is None:
        return value
    return plan.fire(site, value)


def install(plan: FaultPlan) -> FaultPlan:
    global _PLAN
    _PLAN = plan
    return plan


def uninstall() -> None:
    global _PLAN
    _PLAN = None


@contextmanager
def active(plan: FaultPlan):
    """``with active(FaultPlan(seed=7, rules=[...])): ...`` — install
    for the block, always uninstall after (test hygiene)."""
    install(plan)
    try:
        yield plan
    finally:
        uninstall()


def apply_config(cfg) -> None:
    """Wire a config.FaultConfig. Idempotent; a disabled config never
    stomps a plan a test installed explicitly (the apply_config
    convention from observability/trace.py)."""
    if cfg is None or not getattr(cfg, "enabled", False):
        return
    if _PLAN is not None:
        return
    rules = [
        r if isinstance(r, FaultRule) else FaultRule(*r)
        for r in cfg.rules
    ]
    install(FaultPlan(seed=cfg.seed, rules=rules))
