"""gRPC bridge: block batches in, verified state roots back.

Parity: SURVEY §2.9 north-star channel — "Akka regular-sync actors
stream block batches to the TPU host over a thin gRPC bridge". The
service is schema-light by design (raw-bytes methods, RLP payloads) so
the JVM side needs no shared protobuf artifacts — any gRPC client can
call ``khipu.Bridge/ExecuteBlocks`` with an RLP list of block RLPs and
read back rlp([[number, state_root], ...]).

Methods (all request/response = opaque bytes):
  ExecuteBlocks: rlp([block_rlp, ...]) -> rlp([[number_be, root], ...])
                 — executes + persists through the window committer
                 (device-batched trie commits), all roots gated.
  BestBlock:     b"" -> rlp([number_be, hash])
  GetStateRoot:  rlp(number_be) -> root (32 bytes) | b"" if unknown
  GetNodeData:   rlp([hash, ...]) -> rlp([value-or-empty, ...]) — the
                 served node cache (P6 DistributedNodeStorage role):
                 remote hosts heal missing trie nodes through it
  PutNodeData:   rlp([[hash, value], ...]) -> rlp(admitted_be) — the
                 write-replication half: a ShardedNodeClient places
                 each node on every replica of its key so the cluster
                 keeps serving it when one shard dies. Values are
                 content-address verified before admission.
  Ping:          x -> x, EXCEPT the clock-probe sentinel
                 (``CLOCK_PROBE``) which answers rlp(shard_wall_us_be)
                 — the NTP-style offset/RTT estimate the merged chrome
                 trace is built on (observability/export.py)
  GetTraceSpans: b"" -> rlp([trace_id, [span...]]) — the shard's span
                 ring, each span
                 [sid, parent|"" , name, t0_wall_us, t1_wall_us, tid,
                  thread_name, error|"", tags_json] with ABSOLUTE
                 shard-wall microsecond stamps
  GetMetrics:    b"" -> rlp([[name, kind, help, labels_json,
                 value_json], ...]) — one consistent pull of the
                 shard's MetricsRegistry families (instruments + pull
                 collectors), the scrape half of the cluster telemetry
                 plane (observability/telemetry.py): ClusterTelemetry
                 merges these into the shard-labeled exposition

Trace propagation (Dapper-style): every BridgeClient call carries
``khipu-trace-id`` / ``khipu-parent-token`` / ``khipu-sampled`` gRPC
metadata; the server opens a ``bridge.serve.<Method>`` span in its OWN
tracer ring tagged with the remote linkage, so the driver can pull the
ring and nest shard work under the exact RPC span that caused it.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures
from typing import List, Optional

import grpc

from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.chaos import fault_point, fault_value
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.evm.dataword import from_bytes, to_minimal_bytes
from khipu_tpu.observability.trace import (
    Tracer,
    apply_config as apply_trace_config,
    current_tracer,
    use_tracer,
)

SERVICE = "khipu.Bridge"

# gRPC metadata keys the client attaches on EVERY call (values are the
# caller's tracer identity; the keys ship unconditionally so the wire
# format stays greppable — khipu-sampled is "1" (record+link), "0"
# (head sampler dropped this trace id; server skips its serve span
# too), or "" (tracing off on the caller, no decision))
MD_TRACE_ID = "khipu-trace-id"
MD_PARENT_TOKEN = "khipu-parent-token"
MD_SAMPLED = "khipu-sampled"

# Ping clock-probe sentinel: any other payload echoes verbatim (pure
# Ping semantics preserved); this one answers the shard's wall clock in
# microseconds so one timed Ping yields (offset, rtt)
CLOCK_PROBE = b"\x00khipu-clock-probe\x00"


def _identity(b: bytes) -> bytes:
    return b


def _encode_trace_spans(tracer_: Tracer) -> bytes:
    """The GetTraceSpans response: the ring as RLP with absolute
    shard-wall microsecond stamps (the driver re-anchors them with the
    Ping offset estimate). Tags ship as JSON — values are display-only
    on the far side; bytes become hex."""
    rows = []
    for s in tracer_.snapshot():
        tags = {
            k: (v.hex() if isinstance(v, bytes) else v)
            for k, v in s.tags.items()
        }
        rows.append([
            to_minimal_bytes(s.sid),
            to_minimal_bytes(s.parent) if s.parent else b"",
            s.name.encode(),
            to_minimal_bytes(int(tracer_.to_wall(s.t0) * 1e6)),
            to_minimal_bytes(int(tracer_.to_wall(s.t1) * 1e6)),
            to_minimal_bytes(s.tid),
            (s.thread_name or "").encode(),
            b"\x01" if s.error else b"",
            json.dumps(tags).encode(),
        ])
    return rlp_encode([tracer_.trace_id.encode(), rows])


def decode_trace_spans(payload: bytes) -> dict:
    """Inverse of ``_encode_trace_spans``: {traceId, spans:[{...}]}
    with ``t0_wall``/``t1_wall`` back in float seconds."""
    trace_id, rows = rlp_decode(payload)
    spans = []
    for row in rows:
        (sid, parent, name, t0, t1, tid, tname, err, tags) = row
        spans.append({
            "sid": from_bytes(sid),
            "parent": from_bytes(parent) if parent else None,
            "name": name.decode(),
            "t0_wall": from_bytes(t0) / 1e6,
            "t1_wall": from_bytes(t1) / 1e6,
            "tid": from_bytes(tid),
            "thread_name": tname.decode(),
            "error": bool(err),
            "tags": json.loads(tags.decode() or "{}"),
        })
    return {"traceId": trace_id.decode(), "spans": spans}


class BridgeServer:
    def __init__(self, blockchain: Blockchain, config: KhipuConfig,
                 device_commit: bool = False, max_workers: int = 4,
                 tracer: Optional[Tracer] = None, registry=None):
        self.blockchain = blockchain
        self.config = config
        self.device_commit = device_commit
        self.max_workers = max_workers
        self._exec_lock = threading.Lock()  # blocks apply serially
        self._server: Optional[grpc.Server] = None
        # the SHARD's own span ring (per-instance: two in-process
        # servers — the 2-shard tests — must not interleave rings),
        # served raw over GetTraceSpans. Enabled by config or by the
        # operator poking ``server.tracer.enable()``.
        self.tracer = tracer if tracer is not None else Tracer()
        apply_trace_config(config.observability, self.tracer)
        # the registry GetMetrics serves: the process REGISTRY by
        # default; in-process multi-shard tests hand each server its
        # own MetricsRegistry so the scraped families stay per-shard
        if registry is None:
            from khipu_tpu.observability.registry import REGISTRY
            registry = REGISTRY
        self.registry = registry

    # ------------------------------------------------------------ methods

    def _execute_blocks(self, request: bytes, context) -> bytes:
        from khipu_tpu.sync.replay import ReplayDriver

        try:
            items = rlp_decode(request)
            blocks = [Block.decode(rlp_encode(item)) for item in items]
        except Exception as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad batch: {e}"
            )
        with self._exec_lock:
            driver = ReplayDriver(
                self.blockchain, self.config,
                device_commit=self.device_commit,
                tracer=self.tracer,
            )
            try:
                driver.replay(blocks)
            except Exception as e:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"{type(e).__name__}: {e}",
                )
        out = [
            [to_minimal_bytes(b.number), b.header.state_root]
            for b in blocks
        ]
        return rlp_encode(out)

    def _best_block(self, request: bytes, context) -> bytes:
        n = self.blockchain.best_block_number
        header = self.blockchain.get_header_by_number(n)
        return rlp_encode(
            [to_minimal_bytes(n), header.hash if header else b""]
        )

    def _get_state_root(self, request: bytes, context) -> bytes:
        n = from_bytes(rlp_decode(request))
        header = self.blockchain.get_header_by_number(n)
        return header.state_root if header else b""

    def _get_node_data(self, request: bytes, context) -> bytes:
        """Serve trie nodes / code blobs by hash — the cluster-wide
        node-cache endpoint (P6: DistributedNodeStorage.scala:13 role,
        NodeEntity.scala:28's served reads). Request rlp([hash, ...]),
        response rlp([value-or-empty, ...]) positionally; a remote
        khipu host points storage/remote.py's fetch at this method and
        self-heals MPTNodeMissingException across processes."""
        try:
            hashes = rlp_decode(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        storages = self.blockchain.storages
        out = []
        for h in hashes[:384]:  # reference caps node batches (conf:100)
            v = storages.get_node_any(h)
            out.append(v if v is not None else b"")
        return rlp_encode(out)

    def _put_node_data(self, request: bytes, context) -> bytes:
        """Admit replicated nodes (cluster write path). Every value is
        verified against its key before it touches the store — a buggy
        or hostile replicator cannot poison the served cache. Returns
        the count actually admitted."""
        from khipu_tpu.base.crypto.keccak import keccak256

        try:
            pairs = rlp_decode(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        storages = self.blockchain.storages
        admitted = 0
        for h, v in pairs[:384]:
            if len(h) == 32 and v and keccak256(v) == h:
                # same dual admission the heal path uses: the server
                # cannot know which trie the node belongs to, and
                # get_node_any serves from either store
                storages.account_node_storage.put(h, v)
                storages.storage_node_storage.put(h, v)
                admitted += 1
        return rlp_encode(to_minimal_bytes(admitted))

    def _stream_node_data(self, request: bytes, context) -> bytes:
        """Cursor-paged, range-filtered node export — the live-
        rebalance pull path (cluster/rebalance.py). Request
        ``rlp([cursor, count, [[lo, hi], ...]])`` where each
        ``[lo, hi)`` is a half-open 64-bit ring-point range the caller
        is moving; response ``rlp([done, next_cursor, [[hash, value],
        ...]])`` with at most ``count`` pairs whose key hashes into one
        of the ranges and sorts after ``cursor``. Iteration is
        restartable from any cursor (idempotent — exactly what a
        crash-resumed rebalance replays) and serves durably-landed
        nodes via the same ``get_node_any`` resolution the GetNodeData
        cache uses."""
        from khipu_tpu.cluster.ring import _point

        try:
            cursor, count_b, raw_ranges = rlp_decode(request)
            count = min(from_bytes(count_b) or 384, 1024)
            ranges = [
                (from_bytes(lo), from_bytes(hi))
                for lo, hi in raw_ranges
            ]
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        storages = self.blockchain.storages
        try:
            keys = storages.node_keys()
        except Exception as e:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"node store cannot stream: {e}",
            )
        out = []
        done = b"\x01"
        for k in keys:
            if cursor and k <= cursor:
                continue
            if ranges:
                pt = _point(k)
                if not any(lo <= pt < hi for lo, hi in ranges):
                    continue
            if len(out) >= count:
                done = b""  # more matching keys remain
                break
            v = storages.get_node_any(k)
            if v is not None:
                out.append([k, v])
        nxt = out[-1][0] if out else bytes(cursor)
        return rlp_encode([done, nxt, out])

    def _engine_info(self, request: bytes, context) -> bytes:
        """Capability negotiation for segment-ship (cluster/rebalance
        and segment-streamed fast sync): ``rlp([engine, [[topic, seq,
        size], ...]])``. Non-Kesque engines answer with their name and
        an empty manifest — the caller falls back to the paged
        ``StreamNodeData`` path."""
        storages = self.blockchain.storages
        engine = getattr(storages, "kesque_engine", None)
        if engine is None:
            name = getattr(storages, "engine", "unknown")
            return rlp_encode([name.encode(), []])
        manifest = [
            [topic.encode(), to_minimal_bytes(seq), to_minimal_bytes(size)]
            for topic, seq, size in engine.list_segments()
        ]
        return rlp_encode([b"kesque", manifest])

    def _stream_segments(self, request: bytes, context) -> bytes:
        """Raw whole-frame segment chunks — the bulk-movement unit.
        Request ``rlp([topic, seq, offset, max_bytes])``; response
        ``rlp([done, next_offset, raw])``. Restartable from any offset
        (frame boundaries are self-describing), serves only the
        committed prefix, and ships bytes the RECEIVER verifies by
        content address — a corrupt chunk cannot land under a valid
        key."""
        storages = self.blockchain.storages
        engine = getattr(storages, "kesque_engine", None)
        if engine is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "segment streaming requires the kesque engine",
            )
        try:
            topic_b, seq_b, off_b, max_b = rlp_decode(request)
            topic = topic_b.decode()
            seq = from_bytes(seq_b)
            offset = from_bytes(off_b)
            max_bytes = min(from_bytes(max_b) or (1 << 20), 8 << 20)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        try:
            raw, nxt, done = engine.read_chunk(topic, seq, offset,
                                               max_bytes)
        except KeyError as e:
            # compacted away mid-stream: NOT_FOUND tells the puller to
            # refetch the manifest and restart (idempotent by content
            # address)
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return rlp_encode([
            b"\x01" if done else b"", to_minimal_bytes(nxt), raw,
        ])

    def _ping(self, request: bytes, context) -> bytes:
        if request == CLOCK_PROBE:
            # shard wall clock, anchored through the tracer epoch so a
            # test can inject a known offset by shifting epoch_wall —
            # spans and probe answers then shift together, exactly like
            # a skewed host clock would
            now = self.tracer.to_wall(time.perf_counter())
            return rlp_encode(to_minimal_bytes(int(now * 1e6)))
        return request

    def _get_trace_spans(self, request: bytes, context) -> bytes:
        return _encode_trace_spans(self.tracer)

    def _get_metrics(self, request: bytes, context) -> bytes:
        from khipu_tpu.observability.telemetry import encode_metrics

        return encode_metrics(self.registry)

    # ------------------------------------------------------------- server

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        def _guarded(name, fn):
            # chaos seam per served method: a `latency` rule here models
            # a slow shard (what the client's rpc_deadline exists for),
            # a `raise` rule a shard-side failure
            def handler(request, context):
                fault_point(f"bridge.serve.{name}")
                tr = self.tracer
                if not tr.enabled:
                    return fn(request, context)
                # server-side span, linked to the remote parent from
                # the propagated metadata (tags, not a local parent id
                # — the token lives in the CALLER's id space)
                tags = {"method": name}
                md = dict(context.invocation_metadata() or ())
                sampled = md.get(MD_SAMPLED)
                if sampled == "0":
                    # the caller made the head-based per-trace-id drop
                    # decision (trace.trace_sampled) — honor it so one
                    # trace is whole or absent FLEET-wide: no server
                    # span, no orphan fragments in the shard's ring
                    return fn(request, context)
                if sampled == "1":
                    tags["remote_trace"] = md.get(MD_TRACE_ID, "")
                    tok = md.get(MD_PARENT_TOKEN, "")
                    if tok.isdigit():
                        tags["remote_parent"] = int(tok)
                with use_tracer(tr), tr.span(
                    f"bridge.serve.{name}", **tags
                ):
                    return fn(request, context)

            return grpc.unary_unary_rpc_method_handler(
                handler, _identity, _identity
            )

        handlers = {
            "ExecuteBlocks": _guarded(
                "ExecuteBlocks", self._execute_blocks
            ),
            "BestBlock": _guarded("BestBlock", self._best_block),
            "GetStateRoot": _guarded(
                "GetStateRoot", self._get_state_root
            ),
            "GetNodeData": _guarded("GetNodeData", self._get_node_data),
            "PutNodeData": _guarded("PutNodeData", self._put_node_data),
            "StreamNodeData": _guarded(
                "StreamNodeData", self._stream_node_data
            ),
            "EngineInfo": _guarded("EngineInfo", self._engine_info),
            "StreamSegments": _guarded(
                "StreamSegments", self._stream_segments
            ),
            "Ping": _guarded("Ping", self._ping),
            "GetTraceSpans": _guarded(
                "GetTraceSpans", self._get_trace_spans
            ),
            "GetMetrics": _guarded("GetMetrics", self._get_metrics),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers)
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return bound

    def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None


class BridgeClient:
    """The JVM-side caller's shape, for tests and local tooling."""

    def __init__(self, target: str, deadline: Optional[float] = None):
        # ``deadline``: per-RPC gRPC deadline in seconds
        # (ClusterConfig.rpc_deadline) — a hung shard surfaces as
        # DEADLINE_EXCEEDED into the caller's retry/breaker machinery
        # instead of blocking a reader forever. None = no deadline.
        self.channel = grpc.insecure_channel(target)
        self.deadline = deadline

    def _call(self, method: str, payload: bytes) -> bytes:
        fault_point(f"bridge.call.{method}")
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        # Dapper propagation: the caller's tracer identity + innermost
        # span token ride as gRPC metadata on EVERY call (sampled="0"
        # when tracing is off — the keys are unconditional). The
        # ``bridge.call`` span is the client half of the RPC edge; its
        # token is what the server records as remote_parent, so the
        # merged trace nests the server span inside exactly this one.
        t = current_tracer()
        with t.span("bridge.call", method=method) as sp:
            md = (
                (MD_TRACE_ID, t.trace_id),
                (MD_PARENT_TOKEN, str(sp.token or "")),
                # three-valued: "1" = record+link, "0" = the head
                # sampler DROPPED this trace id (tracer on, trace
                # out — the server must skip too so the trace is
                # whole or absent fleet-wide), "" = tracing is off
                # here, no decision made (the server keeps its own
                # local, unlinked serve span)
                (
                    MD_SAMPLED,
                    "1" if t.enabled
                    else ("0" if getattr(t, "_on", False) else ""),
                ),
            )
            return fn(payload, timeout=self.deadline, metadata=md)

    def execute_blocks(self, blocks: List[Block]):
        payload = rlp_encode(
            [rlp_decode(b.encode()) for b in blocks]
        )
        out = rlp_decode(self._call("ExecuteBlocks", payload))
        return [(from_bytes(n), root) for n, root in out]

    def best_block(self):
        n, h = rlp_decode(self._call("BestBlock", b""))
        return from_bytes(n), h

    def get_state_root(self, number: int) -> Optional[bytes]:
        out = self._call(
            "GetStateRoot", rlp_encode(to_minimal_bytes(number))
        )
        return out if out else None

    def get_node_data(self, hashes: List[bytes]):
        """Fetch nodes by hash from the served node cache; returns
        {hash: value} for the ones the server had. Plugs directly into
        RemoteReadThroughNodeStorage's fetch callback. Chunks at the
        server's 384-hash cap so oversized requests don't silently
        report the tail as missing."""
        hashes = list(hashes)
        result = {}
        for start in range(0, len(hashes), 384):
            chunk = hashes[start : start + 384]
            out = rlp_decode(self._call("GetNodeData", rlp_encode(chunk)))
            # data seam: a `corrupt` rule bit-flips a fetched node —
            # the caller's content-address check MUST reject it
            result.update(
                (h, fault_value("bridge.node.value", v))
                for h, v in zip(chunk, out) if v
            )
        return result

    def put_node_data(self, nodes) -> int:
        """Replicate {hash: value} onto this shard; returns the number
        of nodes the server verified and admitted. Chunks at the
        server's 384-pair cap."""
        pairs = [[h, v] for h, v in nodes.items()]
        admitted = 0
        for start in range(0, len(pairs), 384):
            out = self._call(
                "PutNodeData", rlp_encode(pairs[start : start + 384])
            )
            admitted += from_bytes(rlp_decode(out))
        return admitted

    def stream_node_data(self, ranges, cursor: bytes = b"",
                         count: int = 384):
        """One page of the shard's nodes whose ring points fall in
        ``ranges`` (half-open ``[lo, hi)`` 64-bit pairs), resuming
        after ``cursor``: ``(done, next_cursor, [(hash, value), ...])``.
        The caller MUST verify each value by content address before
        forwarding it anywhere (cluster/rebalance.py does)."""
        payload = rlp_encode([
            bytes(cursor),
            to_minimal_bytes(count),
            [[to_minimal_bytes(lo), to_minimal_bytes(hi)]
             for lo, hi in ranges],
        ])
        done, nxt, pairs = rlp_decode(
            self._call("StreamNodeData", payload)
        )
        # data seam: a `corrupt` rule bit-flips a streamed value — the
        # rebalancer's receipt-time keccak check MUST catch it
        return (
            bool(done),
            nxt,
            [(h, fault_value("bridge.node.value", v))
             for h, v in pairs],
        )

    def engine_info(self):
        """``(engine_name, [(topic, seq, size), ...])`` — the shard's
        storage engine and (for Kesque) its segment manifest. The
        rebalancer's capability negotiation: ``name == "kesque"``
        means the peer can segment-ship."""
        name, manifest = rlp_decode(self._call("EngineInfo", b""))
        return (
            name.decode(),
            [
                (topic.decode(), from_bytes(seq), from_bytes(size))
                for topic, seq, size in manifest
            ],
        )

    def stream_segments(self, topic: str, seq: int, offset: int = 0,
                        max_bytes: int = 1 << 20):
        """One raw whole-frame chunk of a shard's segment:
        ``(raw, next_offset, done)``. The caller MUST parse the frames
        and verify every record by content address before admitting it
        (the kesque ingest path does — a bit-flip injected through the
        ``bridge.segment.raw`` corrupt seam must die at the receiver's
        keccak, never in the store)."""
        done, nxt, raw = rlp_decode(self._call(
            "StreamSegments",
            rlp_encode([
                topic.encode(), to_minimal_bytes(seq),
                to_minimal_bytes(offset), to_minimal_bytes(max_bytes),
            ]),
        ))
        return (
            fault_value("bridge.segment.raw", raw),
            from_bytes(nxt),
            bool(done),
        )

    def ping(self, payload: bytes = b"ping") -> bytes:
        return self._call("Ping", payload)

    def clock_probe(self, samples: int = 5):
        """NTP-style clock estimate from timed Ping probes: returns
        ``(offset_s, rtt_s)`` for the MINIMUM-RTT probe, where
        ``offset = shard_clock - local_clock`` and the true offset lies
        within ±rtt/2 of the estimate (the shard stamped its clock
        somewhere inside the round trip; the midpoint assumption is off
        by at most half of it)."""
        best = None
        for _ in range(max(1, samples)):
            t0 = time.time()
            out = self._call("Ping", CLOCK_PROBE)
            t1 = time.time()
            shard_s = from_bytes(rlp_decode(out)) / 1e6
            rtt = max(0.0, t1 - t0)
            offset = shard_s - (t0 + t1) / 2.0
            if best is None or rtt < best[1]:
                best = (offset, rtt)
        return best

    def get_trace_spans(self) -> dict:
        """Pull the shard's span ring: {traceId, spans:[{...}]} with
        absolute shard-wall second stamps (see decode_trace_spans)."""
        return decode_trace_spans(self._call("GetTraceSpans", b""))

    def get_metrics(self):
        """Pull one consistent snapshot of the shard's metric families:
        ``{name: (kind, help, [(labels_dict, value)])}`` — the same
        shape ``MetricsRegistry.families()`` returns locally."""
        from khipu_tpu.observability.telemetry import decode_metrics

        return decode_metrics(self._call("GetMetrics", b""))

    def close(self) -> None:
        self.channel.close()
