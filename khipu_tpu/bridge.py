"""gRPC bridge: block batches in, verified state roots back.

Parity: SURVEY §2.9 north-star channel — "Akka regular-sync actors
stream block batches to the TPU host over a thin gRPC bridge". The
service is schema-light by design (raw-bytes methods, RLP payloads) so
the JVM side needs no shared protobuf artifacts — any gRPC client can
call ``khipu.Bridge/ExecuteBlocks`` with an RLP list of block RLPs and
read back rlp([[number, state_root], ...]).

Methods (all request/response = opaque bytes):
  ExecuteBlocks: rlp([block_rlp, ...]) -> rlp([[number_be, root], ...])
                 — executes + persists through the window committer
                 (device-batched trie commits), all roots gated.
  BestBlock:     b"" -> rlp([number_be, hash])
  GetStateRoot:  rlp(number_be) -> root (32 bytes) | b"" if unknown
  GetNodeData:   rlp([hash, ...]) -> rlp([value-or-empty, ...]) — the
                 served node cache (P6 DistributedNodeStorage role):
                 remote hosts heal missing trie nodes through it
  PutNodeData:   rlp([[hash, value], ...]) -> rlp(admitted_be) — the
                 write-replication half: a ShardedNodeClient places
                 each node on every replica of its key so the cluster
                 keeps serving it when one shard dies. Values are
                 content-address verified before admission.
  Ping:          x -> x
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import List, Optional

import grpc

from khipu_tpu.base.rlp import rlp_decode, rlp_encode
from khipu_tpu.chaos import fault_point, fault_value
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.evm.dataword import from_bytes, to_minimal_bytes

SERVICE = "khipu.Bridge"


def _identity(b: bytes) -> bytes:
    return b


class BridgeServer:
    def __init__(self, blockchain: Blockchain, config: KhipuConfig,
                 device_commit: bool = False, max_workers: int = 4):
        self.blockchain = blockchain
        self.config = config
        self.device_commit = device_commit
        self.max_workers = max_workers
        self._exec_lock = threading.Lock()  # blocks apply serially
        self._server: Optional[grpc.Server] = None

    # ------------------------------------------------------------ methods

    def _execute_blocks(self, request: bytes, context) -> bytes:
        from khipu_tpu.sync.replay import ReplayDriver

        try:
            items = rlp_decode(request)
            blocks = [Block.decode(rlp_encode(item)) for item in items]
        except Exception as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad batch: {e}"
            )
        with self._exec_lock:
            driver = ReplayDriver(
                self.blockchain, self.config,
                device_commit=self.device_commit,
            )
            try:
                driver.replay(blocks)
            except Exception as e:
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"{type(e).__name__}: {e}",
                )
        out = [
            [to_minimal_bytes(b.number), b.header.state_root]
            for b in blocks
        ]
        return rlp_encode(out)

    def _best_block(self, request: bytes, context) -> bytes:
        n = self.blockchain.best_block_number
        header = self.blockchain.get_header_by_number(n)
        return rlp_encode(
            [to_minimal_bytes(n), header.hash if header else b""]
        )

    def _get_state_root(self, request: bytes, context) -> bytes:
        n = from_bytes(rlp_decode(request))
        header = self.blockchain.get_header_by_number(n)
        return header.state_root if header else b""

    def _get_node_data(self, request: bytes, context) -> bytes:
        """Serve trie nodes / code blobs by hash — the cluster-wide
        node-cache endpoint (P6: DistributedNodeStorage.scala:13 role,
        NodeEntity.scala:28's served reads). Request rlp([hash, ...]),
        response rlp([value-or-empty, ...]) positionally; a remote
        khipu host points storage/remote.py's fetch at this method and
        self-heals MPTNodeMissingException across processes."""
        try:
            hashes = rlp_decode(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        storages = self.blockchain.storages
        out = []
        for h in hashes[:384]:  # reference caps node batches (conf:100)
            v = storages.get_node_any(h)
            out.append(v if v is not None else b"")
        return rlp_encode(out)

    def _put_node_data(self, request: bytes, context) -> bytes:
        """Admit replicated nodes (cluster write path). Every value is
        verified against its key before it touches the store — a buggy
        or hostile replicator cannot poison the served cache. Returns
        the count actually admitted."""
        from khipu_tpu.base.crypto.keccak import keccak256

        try:
            pairs = rlp_decode(request)
        except Exception as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad: {e}")
        storages = self.blockchain.storages
        admitted = 0
        for h, v in pairs[:384]:
            if len(h) == 32 and v and keccak256(v) == h:
                # same dual admission the heal path uses: the server
                # cannot know which trie the node belongs to, and
                # get_node_any serves from either store
                storages.account_node_storage.put(h, v)
                storages.storage_node_storage.put(h, v)
                admitted += 1
        return rlp_encode(to_minimal_bytes(admitted))

    def _ping(self, request: bytes, context) -> bytes:
        return request

    # ------------------------------------------------------------- server

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        def _guarded(name, fn):
            # chaos seam per served method: a `latency` rule here models
            # a slow shard (what the client's rpc_deadline exists for),
            # a `raise` rule a shard-side failure
            def handler(request, context):
                fault_point(f"bridge.serve.{name}")
                return fn(request, context)

            return grpc.unary_unary_rpc_method_handler(
                handler, _identity, _identity
            )

        handlers = {
            "ExecuteBlocks": _guarded(
                "ExecuteBlocks", self._execute_blocks
            ),
            "BestBlock": _guarded("BestBlock", self._best_block),
            "GetStateRoot": _guarded(
                "GetStateRoot", self._get_state_root
            ),
            "GetNodeData": _guarded("GetNodeData", self._get_node_data),
            "PutNodeData": _guarded("PutNodeData", self._put_node_data),
            "Ping": _guarded("Ping", self._ping),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self.max_workers)
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        bound = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()
        return bound

    def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None


class BridgeClient:
    """The JVM-side caller's shape, for tests and local tooling."""

    def __init__(self, target: str, deadline: Optional[float] = None):
        # ``deadline``: per-RPC gRPC deadline in seconds
        # (ClusterConfig.rpc_deadline) — a hung shard surfaces as
        # DEADLINE_EXCEEDED into the caller's retry/breaker machinery
        # instead of blocking a reader forever. None = no deadline.
        self.channel = grpc.insecure_channel(target)
        self.deadline = deadline

    def _call(self, method: str, payload: bytes) -> bytes:
        fault_point(f"bridge.call.{method}")
        fn = self.channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        return fn(payload, timeout=self.deadline)

    def execute_blocks(self, blocks: List[Block]):
        payload = rlp_encode(
            [rlp_decode(b.encode()) for b in blocks]
        )
        out = rlp_decode(self._call("ExecuteBlocks", payload))
        return [(from_bytes(n), root) for n, root in out]

    def best_block(self):
        n, h = rlp_decode(self._call("BestBlock", b""))
        return from_bytes(n), h

    def get_state_root(self, number: int) -> Optional[bytes]:
        out = self._call(
            "GetStateRoot", rlp_encode(to_minimal_bytes(number))
        )
        return out if out else None

    def get_node_data(self, hashes: List[bytes]):
        """Fetch nodes by hash from the served node cache; returns
        {hash: value} for the ones the server had. Plugs directly into
        RemoteReadThroughNodeStorage's fetch callback. Chunks at the
        server's 384-hash cap so oversized requests don't silently
        report the tail as missing."""
        hashes = list(hashes)
        result = {}
        for start in range(0, len(hashes), 384):
            chunk = hashes[start : start + 384]
            out = rlp_decode(self._call("GetNodeData", rlp_encode(chunk)))
            # data seam: a `corrupt` rule bit-flips a fetched node —
            # the caller's content-address check MUST reject it
            result.update(
                (h, fault_value("bridge.node.value", v))
                for h, v in zip(chunk, out) if v
            )
        return result

    def put_node_data(self, nodes) -> int:
        """Replicate {hash: value} onto this shard; returns the number
        of nodes the server verified and admitted. Chunks at the
        server's 384-pair cap."""
        pairs = [[h, v] for h, v in nodes.items()]
        admitted = 0
        for start in range(0, len(pairs), 384):
            out = self._call(
                "PutNodeData", rlp_encode(pairs[start : start + 384])
            )
            admitted += from_bytes(rlp_decode(out))
        return admitted

    def ping(self, payload: bytes = b"ping") -> bytes:
        return self._call("Ping", payload)

    def close(self) -> None:
        self.channel.close()
