"""Pending transaction pool.

Parity: transactions/PendingTransactionsService.scala:66 — capacity-
bounded (tx-pool-size = 1000) pending set keyed by tx hash; mined txs
are removed as blocks are saved (RegularSyncService.scala:419); oldest
entries evicted at capacity. Also the ommers pool counterpart
(ommers/OmmersPool.scala:19, size 30).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional

from khipu_tpu.domain.block_header import BlockHeader
from khipu_tpu.domain.transaction import SignedTransaction
from khipu_tpu.observability.journey import JOURNEY


class PendingTransactionsPool:
    def __init__(self, capacity: int = 1000):
        self.capacity = capacity
        # insertion order IS the eviction order (oldest first)
        self._txs: "OrderedDict[bytes, SignedTransaction]" = OrderedDict()
        # (sender, nonce) -> tx hash: the replacement index — at most
        # ONE pooled tx per account slot (geth's price-bump rule,
        # tx_pool.go: a same-nonce resubmission must outbid the pooled
        # one or it is rejected as underpriced)
        self._by_sender_nonce = {}
        # monotonic arrival journal: pending-tx filters read deltas from
        # it, so a tx that enters AND leaves between polls still reports
        self._arrivals: List[bytes] = []
        self._arrival_base = 0  # journal offset after trims
        self._lock = threading.Lock()
        self.evictions = 0  # capacity evictions (oldest-first)
        self.replacements = 0  # same-slot higher-price replacements
        self.rejected_underpriced = 0  # same-slot non-outbidding adds
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector("txpool", self._registry_samples)
        except Exception:
            pass

    def _registry_samples(self) -> list:
        with self._lock:
            return [
                ("khipu_txpool_size", "gauge", {}, len(self._txs)),
                ("khipu_txpool_capacity", "gauge", {}, self.capacity),
                ("khipu_txpool_evictions_total", "counter", {},
                 self.evictions),
                ("khipu_txpool_replacements_total", "counter", {},
                 self.replacements),
                ("khipu_txpool_rejected_underpriced_total", "counter",
                 {}, self.rejected_underpriced),
            ]

    def _drop(self, tx_hash: bytes) -> None:
        """Remove one entry + its slot index (caller holds the lock)."""
        stx = self._txs.pop(tx_hash, None)
        if stx is None:
            return
        slot = (stx.sender, stx.tx.nonce)
        if self._by_sender_nonce.get(slot) == tx_hash:
            del self._by_sender_nonce[slot]

    def add(self, stx: SignedTransaction) -> bool:
        """Add a signature-valid tx; returns False for duplicates and
        for same-sender same-nonce resubmissions that do not outbid
        the pooled tx's gas price (a strictly higher bid REPLACES it —
        geth's replacement rule, so a stuck tx can be repriced).
        Oldest entries are evicted at capacity."""
        if stx.sender is None:
            raise ValueError("unrecoverable signature")
        with self._lock:
            if stx.hash in self._txs:
                return False
            slot = (stx.sender, stx.tx.nonce)
            pooled_hash = self._by_sender_nonce.get(slot)
            replaced = False
            if pooled_hash is not None:
                pooled = self._txs[pooled_hash]
                if stx.tx.gas_price <= pooled.tx.gas_price:
                    self.rejected_underpriced += 1
                    if JOURNEY.enabled:
                        JOURNEY.record(stx.hash, "pool.reject",
                                       reason="underpriced")
                    return False
                del self._txs[pooled_hash]  # outbid: replace in place
                del self._by_sender_nonce[slot]
                self.replacements += 1
                replaced = True
                if JOURNEY.enabled:
                    JOURNEY.record(pooled_hash, "pool.evict",
                                   reason="replaced")
            while len(self._txs) >= self.capacity:
                oldest_hash, oldest = self._txs.popitem(last=False)
                oslot = (oldest.sender, oldest.tx.nonce)
                if self._by_sender_nonce.get(oslot) == oldest_hash:
                    del self._by_sender_nonce[oslot]
                self.evictions += 1
                if JOURNEY.enabled:
                    JOURNEY.record(oldest_hash, "pool.evict",
                                   reason="capacity")
            self._txs[stx.hash] = stx
            self._by_sender_nonce[slot] = stx.hash
            if JOURNEY.enabled:
                JOURNEY.record(stx.hash, "pool.admit", replaced=replaced)
            self._arrivals.append(stx.hash)
            # bound the journal: keep the most recent 4x capacity
            if len(self._arrivals) > 4 * self.capacity:
                trim = 2 * self.capacity
                del self._arrivals[:trim]
                self._arrival_base += trim
            return True

    def cursor(self) -> int:
        """Current end of the arrival journal (install point for
        pending-tx filters)."""
        with self._lock:
            return self._arrival_base + len(self._arrivals)

    def arrivals_since(self, cursor: int):
        """(new_hashes, new_cursor); cursors older than the retained
        journal yield what remains (bounded retention)."""
        with self._lock:
            start = max(cursor - self._arrival_base, 0)
            return (
                list(self._arrivals[start:]),
                self._arrival_base + len(self._arrivals),
            )

    def get(self, tx_hash: bytes) -> Optional[SignedTransaction]:
        with self._lock:
            return self._txs.get(tx_hash)

    def pending(self) -> List[SignedTransaction]:
        with self._lock:
            return list(self._txs.values())

    def remove_mined(self, txs) -> int:
        """Drop txs included in a saved block (:419)."""
        removed = 0
        with self._lock:
            for stx in txs:
                if stx.hash in self._txs:
                    self._drop(stx.hash)
                    removed += 1
        return removed

    def __len__(self) -> int:
        return len(self._txs)


class OmmersPool:
    """Candidate ommer headers for mining (OmmersPool.scala:19)."""

    def __init__(self, capacity: int = 30):
        self.capacity = capacity
        self._headers: "OrderedDict[bytes, BlockHeader]" = OrderedDict()
        self._lock = threading.Lock()

    def add(self, header: BlockHeader) -> None:
        with self._lock:
            self._headers[header.hash] = header
            while len(self._headers) > self.capacity:
                self._headers.popitem(last=False)

    def candidates(self, for_number: int) -> List[BlockHeader]:
        """Ommers must be within 6 generations of the including block."""
        with self._lock:
            return [
                h
                for h in self._headers.values()
                if 0 < for_number - h.number <= 6
            ]
