"""Ethash proof-of-work: epoch cache, light dataset items, hashimoto.

Parity: consensus/pow/EthashAlgo.scala:49 (makeCache :76 — seed chain +
3 rounds of FNV randmemohash; calcDatasetItem :97; hashimoto :143) and
Ethash.scala:52 (epoch cache management, validate :301). Light
verification only — full-dataset mining tables are a miner concern; the
validator computes the handful of dataset items each hashimoto needs
directly from the cache, which is what validate() does in the reference
too.

Numpy does the word mixing (the cache is a [n, 16] uint32 array; FNV
and the 128-byte mix are vectorized); keccak256/512 come from the
native C++ sponge. Sizes are the spec's by default; tests may pass a
reduced cache_bytes to keep epoch generation in CI budget (the
algorithm is size-generic, exactly like the reference's EthashParams).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from khipu_tpu.base.crypto.keccak import keccak256, keccak512

WORD_BYTES = 4
DATASET_BYTES_INIT = 1 << 30
DATASET_BYTES_GROWTH = 1 << 23
CACHE_BYTES_INIT = 1 << 24
CACHE_BYTES_GROWTH = 1 << 17
EPOCH_LENGTH = 30_000
MIX_BYTES = 128
HASH_BYTES = 64
DATASET_PARENTS = 256
CACHE_ROUNDS = 3
ACCESSES = 64
FNV_PRIME = 0x01000193
_U32 = 0xFFFFFFFF


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    i = 2
    while i * i <= n:
        if n % i == 0:
            return False
        i += 1
    return True


def cache_size(epoch: int) -> int:
    sz = CACHE_BYTES_INIT + CACHE_BYTES_GROWTH * epoch - HASH_BYTES
    while not _is_prime(sz // HASH_BYTES):
        sz -= 2 * HASH_BYTES
    return sz


def dataset_size(epoch: int) -> int:
    sz = DATASET_BYTES_INIT + DATASET_BYTES_GROWTH * epoch - MIX_BYTES
    while not _is_prime(sz // MIX_BYTES):
        sz -= 2 * MIX_BYTES
    return sz


def seed_hash(epoch: int) -> bytes:
    seed = b"\x00" * 32
    for _ in range(epoch):
        seed = keccak256(seed)
    return seed


def _fnv(a, b):
    # widen to u64 for the multiply: u32 * u32 wraps (intentionally) but
    # numpy warns on scalar overflow; the mask keeps the math identical
    a64 = np.asarray(a, dtype=np.uint64)
    return (((a64 * FNV_PRIME) & _U32) ^ np.asarray(b, dtype=np.uint64)).astype(
        np.uint32
    )


class EthashCache:
    """One epoch's cache (makeCache :76): seed chain + CACHE_ROUNDS of
    the RandMemoHash strengthening pass."""

    def __init__(self, epoch: int, cache_bytes: Optional[int] = None):
        self.epoch = epoch
        self.seed = seed_hash(epoch)
        n_bytes = cache_bytes if cache_bytes is not None else cache_size(epoch)
        n = n_bytes // HASH_BYTES
        rows = [keccak512(self.seed)]
        for _ in range(n - 1):
            rows.append(keccak512(rows[-1]))
        buf = bytearray(b"".join(rows))
        view = memoryview(buf)
        for _ in range(CACHE_ROUNDS):
            for i in range(n):
                v = int.from_bytes(view[i * 64 : i * 64 + 4], "little") % n
                j = (i - 1 + n) % n
                mixed = bytes(
                    x ^ y
                    for x, y in zip(
                        view[j * 64 : j * 64 + 64], view[v * 64 : v * 64 + 64]
                    )
                )
                view[i * 64 : i * 64 + 64] = keccak512(mixed)
        self.cache = np.frombuffer(bytes(buf), dtype="<u4").reshape(n, 16)
        self.n_rows = n

    def calc_dataset_item(self, i: int) -> np.ndarray:
        """calcDatasetItem :97 — one 64-byte full-dataset item from the
        cache (DATASET_PARENTS FNV-mixed cache rows)."""
        n = self.n_rows
        r = HASH_BYTES // WORD_BYTES  # 16
        mix = self.cache[i % n].copy()
        mix[0] ^= i
        mix = np.frombuffer(keccak512(mix.tobytes()), dtype="<u4").copy()
        for j in range(DATASET_PARENTS):
            parent = int(_fnv(np.uint32(i ^ j), mix[j % r])) % n
            mix = _fnv(mix, self.cache[parent])
        return np.frombuffer(keccak512(mix.tobytes()), dtype="<u4")


def hashimoto_light(
    cache: EthashCache,
    header_hash: bytes,
    nonce: int,
    full_size: Optional[int] = None,
) -> Tuple[bytes, bytes]:
    """hashimoto :143 — returns (mix_digest, result).

    full_size defaults to the epoch's dataset size; reduced-cache tests
    pass a matching reduced size (must be a multiple of MIX_BYTES).
    """
    if full_size is None:
        full_size = dataset_size(cache.epoch)
    n = full_size // HASH_BYTES
    w = MIX_BYTES // WORD_BYTES  # 32
    mixhashes = MIX_BYTES // HASH_BYTES  # 2

    s_bytes = keccak512(header_hash + nonce.to_bytes(8, "little"))
    s = np.frombuffer(s_bytes, dtype="<u4")
    mix = np.tile(s, mixhashes).copy()  # 32 words

    for i in range(ACCESSES):
        p = (
            int(_fnv(np.uint32(i ^ s[0]), mix[i % w])) % (n // mixhashes)
        ) * mixhashes
        newdata = np.concatenate(
            [cache.calc_dataset_item(p + j) for j in range(mixhashes)]
        )
        mix = _fnv(mix, newdata)

    cmix = np.zeros(w // 4, dtype=np.uint32)
    for i in range(0, w, 4):
        cmix[i // 4] = int(
            _fnv(_fnv(_fnv(mix[i], mix[i + 1]), mix[i + 2]), mix[i + 3])
        )
    mix_digest = cmix.tobytes()
    result = keccak256(s_bytes + mix_digest)
    return mix_digest, result


def check_pow(
    cache: EthashCache,
    header_hash: bytes,
    mix_digest: bytes,
    nonce: int,
    difficulty: int,
    full_size: Optional[int] = None,
) -> bool:
    """validate :301: recompute the mix, check digest equality and the
    2^256/difficulty bound."""
    if difficulty <= 0:
        return False  # cheap reject before the 64-access hashimoto
    mix, result = hashimoto_light(cache, header_hash, nonce, full_size)
    if mix != mix_digest:
        return False
    return int.from_bytes(result, "big") <= (1 << 256) // difficulty


def mine(
    cache: EthashCache,
    header_hash: bytes,
    difficulty: int,
    start_nonce: int = 0,
    full_size: Optional[int] = None,
    max_tries: int = 1 << 20,
) -> Tuple[int, bytes]:
    """Miner.scala:40 role (light): scan nonces until the bound holds."""
    if difficulty <= 0:
        raise ValueError("difficulty must be positive")
    bound = (1 << 256) // difficulty
    for nonce in range(start_nonce, start_nonce + max_tries):
        mix, result = hashimoto_light(cache, header_hash, nonce, full_size)
        if int.from_bytes(result, "big") <= bound:
            return nonce, mix
    raise RuntimeError("nonce space exhausted")
