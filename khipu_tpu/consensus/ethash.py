"""Ethash proof-of-work: epoch cache, light dataset items, hashimoto.

Parity: consensus/pow/EthashAlgo.scala:49 (makeCache :76 — seed chain +
3 rounds of FNV randmemohash; calcDatasetItem :97; hashimoto :143) and
Ethash.scala:52 (epoch cache management, validate :301). Light
verification only — full-dataset mining tables are a miner concern; the
validator computes the handful of dataset items each hashimoto needs
directly from the cache, which is what validate() does in the reference
too.

Numpy does the word mixing (the cache is a [n, 16] uint32 array; FNV
and the 128-byte mix are vectorized); keccak256/512 come from the
native C++ sponge. Sizes are the spec's by default; tests may pass a
reduced cache_bytes to keep epoch generation in CI budget (the
algorithm is size-generic, exactly like the reference's EthashParams).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from khipu_tpu.base.crypto.keccak import keccak256, keccak512

WORD_BYTES = 4
DATASET_BYTES_INIT = 1 << 30
DATASET_BYTES_GROWTH = 1 << 23
CACHE_BYTES_INIT = 1 << 24
CACHE_BYTES_GROWTH = 1 << 17
EPOCH_LENGTH = 30_000
MIX_BYTES = 128
HASH_BYTES = 64
DATASET_PARENTS = 256
CACHE_ROUNDS = 3
ACCESSES = 64
FNV_PRIME = 0x01000193
_U32 = 0xFFFFFFFF


def _is_prime(n: int) -> bool:
    if n < 2:
        return False
    i = 2
    while i * i <= n:
        if n % i == 0:
            return False
        i += 1
    return True


def cache_size(epoch: int) -> int:
    sz = CACHE_BYTES_INIT + CACHE_BYTES_GROWTH * epoch - HASH_BYTES
    while not _is_prime(sz // HASH_BYTES):
        sz -= 2 * HASH_BYTES
    return sz


def dataset_size(epoch: int) -> int:
    sz = DATASET_BYTES_INIT + DATASET_BYTES_GROWTH * epoch - MIX_BYTES
    while not _is_prime(sz // MIX_BYTES):
        sz -= 2 * MIX_BYTES
    return sz


def seed_hash(epoch: int) -> bytes:
    seed = b"\x00" * 32
    for _ in range(epoch):
        seed = keccak256(seed)
    return seed


def _fnv(a, b):
    # widen to u64 for the multiply: u32 * u32 wraps (intentionally) but
    # numpy warns on scalar overflow; the mask keeps the math identical
    a64 = np.asarray(a, dtype=np.uint64)
    return (((a64 * FNV_PRIME) & _U32) ^ np.asarray(b, dtype=np.uint64)).astype(
        np.uint32
    )


class EthashCache:
    """One epoch's cache (makeCache :76): seed chain + CACHE_ROUNDS of
    the RandMemoHash strengthening pass."""

    def __init__(self, epoch: int, cache_bytes: Optional[int] = None):
        self.epoch = epoch
        self.seed = seed_hash(epoch)
        n_bytes = cache_bytes if cache_bytes is not None else cache_size(epoch)
        n = n_bytes // HASH_BYTES
        rows = [keccak512(self.seed)]
        for _ in range(n - 1):
            rows.append(keccak512(rows[-1]))
        buf = bytearray(b"".join(rows))
        view = memoryview(buf)
        for _ in range(CACHE_ROUNDS):
            for i in range(n):
                v = int.from_bytes(view[i * 64 : i * 64 + 4], "little") % n
                j = (i - 1 + n) % n
                mixed = bytes(
                    x ^ y
                    for x, y in zip(
                        view[j * 64 : j * 64 + 64], view[v * 64 : v * 64 + 64]
                    )
                )
                view[i * 64 : i * 64 + 64] = keccak512(mixed)
        self.cache = np.frombuffer(bytes(buf), dtype="<u4").reshape(n, 16)
        self.n_rows = n

    def calc_dataset_item(self, i: int) -> np.ndarray:
        """calcDatasetItem :97 — one 64-byte full-dataset item from the
        cache (DATASET_PARENTS FNV-mixed cache rows)."""
        n = self.n_rows
        r = HASH_BYTES // WORD_BYTES  # 16
        mix = self.cache[i % n].copy()
        mix[0] ^= i
        mix = np.frombuffer(keccak512(mix.tobytes()), dtype="<u4").copy()
        for j in range(DATASET_PARENTS):
            parent = int(_fnv(np.uint32(i ^ j), mix[j % r])) % n
            mix = _fnv(mix, self.cache[parent])
        return np.frombuffer(keccak512(mix.tobytes()), dtype="<u4")

    def calc_dataset_batch(self, idxs: np.ndarray) -> np.ndarray:
        """Vectorized calc_dataset_item over a whole index batch: the
        256-parent FNV mix runs as numpy gathers across the batch
        (bit-identical to the scalar path — the generation test diffs
        them), leaving only the two keccak512 passes per item as host
        loops. This is what makes full-DAG generation minutes instead
        of days at spec size."""
        n = self.n_rows
        r = HASH_BYTES // WORD_BYTES  # 16
        idxs = np.asarray(idxs, dtype=np.uint64)
        mix = self.cache[(idxs % n).astype(np.int64)].copy()  # [B, 16]
        mix[:, 0] ^= idxs.astype(np.uint32)
        for b in range(len(idxs)):
            mix[b] = np.frombuffer(
                keccak512(mix[b].tobytes()), dtype="<u4"
            )
        i32 = idxs.astype(np.uint32)
        for j in range(DATASET_PARENTS):
            parent = (
                _fnv(i32 ^ np.uint32(j), mix[:, j % r]).astype(np.int64)
                % n
            )
            mix = _fnv(mix, self.cache[parent])
        out = np.empty_like(mix)
        for b in range(len(idxs)):
            out[b] = np.frombuffer(
                keccak512(mix[b].tobytes()), dtype="<u4"
            )
        return out


class EthashDataset:
    """Full dataset, file-cached (calcDataset + the DAG file cache,
    Ethash.scala:65-164,196): every 64-byte item precomputed from the
    epoch cache, memory-mapped from disk on reuse so miner restarts
    skip the multi-minute regeneration. ``full_size`` defaults to the
    spec size (1 GiB+, the production path); tests pass a reduced size
    (multiple of MIX_BYTES) — the algorithm is size-parametric, so the
    reduced epoch exercises the identical code path."""

    def __init__(self, cache: EthashCache,
                 full_size: Optional[int] = None,
                 cache_dir: Optional[str] = None):
        import os
        import tempfile

        self.cache = cache
        self.full_size = (
            full_size if full_size is not None
            else dataset_size(cache.epoch)
        )
        if self.full_size % MIX_BYTES:
            raise ValueError("full_size must be a multiple of MIX_BYTES")
        n_items = self.full_size // HASH_BYTES
        cache_dir = cache_dir or os.path.join(
            tempfile.gettempdir(), "khipu-ethash"
        )
        os.makedirs(cache_dir, exist_ok=True)
        seed = seed_hash(cache.epoch)
        self.path = os.path.join(
            cache_dir,
            f"full-{seed[:8].hex()}-{self.full_size}.bin",
        )
        if (
            os.path.exists(self.path)
            and os.path.getsize(self.path) == self.full_size
        ):
            self.data = np.memmap(
                self.path, dtype="<u4", mode="r"
            ).reshape(n_items, 16)
            # spot-check SEVERAL rows against the cache derivation: a
            # stale or corrupt DAG file must not validate blocks, and a
            # single fixed probe misses mid-file corruption. Rows are
            # pseudo-random but seeded from the epoch seed, so every
            # reuse of the same file checks the same rows (cheap, and a
            # regression stays reproducible); first/middle/last anchor
            # the extremes.
            rng = np.random.default_rng(
                int.from_bytes(seed[:8], "big") ^ n_items
            )
            probes = {0, n_items // 2, n_items - 1} | {
                int(i) for i in rng.integers(0, n_items, size=8)
            }
            for probe in sorted(probes):
                if not np.array_equal(
                    self.data[probe], cache.calc_dataset_item(probe)
                ):
                    self.data = None  # regenerate below
                    break
        else:
            self.data = None
        if self.data is None:
            # batched generation (calc_dataset_batch): the parent-mix
            # loop vectorizes across each batch; spec-size DAGs take
            # minutes (keccak512-bound), not the days a per-item Python
            # loop would. Written to a temp path + rename so a
            # concurrent generator never serves a half-written DAG.
            arr = np.empty((n_items, 16), dtype="<u4")
            step = 1 << 14
            for start in range(0, n_items, step):
                idxs = np.arange(
                    start, min(start + step, n_items), dtype=np.uint64
                )
                arr[start : start + len(idxs)] = (
                    cache.calc_dataset_batch(idxs)
                )
            tmp = f"{self.path}.{os.getpid()}.tmp"
            arr.tofile(tmp)
            os.replace(tmp, self.path)
            self.data = np.memmap(
                self.path, dtype="<u4", mode="r"
            ).reshape(n_items, 16)

    def item(self, i: int) -> np.ndarray:
        return self.data[i]


def _hashimoto(lookup, n: int, header_hash: bytes,
               nonce: int) -> Tuple[bytes, bytes]:
    """hashimoto :143 core, parametric over the dataset-item source
    (light: derive from cache; full: read the DAG). Returns
    (mix_digest, result)."""
    w = MIX_BYTES // WORD_BYTES  # 32
    mixhashes = MIX_BYTES // HASH_BYTES  # 2

    s_bytes = keccak512(header_hash + nonce.to_bytes(8, "little"))
    s = np.frombuffer(s_bytes, dtype="<u4")
    mix = np.tile(s, mixhashes).copy()  # 32 words

    for i in range(ACCESSES):
        p = (
            int(_fnv(np.uint32(i ^ s[0]), mix[i % w])) % (n // mixhashes)
        ) * mixhashes
        newdata = np.concatenate(
            [lookup(p + j) for j in range(mixhashes)]
        )
        mix = _fnv(mix, newdata)

    cmix = np.zeros(w // 4, dtype=np.uint32)
    for i in range(0, w, 4):
        cmix[i // 4] = int(
            _fnv(_fnv(_fnv(mix[i], mix[i + 1]), mix[i + 2]), mix[i + 3])
        )
    mix_digest = cmix.tobytes()
    result = keccak256(s_bytes + mix_digest)
    return mix_digest, result


def hashimoto_light(
    cache: EthashCache,
    header_hash: bytes,
    nonce: int,
    full_size: Optional[int] = None,
) -> Tuple[bytes, bytes]:
    """Validator-grade path: dataset items derived on the fly from the
    epoch cache. full_size defaults to the epoch's dataset size;
    reduced-cache tests pass a matching reduced size (multiple of
    MIX_BYTES)."""
    if full_size is None:
        full_size = dataset_size(cache.epoch)
    return _hashimoto(
        cache.calc_dataset_item, full_size // HASH_BYTES,
        header_hash, nonce,
    )


def hashimoto_full(
    dataset: EthashDataset, header_hash: bytes, nonce: int
) -> Tuple[bytes, bytes]:
    """Miner-grade path: dataset items read from the precomputed DAG
    (O(1) per access instead of DATASET_PARENTS cache mixes)."""
    return _hashimoto(
        dataset.item, dataset.full_size // HASH_BYTES,
        header_hash, nonce,
    )


def check_pow(
    cache: EthashCache,
    header_hash: bytes,
    mix_digest: bytes,
    nonce: int,
    difficulty: int,
    full_size: Optional[int] = None,
) -> bool:
    """validate :301: recompute the mix, check digest equality and the
    2^256/difficulty bound."""
    if difficulty <= 0:
        return False  # cheap reject before the 64-access hashimoto
    mix, result = hashimoto_light(cache, header_hash, nonce, full_size)
    if mix != mix_digest:
        return False
    return int.from_bytes(result, "big") <= (1 << 256) // difficulty


def _mine(hashimoto_fn, header_hash: bytes, difficulty: int,
          start_nonce: int, max_tries: int) -> Tuple[int, bytes]:
    """One nonce-scan core (Miner.scala:40 role), parametric over the
    hashimoto path — light and full share the bound semantics."""
    if difficulty <= 0:
        raise ValueError("difficulty must be positive")
    bound = (1 << 256) // difficulty
    for nonce in range(start_nonce, start_nonce + max_tries):
        mix, result = hashimoto_fn(header_hash, nonce)
        if int.from_bytes(result, "big") <= bound:
            return nonce, mix
    raise RuntimeError("nonce space exhausted")


def mine(
    cache: EthashCache,
    header_hash: bytes,
    difficulty: int,
    start_nonce: int = 0,
    full_size: Optional[int] = None,
    max_tries: int = 1 << 20,
) -> Tuple[int, bytes]:
    """Validator-grade scan: items derived from the epoch cache."""
    return _mine(
        lambda h, n: hashimoto_light(cache, h, n, full_size),
        header_hash, difficulty, start_nonce, max_tries,
    )


def mine_full(
    dataset: EthashDataset,
    header_hash: bytes,
    difficulty: int,
    start_nonce: int = 0,
    max_tries: int = 1 << 20,
) -> Tuple[int, bytes]:
    """Miner-grade scan over the precomputed DAG (Ethash.scala:65-164
    path): each attempt costs ACCESSES dataset reads instead of
    ACCESSES x DATASET_PARENTS cache mixes."""
    return _mine(
        lambda h, n: hashimoto_full(dataset, h, n),
        header_hash, difficulty, start_nonce, max_tries,
    )
