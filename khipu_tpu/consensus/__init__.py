"""Consensus: Ethash PoW (consensus/pow/ in the reference)."""

from khipu_tpu.consensus.ethash import EthashCache, hashimoto_light, mine

__all__ = ["EthashCache", "hashimoto_light", "mine"]
