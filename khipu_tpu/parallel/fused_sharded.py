"""Multi-chip fused window finalize: the fixpoint program under shard_map.

Single-chip `trie/fused.py` resolves a window's whole placeholder DAG in
one dispatch. This module is its SPMD form for a device mesh (SURVEY
§2.8b/c): node rows shard round-robin across the "nodes" axis, each
round every chip hashes ITS rows and `all_gather`s the digest table so
the child-substitution scatter (which references arbitrary rows) sees
every digest — the same hash-local/gather-global shape the sharded bulk
build uses for level boundaries (parallel/keccak_sharded.py).

Per round per chip: hash(rows/n_dev) + one all_gather of [rows, 32]
digests over ICI. Work scales 1/n_dev; the gathered table is tiny
(32 B/node) next to the encodings, so the collective stays cheap.

Row assignment is ROUND-ROBIN (global row r -> device r % n_dev, local
slot r // n_dev): padding rows land at every device's local tail, so
each device always owns a spare row for dummy (padding) substitutions.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import numpy as np

from khipu_tpu.observability.profiler import D2H, H2D, LEDGER
from khipu_tpu.ops.keccak_jnp import RATE
from khipu_tpu.parallel.mesh import AXIS
from khipu_tpu.trie.fused import (
    FusedUnsupported,
    MAX_DEPTH,
    _pow2,
    topo_levels,
)


@functools.lru_cache(maxsize=32)
def _build_fused_sharded(sig: Tuple[Tuple[int, int, int], ...],
                         rounds: int, n_dev: int, mesh):
    """sig: per class (nblocks, rows_per_dev, nsubs_per_dev).

    Inputs (leading dim = n_dev, sharded on the nodes axis):
      per class: enc u8[n_dev, rpd, nblocks*RATE]
      per class: rows32 i32[n_dev, nsubs*32], cols32 i32[n_dev, nsubs*32],
                 child i32[n_dev, nsubs]   (child indices are GLOBAL
                 positions in the gathered digest table)
    Output: per-class digests u8[n_dev, rpd, 32] (gathered layout).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from khipu_tpu.parallel.compat import shard_map

    from khipu_tpu.ops.keccak_jnp import hash_padded_u8 as _hash

    k = len(sig)

    def shard_body(*args):
        # shards keep the (now size-1) leading device axis: drop it
        encs = [a[0] for a in args[:k]]
        subs = [a[0] for a in args[k:]]

        def all_digests(encs):
            local = jnp.concatenate(
                [_hash(encs[c], sig[c][0]) for c in range(k)], axis=0
            )  # [sum_c rpd_c, 32]
            return jax.lax.all_gather(local, AXIS, tiled=True)

        def body(_, encs):
            G = all_digests(encs)
            out = []
            for c in range(k):
                rows32 = subs[3 * c]
                cols32 = subs[3 * c + 1]
                child = subs[3 * c + 2]
                vals = G[child].reshape(-1)
                out.append(encs[c].at[rows32, cols32].set(vals))
            return out

        encs = jax.lax.fori_loop(0, rounds, body, encs)
        return all_digests(encs)  # replicated full table

    in_specs = tuple([P(AXIS)] * (4 * k))
    run = jax.jit(
        shard_map(
            shard_body, mesh=mesh, in_specs=in_specs,
            # all_gather(tiled) replicates the table on every device;
            # the vma checker can't infer that statically
            out_specs=P(None, None), check_vma=False,
        )
    )
    return run


def fused_resolve_sharded(
    to_resolve: Dict[bytes, bytes],
    deps: Dict[bytes, List[bytes]],
    prefix: bytes,
    mesh,
) -> Dict[bytes, bytes]:
    """Resolve placeholder -> Keccak-256 hash for every entry across the
    mesh. Same contract as trie.fused.fused_resolve."""
    if not to_resolve:
        return {}
    depth = len(topo_levels(deps))
    if depth > MAX_DEPTH:
        raise FusedUnsupported(f"DAG depth {depth} > {MAX_DEPTH}")

    n_dev = int(np.prod(mesh.devices.shape))
    phs = list(to_resolve)

    classes: Dict[int, List[bytes]] = {c: [] for c in (1, 2, 3, 4)}
    for ph in phs:
        nb = len(to_resolve[ph]) // RATE + 1
        classes.setdefault(nb, []).append(ph)
    class_list = sorted(classes)

    # rows per device per class; +n_dev guarantees a spare (padding)
    # local tail row on EVERY device under round-robin assignment
    rpd: Dict[int, int] = {}
    for nb in class_list:
        # _pow2 with floor 16*n_dev returns 16*n_dev*2^k — always a
        # multiple of n_dev, so the per-device split below is exact
        total = _pow2(len(classes[nb]) + n_dev, floor=16 * n_dev)
        rpd[nb] = total // n_dev

    # global digest position in the gathered table:
    # [device d][class c][local slot] with d-major ordering
    sum_rpd = sum(rpd.values())
    offset_c: Dict[int, int] = {}
    acc = 0
    for nb in class_list:
        offset_c[nb] = acc
        acc += rpd[nb]

    def gpos(nb: int, r: int) -> int:
        d, local = r % n_dev, r // n_dev
        return d * sum_rpd + offset_c[nb] + local

    dpos: Dict[bytes, int] = {}
    for nb in class_list:
        for r, ph in enumerate(classes[nb]):
            dpos[ph] = gpos(nb, r)

    enc_bufs: List[np.ndarray] = []
    sub_arrays: List[np.ndarray] = []
    sig: List[Tuple[int, int, int]] = []
    for nb in class_list:
        rows = classes[nb]
        width = nb * RATE
        buf = np.zeros((n_dev, rpd[nb], width), dtype=np.uint8)
        # keccak padding on every row (real rows re-pad below)
        buf[:, :, 0] ^= 0x01
        buf[:, :, width - 1] ^= 0x80
        per_dev_subs: List[List[Tuple[int, int, int]]] = [
            [] for _ in range(n_dev)
        ]
        for r, ph in enumerate(rows):
            enc = to_resolve[ph]
            d, local = r % n_dev, r // n_dev
            buf[d, local, :] = 0
            buf[d, local, : len(enc)] = np.frombuffer(enc, dtype=np.uint8)
            buf[d, local, len(enc)] ^= 0x01
            buf[d, local, width - 1] ^= 0x80
            pos = enc.find(prefix)
            while pos >= 0:
                child = enc[pos : pos + 32]
                cp = dpos.get(child)
                if cp is not None:
                    per_dev_subs[d].append((local, pos, cp))
                pos = enc.find(prefix, pos + 32)
        nsubs = _pow2(
            max(max((len(s) for s in per_dev_subs), default=0), 1),
            floor=256,
        )
        rows32 = np.empty((n_dev, nsubs * 32), dtype=np.int32)
        cols32 = np.empty((n_dev, nsubs * 32), dtype=np.int32)
        child = np.empty((n_dev, nsubs), dtype=np.int32)
        for d in range(n_dev):
            subs = list(per_dev_subs[d])
            while len(subs) < nsubs:  # dummies hit the local spare row
                subs.append((rpd[nb] - 1, 0, 0))
            for m, (local, off, cp) in enumerate(subs):
                rows32[d, m * 32 : (m + 1) * 32] = local
                cols32[d, m * 32 : (m + 1) * 32] = np.arange(
                    off, off + 32, dtype=np.int32
                )
                child[d, m] = cp
        enc_bufs.append(buf)
        sub_arrays.extend([rows32, cols32, child])
        sig.append((nb, rpd[nb], nsubs))

    rounds = _pow2(depth, floor=8)
    run = _build_fused_sharded(tuple(sig), rounds, n_dev, mesh)
    import jax

    # shard dispatch uploads the per-device buffers, the all_gather
    # result comes back as one table — both crossings are ledger sites
    up = sum(b.nbytes for b in enc_bufs) + sum(a.nbytes for a in sub_arrays)
    with LEDGER.transfer("shard.dispatch", H2D, up):
        fut = run(*[*enc_bufs, *sub_arrays])
    with LEDGER.transfer("shard.gather", D2H, int(fut.size)):
        table = np.asarray(jax.device_get(fut))
    out: Dict[bytes, bytes] = {}
    for nb in class_list:
        for r, ph in enumerate(classes[nb]):
            out[ph] = table[gpos(nb, r)].tobytes()
    return out
