"""Sharded batched Keccak-256 + snapshot verification over a device mesh.

Replaces the reference's distributed node cache / multi-host story
(DistributedNodeStorage.scala:13, NodeEntity.scala:28) with SPMD over a
``Mesh``: the node batch is split evenly across chips, each chip runs
the same batched sponge on its shard, and XLA collectives stitch the
results — ``all_gather`` for level boundaries of the bulk trie build,
``psum`` for fast-sync snapshot-verify mismatch counts (config #5).

All functions accept fixed-length (one size class) node batches; the
variable-length entry points in ops/keccak.py bucket into size classes
first, so sharding composes with bucketing.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from khipu_tpu.parallel.compat import shard_map

from khipu_tpu.observability.profiler import D2H, H2D, LEDGER
from khipu_tpu.ops.keccak_jnp import LANES_PER_BLOCK, RATE, absorb
from khipu_tpu.parallel.mesh import AXIS, pad_to_shards


def _fixed_digests(data_u8: jax.Array, length: int) -> jax.Array:
    """Device-side pad + pack + hash: uint8[B, length] -> uint8[B, 32].

    Traceable (no host work), so it can run inside jit / shard_map on
    any backend. Multi-rate padding appends ``nblocks*RATE - length``
    bytes with 0x01 first and 0x80 last (xor-combined when they
    coincide).
    """
    n = data_u8.shape[0]
    nblocks = length // RATE + 1
    tail = np.zeros(nblocks * RATE - length, dtype=np.uint8)
    tail[0] ^= 0x01
    tail[-1] ^= 0x80
    padded = jnp.concatenate(
        [data_u8, jnp.broadcast_to(jnp.asarray(tail), (n, tail.shape[0]))],
        axis=1,
    )
    nwords = nblocks * 2 * LANES_PER_BLOCK
    w = jax.lax.bitcast_convert_type(
        padded.reshape(n, nwords, 4), jnp.uint32
    )  # (B, nwords), little-endian
    blocks = w.reshape(n, nblocks, 2 * LANES_PER_BLOCK).transpose(1, 2, 0)
    words = absorb(blocks, nblocks)  # (8, B)
    return jax.lax.bitcast_convert_type(
        words.T, jnp.uint8
    ).reshape(n, 32)


@functools.lru_cache(maxsize=64)
def _build_sharded_hash(length: int, mesh: Mesh):
    """jit(shard_map(hash-my-shard)): batch dim split on the nodes axis."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(AXIS, None),
        out_specs=P(AXIS, None),
    )
    def hash_shard(shard):  # uint8[B/n_dev, length]
        return _fixed_digests(shard, length)

    return jax.jit(hash_shard)


@functools.lru_cache(maxsize=64)
def _build_level_all_gather(length: int, mesh: Mesh):
    """Hash my shard, then all_gather the level's digests: every chip
    ends with the full digest table for the level, which is what lets
    chip-local parents of the NEXT level resolve children hashed on
    other chips (the level-boundary collective of SURVEY §2.8(c))."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(AXIS, None),
        out_specs=P(None, None),  # replicated full table
        # all_gather(tiled) yields identical values on every device, but
        # the vma checker can't infer that replication statically.
        check_vma=False,
    )
    def level_shard(shard):
        digests = _fixed_digests(shard, length)
        return jax.lax.all_gather(digests, AXIS, tiled=True)

    return jax.jit(level_shard)


@functools.lru_cache(maxsize=64)
def _build_sharded_verify(length: int, mesh: Mesh):
    """Content-address check, sharded: each chip re-hashes its nodes and
    compares against the claimed keys; a psum over the mesh yields the
    global mismatch count (KesqueNodeDataSource.scala:61-63 semantics at
    fast-sync snapshot scale, config #5)."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(AXIS, None), P(AXIS, None)),
        out_specs=P(),  # replicated scalar
    )
    def verify_shard(vals, keys):
        digests = _fixed_digests(vals, length)
        bad = jnp.any(digests != keys, axis=1).astype(jnp.int32)
        return jax.lax.psum(jnp.sum(bad), AXIS)

    return jax.jit(verify_shard)


def _pad_batch(
    arr: np.ndarray, n_shards: int, fill_row: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, int]:
    n = arr.shape[0]
    target = pad_to_shards(n, n_shards, floor=n_shards)
    if target == n:
        return arr, n
    pad = np.zeros((target - n,) + arr.shape[1:], dtype=arr.dtype)
    if fill_row is not None:
        pad[:] = fill_row
    return np.concatenate([arr, pad], axis=0), n


def keccak256_fixed_sharded(data: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Hash N equal-length messages across the mesh: uint8[N, L] -> uint8[N, 32]."""
    n_shards = mesh.devices.size
    padded, n = _pad_batch(np.ascontiguousarray(data, dtype=np.uint8), n_shards)
    with mesh:
        with LEDGER.transfer("shard.keccak", H2D, padded.nbytes):
            out = _build_sharded_hash(data.shape[1], mesh)(jnp.asarray(padded))
    with LEDGER.transfer("shard.keccak", D2H, padded.shape[0] * 32):
        return np.asarray(jax.device_get(out))[:n]


def hash_level_all_gather(data: np.ndarray, mesh: Mesh) -> np.ndarray:
    """Hash one trie level's nodes sharded; return the replicated digest
    table (as the host sees it: uint8[N, 32])."""
    n_shards = mesh.devices.size
    padded, n = _pad_batch(np.ascontiguousarray(data, dtype=np.uint8), n_shards)
    with mesh:
        with LEDGER.transfer("shard.keccak", H2D, padded.nbytes):
            out = _build_level_all_gather(data.shape[1], mesh)(
                jnp.asarray(padded)
            )
    with LEDGER.transfer("shard.gather", D2H, padded.shape[0] * 32):
        return np.asarray(jax.device_get(out))[:n]


@functools.lru_cache(maxsize=64)
def _build_sharded_absorb(nblocks: int, mesh: Mesh):
    """shard_map over the batch dim of pre-padded word-major blocks:
    uint32[nblocks, 34, B] -> uint32[8, B], B split across the mesh."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P(None, None, AXIS),
        out_specs=P(None, AXIS),
    )
    def absorb_shard(blocks):
        return absorb(blocks, nblocks)

    return jax.jit(absorb_shard)


def keccak256_batch_sharded(messages, mesh: Mesh):
    """Variable-length batch hashing across the mesh: the Hasher shape
    (Sequence[bytes] -> List[bytes]) that bulk_build / batch_commit
    take, so whole-trie builds and block commits shard over chips
    (SURVEY §2.8(c); round-3 brief item 6).

    Buckets by rate-block class (like ops.keccak), pads each bucket to
    a multiple of the mesh size, splits the batch dim over the mesh.
    """
    from khipu_tpu.ops.keccak_jnp import (
        bucketed_batch,
        digests_to_bytes,
        pad_batch_count,
        pad_to_blocks,
    )

    n_shards = mesh.devices.size

    def run_bucket(nblocks, msgs):
        blocks = pad_to_blocks(msgs, nblocks)  # [nblocks, 34, B]
        with mesh:
            with LEDGER.transfer("shard.keccak", H2D, blocks.nbytes):
                words = _build_sharded_absorb(nblocks, mesh)(
                    jnp.asarray(blocks)
                )
        with LEDGER.transfer("shard.keccak", D2H, blocks.shape[-1] * 32):
            got = jax.device_get(words)
        return digests_to_bytes(got)

    return bucketed_batch(
        messages,
        lambda nblocks, n: pad_batch_count(n, floor=n_shards),
        run_bucket,
    )


def sharded_hasher(mesh: Mesh):
    """Bind a mesh into a Hasher usable by trie.bulk.bulk_build and
    trie.deferred.batch_commit."""
    return lambda messages: keccak256_batch_sharded(messages, mesh)


def snapshot_verify_sharded(
    values: np.ndarray, keys: np.ndarray, mesh: Mesh
) -> int:
    """Global count of nodes whose keccak256(value) != key.

    Batch-padding rows are made self-consistent (their true digest) so
    they never count as mismatches.
    """
    if values.shape[0] != keys.shape[0]:
        raise ValueError("values/keys batch mismatch")
    n_shards = mesh.devices.size
    values = np.ascontiguousarray(values, dtype=np.uint8)
    keys = np.ascontiguousarray(keys, dtype=np.uint8)
    padded_vals, n = _pad_batch(values, n_shards)
    if padded_vals.shape[0] != n:
        from khipu_tpu.base.crypto.keccak import keccak256

        zero_digest = np.frombuffer(
            keccak256(b"\x00" * values.shape[1]), dtype=np.uint8
        )
        padded_keys, _ = _pad_batch(keys, n_shards, fill_row=zero_digest)
    else:
        padded_keys = keys
    with mesh:
        up = padded_vals.nbytes + padded_keys.nbytes
        with LEDGER.transfer("shard.verify", H2D, up):
            out = _build_sharded_verify(values.shape[1], mesh)(
                jnp.asarray(padded_vals), jnp.asarray(padded_keys)
            )
    with LEDGER.transfer("shard.verify", D2H, 4):
        return int(jax.device_get(out))
