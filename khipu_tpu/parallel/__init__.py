"""Multi-chip parallelism: device meshes + sharded hashing/verification.

The reference scales across hosts with Akka Cluster Sharding of trie
nodes (entity/NodeEntity.scala:28, storage/DistributedNodeStorage.scala:13)
and cluster-singleton services. The TPU-native analog (SURVEY §2.8
mapping (b)/(c)) is data-parallel sharding of node batches over a
``jax.sharding.Mesh`` with XLA collectives over ICI:

* hash a level's dirty nodes sharded across chips (`shard_map`),
* ``all_gather`` the level's digests at level boundaries so every chip
  can resolve parent references (the bulk-build "sequence parallelism"
  of SURVEY §5.7),
* ``psum`` mismatch counts for snapshot verification (config #5).
"""

from khipu_tpu.parallel.mesh import device_mesh
from khipu_tpu.parallel.keccak_sharded import (
    hash_level_all_gather,
    keccak256_fixed_sharded,
    snapshot_verify_sharded,
)

__all__ = [
    "device_mesh",
    "hash_level_all_gather",
    "keccak256_fixed_sharded",
    "snapshot_verify_sharded",
]
