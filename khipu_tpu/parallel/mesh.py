"""Device-mesh construction for the node-sharding axis.

One 1-D mesh axis ("nodes") carries all data parallelism in this
framework: trie nodes are content-addressed and independent under
hashing, so the natural decomposition is an even split of the node
batch across chips — the role Akka Cluster Sharding of NodeEntity plays
in the reference (entity/NodeEntity.scala:28), with ICI collectives
replacing cluster gossip.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

AXIS = "nodes"


def device_mesh(n_devices: Optional[int] = None, axis_name: str = AXIS) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` available devices.

    On real hardware the devices are the v5e slice's chips; in tests a
    virtual CPU mesh (``--xla_force_host_platform_device_count=8``)
    stands in, exactly as akka-multi-node-testkit would have for the
    reference's cluster (SURVEY §4).
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, only {len(devs)} available"
        )
    return Mesh(np.asarray(devs[:n_devices]), (axis_name,))


def pad_to_shards(n: int, n_shards: int, floor: int = 1) -> int:
    """Smallest count >= max(n, floor) divisible by ``n_shards``."""
    n = max(n, floor)
    return ((n + n_shards - 1) // n_shards) * n_shards
