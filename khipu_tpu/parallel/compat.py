"""jax API-drift shims for the parallel package.

``shard_map`` has lived at three addresses across jax releases —
``jax.shard_map`` (new public home), ``jax.sharding.shard_map``
(transitional), and ``jax.experimental.shard_map.shard_map`` (the
original) — and renamed its replication-check kwarg from ``check_rep``
to ``check_vma`` along the way. The parallel modules import THIS
wrapper, which resolves whichever implementation the installed jax
provides and translates the kwarg, so the sharded bulk-build and the
multi-chip fused finalize run unmodified on any of those versions.
"""

from __future__ import annotations

import inspect


def _resolve_shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    try:
        from jax.sharding import shard_map as fn  # type: ignore

        return fn
    except ImportError:
        pass
    from jax.experimental.shard_map import shard_map as fn  # noqa: F811

    return fn


_IMPL = _resolve_shard_map()
_IMPL_PARAMS = frozenset(inspect.signature(_IMPL).parameters)
_UNSET = object()


def shard_map(f=None, *, check_vma=_UNSET, check_rep=_UNSET, **kwargs):
    """Version-portable ``shard_map``.

    Accepts either spelling of the replication-check kwarg and forwards
    the one the installed implementation understands (dropping it if
    the implementation predates both). Usable directly or through
    ``functools.partial`` as a decorator, like the real thing.
    """
    flag = check_vma if check_vma is not _UNSET else check_rep
    if flag is not _UNSET:
        if "check_vma" in _IMPL_PARAMS:
            kwargs["check_vma"] = flag
        elif "check_rep" in _IMPL_PARAMS:
            kwargs["check_rep"] = flag
    if f is None:
        import functools

        return functools.partial(shard_map, **kwargs)
    return _IMPL(f, **kwargs)
