"""Log/block filters + eth_getLogs.

Parity: jsonrpc/FilterManager.scala:86 (log/block/pendingTx filters
with polling) and EthService.getLogs. Queries use each block's header
bloom as a pre-filter (ledger/BloomFilter role) before touching its
receipts — the same pruning real nodes rely on.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.ledger.bloom import bloom_contains


@dataclass
class LogQuery:
    from_block: int
    # None = moving head ("latest"): resolved at each evaluation, so an
    # installed filter keeps following the chain tip
    to_block: Optional[int]
    addresses: Sequence[bytes] = ()  # empty = any
    # topics[i] = tuple of alternatives for position i; empty tuple = any
    topics: Sequence[Sequence[bytes]] = ()


@dataclass
class LogHit:
    address: bytes
    topics: tuple
    data: bytes
    block_number: int
    block_hash: bytes
    tx_hash: bytes
    tx_index: int
    log_index: int
    # True on entries retracting a previously-delivered log whose
    # block a reorg orphaned (eth_getFilterChanges parity: clients
    # un-apply, then receive the adopted branch's logs fresh)
    removed: bool = False


def _matches(log, query: LogQuery) -> bool:
    if query.addresses and log.address not in query.addresses:
        return False
    for i, alternatives in enumerate(query.topics):
        if not alternatives:
            continue
        if i >= len(log.topics) or log.topics[i] not in alternatives:
            return False
    return True


def _bloom_may_match(bloom: bytes, query: LogQuery) -> bool:
    if query.addresses and not any(
        bloom_contains(bloom, a) for a in query.addresses
    ):
        return False
    for alternatives in query.topics:
        if alternatives and not any(
            bloom_contains(bloom, t) for t in alternatives
        ):
            return False
    return True


def get_logs(blockchain: Blockchain, query: LogQuery) -> List[LogHit]:
    hits: List[LogHit] = []
    to_block = (
        query.to_block
        if query.to_block is not None
        else blockchain.best_block_number
    )
    for number in range(query.from_block, to_block + 1):
        header = blockchain.get_header_by_number(number)
        if header is None:
            continue
        if not _bloom_may_match(header.logs_bloom, query):
            continue  # bloom prunes the receipt read entirely
        receipts = blockchain.get_receipts(number)
        if receipts is None:
            continue
        body = None  # fetched lazily: only blocks with a HIT pay it
        log_index = 0
        skip_block = False
        block_hits: List[LogHit] = []  # buffered: all-or-nothing per block
        for tx_index, receipt in enumerate(receipts):
            if skip_block:
                break
            for log in receipt.logs:
                if _matches(log, query):
                    if body is None:
                        from khipu_tpu.domain.block import BlockBody

                        raw = blockchain.storages.block_body_storage.get(
                            number
                        )
                        if raw is None:
                            # receipts without a body (partial store /
                            # mid-reorg): skip the whole block rather
                            # than index into an empty tx list
                            skip_block = True
                            break
                        body = BlockBody.decode(raw)
                    if tx_index >= len(body.transactions):
                        skip_block = True
                        break
                    block_hits.append(
                        LogHit(
                            address=log.address,
                            topics=tuple(log.topics),
                            data=log.data,
                            block_number=number,
                            block_hash=header.hash,
                            tx_hash=body.transactions[tx_index].hash,
                            tx_index=tx_index,
                            log_index=log_index,
                        )
                    )
                log_index += 1
        if not skip_block:
            hits.extend(block_hits)
    return hits


class FilterManager:
    """Installed filters with poll semantics (eth_newFilter /
    eth_getFilterChanges / eth_uninstallFilter).

    Filters a client stops polling are EVICTED after ``ttl`` seconds
    (geth's 5-minute filter deadline): every installed filter holds
    server-side state — a log filter's cursor pins incremental scans,
    a pending-tx filter's cursor pins the pool's arrival journal — so
    an abandoned one is a slow leak an open endpoint accumulates
    forever. The sweep is lazy (piggybacked on install/poll under the
    manager lock): no timer thread, and a filter polled within its TTL
    is never touched."""

    def __init__(self, blockchain: Blockchain, ttl: float = 300.0):
        self.blockchain = blockchain
        self.ttl = ttl
        self._ids = itertools.count(1)
        self._filters = {}
        self._last_poll = {}  # fid -> monotonic time of last touch
        # fid -> queued ``removed: true`` retractions a reorg produced
        # for logs this filter already delivered (drained by changes())
        self._removed = {}
        self._lock = threading.Lock()
        self.evictions = 0
        self.reorgs_seen = 0
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector(
                "filters", self._registry_samples
            )
        except Exception:
            pass

    def _registry_samples(self) -> list:
        with self._lock:
            active = len(self._filters)
            evicted = self.evictions
        return [
            ("khipu_filters_active", "gauge", {}, active),
            ("khipu_filter_evictions_total", "counter", {}, evicted),
        ]

    def _now(self) -> float:
        import time

        return time.monotonic()

    def _sweep(self) -> None:
        """Evict TTL-expired filters (caller holds the lock)."""
        deadline = self._now() - self.ttl
        for fid in [
            f for f, t in self._last_poll.items() if t < deadline
        ]:
            self._filters.pop(fid, None)
            self._last_poll.pop(fid, None)
            self._removed.pop(fid, None)
            self.evictions += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "active": len(self._filters),
                "evictions": self.evictions,
                "ttlSeconds": self.ttl,
            }

    def new_log_filter(self, query: LogQuery) -> int:
        with self._lock:
            self._sweep()
            fid = next(self._ids)
            # first poll catches up from the query's fromBlock (geth
            # semantics); later polls return only the delta
            self._filters[fid] = ("logs", query, query.from_block - 1)
            self._last_poll[fid] = self._now()
            return fid

    def new_block_filter(self) -> int:
        with self._lock:
            self._sweep()
            fid = next(self._ids)
            self._filters[fid] = (
                "blocks", None, self.blockchain.best_block_number
            )
            self._last_poll[fid] = self._now()
            return fid

    def new_pending_tx_filter(self, tx_pool) -> int:
        """Reports hashes of txs that ENTERED the pool since last poll
        — read from the pool's arrival journal, so a tx that enters and
        is mined/evicted between polls is still reported."""
        with self._lock:
            self._sweep()
            fid = next(self._ids)
            self._filters[fid] = ("pending", tx_pool, tx_pool.cursor())
            self._last_poll[fid] = self._now()
            return fid

    def get_log_query(self, fid: int):
        """The installed log filter's query, or None (locked access —
        eth_getFilterLogs must not poke at internals)."""
        with self._lock:
            entry = self._filters.get(fid)
            if entry is not None:
                self._last_poll[fid] = self._now()  # a poll, TTL-wise
        if entry is None or entry[0] != "logs":
            return None
        return entry[1]

    def uninstall(self, fid: int) -> bool:
        with self._lock:
            self._last_poll.pop(fid, None)
            self._removed.pop(fid, None)
            return self._filters.pop(fid, None) is not None

    def note_reorg(self, ancestor_number: int,
                   removed_hits: Sequence[LogHit]) -> None:
        """A reorg orphaned every block above ``ancestor_number``
        (ReorgManager listener — sync/reorg.py). Per installed filter:
        queue ``removed: true`` retractions for logs it already
        delivered, then rewind its cursor to the fork point so the
        adopted branch's results deliver fresh on the next poll.
        Filters whose cursor never crossed the fork are untouched."""
        with self._lock:
            self.reorgs_seen += 1
            for fid, entry in list(self._filters.items()):
                kind, query, last_seen = entry
                if kind == "pending" or last_seen <= ancestor_number:
                    continue  # never delivered anything above the fork
                if kind == "blocks":
                    self._filters[fid] = (kind, query, ancestor_number)
                    continue
                mine = [
                    h for h in removed_hits
                    if query.from_block <= h.block_number <= last_seen
                    and (query.to_block is None
                         or h.block_number <= query.to_block)
                    and _matches(h, query)
                ]
                if mine:
                    self._removed.setdefault(fid, []).extend(mine)
                self._filters[fid] = (
                    kind, query,
                    max(ancestor_number, query.from_block - 1),
                )

    # one poll never scans more than this many blocks; the cursor
    # advances by at most the same amount, so a huge catch-up range is
    # paid down incrementally instead of in one unbounded scan
    MAX_BLOCKS_PER_POLL = 10_000

    def changes(self, fid: int):
        """New results since the last poll."""
        with self._lock:
            # the whole read-advance is atomic under the manager lock:
            # concurrent polls of one filter must neither double-deliver
            # nor rewind the cursor (the pool lock nests inside and
            # nothing takes them in the reverse order)
            self._sweep()
            entry = self._filters.get(fid)
            if entry is None:
                return None
            self._last_poll[fid] = self._now()
            kind, query, last_seen = entry
            if kind == "pending":
                tx_pool = query
                new_hashes, new_cursor = tx_pool.arrivals_since(last_seen)
                self._filters[fid] = ("pending", tx_pool, new_cursor)
                return new_hashes
            best = self.blockchain.best_block_number
            horizon = min(best, last_seen + self.MAX_BLOCKS_PER_POLL)
            if kind == "blocks":
                # a header can vanish mid-scan (reorg shortened the
                # chain after the best_block_number read): stop at the
                # last contiguous header so the cursor never skips past
                # blocks that were never delivered
                out = []
                n = last_seen + 1
                while n <= horizon:
                    header = self.blockchain.get_header_by_number(n)
                    if header is None:
                        break
                    out.append(header.hash)
                    n += 1
                horizon = n - 1
            else:
                import dataclasses

                upper = (
                    query.to_block if query.to_block is not None else best
                )
                window = dataclasses.replace(
                    query,
                    from_block=max(query.from_block, last_seen + 1),
                    to_block=min(upper, horizon),
                )
                out = (
                    get_logs(self.blockchain, window)
                    if window.from_block <= window.to_block
                    else []
                )
                # retractions first: a client un-applies the orphaned
                # logs before applying the adopted branch's
                out = self._removed.pop(fid, []) + out
            self._filters[fid] = (kind, query, horizon)
            return out
