"""personal_* namespace + the keystore-backed eth_sendTransaction /
eth_sign path.

Parity: jsonrpc/PersonalService.scala:72-182 (importRawKey, newAccount,
listAccounts, unlockAccount, lockAccount, sign, ecRecover,
sendTransaction with/without passphrase — nonce defaulting from
current account + pooled txs :147-173, signed-message prefix :176-181).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    SignatureError,
    ecdsa_recover,
    ecdsa_sign,
    pubkey_to_address,
)
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.jsonrpc.eth_service import (
    RpcError,
    data,
    parse_data,
    parse_qty,
    qty,
)
from khipu_tpu.keystore import KeyStore, KeyStoreError, Wallet
from khipu_tpu.txpool import PendingTransactionsPool

DEFAULT_GAS = 90_000  # TransactionRequest.scala defaultGasLimit


def message_to_sign(message: bytes) -> bytes:
    """EIP-191 personal-message digest (PersonalService.scala:176-181):
    kec256("\\x19Ethereum Signed Message:\\n" + len + message)."""
    prefix = b"\x19Ethereum Signed Message:\n" + str(
        len(message)
    ).encode()
    return keccak256(prefix + message)


class PersonalService:
    """Dispatch target for personal_* (and the signing eth_*) methods;
    install alongside EthService on the JSON-RPC server."""

    def __init__(
        self,
        keystore: KeyStore,
        blockchain: Blockchain,
        config: KhipuConfig,
        tx_pool: PendingTransactionsPool,
    ):
        self.keystore = keystore
        self.blockchain = blockchain
        self.config = config
        self.tx_pool = tx_pool
        # address -> (wallet, expiry unix seconds or None)
        self._unlocked: Dict[bytes, tuple] = {}
        self._lock = threading.Lock()

    # -------------------------------------------------------- accounts

    def personal_importRawKey(self, prv: str, passphrase: str) -> str:
        try:
            address = self.keystore.import_key(
                parse_data(prv), passphrase
            )
        except (KeyStoreError, ValueError) as e:
            raise RpcError(-32000, str(e))
        return data(address)

    def personal_newAccount(self, passphrase: str) -> str:
        return data(self.keystore.new_account(passphrase))

    def personal_listAccounts(self) -> list:
        return [data(a) for a in self.keystore.list_accounts()]

    def personal_unlockAccount(
        self, address: str, passphrase: str, duration=None
    ) -> bool:
        addr = parse_data(address)
        try:
            wallet = self.keystore.unlock(addr, passphrase)
        except KeyStoreError as e:
            raise RpcError(-32000, str(e))
        # geth semantics: duration 0 (or omitted) = unlocked until
        # lock/restart — regardless of encoding ("0x0", 0, None)
        dur = parse_qty(duration) if duration is not None else 0
        expiry = time.monotonic() + dur if dur else None
        with self._lock:
            self._unlocked[addr] = (wallet, expiry)
        return True

    def personal_lockAccount(self, address: str) -> bool:
        with self._lock:
            return self._unlocked.pop(parse_data(address), None) is not None

    def _wallet_of(self, addr: bytes) -> Optional[Wallet]:
        with self._lock:
            entry = self._unlocked.get(addr)
            if entry is None:
                return None
            wallet, expiry = entry
            if expiry is not None and time.monotonic() >= expiry:
                del self._unlocked[addr]
                return None
            return wallet

    # --------------------------------------------------------- signing

    def personal_sign(
        self, message: str, address: str, passphrase: Optional[str] = None
    ) -> str:
        addr = parse_data(address)
        if passphrase is not None:
            try:
                wallet = self.keystore.unlock(addr, passphrase)
            except KeyStoreError as e:
                raise RpcError(-32000, str(e))
        else:
            wallet = self._wallet_of(addr)
            if wallet is None:
                raise RpcError(-32000, "account is locked")
        digest = message_to_sign(parse_data(message))
        recid, r, s = ecdsa_sign(digest, wallet.private_key)
        return data(
            r.to_bytes(32, "big")
            + s.to_bytes(32, "big")
            + bytes([27 + recid])
        )

    def eth_sign(self, address: str, message: str) -> str:
        """geth-argument-order variant over the unlocked wallet."""
        return self.personal_sign(message, address, None)

    def personal_ecRecover(self, message: str, signature: str) -> str:
        sig = parse_data(signature)
        if len(sig) != 65:
            raise RpcError(-32000, "signature must be 65 bytes")
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:64], "big")
        v = sig[64]
        recid = v - 27 if v >= 27 else v
        digest = message_to_sign(parse_data(message))
        try:
            pub = ecdsa_recover(digest, recid, r, s)
        except SignatureError as e:
            raise RpcError(-32000, f"invalid signature: {e}")
        return data(pubkey_to_address(pub))

    # ---------------------------------------------------- transactions

    def _next_nonce(self, addr: bytes) -> int:
        best = self.blockchain.best_block_number
        header = self.blockchain.get_header_by_number(best)
        acc = (
            self.blockchain.get_account(addr, header.state_root)
            if header is not None
            else None
        )
        nonce = acc.nonce if acc else self.config.blockchain.account_start_nonce
        # pooled txs from this sender advance the usable nonce
        # (PersonalService.scala:147-173)
        pooled = [
            stx.tx.nonce
            for stx in self.tx_pool.pending()
            if stx.sender == addr
        ]
        if pooled:
            nonce = max(nonce, max(pooled) + 1)
        return nonce

    def _send(self, request: dict, wallet: Wallet) -> str:
        to = parse_data(request["to"]) if request.get("to") else None
        tx = Transaction(
            nonce=(
                parse_qty(request["nonce"])
                if request.get("nonce") is not None
                else self._next_nonce(wallet.address)
            ),
            gas_price=(
                parse_qty(request["gasPrice"])
                if request.get("gasPrice")
                else 10**9
            ),
            gas_limit=(
                parse_qty(request["gas"])
                if request.get("gas")
                else DEFAULT_GAS
            ),
            to=to,
            value=parse_qty(request["value"]) if request.get("value") else 0,
            payload=(
                parse_data(request.get("data") or request.get("input"))
                if (request.get("data") or request.get("input"))
                else b""
            ),
        )
        # EIP-155 replay protection once the fork is active at the tip
        chain_id = (
            self.config.blockchain.chain_id
            if self.blockchain.best_block_number
            >= self.config.blockchain.eip155_block
            else None
        )
        stx = sign_transaction(tx, wallet.private_key, chain_id=chain_id)
        self.tx_pool.add(stx)
        return data(stx.hash)

    def personal_sendTransaction(
        self, request: dict, passphrase: str
    ) -> str:
        if not request.get("from"):
            raise RpcError(-32602, "missing 'from'")
        addr = parse_data(request["from"])
        try:
            wallet = self.keystore.unlock(addr, passphrase)
        except KeyStoreError as e:
            raise RpcError(-32000, str(e))
        return self._send(request, wallet)

    def eth_sendTransaction(self, request: dict) -> str:
        if not request.get("from"):
            raise RpcError(-32602, "missing 'from'")
        wallet = self._wallet_of(parse_data(request["from"]))
        if wallet is None:
            raise RpcError(-32000, "account is locked")
        return self._send(request, wallet)
