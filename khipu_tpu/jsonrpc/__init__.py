"""JSON-RPC API (khipu-eth/.../jsonrpc/ role)."""

from khipu_tpu.jsonrpc.eth_service import EthService
from khipu_tpu.jsonrpc.server import JsonRpcServer

__all__ = ["EthService", "JsonRpcServer"]
