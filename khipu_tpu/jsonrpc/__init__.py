"""JSON-RPC API (khipu-eth/.../jsonrpc/ role)."""

from khipu_tpu.jsonrpc.eth_service import EthService
from khipu_tpu.jsonrpc.personal_service import PersonalService
from khipu_tpu.jsonrpc.server import JsonRpcServer

__all__ = ["EthService", "JsonRpcServer", "PersonalService"]
