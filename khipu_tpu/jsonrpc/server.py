"""JSON-RPC 2.0 HTTP server over the stdlib threading HTTPServer.

Parity: jsonrpc/http/JsonRpcHttpServer.scala:30 (akka-http POST + CORS)
+ JsonRpcController dispatch tables. Any public method of the
registered services named like ``eth_...``/``net_...``/``web3_...``
is callable; batch requests supported per the spec.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

from khipu_tpu.jsonrpc.eth_service import EthService, RpcError

_ALLOWED_PREFIXES = ("eth_", "net_", "web3_", "khipu_", "personal_")


class JsonRpcServer:
    def __init__(self, service: EthService, host: str = "127.0.0.1",
                 port: int = 8546, extra_services: tuple = (),
                 serving=None, max_batch: int = 100,
                 max_body_bytes: int = 2 << 20):
        """``extra_services`` are additional dispatch targets searched
        after the primary service (PersonalService installs here —
        JsonRpcController's per-namespace handler tables).

        ``serving`` is an optional admission/SLO plane
        (serving.ServingPlane): when set, every resolvable method
        passes ``admit``/``finish`` around dispatch — over-limit
        requests come back ``-32005`` instead of queueing in the
        ThreadingHTTPServer without bound. ``max_batch`` /
        ``max_body_bytes`` bound what one POST can ask for (a single
        huge batch is otherwise an amplification lever no concurrency
        limit sees — one socket, thousands of dispatches)."""
        self.service = service
        self.services = (service, *extra_services)
        self.host = host
        self.port = port
        self.serving = serving
        if serving is not None and serving.config is not None:
            max_batch = serving.config.max_batch
            max_body_bytes = serving.config.max_body_bytes
        self.max_batch = max_batch
        self.max_body_bytes = max_body_bytes
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- dispatch

    # methods that sign with (or unlock) keystore keys: a webpage must
    # never reach these through the open-CORS HTTP endpoint — any site
    # could otherwise spend from an unlocked account (the reason geth
    # refuses personal_* over HTTP). Browser requests carry an Origin
    # header; curl/native tooling does not.
    _SIGNING_METHODS = frozenset({"eth_sendTransaction", "eth_sign"})

    @classmethod
    def _is_signing(cls, method: str) -> bool:
        return method.startswith("personal_") or method in cls._SIGNING_METHODS

    def handle(self, request: Any, browser_origin: bool = False) -> Any:
        if isinstance(request, list):  # batch
            if len(request) > self.max_batch:
                return {
                    "jsonrpc": "2.0", "id": None,
                    "error": {
                        "code": -32600,
                        "message": f"batch too large "
                        f"(max {self.max_batch})",
                    },
                }
            return [self._handle_one(r, browser_origin) for r in request]
        return self._handle_one(request, browser_origin)

    def _handle_one(self, req: Any, browser_origin: bool = False) -> Dict:
        if not isinstance(req, dict):
            return {
                "jsonrpc": "2.0", "id": None,
                "error": {"code": -32600, "message": "invalid request"},
            }
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params", []) or []
        base = {"jsonrpc": "2.0", "id": rid}
        if not any(method.startswith(p) for p in _ALLOWED_PREFIXES):
            return {**base, "error": {"code": -32601, "message": f"method {method!r} not found"}}
        if browser_origin and self._is_signing(method):
            return {**base, "error": {
                "code": -32601,
                "message": "account methods are not available to "
                "browser origins",
            }}
        fn = next(
            (
                f
                for s in self.services
                for f in (getattr(s, method, None),)
                if callable(f)
            ),
            None,
        )
        if fn is None:
            return {**base, "error": {"code": -32601, "message": f"method {method!r} not found"}}
        # admission gate (serving/admission.py): resolvable methods
        # only — unknown-method noise must not consume slots or skew
        # the per-method SLO families
        ticket = None
        if self.serving is not None:
            try:
                ticket = self.serving.admit(method)
            except RpcError as e:  # ServerBusy, already counted as shed
                return {**base, "error": {"code": e.code, "message": str(e)}}
        error = True
        try:
            out = {**base, "result": fn(*params)}
            error = False
            return out
        except RpcError as e:
            return {**base, "error": {"code": e.code, "message": str(e)}}
        except TypeError as e:
            return {**base, "error": {"code": -32602, "message": f"invalid params: {e}"}}
        except Exception as e:  # internal error — never kill the server
            return {**base, "error": {"code": -32603, "message": f"{type(e).__name__}: {e}"}}
        finally:
            if ticket is not None:
                self.serving.finish(method, ticket, error=error)

    # --------------------------------------------------------- server

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive: HTTP/1.1 defaults to persistent connections,
            # so a pooled client (loadgen.HttpTransport, any real SDK)
            # pays the TCP handshake once per worker instead of once
            # per request. The contract that makes this safe is that
            # EVERY response path below sends an exact Content-Length
            # — shed (-32005) and parse errors ride the normal path,
            # and the oversized-body refusal explicitly closes (the
            # unread body makes the stream unresyncable).
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                if length > outer.max_body_bytes:
                    # refuse BEFORE reading: a spec-shaped error goes
                    # back and the connection closes (the body is
                    # unread, so the stream cannot be resynced)
                    payload = json.dumps({
                        "jsonrpc": "2.0", "id": None,
                        "error": {
                            "code": -32600,
                            "message": "request body too large "
                            f"(max {outer.max_body_bytes} bytes)",
                        },
                    }).encode()
                    self.close_connection = True
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/json"
                    )
                    self.send_header(
                        "Content-Length", str(len(payload))
                    )
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                body = self.rfile.read(length)
                t0 = time.perf_counter()
                try:
                    request = json.loads(body)
                    response = outer.handle(
                        request,
                        browser_origin=self.headers.get("Origin")
                        is not None,
                    )
                except json.JSONDecodeError:
                    response = {
                        "jsonrpc": "2.0", "id": None,
                        "error": {"code": -32700, "message": "parse error"},
                    }
                served_ms = (time.perf_counter() - t0) * 1e3
                payload = json.dumps(response).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Content-Length", str(len(payload)))
                # server-side dispatch time, so a pooled client can
                # subtract it from wall time and report the transport
                # overhead as its own number (loadgen.HttpTransport)
                self.send_header(
                    "X-Khipu-Served-Ms", f"{served_ms:.3f}"
                )
                self.end_headers()
                self.wfile.write(payload)

            def do_OPTIONS(self):  # CORS preflight
                self.send_response(204)
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header(
                    "Access-Control-Allow-Headers", "Content-Type"
                )
                self.send_header("Access-Control-Allow-Methods", "POST")
                self.end_headers()

            def log_message(self, *args):
                pass  # quiet

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port  # resolves port=0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
