"""eth_* / net_* / web3_* method implementations.

Parity: jsonrpc/EthService.scala (getBalance/call/estimateGas/
getBlockByNumber/... backed by Blockchain + Ledger.simulateTransaction),
NetService, Web3Service. Hex-string codecs follow the JSON-RPC spec
("quantities" minimal-hex, "data" even-length 0x-prefixed).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.config import KhipuConfig
from khipu_tpu.domain.block import Block
from khipu_tpu.domain.blockchain import Blockchain
from khipu_tpu.domain.receipt import Receipt
from khipu_tpu.domain.transaction import (
    SignedTransaction,
    contract_address,
)
from khipu_tpu.ledger.bloom import bloom_of_logs
from khipu_tpu.ledger.simulate import estimate_gas, simulate_call
from khipu_tpu.txpool import PendingTransactionsPool

CLIENT_VERSION = "khipu-tpu/0.3"


class RpcError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


def qty(n: int) -> str:
    return hex(n)


def data(b: Optional[bytes]) -> Optional[str]:
    return "0x" + b.hex() if b is not None else None


def parse_qty(s: Union[str, int]) -> int:
    if isinstance(s, int):
        return s
    return int(s, 16)


def parse_data(s: str) -> bytes:
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


class EthService:
    def __init__(
        self,
        blockchain: Blockchain,
        config: KhipuConfig,
        tx_pool: Optional[PendingTransactionsPool] = None,
        cluster=None,
        tracer=None,
        read_view=None,
        serving=None,
        telemetry=None,
        reorg_manager=None,
    ):
        self.blockchain = blockchain
        self.config = config
        # `is None`, not `or`: an EMPTY pool is falsy (__len__ == 0),
        # and `or` would silently swap the caller's pool for a private
        # one — sendRawTransaction would then land txs the rest of the
        # node (miner, pressure signals) never sees
        self.tx_pool = (
            tx_pool if tx_pool is not None else PendingTransactionsPool()
        )
        # read-your-writes overlay (serving/readview.py): when set,
        # account reads at latest/pending resolve through it so
        # executed-but-not-yet-persisted window state is visible and
        # per-key reads never regress mid-pipeline
        self.read_view = read_view
        # the serving plane (admission + SLO), surfaced in
        # khipu_metrics; dispatch-side enforcement lives in
        # JsonRpcServer, which holds the same object
        self.serving = serving
        # sharded node-cache cluster client (cluster/client.py); when
        # set, khipu_metrics surfaces its per-shard counters
        self.cluster = cluster
        # cluster telemetry plane (observability/telemetry.py); when
        # set, khipu_cluster_metrics_text / khipu_cluster_report serve
        # the merged shard view
        self.telemetry = telemetry
        # the flight recorder the khipu_traces / khipu_dump_chrome_trace
        # RPCs serve from (a board-owned instance when embedded in a
        # ServiceBoard; the process default otherwise)
        if tracer is None:
            from khipu_tpu.observability.trace import tracer
        self.tracer = tracer
        from khipu_tpu.jsonrpc.filters import FilterManager

        # eager: a lazy-init race under concurrent RPC threads could
        # orphan one client's installed filter ids
        self._filter_manager = FilterManager(
            blockchain, ttl=config.serving.filter_ttl
        )
        # chain switches retract delivered logs (`removed: true`) and
        # rewind filter cursors to the fork point (sync/reorg.py)
        if reorg_manager is not None:
            reorg_manager.add_listener(self._filter_manager.note_reorg)
        # chain-head + store-cache samples for the unified registry
        # (replace-by-key: the newest service owns the slot)
        try:
            from khipu_tpu.observability.registry import REGISTRY

            REGISTRY.register_collector("chain", self._registry_samples)
        except Exception:
            pass

    def _registry_samples(self) -> list:
        s = self.blockchain.storages
        out = [
            ("khipu_best_block_number", "gauge", {},
             self.blockchain.best_block_number),
            ("khipu_pending_txs", "gauge", {}, len(self.tx_pool)),
        ]
        for name, store in (
            ("account", s.account_node_storage),
            ("storage", s.storage_node_storage),
            ("evmcode", s.evmcode_storage),
        ):
            lb = {"store": name}
            out.append(("khipu_store_cache_hit_rate", "gauge", lb,
                        round(store.cache_hit_rate, 4)))
            out.append(("khipu_store_cache_reads_total", "counter", lb,
                        store.cache_read_count))
        return out

    # ------------------------------------------------------- block tags

    def _resolve_block(self, tag: Union[str, int]) -> int:
        if isinstance(tag, int):
            return tag
        if tag in ("latest", "pending", "safe", "finalized"):
            return self.blockchain.best_block_number
        if tag == "earliest":
            return 0
        return parse_qty(tag)

    def _header(self, tag):
        n = self._resolve_block(tag)
        h = self.blockchain.get_header_by_number(n)
        if h is None:
            raise RpcError(-32000, f"unknown block {tag}")
        return h

    # ------------------------------------------------------------- web3

    def web3_clientVersion(self) -> str:
        return CLIENT_VERSION

    def web3_sha3(self, payload: str) -> str:
        return data(keccak256(parse_data(payload)))

    def net_version(self) -> str:
        return str(self.config.blockchain.chain_id)

    def eth_chainId(self) -> str:
        return qty(self.config.blockchain.chain_id)

    def eth_protocolVersion(self) -> str:
        return qty(63)  # PV63 (SURVEY §2.7 wire messages)

    # -------------------------------------------------------------- eth

    # tags the ReadView overlay serves (numeric/historic tags always
    # read the committed store — the overlay only covers the head)
    _HEAD_TAGS = ("latest", "pending", "safe", "finalized")

    def eth_blockNumber(self) -> str:
        if self.read_view is not None:
            return qty(self.read_view.head_number())
        return qty(self.blockchain.best_block_number)

    def eth_getBalance(self, address: str, tag="latest") -> str:
        addr = parse_data(address)
        if self.read_view is not None and tag in self._HEAD_TAGS:
            _, acc = self.read_view.get_account(addr)
            return qty(acc.balance if acc else 0)
        header = self._header(tag)
        acc = self.blockchain.get_account(addr, header.state_root)
        return qty(acc.balance if acc else 0)

    def eth_getTransactionCount(self, address: str, tag="latest") -> str:
        addr = parse_data(address)
        if self.read_view is not None and tag in self._HEAD_TAGS:
            _, acc = self.read_view.get_account(addr)
            count = acc.nonce if acc else 0
        else:
            header = self._header(tag)
            acc = self.blockchain.get_account(addr, header.state_root)
            count = acc.nonce if acc else 0
        if tag == "pending":
            # pooled txs advance the usable nonce (wallets pick the next
            # nonce from the pending count)
            count += sum(
                1 for stx in self.tx_pool.pending() if stx.sender == addr
            )
        return qty(count)

    def eth_getCode(self, address: str, tag="latest") -> str:
        header = self._header(tag)
        world = self.blockchain.get_world_state(header.state_root)
        return data(world.get_code(parse_data(address)))

    def eth_getStorageAt(self, address: str, slot: str, tag="latest") -> str:
        header = self._header(tag)
        world = self.blockchain.get_world_state(header.state_root)
        value = world.get_storage(parse_data(address), parse_qty(slot))
        return data(value.to_bytes(32, "big"))

    def eth_gasPrice(self) -> str:
        return qty(10**9)

    def eth_getBlockTransactionCountByNumber(self, tag) -> Optional[str]:
        block = self.blockchain.get_block_by_number(
            self._resolve_block(tag)
        )
        return qty(len(block.body.transactions)) if block else None

    def eth_getUncleCountByBlockNumber(self, tag) -> Optional[str]:
        block = self.blockchain.get_block_by_number(
            self._resolve_block(tag)
        )
        return qty(len(block.body.ommers)) if block else None

    def _number_of_hash(self, block_hash: str) -> Optional[int]:
        return self.blockchain.storages.block_numbers.number_of(
            parse_data(block_hash)
        )

    def eth_getBlockTransactionCountByHash(self, block_hash: str):
        n = self._number_of_hash(block_hash)
        return (
            None if n is None
            else self.eth_getBlockTransactionCountByNumber(n)
        )

    def eth_getUncleCountByBlockHash(self, block_hash: str):
        n = self._number_of_hash(block_hash)
        return None if n is None else self.eth_getUncleCountByBlockNumber(n)

    def eth_getTransactionByBlockNumberAndIndex(self, tag, index):
        n = self._resolve_block(tag)
        i = index if isinstance(index, int) else int(str(index), 16)
        block = self.blockchain.get_block_by_number(n)
        if block is None or i >= len(block.body.transactions):
            return None
        return self._tx_json(block.body.transactions[i], block, i)

    def eth_getTransactionByBlockHashAndIndex(self, block_hash: str, index):
        n = self._number_of_hash(block_hash)
        if n is None:
            return None
        return self.eth_getTransactionByBlockNumberAndIndex(n, index)

    def _uncle_json(self, block, i: int):
        if block is None or i >= len(block.body.ommers):
            return None
        # EthService.getUncleByBlockHashAndIndex: a header-only block
        # JSON (uncles carry no body)
        u = block.body.ommers[i]
        return {
            "number": qty(u.number),
            "hash": data(u.hash),
            "parentHash": data(u.parent_hash),
            "miner": data(u.beneficiary),
            "stateRoot": data(u.state_root),
            "difficulty": qty(u.difficulty),
            "gasLimit": qty(u.gas_limit),
            "gasUsed": qty(u.gas_used),
            "timestamp": qty(u.unix_timestamp),
            "extraData": data(u.extra_data),
            "uncles": [],
            "transactions": [],
        }

    def eth_getUncleByBlockNumberAndIndex(self, tag, index):
        i = index if isinstance(index, int) else int(str(index), 16)
        block = self.blockchain.get_block_by_number(self._resolve_block(tag))
        return self._uncle_json(block, i)

    def eth_getUncleByBlockHashAndIndex(self, block_hash: str, index):
        n = self._number_of_hash(block_hash)
        if n is None:
            return None
        return self.eth_getUncleByBlockNumberAndIndex(n, index)

    def net_listening(self) -> bool:
        return True

    def net_peerCount(self) -> str:
        manager = getattr(self, "peer_manager", None)
        alive = (
            sum(1 for p in manager.peers if p.alive) if manager else 0
        )
        return qty(alive)

    def eth_accounts(self):
        # keystore-backed accounts surface through personal_listAccounts;
        # the bare node exposes none (reference returns the same)
        return []

    def eth_mining(self) -> bool:
        return getattr(self, "miner", None) is not None

    def eth_hashrate(self) -> str:
        return qty(0)  # external miners report via submitHashrate (absent)

    def eth_getBlockByNumber(self, tag, full_txs: bool = False):
        n = self._resolve_block(tag)
        block = self.blockchain.get_block_by_number(n)
        if block is None:
            return None
        return self._block_json(block, full_txs)

    def eth_getBlockByHash(self, block_hash: str, full_txs: bool = False):
        n = self.blockchain.storages.block_numbers.number_of(
            parse_data(block_hash)
        )
        if n is None:
            return None
        return self.eth_getBlockByNumber(n, full_txs)

    def eth_getTransactionByHash(self, tx_hash: str):
        h = parse_data(tx_hash)
        loc = self.blockchain.storages.transaction_storage.get(h)
        if loc is None:
            pending = self.tx_pool.get(h)
            if pending is None:
                return None
            return self._tx_json(pending, None, None)
        number, index = loc
        block = self.blockchain.get_block_by_number(number)
        if block is None or index >= len(block.body.transactions):
            return None
        return self._tx_json(block.body.transactions[index], block, index)

    def eth_getTransactionReceipt(self, tx_hash: str):
        h = parse_data(tx_hash)
        loc = self.blockchain.storages.transaction_storage.get(h)
        if loc is None:
            return None
        number, index = loc
        block = self.blockchain.get_block_by_number(number)
        receipts = self.blockchain.get_receipts(number)
        if block is None or receipts is None or index >= len(receipts):
            return None
        r = receipts[index]
        prev_gas = receipts[index - 1].cumulative_gas_used if index else 0
        stx = block.body.transactions[index]
        # logIndex is the log's position within the BLOCK (spec), so
        # count the logs of every earlier receipt first
        log_base = sum(len(rc.logs) for rc in receipts[:index])
        out: Dict[str, Any] = {
            "transactionHash": data(h),
            "transactionIndex": qty(index),
            "blockHash": data(block.hash),
            "blockNumber": qty(number),
            "from": data(stx.sender),
            "to": data(stx.tx.to),
            "contractAddress": (
                data(contract_address(stx.sender, stx.tx.nonce))
                if stx.tx.is_contract_creation and stx.sender
                else None
            ),
            "cumulativeGasUsed": qty(r.cumulative_gas_used),
            "gasUsed": qty(r.cumulative_gas_used - prev_gas),
            "logsBloom": data(r.logs_bloom),
            "logs": [
                {
                    "address": data(log.address),
                    "topics": [data(t) for t in log.topics],
                    "data": data(log.data),
                    "blockNumber": qty(number),
                    "blockHash": data(block.hash),
                    "transactionHash": data(h),
                    "transactionIndex": qty(index),
                    "logIndex": qty(log_base + i),
                }
                for i, log in enumerate(r.logs)
            ],
        }
        if isinstance(r.post_tx_state, int):
            out["status"] = qty(r.post_tx_state)
        else:
            out["root"] = data(r.post_tx_state)
        return out

    def eth_call(self, call: dict, tag="latest") -> str:
        header = self._header(tag)
        result = simulate_call(
            self.blockchain.get_world_state, header, self.config,
            **self._call_kwargs(call),
        )
        if result.is_revert:
            raise RpcError(3, "execution reverted: 0x" + result.output.hex())
        if result.error:
            raise RpcError(-32000, result.error)
        return data(result.output)

    def eth_estimateGas(self, call: dict, tag="latest") -> str:
        header = self._header(tag)
        try:
            return qty(
                estimate_gas(
                    self.blockchain.get_world_state, header, self.config,
                    **self._call_kwargs(call),
                )
            )
        except ValueError as e:
            raise RpcError(-32000, str(e))

    def eth_sendRawTransaction(self, raw: str) -> str:
        stx = SignedTransaction.decode(parse_data(raw))
        if stx.sender is None:
            raise RpcError(-32000, "invalid signature")
        from khipu_tpu.observability.journey import JOURNEY

        if JOURNEY.enabled:
            # passport ingress: the tx entered through the serving
            # plane — the trace id of the serving ring rides along so
            # the journey links into the merged chrome trace
            JOURNEY.record(
                stx.hash, "ingress", source="rpc",
                trace_id=(self.tracer.trace_id
                          if self.tracer is not None
                          and self.tracer.enabled else None),
            )
        if not self.tx_pool.add(stx):
            # geth parity: a rejected add is an ERROR, not a silent
            # hash — the wallet must know its tx is not in the pool
            if self.tx_pool.get(stx.hash) is not None:
                raise RpcError(-32000, "already known")
            raise RpcError(
                -32000, "replacement transaction underpriced"
            )
        return data(stx.hash)

    def eth_pendingTransactions(self) -> List[dict]:
        return [
            self._tx_json(stx, None, None) for stx in self.tx_pool.pending()
        ]

    def eth_syncing(self):
        return False

    # ------------------------------------------------------- logs/filters

    def _parse_log_query(self, params: dict):
        from khipu_tpu.jsonrpc.filters import LogQuery

        from_block = self._resolve_block(params.get("fromBlock", "latest"))
        to_raw = params.get("toBlock", "latest")
        # "latest"/"pending" stay a MOVING head (None) so installed
        # filters keep following the tip; numeric tags pin the range
        if to_raw in ("latest", "pending", "safe", "finalized"):
            to_block = None
        else:
            to_block = self._resolve_block(to_raw)
        addr = params.get("address")
        if addr is None:
            addresses = ()
        elif isinstance(addr, list):
            addresses = tuple(parse_data(a) for a in addr)
        else:
            addresses = (parse_data(addr),)
        topics = []
        for t in params.get("topics", []) or []:
            if t is None:
                topics.append(())
            elif isinstance(t, list):
                topics.append(tuple(parse_data(x) for x in t))
            else:
                topics.append((parse_data(t),))
        return LogQuery(from_block, to_block, addresses, tuple(topics))

    @staticmethod
    def _log_json(hit) -> dict:
        return {
            "address": data(hit.address),
            "topics": [data(t) for t in hit.topics],
            "data": data(hit.data),
            "blockNumber": qty(hit.block_number),
            "blockHash": data(hit.block_hash),
            "transactionHash": data(hit.tx_hash),
            "transactionIndex": qty(hit.tx_index),
            "logIndex": qty(hit.log_index),
            "removed": bool(getattr(hit, "removed", False)),
        }

    def _check_log_range(self, query) -> None:
        upper = (
            query.to_block
            if query.to_block is not None
            else self.blockchain.best_block_number
        )
        if upper - query.from_block > 10_000:
            raise RpcError(-32005, "block range too large (max 10000)")

    def eth_getLogs(self, params: dict) -> list:
        from khipu_tpu.jsonrpc.filters import get_logs

        query = self._parse_log_query(params)
        self._check_log_range(query)
        return [
            self._log_json(h) for h in get_logs(self.blockchain, query)
        ]

    @property
    def _filters(self):
        return self._filter_manager

    def eth_newFilter(self, params: dict) -> str:
        return qty(self._filters.new_log_filter(
            self._parse_log_query(params)
        ))

    def eth_newBlockFilter(self) -> str:
        return qty(self._filters.new_block_filter())

    def eth_newPendingTransactionFilter(self) -> str:
        return qty(self._filters.new_pending_tx_filter(self.tx_pool))

    def eth_getFilterLogs(self, fid: str) -> list:
        """Full (non-delta) result set of an installed log filter."""
        from khipu_tpu.jsonrpc.filters import get_logs

        query = self._filters.get_log_query(parse_qty(fid))
        if query is None:
            raise RpcError(-32000, "filter not found")
        self._check_log_range(query)  # same DoS cap as eth_getLogs
        return [
            self._log_json(h)
            for h in get_logs(self.blockchain, query)
        ]

    def eth_uninstallFilter(self, fid: str) -> bool:
        return self._filters.uninstall(parse_qty(fid))

    def eth_getFilterChanges(self, fid: str) -> list:
        out = self._filters.changes(parse_qty(fid))
        if out is None:
            raise RpcError(-32000, "filter not found")
        return [
            data(x) if isinstance(x, bytes) else self._log_json(x)
            for x in out
        ]

    def khipu_metrics(self) -> dict:
        """Metrics surface (SURVEY §5.5): storage counters + clocks +
        chain head, one structured snapshot."""
        s = self.blockchain.storages
        out = {
            "bestBlockNumber": self.blockchain.best_block_number,
            "pendingTxs": len(self.tx_pool),
            "stores": {},
        }
        for name, store in (
            ("account", s.account_node_storage),
            ("storage", s.storage_node_storage),
            ("evmcode", s.evmcode_storage),
        ):
            src = store.source
            out["stores"][name] = {
                "cacheHitRate": round(store.cache_hit_rate, 4),
                "cacheReadCount": store.cache_read_count,
                "count": getattr(src, "count", None),
                "readSeconds": round(src.clock.elapsed_ns / 1e9, 6)
                if hasattr(src, "clock") else None,
            }
        if self.cluster is not None:
            # per-shard hit rate / latency / failovers / breaker state
            # (cluster/client.py ShardMetrics)
            out["cluster"] = self.cluster.metrics_snapshot()
        # window-pipeline gauges (sync/replay.PIPELINE_GAUGES): depth,
        # windows sealed/collected/in-flight, driver stall vs collector
        # busy seconds, and the occupancy fraction of the last run
        from khipu_tpu.sync.replay import PIPELINE_GAUGES

        out["pipeline"] = {
            "depth": PIPELINE_GAUGES["depth"],
            "inFlight": PIPELINE_GAUGES["in_flight"],
            "windowsSealed": PIPELINE_GAUGES["windows_sealed"],
            "windowsCollected": PIPELINE_GAUGES["windows_collected"],
            "occupancy": PIPELINE_GAUGES["occupancy"],
            "driverStallSeconds": PIPELINE_GAUGES["driver_stall_s"],
            "collectorBusySeconds": PIPELINE_GAUGES["collector_busy_s"],
            "collectorDeaths": PIPELINE_GAUGES["collector_deaths"],
            "syncFallbackWindows": PIPELINE_GAUGES[
                "sync_fallback_windows"
            ],
        }
        # graceful-degradation + robustness gauges (docs/recovery.md):
        # fused->host fallbacks, WAL depth, fired chaos faults
        from khipu_tpu.chaos import fault_log
        from khipu_tpu.ledger.window import WINDOW_GAUGES

        out["robustness"] = {
            "fusedFallbacks": WINDOW_GAUGES["fused_fallbacks"],
            "journalDepth": (
                s.window_journal.depth
                if self.config.sync.commit_journal else 0
            ),
            "faults": fault_log.snapshot(),
        }
        # serving plane (serving/__init__.py): admission limits /
        # sheds, per-method SLO evaluation + error budget, read-view
        # overlay occupancy
        if self.serving is not None:
            out["serving"] = self.serving.snapshot()
        elif self.read_view is not None:
            out["serving"] = {"readView": self.read_view.snapshot()}
        # installed-filter occupancy + TTL evictions (jsonrpc/filters)
        out["filters"] = self._filter_manager.snapshot()
        # the unified-registry superset: every registered instrument +
        # pull collector in one consistent snapshot (the same samples
        # khipu_metrics_text exposes), plus the per-phase latency
        # histograms the recorder feeds, flattened for dashboards
        from khipu_tpu.observability.registry import REGISTRY

        reg = REGISTRY.snapshot()
        out["registry"] = reg
        hist = reg.get("khipu_phase_latency_seconds")
        out["phaseLatency"] = {}
        if isinstance(hist, dict):
            for lk, v in hist.items():
                if not isinstance(v, dict):
                    continue
                phase = lk.split('"')[1] if '"' in lk else lk
                out["phaseLatency"][phase] = {
                    "count": v["count"],
                    "sumSeconds": v["sum"],
                }
        return out

    def khipu_metrics_text(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the unified
        registry — the same samples ``khipu_metrics`` serves under
        ``registry``, as a scraper-ready document."""
        from khipu_tpu.observability.registry import REGISTRY

        return REGISTRY.prometheus_text()

    def khipu_cluster_metrics_text(self) -> str:
        """Merged cluster exposition (observability/telemetry.py):
        every scraped shard's families in one Prometheus document —
        counters/gauges ``shard``-labeled, aligned histograms summed,
        stale shards aged out. Requires an attached ClusterTelemetry
        (``ServiceBoard.start_telemetry``)."""
        if self.telemetry is None:
            raise RpcError(-32000, "cluster telemetry not enabled")
        return self.telemetry.cluster_text()

    def khipu_cluster_report(self) -> dict:
        """Cluster health report: per-shard up/down, scrape staleness,
        health-score breakdown, key gauges, and the admission-facing
        pressure value."""
        if self.telemetry is None:
            raise RpcError(-32000, "cluster telemetry not enabled")
        return self.telemetry.report()

    def khipu_traces(self) -> dict:
        """Flight-recorder summary (observability/export.snapshot):
        ring/drop counters, traced block numbers, per-phase latency
        percentiles, occupancy timeline and compile-cache pressure."""
        from khipu_tpu.observability import export

        return export.snapshot(tracer_=self.tracer)

    def khipu_trace_block(self, number) -> dict:
        """Full lifecycle record of ONE block: every span tagged with
        (or covering) its number, grouped into the canonical
        announce -> import -> window.build -> ... -> window.persist
        phase order with cross-thread parent links intact."""
        from khipu_tpu.observability import export

        n = parse_qty(number) if isinstance(number, str) else int(number)
        return export.trace_block(n, tracer_=self.tracer)

    def khipu_tx_journey(self, tx_hash) -> dict:
        """One transaction's passport (observability/journey.py): the
        ordered lifecycle events it crossed — ingress, pool, schedule
        decision (batch + lane), execute lane, seal, journal-intent,
        durable, reorg retraction/re-inclusion, per-replica visibility
        — each with a monotonic timestamp, absolute wall time, the
        stamping node, and the owning flight-recorder trace id (the
        exemplar link into the merged chrome trace)."""
        from khipu_tpu.observability.journey import JOURNEY

        if not JOURNEY.enabled:
            raise RpcError(-32000, "tx journeys not enabled")
        h = parse_data(tx_hash) if isinstance(tx_hash, str) else tx_hash
        rec = JOURNEY.export(h)
        if rec is None:
            raise RpcError(
                -32000,
                "no journey for this tx (evicted, unsampled, or "
                "never seen)",
            )
        return rec

    def khipu_window_report(self, number) -> dict:
        """Data-movement record of the window containing block ``n``:
        phase x bytes x site from the TransferLedger (which bytes
        crossed the host↔device boundary, from which call site, during
        which pipeline phase), collect traffic classified into
        placeholder-resolution vs store-write vs block-save, merged
        with the span-derived phase wall seconds when the ring still
        holds the window's spans."""
        from khipu_tpu.observability import recorder

        n = parse_qty(number) if isinstance(number, str) else int(number)
        return recorder.window_report(n, self.tracer.snapshot())

    def khipu_window_costs(self, number) -> dict:
        """Roofline verdict for the window containing block ``n``:
        per-seal-sub-phase attainable vs achieved seconds against the
        calibrated floors (docs/roofline.md — tunnel rate, dispatch
        RTT, kernel hash rate), each classified bytes-bound /
        dispatch-bound / compute-bound / fixed-overhead, plus the
        headline verdict naming the costliest sub-phase."""
        from khipu_tpu.observability import costmodel

        n = parse_qty(number) if isinstance(number, str) else int(number)
        return costmodel.window_costs(
            n, self.tracer.snapshot(), tracer_=self.tracer
        )

    def khipu_dump_chrome_trace(self, path: str) -> dict:
        """Write the ring's spans as Chrome trace_event JSON (load in
        perfetto / chrome://tracing); returns {path, spans, shards}.
        With a cluster attached, every reachable shard's span ring is
        pulled over the bridge and merged onto the driver timeline
        (offset-corrected — observability/export.merged_chrome_trace),
        so the dump is ONE nested driver -> bridge -> shard trace."""
        from khipu_tpu.observability import export

        spans = self.tracer.snapshot()
        shards = []
        if self.cluster is not None:
            try:
                shards = self.cluster.collect_traces()
            except Exception:
                shards = []
        if shards:
            export.dump_merged_chrome_trace(
                path, shards, spans, tracer_=self.tracer
            )
        else:
            export.dump_chrome_trace(path, spans, tracer_=self.tracer)
        return {"path": path, "spans": len(spans), "shards": len(shards)}

    # ------------------------------------------------------------ codecs

    @staticmethod
    def _call_kwargs(call: dict) -> dict:
        out: Dict[str, Any] = {}
        if call.get("from"):
            out["sender"] = parse_data(call["from"])
        if call.get("to"):
            out["to"] = parse_data(call["to"])
        if call.get("gas"):
            out["gas"] = parse_qty(call["gas"])
        if call.get("gasPrice"):
            out["gas_price"] = parse_qty(call["gasPrice"])
        if call.get("value"):
            out["value"] = parse_qty(call["value"])
        if call.get("data") or call.get("input"):
            out["data"] = parse_data(call.get("data") or call.get("input"))
        return out

    def _tx_json(self, stx: SignedTransaction, block, index):
        tx = stx.tx
        return {
            "hash": data(stx.hash),
            "nonce": qty(tx.nonce),
            "from": data(stx.sender),
            "to": data(tx.to),
            "value": qty(tx.value),
            "gas": qty(tx.gas_limit),
            "gasPrice": qty(tx.gas_price),
            "input": data(tx.payload),
            "v": qty(stx.v),
            "r": qty(stx.r),
            "s": qty(stx.s),
            "blockHash": data(block.hash) if block else None,
            "blockNumber": qty(block.number) if block else None,
            "transactionIndex": qty(index) if index is not None else None,
        }

    def _block_json(self, block: Block, full_txs: bool):
        h = block.header
        return {
            "number": qty(h.number),
            "hash": data(block.hash),
            "parentHash": data(h.parent_hash),
            "sha3Uncles": data(h.ommers_hash),
            "miner": data(h.beneficiary),
            "stateRoot": data(h.state_root),
            "transactionsRoot": data(h.transactions_root),
            "receiptsRoot": data(h.receipts_root),
            "logsBloom": data(h.logs_bloom),
            "difficulty": qty(h.difficulty),
            "totalDifficulty": qty(
                self.blockchain.get_total_difficulty(h.number) or 0
            ),
            "gasLimit": qty(h.gas_limit),
            "gasUsed": qty(h.gas_used),
            "timestamp": qty(h.unix_timestamp),
            "extraData": data(h.extra_data),
            "mixHash": data(h.mix_hash),
            "nonce": data(h.nonce),
            "size": qty(len(block.encode())),
            "transactions": [
                self._tx_json(tx, block, i) if full_txs else data(tx.hash)
                for i, tx in enumerate(block.body.transactions)
            ],
            "uncles": [data(o.hash) for o in block.body.ommers],
        }
