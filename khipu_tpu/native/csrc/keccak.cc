// Native Keccak-256/512 (original Keccak padding 0x01, not SHA-3's 0x06).
//
// Role parity: the reference's hot-loop sponge is JVM Scala
// (khipu-base/src/main/scala/khipu/crypto/hash/KeccakCore.scala:38); per
// SURVEY.md §2.10 this is one of the two components whose role needs a
// native equivalent in the rebuild. Device-side batched hashing lives in
// khipu_tpu/ops (Pallas); this C++ path serves the host: content
// addressing, tx/header hashes, the MPT oracle, EVM SHA3.
//
// Exposed C ABI (ctypes, see khipu_tpu/native/keccak.py):
//   khipu_keccak(rate_bytes, in, in_len, out, out_len)
//   khipu_keccak_batch(rate_bytes, msgs, offsets, n, out, out_len)

#include <cstdint>
#include <cstring>

namespace {

constexpr uint64_t kRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

inline uint64_t rotl(uint64_t x, int s) {
  // s == 0 occurs (kRho[0]); x >> 64 would be undefined behavior.
  return s ? (x << s) | (x >> (64 - s)) : x;
}

void keccak_f1600(uint64_t a[25]) {
  for (int round = 0; round < 24; ++round) {
    // theta
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20];
    for (int x = 0; x < 5; ++x) {
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
      for (int y = 0; y < 25; y += 5) a[x + y] ^= d[x];
    }
    // rho + pi
    uint64_t b[25];
    static constexpr int kRho[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55,
                                     20, 3,  10, 43, 25, 39, 41, 45, 15,
                                     21, 8,  18, 2,  61, 56, 14};
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y)
        b[y + 5 * ((2 * x + 3 * y) % 5)] = rotl(a[x + 5 * y], kRho[x + 5 * y]);
    // chi
    for (int y = 0; y < 25; y += 5)
      for (int x = 0; x < 5; ++x)
        a[y + x] = b[y + x] ^ ((~b[y + (x + 1) % 5]) & b[y + (x + 2) % 5]);
    // iota
    a[0] ^= kRC[round];
  }
}

void keccak(int rate, const uint8_t* in, uint64_t in_len, uint8_t* out,
            int out_len) {
  uint64_t a[25] = {0};
  uint8_t block[200];
  // absorb full blocks
  while (in_len >= static_cast<uint64_t>(rate)) {
    for (int i = 0; i < rate / 8; ++i) {
      uint64_t w;
      std::memcpy(&w, in + 8 * i, 8);  // little-endian hosts only
      a[i] ^= w;
    }
    keccak_f1600(a);
    in += rate;
    in_len -= rate;
  }
  // final block with original-Keccak multi-rate padding (0x01 ... 0x80)
  std::memset(block, 0, rate);
  std::memcpy(block, in, in_len);
  block[in_len] = 0x01;
  block[rate - 1] |= 0x80;
  for (int i = 0; i < rate / 8; ++i) {
    uint64_t w;
    std::memcpy(&w, block + 8 * i, 8);
    a[i] ^= w;
  }
  keccak_f1600(a);
  // squeeze (out_len <= rate for 256/512)
  std::memcpy(out, a, out_len);
}

}  // namespace

extern "C" {

void khipu_keccak(int rate, const uint8_t* in, uint64_t in_len, uint8_t* out,
                  int out_len) {
  keccak(rate, in, in_len, out, out_len);
}

// msgs: concatenated messages; offsets: n+1 cumulative offsets.
void khipu_keccak_batch(int rate, const uint8_t* msgs,
                        const uint64_t* offsets, uint64_t n, uint8_t* out,
                        int out_len) {
  for (uint64_t i = 0; i < n; ++i)
    keccak(rate, msgs + offsets[i], offsets[i + 1] - offsets[i],
           out + i * out_len, out_len);
}
}
