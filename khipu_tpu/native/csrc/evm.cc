// Native EVM interpreter core.
//
// Role: the hot interpreter loop of khipu's VM
// (khipu-eth/src/main/scala/khipu/vm/VM.scala:14-60, OpCode.scala:211-1646,
// ProgramState.scala:29) rebuilt as a C++ core so transaction execution
// (a) runs at native speed and (b) releases the CPython GIL, giving the
// optimistic parallel executor (Ledger.scala:337-461 role) a real
// wall-clock multicore speedup — the reference's headline claim.
//
// Split of responsibilities (see khipu_tpu/evm/native_vm.py):
//   * C++ owns: u256 arithmetic, stack/memory, gas accounting, the full
//     Frontier..Istanbul opcode set, nested call/create frames, and a
//     tx-scoped write OVERLAY for read-your-writes semantics.
//   * Python owns: underlying state (BlockWorldState) via read callbacks
//     (each callback lands on the world's RECORDING accessor, so the
//     parallel merge algebra's read sets stay exact), and precompiles.
//   * Writes are emitted as an OP LOG — the literal sequence of world
//     mutations (add_balance/save_storage/...) the Python VM would have
//     made, truncated when a frame reverts. The adapter replays the log
//     through the same BlockWorldState methods, so write-log / delta /
//     race-set semantics are bit-identical to the Python VM.
//
// Reads that hit the overlay (values this tx itself wrote) are NOT
// re-recorded as reads: a tx-internal observation cannot depend on an
// earlier parallel tx, so skipping the record is sound for the merge
// (it can only reduce false conflicts; see ledger/world.py merge()).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <array>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" void khipu_keccak(int rate, const uint8_t* in, uint64_t in_len,
                             uint8_t* out, int out_len);

namespace evm {

// ===================================================================== u256

struct U256 {
  uint64_t w[4] = {0, 0, 0, 0};  // little-endian limbs
};

static inline bool is_zero(const U256& a) {
  return (a.w[0] | a.w[1] | a.w[2] | a.w[3]) == 0;
}

static inline int ucmp(const U256& a, const U256& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i] != b.w[i]) return a.w[i] < b.w[i] ? -1 : 1;
  }
  return 0;
}

static inline bool eq(const U256& a, const U256& b) { return ucmp(a, b) == 0; }

static inline U256 from_u64(uint64_t x) {
  U256 r;
  r.w[0] = x;
  return r;
}

static inline U256 add(const U256& a, const U256& b) {
  U256 r;
  unsigned __int128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (unsigned __int128)a.w[i] + b.w[i];
    r.w[i] = (uint64_t)c;
    c >>= 64;
  }
  return r;
}

static inline U256 sub(const U256& a, const U256& b) {
  U256 r;
  unsigned __int128 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    unsigned __int128 d =
        (unsigned __int128)a.w[i] - b.w[i] - (uint64_t)borrow;
    r.w[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  return r;
}

static inline U256 neg(const U256& a) { return sub(U256{}, a); }

static inline U256 mul(const U256& a, const U256& b) {
  U256 r;
  for (int i = 0; i < 4; ++i) {
    if (a.w[i] == 0) continue;
    unsigned __int128 carry = 0;
    for (int j = 0; i + j < 4; ++j) {
      unsigned __int128 cur =
          (unsigned __int128)a.w[i] * b.w[j] + r.w[i + j] + carry;
      r.w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
  }
  return r;
}

// full 256x256 -> 512 (for MULMOD)
static inline void mul_full(const U256& a, const U256& b, uint64_t out[8]) {
  std::memset(out, 0, 8 * sizeof(uint64_t));
  for (int i = 0; i < 4; ++i) {
    if (a.w[i] == 0) continue;
    unsigned __int128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      unsigned __int128 cur =
          (unsigned __int128)a.w[i] * b.w[j] + out[i + j] + carry;
      out[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    out[i + 4] = (uint64_t)carry;
  }
}

static inline int bit_length(const U256& a) {
  for (int i = 3; i >= 0; --i) {
    if (a.w[i]) return 64 * i + (64 - __builtin_clzll(a.w[i]));
  }
  return 0;
}

static inline U256 shl(const U256& a, unsigned s) {
  U256 r;
  if (s >= 256) return r;
  unsigned limb = s / 64, off = s % 64;
  for (int i = 3; i >= 0; --i) {
    uint64_t v = 0;
    int src = i - (int)limb;
    if (src >= 0) {
      v = a.w[src] << off;
      if (off && src - 1 >= 0) v |= a.w[src - 1] >> (64 - off);
    }
    r.w[i] = v;
  }
  return r;
}

static inline U256 shr(const U256& a, unsigned s) {
  U256 r;
  if (s >= 256) return r;
  unsigned limb = s / 64, off = s % 64;
  for (int i = 0; i < 4; ++i) {
    uint64_t v = 0;
    unsigned src = i + limb;
    if (src < 4) {
      v = a.w[src] >> off;
      if (off && src + 1 < 4) v |= a.w[src + 1] << (64 - off);
    }
    r.w[i] = v;
  }
  return r;
}

static inline bool sign_bit(const U256& a) { return (a.w[3] >> 63) != 0; }

static inline U256 sar(const U256& a, unsigned s) {
  bool negv = sign_bit(a);
  if (s >= 256) {
    U256 r;
    if (negv) r.w[0] = r.w[1] = r.w[2] = r.w[3] = ~0ULL;
    return r;
  }
  U256 r = shr(a, s);
  if (negv && s > 0) {
    // fill the vacated top s bits with ones
    U256 ones;
    ones.w[0] = ones.w[1] = ones.w[2] = ones.w[3] = ~0ULL;
    r = {r.w[0] | shl(ones, 256 - s).w[0], r.w[1] | shl(ones, 256 - s).w[1],
         r.w[2] | shl(ones, 256 - s).w[2], r.w[3] | shl(ones, 256 - s).w[3]};
  }
  return r;
}

// ---- division: generic little-endian base-2^32 digits (Knuth D) ----

static int digits_of(const uint64_t* limbs, int nlimbs, uint32_t* d) {
  int n = 0;
  for (int i = 0; i < nlimbs; ++i) {
    d[2 * i] = (uint32_t)limbs[i];
    d[2 * i + 1] = (uint32_t)(limbs[i] >> 32);
  }
  n = 2 * nlimbs;
  while (n > 0 && d[n - 1] == 0) --n;
  return n;
}

static void digits_to_u256(const uint32_t* d, int n, U256& out) {
  out = U256{};
  for (int i = 0; i < n && i < 8; ++i) {
    out.w[i / 2] |= (uint64_t)d[i] << (32 * (i % 2));
  }
}

// u[0..un-1] / v[0..vn-1]  ->  q[0..un-vn], r[0..vn-1]; vn>=1, v[vn-1]!=0
static void divmod_digits(const uint32_t* u_in, int un, const uint32_t* v_in,
                          int vn, uint32_t* q, uint32_t* r) {
  if (un < vn) {
    for (int i = 0; i < vn; ++i) r[i] = i < un ? u_in[i] : 0;
    return;  // q stays zero (caller pre-zeroes)
  }
  if (vn == 1) {
    uint64_t rem = 0, d = v_in[0];
    for (int i = un - 1; i >= 0; --i) {
      uint64_t cur = (rem << 32) | u_in[i];
      q[i] = (uint32_t)(cur / d);
      rem = cur % d;
    }
    r[0] = (uint32_t)rem;
    for (int i = 1; i < vn; ++i) r[i] = 0;
    return;
  }
  // normalize
  int s = __builtin_clz(v_in[vn - 1]);
  std::vector<uint32_t> v(vn), u(un + 1);
  for (int i = vn - 1; i > 0; --i)
    v[i] = (uint32_t)((v_in[i] << s) | (s ? (uint64_t)v_in[i - 1] >> (32 - s) : 0));
  v[0] = v_in[0] << s;
  u[un] = s ? (uint32_t)((uint64_t)u_in[un - 1] >> (32 - s)) : 0;
  for (int i = un - 1; i > 0; --i)
    u[i] = (uint32_t)((u_in[i] << s) | (s ? (uint64_t)u_in[i - 1] >> (32 - s) : 0));
  u[0] = u_in[0] << s;

  for (int j = un - vn; j >= 0; --j) {
    uint64_t top = ((uint64_t)u[j + vn] << 32) | u[j + vn - 1];
    uint64_t qhat = top / v[vn - 1];
    uint64_t rhat = top % v[vn - 1];
    while (qhat > 0xFFFFFFFFull ||
           (unsigned __int128)qhat * v[vn - 2] >
               (((unsigned __int128)rhat << 32) | u[j + vn - 2])) {
      --qhat;
      rhat += v[vn - 1];
      if (rhat > 0xFFFFFFFFull) break;
    }
    // multiply-subtract
    int64_t borrow = 0;
    uint64_t carry = 0;
    for (int i = 0; i < vn; ++i) {
      uint64_t p = qhat * v[i] + carry;
      carry = p >> 32;
      int64_t t = (int64_t)u[i + j] - (int64_t)(uint32_t)p - borrow;
      u[i + j] = (uint32_t)t;
      borrow = t < 0 ? 1 : 0;
    }
    int64_t t = (int64_t)u[j + vn] - (int64_t)carry - borrow;
    u[j + vn] = (uint32_t)t;
    if (t < 0) {
      // add back
      --qhat;
      uint64_t c = 0;
      for (int i = 0; i < vn; ++i) {
        uint64_t sum = (uint64_t)u[i + j] + v[i] + c;
        u[i + j] = (uint32_t)sum;
        c = sum >> 32;
      }
      u[j + vn] = (uint32_t)((uint64_t)u[j + vn] + c);
    }
    q[j] = (uint32_t)qhat;
  }
  // denormalize remainder
  for (int i = 0; i < vn - 1; ++i)
    r[i] = (uint32_t)((u[i] >> s) | (s ? (uint64_t)u[i + 1] << (32 - s) : 0));
  r[vn - 1] = u[vn - 1] >> s;
}

static void udivmod(const U256& a, const U256& b, U256& q, U256& r) {
  uint32_t ud[8], vd[8], qd[9] = {0}, rd[8] = {0};
  int un = digits_of(a.w, 4, ud);
  int vn = digits_of(b.w, 4, vd);
  if (vn == 0) {  // div by zero -> 0,0 (EVM semantics)
    q = U256{};
    r = U256{};
    return;
  }
  if (un == 0) {
    q = U256{};
    r = U256{};
    return;
  }
  divmod_digits(ud, un, vd, vn, qd, rd);
  digits_to_u256(qd, un >= vn ? un - vn + 1 : 0, q);
  digits_to_u256(rd, vn, r);
}

// 512 % 256 (for MULMOD)
static U256 mod512(const uint64_t prod[8], const U256& m) {
  uint32_t ud[16], vd[8], qd[17] = {0}, rd[8] = {0};
  int un = digits_of(prod, 8, ud);
  int vn = digits_of(m.w, 4, vd);
  U256 r{};
  if (vn == 0 || un == 0) return r;
  divmod_digits(ud, un, vd, vn, qd, rd);
  digits_to_u256(rd, vn, r);
  return r;
}

static U256 sdiv(const U256& a, const U256& b) {
  if (is_zero(b)) return U256{};
  bool na = sign_bit(a), nb = sign_bit(b);
  U256 ua = na ? neg(a) : a, ub = nb ? neg(b) : b, q, r;
  udivmod(ua, ub, q, r);
  return (na != nb) ? neg(q) : q;
}

static U256 smod(const U256& a, const U256& b) {
  if (is_zero(b)) return U256{};
  bool na = sign_bit(a), nb = sign_bit(b);
  U256 ua = na ? neg(a) : a, ub = nb ? neg(b) : b, q, r;
  udivmod(ua, ub, q, r);
  return na ? neg(r) : r;
}

static U256 uexp(const U256& base, const U256& e) {
  U256 result = from_u64(1), b = base;
  int bits = bit_length(e);
  for (int i = 0; i < bits; ++i) {
    if ((e.w[i / 64] >> (i % 64)) & 1) result = mul(result, b);
    b = mul(b, b);
  }
  return result;
}

static U256 signextend(const U256& k, const U256& x) {
  if (k.w[1] | k.w[2] | k.w[3] || k.w[0] >= 31) return x;
  unsigned bit = 8 * ((unsigned)k.w[0] + 1) - 1;
  bool set = (x.w[bit / 64] >> (bit % 64)) & 1;
  U256 r = x;
  for (unsigned i = bit + 1; i < 256; ++i) {
    if (set)
      r.w[i / 64] |= 1ULL << (i % 64);
    else
      r.w[i / 64] &= ~(1ULL << (i % 64));
  }
  return r;
}

static U256 byte_at(const U256& i, const U256& x) {
  if (i.w[1] | i.w[2] | i.w[3] || i.w[0] >= 32) return U256{};
  unsigned shift = 8 * (31 - (unsigned)i.w[0]);
  U256 t = shr(x, shift);
  return from_u64(t.w[0] & 0xFF);
}

static inline void to_be32(const U256& a, uint8_t out[32]) {
  for (int i = 0; i < 4; ++i) {
    uint64_t w = a.w[3 - i];
    for (int j = 0; j < 8; ++j) out[8 * i + j] = (uint8_t)(w >> (8 * (7 - j)));
  }
}

static inline U256 from_be(const uint8_t* b, size_t len) {
  U256 r;
  if (len > 32) {
    b += len - 32;
    len = 32;
  }
  for (size_t i = 0; i < len; ++i) {
    size_t bit = 8 * (len - 1 - i);
    r.w[bit / 64] |= (uint64_t)b[i] << (bit % 64);
  }
  return r;
}

static inline uint64_t sat_u64(const U256& a) {
  return (a.w[1] | a.w[2] | a.w[3]) ? ~0ULL : a.w[0];
}

// ================================================================== ABI

using Addr = std::array<uint8_t, 20>;
using B32 = std::array<uint8_t, 32>;

struct AddrHash {
  size_t operator()(const Addr& a) const {
    uint64_t h = 1469598103934665603ULL;
    for (uint8_t c : a) h = (h ^ c) * 1099511628211ULL;
    return (size_t)h;
  }
};

struct SKey {  // (address, storage slot)
  Addr a;
  B32 k;
  bool operator==(const SKey& o) const { return a == o.a && k == o.k; }
};

struct SKeyHash {
  size_t operator()(const SKey& s) const {
    uint64_t h = 1469598103934665603ULL;
    for (uint8_t c : s.a) h = (h ^ c) * 1099511628211ULL;
    for (uint8_t c : s.k) h = (h ^ c) * 1099511628211ULL;
    return (size_t)h;
  }
};

// fee schedule indices — MUST match FEE_FIELDS in native_vm.py
enum Fee {
  F_zero, F_base, F_verylow, F_low, F_mid, F_high, F_balance, F_sload,
  F_jumpdest, F_sset, F_sreset, F_r_sclear, F_r_selfdestruct,
  F_selfdestruct, F_create, F_codedeposit, F_call, F_callvalue,
  F_callstipend, F_newaccount, F_exp, F_expbyte, F_memory, F_txcreate,
  F_txdatazero, F_txdatanonzero, F_transaction, F_log, F_logdata,
  F_logtopic, F_sha3, F_sha3word, F_copy, F_blockhash, F_extcode,
  F_extcodehash, F_sstore_noop, F_sstore_init, F_sstore_clean,
  F_sstore_sentry, F_COUNT
};

// cfg u64 array layout — MUST match native_vm.py pack_config
enum Cfg {
  C_chain_id, C_start_nonce, C_contract_start_nonce, C_max_code_size,
  C_homestead, C_eip150, C_eip161, C_eip170, C_byzantium,
  C_constantinople, C_istanbul, C_eip161_patch, C_FEES0  // fees follow
};

typedef int (*cb_exists_t)(void*, const uint8_t*);
typedef int (*cb_is_dead_t)(void*, const uint8_t*);
typedef void (*cb_get_account_t)(void*, const uint8_t*, uint8_t*);  // out[73]
typedef void (*cb_get_code_hash_t)(void*, const uint8_t*, uint8_t*);
typedef void (*cb_get_code_t)(void*, const uint8_t*, const uint8_t**,
                              uint64_t*);
typedef void (*cb_get_storage_t)(void*, const uint8_t*, const uint8_t*,
                                 uint8_t*);
typedef int (*cb_blockhash_t)(void*, uint64_t, uint8_t*);
typedef int (*cb_precompile_t)(void*, uint32_t, const uint8_t*, uint64_t,
                               uint64_t, const uint8_t**, uint64_t*,
                               uint64_t*);

struct Callbacks {  // unpacked from the void*[9] the adapter passes
  void* h;
  cb_exists_t exists;
  cb_is_dead_t is_dead;
  cb_get_account_t get_account;
  cb_get_code_hash_t get_code_hash;
  cb_get_code_t get_code;
  cb_get_storage_t get_storage;
  cb_get_storage_t get_original;
  cb_blockhash_t blockhash;
  cb_precompile_t precompile;
};

struct BlockCtx {
  uint64_t number, timestamp, gas_limit;
  U256 difficulty;
  Addr beneficiary;
};

// error codes (native_vm.py maps these to the Python VM's error strings)
enum Err {
  OK = 0, REVERT = 1, E_OOG = 2, E_STACK_UNDER = 3, E_STACK_OVER = 4,
  E_INVALID_OP = 5, E_INVALID_JUMP = 6, E_STATIC = 7, E_RETURNDATA = 8,
  E_COLLISION = 9, E_CODE_SIZE = 10, E_DEPOSIT_OOG = 11,
  E_PRECOMPILE = 12, E_PRECOMPILE_OOG = 13, E_DEPTH = 14
};

struct VmError {
  int code;
  explicit VmError(int c) : code(c) {}
};

// op log opcodes — MUST match native_vm.py _replay_oplog
enum WOp {
  W_ADD_BALANCE = 1, W_INC_NONCE = 2, W_SAVE_STORAGE = 3, W_SAVE_CODE = 4,
  W_CREATE_ACCOUNT = 5, W_INIT_IF_MISSING = 6, W_TRANSFER = 7, W_TOUCH = 8,
  W_SD_MARK = 9, W_LOG = 10
};

// ============================================================= overlay

struct AcctW {
  bool has_abs = false;        // absolute account value known (create/init)
  uint64_t abs_nonce = 0;
  U256 abs_balance{};
  bool storage_cleared = false;  // CREATE wiped the storage view
  U256 bal_delta{};              // wrapping mod 2^256 (two's complement)
  uint64_t nonce_delta = 0;
  bool code_set = false;
  uint32_t code_idx = 0;  // into TxCtx::code_arena
  bool any_delta() const { return nonce_delta != 0 || !is_zero(bal_delta); }
};

struct FrameState {  // copied at call-frame boundaries (world.copy() role)
  std::unordered_map<Addr, AcctW, AddrHash> accts;
  std::unordered_map<SKey, U256, SKeyHash> storage;
  std::set<Addr> selfdestructed;
};

struct BaseAcct {
  bool exists;
  uint64_t nonce;
  U256 balance;
  B32 code_hash;
};

struct TxCtx {
  const uint64_t* cfg;
  Callbacks cb;
  BlockCtx blk;
  std::vector<uint8_t> oplog;
  std::vector<std::vector<uint8_t>> code_arena;
  // base caches: the underlying Python world is immutable during the
  // native call (all writes stay in the overlay), so caching is sound.
  std::unordered_map<Addr, BaseAcct, AddrHash> base_acct;
  std::unordered_map<Addr, B32, AddrHash> base_codehash;
  std::unordered_map<Addr, std::pair<const uint8_t*, uint64_t>, AddrHash>
      base_code;
  std::unordered_map<SKey, U256, SKeyHash> base_storage;
  std::unordered_map<SKey, U256, SKeyHash> base_original;
  std::unordered_map<Addr, bool, AddrHash> base_exists;
  std::unordered_map<Addr, bool, AddrHash> base_dead;
  FrameState frame;

  uint64_t fee(int f) const { return cfg[C_FEES0 + f]; }
  bool flag(int c) const { return cfg[c] != 0; }
};

// ------------------------------------------------------- oplog writers

static void put_u32(std::vector<uint8_t>& v, uint32_t x) {
  v.push_back((uint8_t)x);
  v.push_back((uint8_t)(x >> 8));
  v.push_back((uint8_t)(x >> 16));
  v.push_back((uint8_t)(x >> 24));
}

static void put_u64(std::vector<uint8_t>& v, uint64_t x) {
  put_u32(v, (uint32_t)x);
  put_u32(v, (uint32_t)(x >> 32));
}

static void put_addr(std::vector<uint8_t>& v, const Addr& a) {
  v.insert(v.end(), a.begin(), a.end());
}

static void put_b32(std::vector<uint8_t>& v, const U256& x) {
  uint8_t buf[32];
  to_be32(x, buf);
  v.insert(v.end(), buf, buf + 32);
}

// -------------------------------------------------------- read helpers

static const BaseAcct& base_account(TxCtx& tx, const Addr& a) {
  auto it = tx.base_acct.find(a);
  if (it != tx.base_acct.end()) return it->second;
  uint8_t out[73];
  tx.cb.get_account(tx.cb.h, a.data(), out);  // records ON_ACCOUNT read
  BaseAcct b;
  b.exists = out[0] != 0;
  b.nonce = 0;
  for (int i = 0; i < 8; ++i) b.nonce |= (uint64_t)out[1 + i] << (8 * i);
  b.balance = from_be(out + 9, 32);
  std::memcpy(b.code_hash.data(), out + 41, 32);
  return tx.base_acct.emplace(a, b).first->second;
}

static U256 r_balance(TxCtx& tx, const Addr& a) {
  auto it = tx.frame.accts.find(a);
  U256 base{};
  if (it != tx.frame.accts.end() && it->second.has_abs) {
    base = it->second.abs_balance;
  } else {
    const BaseAcct& b = base_account(tx, a);
    if (b.exists) base = b.balance;
  }
  if (it != tx.frame.accts.end()) base = add(base, it->second.bal_delta);
  return base;
}

static uint64_t r_nonce(TxCtx& tx, const Addr& a) {
  auto it = tx.frame.accts.find(a);
  uint64_t base;
  if (it != tx.frame.accts.end() && it->second.has_abs) {
    base = it->second.abs_nonce;
  } else {
    const BaseAcct& b = base_account(tx, a);
    base = b.exists ? b.nonce : tx.cfg[C_start_nonce];
  }
  if (it != tx.frame.accts.end()) base += it->second.nonce_delta;
  return base;
}

static bool r_exists(TxCtx& tx, const Addr& a) {
  auto it = tx.frame.accts.find(a);
  if (it != tx.frame.accts.end()) {
    if (it->second.has_abs) return true;
    // a nonzero positive delta materializes the account (world.py
    // _current_account: delta with nonce|balance conjures it)
    if (it->second.any_delta() && !sign_bit(it->second.bal_delta)) return true;
  }
  auto c = tx.base_exists.find(a);
  if (c != tx.base_exists.end()) return c->second;
  bool v = tx.cb.exists(tx.cb.h, a.data()) != 0;  // records ON_ADDRESS
  tx.base_exists.emplace(a, v);
  return v;
}

static const std::vector<uint8_t>* overlay_code(TxCtx& tx, const Addr& a) {
  auto it = tx.frame.accts.find(a);
  if (it != tx.frame.accts.end() && it->second.code_set)
    return &tx.code_arena[it->second.code_idx];
  return nullptr;
}

static void r_code(TxCtx& tx, const Addr& a, const uint8_t** p, uint64_t* n) {
  if (const auto* c = overlay_code(tx, a)) {
    *p = c->data();
    *n = c->size();
    return;
  }
  auto it = tx.frame.accts.find(a);
  if (it != tx.frame.accts.end() && it->second.has_abs) {
    *p = nullptr;
    *n = 0;  // created/initialized empty account: no code
    return;
  }
  auto c = tx.base_code.find(a);
  if (c != tx.base_code.end()) {
    *p = c->second.first;
    *n = c->second.second;
    return;
  }
  const uint8_t* ptr = nullptr;
  uint64_t len = 0;
  tx.cb.get_code(tx.cb.h, a.data(), &ptr, &len);  // records ON_CODE
  tx.base_code.emplace(a, std::make_pair(ptr, len));
  *p = ptr;
  *n = len;
}

static const uint8_t EMPTY_HASH[32] = {
    0xc5, 0xd2, 0x46, 0x01, 0x86, 0xf7, 0x23, 0x3c, 0x92, 0x7e, 0x7d,
    0xb2, 0xdc, 0xc7, 0x03, 0xc0, 0xe5, 0x00, 0xb6, 0x53, 0xca, 0x82,
    0x27, 0x3b, 0x7b, 0xfa, 0xd8, 0x04, 0x5d, 0x85, 0xa4, 0x70};

static void r_code_hash(TxCtx& tx, const Addr& a, uint8_t out[32]) {
  if (const auto* c = overlay_code(tx, a)) {
    if (c->empty())
      std::memcpy(out, EMPTY_HASH, 32);
    else
      khipu_keccak(136, c->data(), c->size(), out, 32);
    return;
  }
  auto it = tx.frame.accts.find(a);
  if (it != tx.frame.accts.end() && it->second.has_abs) {
    std::memcpy(out, EMPTY_HASH, 32);
    return;
  }
  auto c = tx.base_codehash.find(a);
  if (c != tx.base_codehash.end()) {
    std::memcpy(out, c->second.data(), 32);
    return;
  }
  B32 h;
  tx.cb.get_code_hash(tx.cb.h, a.data(), h.data());  // records ON_CODE
  tx.base_codehash.emplace(a, h);
  std::memcpy(out, h.data(), 32);
}

static bool r_dead(TxCtx& tx, const Addr& a) {
  auto it = tx.frame.accts.find(a);
  if (it != tx.frame.accts.end()) {
    const AcctW& w = it->second;
    if (w.has_abs) {
      uint64_t nonce = w.abs_nonce + w.nonce_delta;
      U256 bal = add(w.abs_balance, w.bal_delta);
      bool code_empty = true;
      if (w.code_set) code_empty = tx.code_arena[w.code_idx].empty();
      return nonce == tx.cfg[C_start_nonce] && is_zero(bal) && code_empty;
    }
    if (w.any_delta() && !sign_bit(w.bal_delta)) return false;
  }
  auto c = tx.base_dead.find(a);
  if (c != tx.base_dead.end()) return c->second;
  bool v = tx.cb.is_dead(tx.cb.h, a.data()) != 0;  // records both reads
  tx.base_dead.emplace(a, v);
  return v;
}

static U256 r_storage(TxCtx& tx, const Addr& a, const U256& key) {
  SKey sk;
  sk.a = a;
  to_be32(key, sk.k.data());
  auto it = tx.frame.storage.find(sk);
  if (it != tx.frame.storage.end()) return it->second;
  auto ac = tx.frame.accts.find(a);
  if (ac != tx.frame.accts.end() && ac->second.storage_cleared) return U256{};
  auto c = tx.base_storage.find(sk);
  if (c != tx.base_storage.end()) return c->second;
  uint8_t out[32];
  tx.cb.get_storage(tx.cb.h, a.data(), sk.k.data(), out);  // ON_STORAGE
  U256 v = from_be(out, 32);
  tx.base_storage.emplace(sk, v);
  return v;
}

static U256 r_original(TxCtx& tx, const Addr& a, const U256& key) {
  SKey sk;
  sk.a = a;
  to_be32(key, sk.k.data());
  auto ac = tx.frame.accts.find(a);
  if (ac != tx.frame.accts.end() && ac->second.storage_cleared) return U256{};
  auto c = tx.base_original.find(sk);
  if (c != tx.base_original.end()) return c->second;
  uint8_t out[32];
  tx.cb.get_original(tx.cb.h, a.data(), sk.k.data(), out);  // ON_STORAGE
  U256 v = from_be(out, 32);
  tx.base_original.emplace(sk, v);
  return v;
}

// -------------------------------------------------------- write helpers
// Each mirrors one BlockWorldState mutator: update the overlay AND emit
// the op so the adapter replays the identical call sequence.

static void w_add_balance(TxCtx& tx, const Addr& a, const U256& amt,
                          bool negative) {
  AcctW& w = tx.frame.accts[a];
  w.bal_delta = negative ? sub(w.bal_delta, amt) : add(w.bal_delta, amt);
  tx.oplog.push_back(W_ADD_BALANCE);
  put_addr(tx.oplog, a);
  tx.oplog.push_back(negative ? 1 : 0);
  put_b32(tx.oplog, amt);
}

static void w_inc_nonce(TxCtx& tx, const Addr& a) {
  tx.frame.accts[a].nonce_delta += 1;
  tx.oplog.push_back(W_INC_NONCE);
  put_addr(tx.oplog, a);
  put_u64(tx.oplog, 1);
}

static void w_save_storage(TxCtx& tx, const Addr& a, const U256& key,
                           const U256& val) {
  SKey sk;
  sk.a = a;
  to_be32(key, sk.k.data());
  tx.frame.storage[sk] = val;
  tx.oplog.push_back(W_SAVE_STORAGE);
  put_addr(tx.oplog, a);
  tx.oplog.insert(tx.oplog.end(), sk.k.begin(), sk.k.end());
  put_b32(tx.oplog, val);
}

static void w_save_code(TxCtx& tx, const Addr& a, const uint8_t* code,
                        uint64_t len) {
  AcctW& w = tx.frame.accts[a];
  w.code_set = true;
  w.code_idx = (uint32_t)tx.code_arena.size();
  tx.code_arena.emplace_back(code, code + len);
  tx.oplog.push_back(W_SAVE_CODE);
  put_addr(tx.oplog, a);
  put_u32(tx.oplog, (uint32_t)len);
  tx.oplog.insert(tx.oplog.end(), code, code + len);
}

static void w_create_account(TxCtx& tx, const Addr& a, uint64_t nonce,
                             const U256& balance) {
  AcctW& w = tx.frame.accts[a];
  w.has_abs = true;
  w.abs_nonce = nonce;
  w.abs_balance = balance;
  w.storage_cleared = true;
  w.bal_delta = U256{};
  w.nonce_delta = 0;
  // world.create_account sets codes[addr] = b""
  w.code_set = true;
  w.code_idx = (uint32_t)tx.code_arena.size();
  tx.code_arena.emplace_back();
  // wipe frame-local storage writes for a (fresh TrieStorage)
  for (auto it = tx.frame.storage.begin(); it != tx.frame.storage.end();) {
    if (it->first.a == a)
      it = tx.frame.storage.erase(it);
    else
      ++it;
  }
  tx.oplog.push_back(W_CREATE_ACCOUNT);
  put_addr(tx.oplog, a);
  put_u64(tx.oplog, nonce);
  put_b32(tx.oplog, balance);
}

static void w_init_if_missing(TxCtx& tx, const Addr& a) {
  if (!r_exists(tx, a)) {  // records ON_ADDRESS read, like the Python
    AcctW& w = tx.frame.accts[a];
    w.has_abs = true;
    w.abs_nonce = tx.cfg[C_start_nonce];
    w.abs_balance = U256{};
  }
  tx.oplog.push_back(W_INIT_IF_MISSING);
  put_addr(tx.oplog, a);
}

static void w_transfer(TxCtx& tx, const Addr& from, const Addr& to,
                       const U256& value) {
  if (!is_zero(value) && from != to) {
    tx.frame.accts[from].bal_delta = sub(tx.frame.accts[from].bal_delta, value);
    tx.frame.accts[to].bal_delta = add(tx.frame.accts[to].bal_delta, value);
  }
  tx.oplog.push_back(W_TRANSFER);
  put_addr(tx.oplog, from);
  put_addr(tx.oplog, to);
  put_b32(tx.oplog, value);
}

static void w_touch(TxCtx& tx, const Addr& a) {
  tx.oplog.push_back(W_TOUCH);
  put_addr(tx.oplog, a);
}

static void w_sd_mark(TxCtx& tx, const Addr& a) {
  tx.frame.selfdestructed.insert(a);
  tx.oplog.push_back(W_SD_MARK);
  put_addr(tx.oplog, a);
}

static void w_log(TxCtx& tx, const Addr& a, const U256* topics, int ntopics,
                  const uint8_t* data, uint64_t dlen) {
  tx.oplog.push_back(W_LOG);
  put_addr(tx.oplog, a);
  tx.oplog.push_back((uint8_t)ntopics);
  for (int i = 0; i < ntopics; ++i) put_b32(tx.oplog, topics[i]);
  put_u32(tx.oplog, (uint32_t)dlen);
  tx.oplog.insert(tx.oplog.end(), data, data + dlen);
}

// ============================================================ interpreter

struct Mem {
  std::vector<uint8_t> data;
  uint64_t active_words = 0;

  void expand(uint64_t off, uint64_t size) {
    if (size == 0) return;
    uint64_t words = (off + size + 31) / 32;
    if (words > active_words) active_words = words;
    uint64_t need = words * 32;
    if (data.size() < need) data.resize(need, 0);
  }
  void store(uint64_t off, const uint8_t* src, uint64_t n) {
    expand(off, n);
    if (n) std::memcpy(data.data() + off, src, n);
  }
  void load(uint64_t off, uint64_t n, std::vector<uint8_t>& out) {
    if (n == 0) {  // zero-size loads never expand; off may be huge
      out.clear();
      return;
    }
    expand(off, n);
    out.assign(data.begin() + off, data.begin() + off + n);
  }
};

static const uint64_t MEM_WORD_CAP = 1ULL << 40;  // beyond this, cost > any gas

// word count after touching [off, off+size); saturating
static uint64_t words_after(uint64_t cur, const U256& off, const U256& size) {
  if (is_zero(size)) return cur;
  if ((off.w[1] | off.w[2] | off.w[3]) || (size.w[1] | size.w[2] | size.w[3]))
    return MEM_WORD_CAP;
  unsigned __int128 end = (unsigned __int128)off.w[0] + size.w[0] + 31;
  uint64_t words = (uint64_t)(end / 32);
  if (words > MEM_WORD_CAP) return MEM_WORD_CAP;
  return cur > words ? cur : words;
}

static unsigned __int128 mem_cost_words(uint64_t words, uint64_t g_memory) {
  return (unsigned __int128)g_memory * words +
         ((unsigned __int128)words * words) / 512;
}

struct Frame {
  TxCtx& tx;
  // message env
  Addr owner, caller, origin;
  U256 gas_price, value;
  const uint8_t* input;
  uint64_t input_len;
  uint32_t depth;
  bool is_static;
  // interpreter state
  const uint8_t* code;
  uint64_t code_len;
  int64_t gas;
  uint64_t pc = 0;
  std::vector<U256> stack;
  Mem mem;
  std::vector<uint8_t> returndata;
  std::vector<uint8_t> output;
  int64_t refund = 0;
  bool halted = false, reverted = false;
  std::vector<uint8_t> jumpdest_bits;

  Frame(TxCtx& t) : tx(t) { stack.reserve(64); }

  void analyze_jumpdests() {
    jumpdest_bits.assign((code_len + 7) / 8, 0);
    for (uint64_t i = 0; i < code_len;) {
      uint8_t op = code[i];
      if (op == 0x5B) {
        jumpdest_bits[i / 8] |= 1 << (i % 8);
        ++i;
      } else if (op >= 0x60 && op <= 0x7F) {
        i += op - 0x60 + 2;
      } else {
        ++i;
      }
    }
  }
  bool valid_jumpdest(uint64_t d) const {
    return d < code_len && (jumpdest_bits[d / 8] >> (d % 8)) & 1;
  }

  void charge(unsigned __int128 cost) {
    if (cost > (unsigned __int128)gas) throw VmError(E_OOG);
    gas -= (int64_t)cost;
  }
  uint64_t fee(int f) const { return tx.fee(f); }

  void push(const U256& v) {
    if (stack.size() >= 1024) throw VmError(E_STACK_OVER);
    stack.push_back(v);
  }
  U256 pop() {
    if (stack.empty()) throw VmError(E_STACK_UNDER);
    U256 v = stack.back();
    stack.pop_back();
    return v;
  }
  // expansion gas for touching [off, off+size)
  unsigned __int128 mem_gas(const U256& off, const U256& size) {
    uint64_t nw = words_after(mem.active_words, off, size);
    if (nw <= mem.active_words) return 0;
    uint64_t g = fee(F_memory);
    return mem_cost_words(nw, g) - mem_cost_words(mem.active_words, g);
  }
};

struct RunResult {
  int status = OK;  // OK / REVERT / error code
  int64_t gas_remaining = 0;
  int64_t refund = 0;
  std::vector<uint8_t> output;
};

static Addr to_addr(const U256& w) {
  Addr a;
  uint8_t b[32];
  to_be32(w, b);
  std::memcpy(a.data(), b + 12, 20);
  return a;
}

static U256 addr_to_word(const Addr& a) { return from_be(a.data(), 20); }

struct MsgEnv {
  Addr owner, caller, origin;
  U256 gas_price, value;
  const uint8_t* input;
  uint64_t input_len;
  uint32_t depth;
  bool is_static;
};

static RunResult run_frame(TxCtx& tx, const MsgEnv& env, const uint8_t* code,
                           uint64_t code_len, int64_t gas);
static RunResult execute_message(TxCtx& tx, const MsgEnv& env,
                                 const uint8_t* code, uint64_t code_len,
                                 int64_t gas, const Addr& code_addr);
static RunResult create_contract(TxCtx& tx, const Addr& caller,
                                 const Addr& origin, const Addr& new_addr,
                                 int64_t gas, const U256& gas_price,
                                 const U256& value, const uint8_t* init_code,
                                 uint64_t init_len, uint32_t depth);

// is `a` a precompile address under this config? returns 0 if not, else 1..9
static uint32_t precompile_id(const TxCtx& tx, const Addr& a) {
  for (int i = 0; i < 19; ++i)
    if (a[i] != 0) return 0;
  uint8_t last = a[19];
  if (last >= 1 && last <= 4) return last;
  if (last >= 5 && last <= 8) return tx.flag(C_byzantium) ? last : 0;
  if (last == 9) return tx.flag(C_istanbul) ? last : 0;
  return 0;
}

// minimal RLP of [addr20, minimal_nonce] for CREATE address derivation
static void create_address(const Addr& sender, uint64_t nonce, Addr& out) {
  uint8_t payload[32];
  int n = 0;
  payload[n++] = 0x80 + 20;
  std::memcpy(payload + n, sender.data(), 20);
  n += 20;
  if (nonce == 0) {
    payload[n++] = 0x80;
  } else if (nonce < 0x80) {
    payload[n++] = (uint8_t)nonce;
  } else {
    uint8_t tmp[8];
    int len = 0;
    for (int i = 7; i >= 0; --i) {
      uint8_t b = (uint8_t)(nonce >> (8 * i));
      if (len == 0 && b == 0) continue;
      tmp[len++] = b;
    }
    payload[n++] = 0x80 + len;
    std::memcpy(payload + n, tmp, len);
    n += len;
  }
  uint8_t rlp[40];
  rlp[0] = 0xC0 + n;
  std::memcpy(rlp + 1, payload, n);
  uint8_t h[32];
  khipu_keccak(136, rlp, n + 1, h, 32);
  std::memcpy(out.data(), h + 12, 20);
}

static void create2_address(const Addr& sender, const U256& salt,
                            const uint8_t* init, uint64_t init_len,
                            Addr& out) {
  uint8_t ih[32];
  khipu_keccak(136, init, init_len, ih, 32);
  uint8_t buf[85];
  buf[0] = 0xFF;
  std::memcpy(buf + 1, sender.data(), 20);
  to_be32(salt, buf + 21);
  std::memcpy(buf + 53, ih, 32);
  uint8_t h[32];
  khipu_keccak(136, buf, 85, h, 32);
  std::memcpy(out.data(), h + 12, 20);
}

// 63/64 rule (EvmConfig sub_gas_cap_divisor); charges the child gas
static int64_t consume_child_gas(Frame& f, const U256& requested) {
  uint64_t req = sat_u64(requested);
  int64_t child;
  if (f.tx.flag(C_eip150)) {
    int64_t cap = f.gas - f.gas / 64;
    child = req < (uint64_t)cap ? (int64_t)req : cap;
  } else {
    if (req > (uint64_t)f.gas) throw VmError(E_OOG);
    child = (int64_t)req;
  }
  f.charge((unsigned __int128)child);
  return child;
}

// CALL-family postlude (vm.py _finish_child)
static void finish_child(Frame& f, RunResult& r, uint64_t out_off,
                         uint64_t out_size) {
  bool byz = f.tx.flag(C_byzantium);
  if (r.status == OK || r.status == REVERT) {
    if (!r.output.empty() && out_size) {
      uint64_t n = r.output.size() < out_size ? r.output.size() : out_size;
      std::memcpy(f.mem.data.data() + out_off, r.output.data(), n);
    }
    f.gas += r.gas_remaining;
    if (r.status == OK) {
      f.refund += r.refund;
      f.push(from_u64(1));
    } else {
      f.push(U256{});
    }
    if (byz) f.returndata = r.output;
  } else {
    f.push(U256{});
    if (byz) f.returndata.clear();
  }
}

enum CallKind { K_CALL, K_CALLCODE, K_DELEGATE, K_STATIC };

static void op_call_family(Frame& f, CallKind kind) {
  TxCtx& tx = f.tx;
  bool has_value = (kind == K_CALL || kind == K_CALLCODE);
  U256 gas_req = f.pop();
  Addr to = to_addr(f.pop());
  U256 value = has_value ? f.pop() : U256{};
  U256 in_off_w = f.pop(), in_size_w = f.pop();
  U256 out_off_w = f.pop(), out_size_w = f.pop();

  if (kind == K_CALL && !is_zero(value) && f.is_static)
    throw VmError(E_STATIC);

  unsigned __int128 cost = f.fee(F_call);
  if (has_value && !is_zero(value)) cost += f.fee(F_callvalue);
  if (kind == K_CALL) {
    if (tx.flag(C_eip161)) {
      if (!is_zero(value) && r_dead(tx, to)) cost += f.fee(F_newaccount);
    } else if (!r_exists(tx, to)) {
      cost += f.fee(F_newaccount);
    }
  }
  cost += f.mem_gas(in_off_w, in_size_w);
  // output expansion relative to post-input memory (vm.py quirk kept)
  uint64_t mem_after_in = words_after(f.mem.active_words, in_off_w, in_size_w);
  if (!is_zero(out_size_w)) {
    uint64_t out_words = words_after(0, out_off_w, out_size_w);
    if (out_words > mem_after_in) {
      uint64_t g = f.fee(F_memory);
      cost += mem_cost_words(out_words, g) - mem_cost_words(mem_after_in, g);
    }
  }
  f.charge(cost);
  int64_t child_gas = consume_child_gas(f, gas_req);
  if (has_value && !is_zero(value)) child_gas += (int64_t)f.fee(F_callstipend);

  uint64_t in_off = sat_u64(in_off_w), in_size = sat_u64(in_size_w);
  uint64_t out_off = sat_u64(out_off_w), out_size = sat_u64(out_size_w);
  f.mem.expand(in_off, in_size);
  f.mem.expand(out_off, out_size);
  std::vector<uint8_t> input;
  f.mem.load(in_off, in_size, input);

  bool byz = tx.flag(C_byzantium);
  if (f.depth + 1 > 1024 ||
      (has_value && !is_zero(value) &&
       ucmp(r_balance(tx, f.owner), value) < 0)) {
    f.gas += child_gas;  // child never ran
    f.push(U256{});
    if (byz) f.returndata.clear();
    f.pc += 1;
    return;
  }

  FrameState saved = tx.frame;  // world.copy() at the call boundary
  size_t oplog_mark = tx.oplog.size();

  MsgEnv env;
  env.origin = f.origin;
  env.gas_price = f.gas_price;
  env.input = input.data();
  env.input_len = input.size();
  env.depth = f.depth + 1;
  if (kind == K_CALL) {
    if (!tx.flag(C_eip161)) w_init_if_missing(tx, to);
    w_transfer(tx, f.owner, to, value);
    w_touch(tx, to);
    env.owner = to;
    env.caller = f.owner;
    env.value = value;
    env.is_static = f.is_static;
  } else if (kind == K_CALLCODE) {
    env.owner = f.owner;
    env.caller = f.owner;
    env.value = value;
    env.is_static = f.is_static;
  } else if (kind == K_DELEGATE) {
    env.owner = f.owner;
    env.caller = f.caller;
    env.value = f.value;
    env.is_static = f.is_static;
  } else {  // STATICCALL
    w_touch(tx, to);
    env.owner = to;
    env.caller = f.owner;
    env.value = U256{};
    env.is_static = true;
  }
  const uint8_t* code = nullptr;
  uint64_t code_len = 0;
  r_code(tx, to, &code, &code_len);
  RunResult r = execute_message(tx, env, code, code_len, child_gas, to);
  if (r.status != OK) {  // revert or error: discard the child's writes
    tx.frame = std::move(saved);
    tx.oplog.resize(oplog_mark);
    // mainnet #2,675,119 compat (OpCode.scala:1425-1436): a failed
    // call to the ripemd precompile keeps its touch in the parent
    if (tx.flag(C_eip161_patch)) {
      bool is_ripemd = to[19] == 0x03;
      for (int i = 0; i < 19 && is_ripemd; ++i)
        if (to[i] != 0) is_ripemd = false;
      if (is_ripemd) w_touch(tx, to);
    }
  }
  finish_child(f, r, out_off, out_size);
  f.pc += 1;
}

static void op_create_family(Frame& f, bool is_create2) {
  TxCtx& tx = f.tx;
  if (f.is_static) throw VmError(E_STATIC);
  U256 value = f.pop();
  U256 off_w = f.pop(), size_w = f.pop();
  U256 salt = is_create2 ? f.pop() : U256{};

  unsigned __int128 cost = f.fee(F_create) + f.mem_gas(off_w, size_w);
  if (is_create2) {
    unsigned __int128 words =
        ((unsigned __int128)sat_u64(size_w) + 31) / 32;
    cost += (unsigned __int128)f.fee(F_sha3word) * words;
  }
  f.charge(cost);
  uint64_t off = sat_u64(off_w), size = sat_u64(size_w);
  std::vector<uint8_t> init_code;
  f.mem.load(off, size, init_code);

  bool byz = tx.flag(C_byzantium);
  if (f.depth + 1 > 1024 || ucmp(r_balance(tx, f.owner), value) < 0) {
    f.push(U256{});
    if (byz) f.returndata.clear();
    f.pc += 1;
    return;
  }

  int64_t child_gas = consume_child_gas(f, from_u64((uint64_t)f.gas));
  uint64_t nonce = r_nonce(tx, f.owner);
  w_inc_nonce(tx, f.owner);
  Addr new_addr;
  if (is_create2)
    create2_address(f.owner, salt, init_code.data(), init_code.size(),
                    new_addr);
  else
    create_address(f.owner, nonce, new_addr);

  RunResult r = create_contract(tx, f.owner, f.origin, new_addr, child_gas,
                                f.gas_price, value, init_code.data(),
                                init_code.size(), f.depth + 1);
  if (r.status == OK) {
    f.gas += r.gas_remaining;
    f.refund += r.refund;
    f.push(addr_to_word(new_addr));
    if (byz) f.returndata.clear();
  } else if (r.status == REVERT) {
    f.gas += r.gas_remaining;
    f.push(U256{});
    if (byz) f.returndata = r.output;
  } else {
    f.push(U256{});
    if (byz) f.returndata.clear();
  }
  f.pc += 1;
}

static void op_selfdestruct(Frame& f) {
  TxCtx& tx = f.tx;
  if (f.is_static) throw VmError(E_STATIC);
  Addr ben = to_addr(f.pop());
  unsigned __int128 cost = f.fee(F_selfdestruct);
  if (tx.flag(C_eip150)) {
    if (tx.flag(C_eip161)) {
      if (!is_zero(r_balance(tx, f.owner)) && r_dead(tx, ben))
        cost += f.fee(F_newaccount);
    } else if (!r_exists(tx, ben)) {
      cost += f.fee(F_newaccount);
    }
  }
  f.charge(cost);
  if (!tx.frame.selfdestructed.count(f.owner)) {
    f.refund += (int64_t)f.fee(F_r_selfdestruct);
    w_sd_mark(tx, f.owner);
  }
  U256 bal = r_balance(tx, f.owner);
  if (!tx.flag(C_eip161)) w_init_if_missing(tx, ben);
  w_add_balance(tx, ben, bal, false);
  // re-read handles beneficiary == owner (funds destroyed)
  w_add_balance(tx, f.owner, r_balance(tx, f.owner), true);
  w_touch(tx, ben);
  f.halted = true;
}

static void op_sstore(Frame& f) {
  TxCtx& tx = f.tx;
  if (f.is_static) throw VmError(E_STATIC);
  U256 key = f.pop(), value = f.pop();
  const Addr& owner = f.owner;
  if (tx.flag(C_istanbul)) {
    // EIP-2200 net metering (vm.py _op_sstore Istanbul branch)
    if ((uint64_t)f.gas <= f.fee(F_sstore_sentry)) throw VmError(E_OOG);
    U256 current = r_storage(tx, owner, key);
    if (eq(value, current)) {
      f.charge(f.fee(F_sstore_noop));
      f.pc += 1;
      return;
    }
    U256 original = r_original(tx, owner, key);
    if (eq(original, current)) {
      if (is_zero(original)) {
        f.charge(f.fee(F_sstore_init));
      } else {
        f.charge(f.fee(F_sstore_clean));
        if (is_zero(value)) f.refund += (int64_t)f.fee(F_r_sclear);
      }
    } else {
      f.charge(f.fee(F_sstore_noop));
      if (!is_zero(original)) {
        if (is_zero(current)) f.refund -= (int64_t)f.fee(F_r_sclear);
        if (is_zero(value)) f.refund += (int64_t)f.fee(F_r_sclear);
      }
      if (eq(original, value)) {
        if (is_zero(original))
          f.refund += (int64_t)(f.fee(F_sstore_init) - f.fee(F_sstore_noop));
        else
          f.refund += (int64_t)(f.fee(F_sstore_clean) - f.fee(F_sstore_noop));
      }
    }
    w_save_storage(tx, owner, key, value);
    f.pc += 1;
    return;
  }
  // Frontier..Petersburg metering
  U256 current = r_storage(tx, owner, key);
  if (is_zero(current) && !is_zero(value)) {
    f.charge(f.fee(F_sset));
  } else {
    f.charge(f.fee(F_sreset));
    if (!is_zero(current) && is_zero(value))
      f.refund += (int64_t)f.fee(F_r_sclear);
  }
  w_save_storage(tx, owner, key, value);
  f.pc += 1;
}

// the fetch-decode-execute loop (vm.py run / VM.scala:14-60)
static RunResult run_frame(TxCtx& tx, const MsgEnv& env, const uint8_t* code,
                           uint64_t code_len, int64_t gas) {
  Frame f(tx);
  f.owner = env.owner;
  f.caller = env.caller;
  f.origin = env.origin;
  f.gas_price = env.gas_price;
  f.value = env.value;
  f.input = env.input;
  f.input_len = env.input_len;
  f.depth = env.depth;
  f.is_static = env.is_static;
  f.code = code;
  f.code_len = code_len;
  f.gas = gas;
  f.analyze_jumpdests();

  RunResult out;
  try {
    while (!f.halted) {
      uint8_t op = f.pc < code_len ? code[f.pc] : 0x00;
      switch (op) {
        case 0x00:  // STOP
          f.charge(f.fee(F_zero));
          f.halted = true;
          break;
        case 0x01: {  // ADD
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          f.push(add(a, b));
          f.pc += 1;
          break;
        }
        case 0x02: {  // MUL
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop();
          f.push(mul(a, b));
          f.pc += 1;
          break;
        }
        case 0x03: {  // SUB
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          f.push(sub(a, b));
          f.pc += 1;
          break;
        }
        case 0x04: {  // DIV
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop(), q, r;
          udivmod(a, b, q, r);
          f.push(is_zero(b) ? U256{} : q);
          f.pc += 1;
          break;
        }
        case 0x05: {  // SDIV
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop();
          f.push(sdiv(a, b));
          f.pc += 1;
          break;
        }
        case 0x06: {  // MOD
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop(), q, r;
          udivmod(a, b, q, r);
          f.push(is_zero(b) ? U256{} : r);
          f.pc += 1;
          break;
        }
        case 0x07: {  // SMOD
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop();
          f.push(smod(a, b));
          f.pc += 1;
          break;
        }
        case 0x08: {  // ADDMOD
          f.charge(f.fee(F_mid));
          U256 a = f.pop(), b = f.pop(), n = f.pop();
          if (is_zero(n)) {
            f.push(U256{});
          } else {
            uint64_t wide[8] = {0};
            // a + b can be 257 bits: do it in the 512-bit buffer
            unsigned __int128 c = 0;
            for (int i = 0; i < 4; ++i) {
              c += (unsigned __int128)a.w[i] + b.w[i];
              wide[i] = (uint64_t)c;
              c >>= 64;
            }
            wide[4] = (uint64_t)c;
            f.push(mod512(wide, n));
          }
          f.pc += 1;
          break;
        }
        case 0x09: {  // MULMOD
          f.charge(f.fee(F_mid));
          U256 a = f.pop(), b = f.pop(), n = f.pop();
          if (is_zero(n)) {
            f.push(U256{});
          } else {
            uint64_t wide[8];
            mul_full(a, b, wide);
            f.push(mod512(wide, n));
          }
          f.pc += 1;
          break;
        }
        case 0x0A: {  // EXP
          U256 a = f.pop(), e = f.pop();
          uint64_t nbytes = (bit_length(e) + 7) / 8;
          f.charge((unsigned __int128)f.fee(F_exp) +
                   (unsigned __int128)f.fee(F_expbyte) * nbytes);
          f.push(uexp(a, e));
          f.pc += 1;
          break;
        }
        case 0x0B: {  // SIGNEXTEND
          f.charge(f.fee(F_low));
          U256 a = f.pop(), b = f.pop();
          f.push(signextend(a, b));
          f.pc += 1;
          break;
        }
        case 0x10: {  // LT
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          f.push(from_u64(ucmp(a, b) < 0 ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x11: {  // GT
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          f.push(from_u64(ucmp(a, b) > 0 ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x12: {  // SLT
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          bool na = sign_bit(a), nb = sign_bit(b);
          bool lt = (na != nb) ? na : (ucmp(a, b) < 0);
          f.push(from_u64(lt ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x13: {  // SGT
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          bool na = sign_bit(a), nb = sign_bit(b);
          bool gt = (na != nb) ? nb : (ucmp(a, b) > 0);
          f.push(from_u64(gt ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x14: {  // EQ
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop();
          f.push(from_u64(eq(a, b) ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x15: {  // ISZERO
          f.charge(f.fee(F_verylow));
          f.push(from_u64(is_zero(f.pop()) ? 1 : 0));
          f.pc += 1;
          break;
        }
        case 0x16: {  // AND
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] & b.w[i];
          f.push(r);
          f.pc += 1;
          break;
        }
        case 0x17: {  // OR
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] | b.w[i];
          f.push(r);
          f.pc += 1;
          break;
        }
        case 0x18: {  // XOR
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), b = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = a.w[i] ^ b.w[i];
          f.push(r);
          f.pc += 1;
          break;
        }
        case 0x19: {  // NOT
          f.charge(f.fee(F_verylow));
          U256 a = f.pop(), r;
          for (int i = 0; i < 4; ++i) r.w[i] = ~a.w[i];
          f.push(r);
          f.pc += 1;
          break;
        }
        case 0x1A: {  // BYTE
          f.charge(f.fee(F_verylow));
          U256 i = f.pop(), x = f.pop();
          f.push(byte_at(i, x));
          f.pc += 1;
          break;
        }
        case 0x1B: {  // SHL (EIP-145)
          if (!tx.flag(C_constantinople)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_verylow));
          U256 s = f.pop(), x = f.pop();
          f.push((s.w[1] | s.w[2] | s.w[3] || s.w[0] >= 256)
                     ? U256{}
                     : shl(x, (unsigned)s.w[0]));
          f.pc += 1;
          break;
        }
        case 0x1C: {  // SHR
          if (!tx.flag(C_constantinople)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_verylow));
          U256 s = f.pop(), x = f.pop();
          f.push((s.w[1] | s.w[2] | s.w[3] || s.w[0] >= 256)
                     ? U256{}
                     : shr(x, (unsigned)s.w[0]));
          f.pc += 1;
          break;
        }
        case 0x1D: {  // SAR
          if (!tx.flag(C_constantinople)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_verylow));
          U256 s = f.pop(), x = f.pop();
          unsigned sh = (s.w[1] | s.w[2] | s.w[3] || s.w[0] >= 256)
                            ? 256
                            : (unsigned)s.w[0];
          f.push(sar(x, sh));
          f.pc += 1;
          break;
        }
        case 0x20: {  // SHA3
          U256 off_w = f.pop(), size_w = f.pop();
          unsigned __int128 words =
              ((unsigned __int128)sat_u64(size_w) + 31) / 32;
          f.charge((unsigned __int128)f.fee(F_sha3) +
                   (unsigned __int128)f.fee(F_sha3word) * words +
                   f.mem_gas(off_w, size_w));
          uint64_t off = sat_u64(off_w), size = sat_u64(size_w);
          f.mem.expand(off, size);
          uint8_t h[32];
          khipu_keccak(136, size ? f.mem.data.data() + off : nullptr, size, h,
                       32);
          f.push(from_be(h, 32));
          f.pc += 1;
          break;
        }
        case 0x30:  // ADDRESS
          f.charge(f.fee(F_base));
          f.push(addr_to_word(f.owner));
          f.pc += 1;
          break;
        case 0x31: {  // BALANCE
          Addr a = to_addr(f.pop());
          f.charge(f.fee(F_balance));
          f.push(r_balance(tx, a));
          f.pc += 1;
          break;
        }
        case 0x32:  // ORIGIN
          f.charge(f.fee(F_base));
          f.push(addr_to_word(f.origin));
          f.pc += 1;
          break;
        case 0x33:  // CALLER
          f.charge(f.fee(F_base));
          f.push(addr_to_word(f.caller));
          f.pc += 1;
          break;
        case 0x34:  // CALLVALUE
          f.charge(f.fee(F_base));
          f.push(f.value);
          f.pc += 1;
          break;
        case 0x35: {  // CALLDATALOAD
          U256 off_w = f.pop();
          f.charge(f.fee(F_verylow));
          uint64_t off = sat_u64(off_w);
          if (off >= f.input_len) {
            f.push(U256{});
          } else {
            uint8_t buf[32] = {0};
            uint64_t n = f.input_len - off;
            if (n > 32) n = 32;
            std::memcpy(buf, f.input + off, n);
            f.push(from_be(buf, 32));
          }
          f.pc += 1;
          break;
        }
        case 0x36:  // CALLDATASIZE
          f.charge(f.fee(F_base));
          f.push(from_u64(f.input_len));
          f.pc += 1;
          break;
        case 0x37: {  // CALLDATACOPY
          U256 dst_w = f.pop(), src_w = f.pop(), size_w = f.pop();
          unsigned __int128 words =
              ((unsigned __int128)sat_u64(size_w) + 31) / 32;
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   (unsigned __int128)f.fee(F_copy) * words +
                   f.mem_gas(dst_w, size_w));
          uint64_t dst = sat_u64(dst_w), src = sat_u64(src_w),
                   size = sat_u64(size_w);
          f.mem.expand(dst, size);
          // avail guards src+i wraparound at src near 2^64 (zero-pad
          // region, matching vm.py _zero_slice)
          uint64_t avail = src < f.input_len ? f.input_len - src : 0;
          for (uint64_t i = 0; i < size; ++i)
            f.mem.data[dst + i] = (i < avail) ? f.input[src + i] : 0;
          f.pc += 1;
          break;
        }
        case 0x38:  // CODESIZE
          f.charge(f.fee(F_base));
          f.push(from_u64(f.code_len));
          f.pc += 1;
          break;
        case 0x39: {  // CODECOPY
          U256 dst_w = f.pop(), src_w = f.pop(), size_w = f.pop();
          unsigned __int128 words =
              ((unsigned __int128)sat_u64(size_w) + 31) / 32;
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   (unsigned __int128)f.fee(F_copy) * words +
                   f.mem_gas(dst_w, size_w));
          uint64_t dst = sat_u64(dst_w), src = sat_u64(src_w),
                   size = sat_u64(size_w);
          f.mem.expand(dst, size);
          uint64_t avail = src < f.code_len ? f.code_len - src : 0;
          for (uint64_t i = 0; i < size; ++i)
            f.mem.data[dst + i] = (i < avail) ? f.code[src + i] : 0;
          f.pc += 1;
          break;
        }
        case 0x3A:  // GASPRICE
          f.charge(f.fee(F_base));
          f.push(f.gas_price);
          f.pc += 1;
          break;
        case 0x3B: {  // EXTCODESIZE
          Addr a = to_addr(f.pop());
          f.charge(f.fee(F_extcode));
          const uint8_t* p = nullptr;
          uint64_t n = 0;
          r_code(tx, a, &p, &n);
          f.push(from_u64(n));
          f.pc += 1;
          break;
        }
        case 0x3C: {  // EXTCODECOPY
          Addr a = to_addr(f.pop());
          U256 dst_w = f.pop(), src_w = f.pop(), size_w = f.pop();
          unsigned __int128 words =
              ((unsigned __int128)sat_u64(size_w) + 31) / 32;
          f.charge((unsigned __int128)f.fee(F_extcode) +
                   (unsigned __int128)f.fee(F_copy) * words +
                   f.mem_gas(dst_w, size_w));
          uint64_t dst = sat_u64(dst_w), src = sat_u64(src_w),
                   size = sat_u64(size_w);
          f.mem.expand(dst, size);
          const uint8_t* p = nullptr;
          uint64_t n = 0;
          r_code(tx, a, &p, &n);
          uint64_t avail = src < n ? n - src : 0;
          for (uint64_t i = 0; i < size; ++i)
            f.mem.data[dst + i] = (i < avail) ? p[src + i] : 0;
          f.pc += 1;
          break;
        }
        case 0x3D:  // RETURNDATASIZE
          if (!tx.flag(C_byzantium)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_base));
          f.push(from_u64(f.returndata.size()));
          f.pc += 1;
          break;
        case 0x3E: {  // RETURNDATACOPY
          if (!tx.flag(C_byzantium)) throw VmError(E_INVALID_OP);
          U256 dst_w = f.pop(), src_w = f.pop(), size_w = f.pop();
          unsigned __int128 words =
              ((unsigned __int128)sat_u64(size_w) + 31) / 32;
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   (unsigned __int128)f.fee(F_copy) * words +
                   f.mem_gas(dst_w, size_w));
          uint64_t dst = sat_u64(dst_w), src = sat_u64(src_w),
                   size = sat_u64(size_w);
          if ((unsigned __int128)src + size > f.returndata.size())
            throw VmError(E_RETURNDATA);
          f.mem.store(dst, f.returndata.data() + src, size);
          f.pc += 1;
          break;
        }
        case 0x3F: {  // EXTCODEHASH
          if (!tx.flag(C_constantinople)) throw VmError(E_INVALID_OP);
          Addr a = to_addr(f.pop());
          f.charge(f.fee(F_extcodehash));
          if (r_dead(tx, a)) {
            f.push(U256{});
          } else {
            uint8_t h[32];
            r_code_hash(tx, a, h);
            f.push(from_be(h, 32));
          }
          f.pc += 1;
          break;
        }
        case 0x40: {  // BLOCKHASH
          U256 n_w = f.pop();
          f.charge(f.fee(F_blockhash));
          uint64_t cur = tx.blk.number;
          uint64_t n = sat_u64(n_w);
          bool in_range = !(n_w.w[1] | n_w.w[2] | n_w.w[3]) &&
                          cur >= 1 && n < cur &&
                          n + 256 >= cur;
          if (in_range) {
            uint8_t h[32];
            if (tx.cb.blockhash(tx.cb.h, n, h))
              f.push(from_be(h, 32));
            else
              f.push(U256{});
          } else {
            f.push(U256{});
          }
          f.pc += 1;
          break;
        }
        case 0x41:  // COINBASE
          f.charge(f.fee(F_base));
          f.push(addr_to_word(tx.blk.beneficiary));
          f.pc += 1;
          break;
        case 0x42:  // TIMESTAMP
          f.charge(f.fee(F_base));
          f.push(from_u64(tx.blk.timestamp));
          f.pc += 1;
          break;
        case 0x43:  // NUMBER
          f.charge(f.fee(F_base));
          f.push(from_u64(tx.blk.number));
          f.pc += 1;
          break;
        case 0x44:  // DIFFICULTY
          f.charge(f.fee(F_base));
          f.push(tx.blk.difficulty);
          f.pc += 1;
          break;
        case 0x45:  // GASLIMIT
          f.charge(f.fee(F_base));
          f.push(from_u64(tx.blk.gas_limit));
          f.pc += 1;
          break;
        case 0x46:  // CHAINID (Istanbul)
          if (!tx.flag(C_istanbul)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_base));
          f.push(from_u64(tx.cfg[C_chain_id]));
          f.pc += 1;
          break;
        case 0x47:  // SELFBALANCE (Istanbul)
          if (!tx.flag(C_istanbul)) throw VmError(E_INVALID_OP);
          f.charge(f.fee(F_low));
          f.push(r_balance(tx, f.owner));
          f.pc += 1;
          break;
        case 0x50:  // POP
          f.charge(f.fee(F_base));
          f.pop();
          f.pc += 1;
          break;
        case 0x51: {  // MLOAD
          U256 off_w = f.pop();
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   f.mem_gas(off_w, from_u64(32)));
          uint64_t off = sat_u64(off_w);
          f.mem.expand(off, 32);
          f.push(from_be(f.mem.data.data() + off, 32));
          f.pc += 1;
          break;
        }
        case 0x52: {  // MSTORE
          U256 off_w = f.pop(), val = f.pop();
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   f.mem_gas(off_w, from_u64(32)));
          uint64_t off = sat_u64(off_w);
          f.mem.expand(off, 32);
          to_be32(val, f.mem.data.data() + off);
          f.pc += 1;
          break;
        }
        case 0x53: {  // MSTORE8
          U256 off_w = f.pop(), val = f.pop();
          f.charge((unsigned __int128)f.fee(F_verylow) +
                   f.mem_gas(off_w, from_u64(1)));
          uint64_t off = sat_u64(off_w);
          f.mem.expand(off, 1);
          f.mem.data[off] = (uint8_t)(val.w[0] & 0xFF);
          f.pc += 1;
          break;
        }
        case 0x54: {  // SLOAD
          U256 key = f.pop();
          f.charge(f.fee(F_sload));
          f.push(r_storage(tx, f.owner, key));
          f.pc += 1;
          break;
        }
        case 0x55:  // SSTORE
          op_sstore(f);
          break;
        case 0x56: {  // JUMP
          U256 dest_w = f.pop();
          f.charge(f.fee(F_mid));
          uint64_t dest = sat_u64(dest_w);
          if ((dest_w.w[1] | dest_w.w[2] | dest_w.w[3]) ||
              !f.valid_jumpdest(dest))
            throw VmError(E_INVALID_JUMP);
          f.pc = dest;
          break;
        }
        case 0x57: {  // JUMPI
          U256 dest_w = f.pop(), cond = f.pop();
          f.charge(f.fee(F_high));
          if (!is_zero(cond)) {
            uint64_t dest = sat_u64(dest_w);
            if ((dest_w.w[1] | dest_w.w[2] | dest_w.w[3]) ||
                !f.valid_jumpdest(dest))
              throw VmError(E_INVALID_JUMP);
            f.pc = dest;
          } else {
            f.pc += 1;
          }
          break;
        }
        case 0x58:  // PC
          f.charge(f.fee(F_base));
          f.push(from_u64(f.pc));
          f.pc += 1;
          break;
        case 0x59:  // MSIZE
          f.charge(f.fee(F_base));
          f.push(from_u64(f.mem.active_words * 32));
          f.pc += 1;
          break;
        case 0x5A:  // GAS
          f.charge(f.fee(F_base));
          f.push(from_u64((uint64_t)f.gas));
          f.pc += 1;
          break;
        case 0x5B:  // JUMPDEST
          f.charge(f.fee(F_jumpdest));
          f.pc += 1;
          break;
        case 0xF0:  // CREATE
          op_create_family(f, false);
          break;
        case 0xF1:  // CALL
          op_call_family(f, K_CALL);
          break;
        case 0xF2:  // CALLCODE
          op_call_family(f, K_CALLCODE);
          break;
        case 0xF3: {  // RETURN
          U256 off_w = f.pop(), size_w = f.pop();
          f.charge((unsigned __int128)f.fee(F_zero) +
                   f.mem_gas(off_w, size_w));
          uint64_t off = sat_u64(off_w), size = sat_u64(size_w);
          f.mem.load(off, size, f.output);
          f.halted = true;
          f.pc += 1;
          break;
        }
        case 0xF4:  // DELEGATECALL (Homestead)
          if (!tx.flag(C_homestead)) throw VmError(E_INVALID_OP);
          op_call_family(f, K_DELEGATE);
          break;
        case 0xF5:  // CREATE2 (Constantinople)
          if (!tx.flag(C_constantinople)) throw VmError(E_INVALID_OP);
          op_create_family(f, true);
          break;
        case 0xFA:  // STATICCALL (Byzantium)
          if (!tx.flag(C_byzantium)) throw VmError(E_INVALID_OP);
          op_call_family(f, K_STATIC);
          break;
        case 0xFD: {  // REVERT (Byzantium)
          if (!tx.flag(C_byzantium)) throw VmError(E_INVALID_OP);
          U256 off_w = f.pop(), size_w = f.pop();
          f.charge((unsigned __int128)f.fee(F_zero) +
                   f.mem_gas(off_w, size_w));
          uint64_t off = sat_u64(off_w), size = sat_u64(size_w);
          f.mem.load(off, size, f.output);
          f.halted = true;
          f.reverted = true;
          f.pc += 1;
          break;
        }
        case 0xFE:  // INVALID
          throw VmError(E_INVALID_OP);
        case 0xFF:  // SELFDESTRUCT
          op_selfdestruct(f);
          break;
        default: {
          if (op >= 0x60 && op <= 0x7F) {  // PUSH1..PUSH32
            f.charge(f.fee(F_verylow));
            unsigned n = op - 0x60 + 1;
            uint8_t buf[32] = {0};
            for (unsigned i = 0; i < n; ++i) {
              uint64_t p = f.pc + 1 + i;
              buf[32 - n + i] = p < code_len ? code[p] : 0;
            }
            f.push(from_be(buf, 32));
            f.pc += 1 + n;
          } else if (op >= 0x80 && op <= 0x8F) {  // DUP1..DUP16
            f.charge(f.fee(F_verylow));
            unsigned i = op - 0x80 + 1;
            if (f.stack.size() < i) throw VmError(E_STACK_UNDER);
            if (f.stack.size() >= 1024) throw VmError(E_STACK_OVER);
            f.stack.push_back(f.stack[f.stack.size() - i]);
            f.pc += 1;
          } else if (op >= 0x90 && op <= 0x9F) {  // SWAP1..SWAP16
            f.charge(f.fee(F_verylow));
            unsigned i = op - 0x90 + 1;
            if (f.stack.size() < i + 1) throw VmError(E_STACK_UNDER);
            std::swap(f.stack[f.stack.size() - 1],
                      f.stack[f.stack.size() - 1 - i]);
            f.pc += 1;
          } else if (op >= 0xA0 && op <= 0xA4) {  // LOG0..LOG4
            if (f.is_static) throw VmError(E_STATIC);
            int ntopics = op - 0xA0;
            U256 off_w = f.pop(), size_w = f.pop();
            U256 topics[4];
            for (int i = 0; i < ntopics; ++i) topics[i] = f.pop();
            f.charge((unsigned __int128)f.fee(F_log) +
                     (unsigned __int128)f.fee(F_logtopic) * ntopics +
                     (unsigned __int128)f.fee(F_logdata) * sat_u64(size_w) +
                     f.mem_gas(off_w, size_w));
            uint64_t off = sat_u64(off_w), size = sat_u64(size_w);
            f.mem.expand(off, size);
            w_log(tx, f.owner, topics, ntopics,
                  size ? f.mem.data.data() + off : nullptr, size);
            f.pc += 1;
          } else {
            throw VmError(E_INVALID_OP);
          }
          break;
        }
      }
    }
  } catch (const VmError& e) {
    out.status = e.code;
    out.gas_remaining = 0;
    return out;
  }
  out.status = f.reverted ? REVERT : OK;
  out.gas_remaining = f.gas;
  out.refund = f.refund;
  out.output = std::move(f.output);
  return out;
}

// precompile-or-bytecode dispatch (vm.py _execute_message)
static RunResult execute_message(TxCtx& tx, const MsgEnv& env,
                                 const uint8_t* code, uint64_t code_len,
                                 int64_t gas, const Addr& code_addr) {
  uint32_t pid = precompile_id(tx, code_addr);
  if (pid != 0) {
    const uint8_t* out = nullptr;
    uint64_t outlen = 0, gas_left = 0;
    int status = tx.cb.precompile(tx.cb.h, pid, env.input, env.input_len,
                                  (uint64_t)gas, &out, &outlen, &gas_left);
    RunResult r;
    if (status == 0) {
      r.status = OK;
      r.gas_remaining = (int64_t)gas_left;
      r.output.assign(out, out + outlen);
    } else if (status == 1) {
      r.status = E_PRECOMPILE_OOG;
    } else {
      r.status = E_PRECOMPILE;
    }
    return r;
  }
  if (code_len == 0) {
    RunResult r;
    r.status = OK;
    r.gas_remaining = gas;
    return r;
  }
  return run_frame(tx, env, code, code_len, gas);
}

// shared CREATE/CREATE2/tx-creation body (vm.py create_contract)
static RunResult create_contract(TxCtx& tx, const Addr& caller,
                                 const Addr& origin, const Addr& new_addr,
                                 int64_t gas, const U256& gas_price,
                                 const U256& value, const uint8_t* init_code,
                                 uint64_t init_len, uint32_t depth) {
  FrameState saved = tx.frame;
  size_t oplog_mark = tx.oplog.size();

  // EIP-684 collision: existing nonce or code at the target
  const BaseAcct* base = nullptr;
  bool exists;
  uint64_t cur_nonce = 0;
  bool code_hash_empty = true;
  {
    auto it = tx.frame.accts.find(new_addr);
    if (it != tx.frame.accts.end() && it->second.has_abs) {
      exists = true;
      cur_nonce = it->second.abs_nonce + it->second.nonce_delta;
      const auto* c = overlay_code(tx, new_addr);
      code_hash_empty = !c || c->empty();
    } else if (it != tx.frame.accts.end() && it->second.any_delta() &&
               !sign_bit(it->second.bal_delta)) {
      // delta-materialized account: nonce delta only, no code
      base = &base_account(tx, new_addr);
      exists = true;
      cur_nonce = (base->exists ? base->nonce : tx.cfg[C_start_nonce]) +
                  it->second.nonce_delta;
      code_hash_empty = std::memcmp(base->exists ? base->code_hash.data()
                                                 : EMPTY_HASH,
                                    EMPTY_HASH, 32) == 0;
    } else {
      base = &base_account(tx, new_addr);  // records ON_ACCOUNT read
      exists = base->exists;
      cur_nonce = base->nonce;
      code_hash_empty =
          std::memcmp(base->code_hash.data(), EMPTY_HASH, 32) == 0;
      if (it != tx.frame.accts.end())
        cur_nonce += it->second.nonce_delta;
    }
  }
  if (exists &&
      (cur_nonce != tx.cfg[C_start_nonce] || !code_hash_empty)) {
    RunResult r;
    r.status = E_COLLISION;
    return r;
  }

  U256 prior_balance = r_balance(tx, new_addr);
  w_create_account(tx, new_addr, tx.cfg[C_contract_start_nonce],
                   prior_balance);
  w_transfer(tx, caller, new_addr, value);

  MsgEnv env;
  env.owner = new_addr;
  env.caller = caller;
  env.origin = origin;
  env.gas_price = gas_price;
  env.value = value;
  env.input = nullptr;
  env.input_len = 0;
  env.depth = depth;
  env.is_static = false;

  RunResult r = run_frame(tx, env, init_code, init_len, gas);
  if (r.status != OK) {
    tx.frame = std::move(saved);
    tx.oplog.resize(oplog_mark);
    return r;
  }
  uint64_t code_size = r.output.size();
  if (tx.flag(C_eip170) && code_size > tx.cfg[C_max_code_size]) {
    tx.frame = std::move(saved);
    tx.oplog.resize(oplog_mark);
    RunResult e;
    e.status = E_CODE_SIZE;
    return e;
  }
  int64_t deposit = (int64_t)(code_size * tx.fee(F_codedeposit));
  if (r.gas_remaining >= deposit) {
    r.gas_remaining -= deposit;
    w_save_code(tx, new_addr, r.output.data(), code_size);
  } else if (tx.flag(C_homestead)) {  // fail_on_create_deposit_oog
    tx.frame = std::move(saved);
    tx.oplog.resize(oplog_mark);
    RunResult e;
    e.status = E_DEPOSIT_OOG;
    return e;
  } else {
    w_save_code(tx, new_addr, nullptr, 0);  // Frontier: keep empty
  }
  return r;
}

}  // namespace evm

// ================================================================ C API

extern "C" {

struct EvmResultC {
  int32_t status;  // evm::Err
  int32_t _pad;
  uint64_t gas_remaining;
  int64_t refund;
  const uint8_t* output;
  uint64_t output_len;
  const uint8_t* oplog;
  uint64_t oplog_len;
  void* owner_;
};

struct ResultHolder {
  EvmResultC pub;
  std::vector<uint8_t> output;
  std::vector<uint8_t> oplog;
};

static EvmResultC* finish(evm::TxCtx& tx, evm::RunResult& r) {
  auto* h = new ResultHolder();
  h->output = std::move(r.output);
  h->oplog = std::move(tx.oplog);
  h->pub.status = r.status;
  h->pub.gas_remaining = (uint64_t)(r.gas_remaining > 0 ? r.gas_remaining : 0);
  h->pub.refund = r.refund;
  h->pub.output = h->output.data();
  h->pub.output_len = h->output.size();
  h->pub.oplog = h->oplog.data();
  h->pub.oplog_len = h->oplog.size();
  h->pub.owner_ = h;
  return &h->pub;
}

static void unpack(evm::TxCtx& tx, const uint64_t* cfg, void** cbs,
                   void* handle, const uint64_t* blk_nums,
                   const uint8_t* blk_bytes) {
  tx.cfg = cfg;
  tx.cb.h = handle;
  tx.cb.exists = (evm::cb_exists_t)cbs[0];
  tx.cb.is_dead = (evm::cb_is_dead_t)cbs[1];
  tx.cb.get_account = (evm::cb_get_account_t)cbs[2];
  tx.cb.get_code_hash = (evm::cb_get_code_hash_t)cbs[3];
  tx.cb.get_code = (evm::cb_get_code_t)cbs[4];
  tx.cb.get_storage = (evm::cb_get_storage_t)cbs[5];
  tx.cb.get_original = (evm::cb_get_storage_t)cbs[6];
  tx.cb.blockhash = (evm::cb_blockhash_t)cbs[7];
  tx.cb.precompile = (evm::cb_precompile_t)cbs[8];
  tx.blk.number = blk_nums[0];
  tx.blk.timestamp = blk_nums[1];
  tx.blk.gas_limit = blk_nums[2];
  tx.blk.difficulty = evm::from_be(blk_bytes, 32);
  std::memcpy(tx.blk.beneficiary.data(), blk_bytes + 32, 20);
}

EvmResultC* khipu_evm_call(const uint64_t* cfg, void** cbs, void* handle,
                           const uint64_t* blk_nums, const uint8_t* blk_bytes,
                           const uint8_t* owner, const uint8_t* caller,
                           const uint8_t* origin, const uint8_t* gas_price32,
                           const uint8_t* value32, const uint8_t* input,
                           uint64_t input_len, uint32_t depth,
                           uint32_t is_static, const uint8_t* code,
                           uint64_t code_len, const uint8_t* code_addr,
                           uint64_t gas, uint32_t pre_transfer) {
  evm::TxCtx tx;
  unpack(tx, cfg, cbs, handle, blk_nums, blk_bytes);
  evm::MsgEnv env;
  std::memcpy(env.owner.data(), owner, 20);
  std::memcpy(env.caller.data(), caller, 20);
  std::memcpy(env.origin.data(), origin, 20);
  env.gas_price = evm::from_be(gas_price32, 32);
  env.value = evm::from_be(value32, 32);
  env.input = input;
  env.input_len = input_len;
  env.depth = depth;
  env.is_static = is_static != 0;
  evm::Addr caddr;
  std::memcpy(caddr.data(), code_addr, 20);
  if (pre_transfer) {
    // the tx-level value transfer execute_transaction applies to the
    // child world before _execute_message (ledger.py:179-181); emitting
    // it here makes it roll back with the frame on error/revert
    evm::w_transfer(tx, env.caller, env.owner, env.value);
    evm::w_touch(tx, env.owner);
  }
  evm::RunResult r =
      evm::execute_message(tx, env, code, code_len, (int64_t)gas, caddr);
  if (r.status != evm::OK) tx.oplog.clear();
  return finish(tx, r);
}

EvmResultC* khipu_evm_create(const uint64_t* cfg, void** cbs, void* handle,
                             const uint64_t* blk_nums,
                             const uint8_t* blk_bytes, const uint8_t* caller,
                             const uint8_t* origin, const uint8_t* new_addr,
                             const uint8_t* gas_price32,
                             const uint8_t* value32, const uint8_t* init_code,
                             uint64_t code_len, uint32_t depth, uint64_t gas) {
  evm::TxCtx tx;
  unpack(tx, cfg, cbs, handle, blk_nums, blk_bytes);
  evm::Addr c, o, na;
  std::memcpy(c.data(), caller, 20);
  std::memcpy(o.data(), origin, 20);
  std::memcpy(na.data(), new_addr, 20);
  evm::RunResult r = evm::create_contract(
      tx, c, o, na, (int64_t)gas, evm::from_be(gas_price32, 32),
      evm::from_be(value32, 32), init_code, code_len, depth);
  if (r.status != evm::OK) tx.oplog.clear();
  return finish(tx, r);
}

void khipu_evm_free(EvmResultC* r) {
  if (r) delete (ResultHolder*)r->owner_;
}

// test hook: raw u256 arithmetic, differential-tested from Python
// op: 0 add 1 sub 2 mul 3 div 4 mod 5 sdiv 6 smod 7 exp 8 addmod
//     9 mulmod 10 signextend 11 byte 12 shl 13 shr 14 sar
void khipu_evm_test_arith(int op, const uint8_t* a32, const uint8_t* b32,
                          const uint8_t* c32, uint8_t* out32) {
  using namespace evm;
  U256 a = from_be(a32, 32), b = from_be(b32, 32), c = from_be(c32, 32);
  U256 r, q, rem;
  switch (op) {
    case 0: r = add(a, b); break;
    case 1: r = sub(a, b); break;
    case 2: r = mul(a, b); break;
    case 3: udivmod(a, b, q, rem); r = is_zero(b) ? U256{} : q; break;
    case 4: udivmod(a, b, q, rem); r = is_zero(b) ? U256{} : rem; break;
    case 5: r = sdiv(a, b); break;
    case 6: r = smod(a, b); break;
    case 7: r = uexp(a, b); break;
    case 8: {
      if (is_zero(c)) { r = U256{}; break; }
      uint64_t wide[8] = {0};
      unsigned __int128 cc = 0;
      for (int i = 0; i < 4; ++i) {
        cc += (unsigned __int128)a.w[i] + b.w[i];
        wide[i] = (uint64_t)cc;
        cc >>= 64;
      }
      wide[4] = (uint64_t)cc;
      r = mod512(wide, c);
      break;
    }
    case 9: {
      if (is_zero(c)) { r = U256{}; break; }
      uint64_t wide[8];
      mul_full(a, b, wide);
      r = mod512(wide, c);
      break;
    }
    case 10: r = signextend(a, b); break;
    case 11: r = byte_at(a, b); break;
    case 12: r = (a.w[1] | a.w[2] | a.w[3] || a.w[0] >= 256) ? U256{} : shl(b, (unsigned)a.w[0]); break;
    case 13: r = (a.w[1] | a.w[2] | a.w[3] || a.w[0] >= 256) ? U256{} : shr(b, (unsigned)a.w[0]); break;
    case 14: r = sar(b, (a.w[1] | a.w[2] | a.w[3] || a.w[0] >= 256) ? 256 : (unsigned)a.w[0]); break;
    default: break;
  }
  to_be32(r, out32);
}

}  // extern "C"
