// secp256k1 curve arithmetic (mod-p field + Jacobian points) in C++.
//
// Role parity: the reference leans on spongycastle's native-speed ECDSA
// (crypto/ECDSASignature.scala:115 recover with cached Q precompute).
// Python keeps the protocol layer (RFC 6979, recid bookkeeping, mod-n
// scalar algebra — a handful of big-int ops per signature); this file
// supplies the hot part: double-scalar multiplication k1*A + k2*B over
// the curve, which dominates recover/verify/ECDH at ~4k field
// multiplications each.
//
// Field: p = 2^256 - 2^32 - 977. 4x64-bit limbs, little-endian;
// products reduce via the special form (fold high limbs times
// 2^32 + 977 into the low half).
//
// C ABI (ctypes, khipu_tpu/native/secp.py):
//   khipu_ec_mul_add(ax, ay, k1, bx, by, k2, outx, outy) -> int
//     computes k1*A + k2*B; a null ax means A = G (same for bx).
//     k = NULL or zero skips that term. Returns 0 on success, 1 if the
//     result is the point at infinity.

#include <cstdint>
#include <cstring>

namespace {

typedef unsigned __int128 u128;

struct Fe {
  uint64_t v[4];
};

constexpr Fe P = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                   0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
// 2^256 mod p = 2^32 + 977
constexpr uint64_t kFold = 0x1000003D1ULL;

constexpr Fe GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                    0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
constexpr Fe GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                    0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

inline bool fe_is_zero(const Fe& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int fe_cmp(const Fe& a, const Fe& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] < b.v[i]) return -1;
    if (a.v[i] > b.v[i]) return 1;
  }
  return 0;
}

inline void fe_sub_p_if_ge(Fe& a) {
  if (fe_cmp(a, P) >= 0) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
      u128 d = (u128)a.v[i] - P.v[i] - (uint64_t)borrow;
      a.v[i] = (uint64_t)d;
      borrow = (d >> 64) ? 1 : 0;
    }
  }
}

inline void fe_add(Fe& r, const Fe& a, const Fe& b) {
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + (uint64_t)carry;
    r.v[i] = (uint64_t)s;
    carry = s >> 64;
  }
  if (carry) {  // fold 2^256 -> kFold
    u128 s = (u128)r.v[0] + kFold;
    r.v[0] = (uint64_t)s;
    u128 c = s >> 64;
    for (int i = 1; c && i < 4; ++i) {
      s = (u128)r.v[i] + (uint64_t)c;
      r.v[i] = (uint64_t)s;
      c = s >> 64;
    }
  }
  fe_sub_p_if_ge(r);
}

inline void fe_sub(Fe& r, const Fe& a, const Fe& b) {
  u128 borrow = 0;
  Fe t;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - (uint64_t)borrow;
    t.v[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // add p back
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)t.v[i] + P.v[i] + (uint64_t)carry;
      t.v[i] = (uint64_t)s;
      carry = s >> 64;
    }
  }
  r = t;
}

// full 256x256 -> 512 multiply, then fold twice
void fe_mul(Fe& r, const Fe& a, const Fe& b) {
  uint64_t w[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + w[i + j] + (uint64_t)carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
  // fold high half: r = low + high * kFold (kFold < 2^33 so the
  // product of a 256-bit high by kFold is < 2^290; do it limbwise)
  uint64_t low[5] = {w[0], w[1], w[2], w[3], 0};
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)w[4 + i] * kFold + low[i] + (uint64_t)carry;
    low[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  low[4] = (uint64_t)carry;
  // second fold of the (small) overflow limb
  u128 cur = (u128)low[4] * kFold + low[0];
  Fe t;
  t.v[0] = (uint64_t)cur;
  u128 c = cur >> 64;
  for (int i = 1; i < 4; ++i) {
    u128 s = (u128)low[i] + (uint64_t)c;
    t.v[i] = (uint64_t)s;
    c = s >> 64;
  }
  if (c) {  // one more tiny fold
    u128 s = (u128)t.v[0] + kFold;
    t.v[0] = (uint64_t)s;
    u128 c2 = s >> 64;
    for (int i = 1; c2 && i < 4; ++i) {
      s = (u128)t.v[i] + (uint64_t)c2;
      t.v[i] = (uint64_t)s;
      c2 = s >> 64;
    }
  }
  fe_sub_p_if_ge(t);
  r = t;
}

inline void fe_sqr(Fe& r, const Fe& a) { fe_mul(r, a, a); }

void fe_pow(Fe& r, const Fe& base, const Fe& exp) {
  Fe result = {{1, 0, 0, 0}};
  Fe b = base;
  for (int limb = 0; limb < 4; ++limb) {
    uint64_t e = exp.v[limb];
    for (int bit = 0; bit < 64; ++bit) {
      if (e & 1) fe_mul(result, result, b);
      e >>= 1;
      fe_sqr(b, b);
    }
  }
  r = result;
}

void fe_inv(Fe& r, const Fe& a) {
  Fe p2 = P;
  // p - 2
  p2.v[0] -= 2;  // no borrow: low limb ends ...FC2F
  fe_pow(r, a, p2);
}

// Jacobian point; inf encoded as z == 0
struct Pt {
  Fe x, y, z;
};

inline bool pt_is_inf(const Pt& p) { return fe_is_zero(p.z); }

void pt_double(Pt& r, const Pt& p) {
  if (pt_is_inf(p) || fe_is_zero(p.y)) {
    r = {{{0}}, {{0}}, {{0}}};
    return;
  }
  Fe ysq, s, m, t;
  fe_sqr(ysq, p.y);
  fe_mul(s, p.x, ysq);
  Fe four = {{4, 0, 0, 0}};
  fe_mul(s, s, four);
  fe_sqr(m, p.x);
  Fe three = {{3, 0, 0, 0}};
  fe_mul(m, m, three);
  Fe x2, two = {{2, 0, 0, 0}};
  fe_sqr(x2, m);
  fe_mul(t, s, two);
  fe_sub(x2, x2, t);
  Fe y2, ysq2, eight = {{8, 0, 0, 0}};
  fe_sub(t, s, x2);
  fe_mul(y2, m, t);
  fe_sqr(ysq2, ysq);
  fe_mul(ysq2, ysq2, eight);
  fe_sub(y2, y2, ysq2);
  Fe z2;
  fe_mul(z2, p.y, p.z);
  fe_mul(z2, z2, two);
  r.x = x2;
  r.y = y2;
  r.z = z2;
}

void pt_add(Pt& r, const Pt& p, const Pt& q) {
  if (pt_is_inf(p)) { r = q; return; }
  if (pt_is_inf(q)) { r = p; return; }
  Fe z1z1, z2z2, u1, u2, s1, s2;
  fe_sqr(z1z1, p.z);
  fe_sqr(z2z2, q.z);
  fe_mul(u1, p.x, z2z2);
  fe_mul(u2, q.x, z1z1);
  Fe t;
  fe_mul(t, q.z, z2z2);
  fe_mul(s1, p.y, t);
  fe_mul(t, p.z, z1z1);
  fe_mul(s2, q.y, t);
  if (fe_cmp(u1, u2) == 0) {
    if (fe_cmp(s1, s2) != 0) {
      r = {{{0}}, {{0}}, {{0}}};
      return;
    }
    pt_double(r, p);
    return;
  }
  Fe h, rr, hh, hhh, v;
  fe_sub(h, u2, u1);
  fe_sub(rr, s2, s1);
  fe_sqr(hh, h);
  fe_mul(hhh, h, hh);
  fe_mul(v, u1, hh);
  Fe x3, two = {{2, 0, 0, 0}};
  fe_sqr(x3, rr);
  fe_sub(x3, x3, hhh);
  fe_mul(t, v, two);
  fe_sub(x3, x3, t);
  Fe y3;
  fe_sub(t, v, x3);
  fe_mul(y3, rr, t);
  Fe s1hhh;
  fe_mul(s1hhh, s1, hhh);
  fe_sub(y3, y3, s1hhh);
  Fe z3;
  fe_mul(z3, p.z, q.z);
  fe_mul(z3, z3, h);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

void pt_mul(Pt& r, const Pt& p, const Fe& k) {
  Pt acc = {{{0}}, {{0}}, {{0}}};
  Pt add = p;
  for (int limb = 0; limb < 4; ++limb) {
    uint64_t e = k.v[limb];
    for (int bit = 0; bit < 64; ++bit) {
      if (e & 1) pt_add(acc, acc, add);
      e >>= 1;
      pt_double(add, add);
    }
  }
  r = acc;
}

void fe_from_be(Fe& r, const uint8_t* b) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; ++j) limb = (limb << 8) | b[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
}

void fe_to_be(uint8_t* b, const Fe& a) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = a.v[3 - i];
    for (int j = 7; j >= 0; --j) {
      b[i * 8 + j] = (uint8_t)limb;
      limb >>= 8;
    }
  }
}

}  // namespace

extern "C" {

// k1*A + k2*B in affine out coords. ax/bx NULL => that base is G.
// k1/k2 NULL or zero => term skipped. Returns 1 for infinity.
int khipu_ec_mul_add(const uint8_t* ax, const uint8_t* ay,
                     const uint8_t* k1, const uint8_t* bx,
                     const uint8_t* by, const uint8_t* k2,
                     uint8_t* outx, uint8_t* outy) {
  Pt acc = {{{0}}, {{0}}, {{0}}};
  const Fe one = {{1, 0, 0, 0}};
  if (k1) {
    Fe s;
    fe_from_be(s, k1);
    if (!fe_is_zero(s)) {
      Pt a;
      if (ax) {
        fe_from_be(a.x, ax);
        fe_from_be(a.y, ay);
      } else {
        a.x = GX;
        a.y = GY;
      }
      a.z = one;
      Pt t;
      pt_mul(t, a, s);
      pt_add(acc, acc, t);
    }
  }
  if (k2) {
    Fe s;
    fe_from_be(s, k2);
    if (!fe_is_zero(s)) {
      Pt b;
      if (bx) {
        fe_from_be(b.x, bx);
        fe_from_be(b.y, by);
      } else {
        b.x = GX;
        b.y = GY;
      }
      b.z = one;
      Pt t;
      pt_mul(t, b, s);
      pt_add(acc, acc, t);
    }
  }
  if (pt_is_inf(acc)) return 1;
  Fe zinv, zinv2, zinv3, x, y;
  fe_inv(zinv, acc.z);
  fe_sqr(zinv2, zinv);
  fe_mul(zinv3, zinv2, zinv);
  fe_mul(x, acc.x, zinv2);
  fe_mul(y, acc.y, zinv3);
  fe_to_be(outx, x);
  fe_to_be(outy, y);
  return 0;
}

}  // extern "C"
