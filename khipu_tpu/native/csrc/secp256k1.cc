// secp256k1 curve arithmetic (mod-p field + Jacobian points) in C++.
//
// Role parity: the reference leans on spongycastle's native-speed ECDSA
// (crypto/ECDSASignature.scala:115 recover with cached Q precompute).
// Python keeps the protocol layer (RFC 6979, recid bookkeeping, mod-n
// scalar algebra — a handful of big-int ops per signature); this file
// supplies the hot part: double-scalar multiplication k1*A + k2*B over
// the curve, which dominates recover/verify/ECDH at ~4k field
// multiplications each.
//
// Field: p = 2^256 - 2^32 - 977. 4x64-bit limbs, little-endian;
// products reduce via the special form (fold high limbs times
// 2^32 + 977 into the low half).
//
// C ABI (ctypes, khipu_tpu/native/secp.py):
//   khipu_ec_mul_add(ax, ay, k1, bx, by, k2, outx, outy) -> int
//     computes k1*A + k2*B; a null ax means A = G (same for bx).
//     k = NULL or zero skips that term. Returns 0 on success, 1 if the
//     result is the point at infinity.

#include <cstdint>
#include <cstring>

namespace {

typedef unsigned __int128 u128;

struct Fe {
  uint64_t v[4];
};

constexpr Fe P = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                   0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
// 2^256 mod p = 2^32 + 977
constexpr uint64_t kFold = 0x1000003D1ULL;

constexpr Fe GX = {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL,
                    0x55A06295CE870B07ULL, 0x79BE667EF9DCBBACULL}};
constexpr Fe GY = {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL,
                    0x5DA4FBFC0E1108A8ULL, 0x483ADA7726A3C465ULL}};

inline bool fe_is_zero(const Fe& a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int fe_cmp(const Fe& a, const Fe& b) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] < b.v[i]) return -1;
    if (a.v[i] > b.v[i]) return 1;
  }
  return 0;
}

inline void fe_sub_p_if_ge(Fe& a) {
  if (fe_cmp(a, P) >= 0) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
      u128 d = (u128)a.v[i] - P.v[i] - (uint64_t)borrow;
      a.v[i] = (uint64_t)d;
      borrow = (d >> 64) ? 1 : 0;
    }
  }
}

inline void fe_add(Fe& r, const Fe& a, const Fe& b) {
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 s = (u128)a.v[i] + b.v[i] + (uint64_t)carry;
    r.v[i] = (uint64_t)s;
    carry = s >> 64;
  }
  if (carry) {  // fold 2^256 -> kFold
    u128 s = (u128)r.v[0] + kFold;
    r.v[0] = (uint64_t)s;
    u128 c = s >> 64;
    for (int i = 1; c && i < 4; ++i) {
      s = (u128)r.v[i] + (uint64_t)c;
      r.v[i] = (uint64_t)s;
      c = s >> 64;
    }
  }
  fe_sub_p_if_ge(r);
}

inline void fe_sub(Fe& r, const Fe& a, const Fe& b) {
  u128 borrow = 0;
  Fe t;
  for (int i = 0; i < 4; ++i) {
    u128 d = (u128)a.v[i] - b.v[i] - (uint64_t)borrow;
    t.v[i] = (uint64_t)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // add p back
    u128 carry = 0;
    for (int i = 0; i < 4; ++i) {
      u128 s = (u128)t.v[i] + P.v[i] + (uint64_t)carry;
      t.v[i] = (uint64_t)s;
      carry = s >> 64;
    }
  }
  r = t;
}

// full 256x256 -> 512 multiply, then fold twice
void fe_mul(Fe& r, const Fe& a, const Fe& b) {
  uint64_t w[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + w[i + j] + (uint64_t)carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
  // fold high half: r = low + high * kFold (kFold < 2^33 so the
  // product of a 256-bit high by kFold is < 2^290; do it limbwise)
  uint64_t low[5] = {w[0], w[1], w[2], w[3], 0};
  u128 carry = 0;
  for (int i = 0; i < 4; ++i) {
    u128 cur = (u128)w[4 + i] * kFold + low[i] + (uint64_t)carry;
    low[i] = (uint64_t)cur;
    carry = cur >> 64;
  }
  low[4] = (uint64_t)carry;
  // second fold of the (small) overflow limb
  u128 cur = (u128)low[4] * kFold + low[0];
  Fe t;
  t.v[0] = (uint64_t)cur;
  u128 c = cur >> 64;
  for (int i = 1; i < 4; ++i) {
    u128 s = (u128)low[i] + (uint64_t)c;
    t.v[i] = (uint64_t)s;
    c = s >> 64;
  }
  if (c) {  // one more tiny fold
    u128 s = (u128)t.v[0] + kFold;
    t.v[0] = (uint64_t)s;
    u128 c2 = s >> 64;
    for (int i = 1; c2 && i < 4; ++i) {
      s = (u128)t.v[i] + (uint64_t)c2;
      t.v[i] = (uint64_t)s;
      c2 = s >> 64;
    }
  }
  fe_sub_p_if_ge(t);
  r = t;
}

inline void fe_sqr(Fe& r, const Fe& a) { fe_mul(r, a, a); }

void fe_pow(Fe& r, const Fe& base, const Fe& exp) {
  Fe result = {{1, 0, 0, 0}};
  Fe b = base;
  for (int limb = 0; limb < 4; ++limb) {
    uint64_t e = exp.v[limb];
    for (int bit = 0; bit < 64; ++bit) {
      if (e & 1) fe_mul(result, result, b);
      e >>= 1;
      fe_sqr(b, b);
    }
  }
  r = result;
}

void fe_inv(Fe& r, const Fe& a) {
  Fe p2 = P;
  // p - 2
  p2.v[0] -= 2;  // no borrow: low limb ends ...FC2F
  fe_pow(r, a, p2);
}

// Jacobian point; inf encoded as z == 0
struct Pt {
  Fe x, y, z;
};

inline bool pt_is_inf(const Pt& p) { return fe_is_zero(p.z); }

void pt_double(Pt& r, const Pt& p) {
  if (pt_is_inf(p) || fe_is_zero(p.y)) {
    r = {{{0}}, {{0}}, {{0}}};
    return;
  }
  Fe ysq, s, m, t;
  fe_sqr(ysq, p.y);
  fe_mul(s, p.x, ysq);
  Fe four = {{4, 0, 0, 0}};
  fe_mul(s, s, four);
  fe_sqr(m, p.x);
  Fe three = {{3, 0, 0, 0}};
  fe_mul(m, m, three);
  Fe x2, two = {{2, 0, 0, 0}};
  fe_sqr(x2, m);
  fe_mul(t, s, two);
  fe_sub(x2, x2, t);
  Fe y2, ysq2, eight = {{8, 0, 0, 0}};
  fe_sub(t, s, x2);
  fe_mul(y2, m, t);
  fe_sqr(ysq2, ysq);
  fe_mul(ysq2, ysq2, eight);
  fe_sub(y2, y2, ysq2);
  Fe z2;
  fe_mul(z2, p.y, p.z);
  fe_mul(z2, z2, two);
  r.x = x2;
  r.y = y2;
  r.z = z2;
}

void pt_add(Pt& r, const Pt& p, const Pt& q) {
  if (pt_is_inf(p)) { r = q; return; }
  if (pt_is_inf(q)) { r = p; return; }
  Fe z1z1, z2z2, u1, u2, s1, s2;
  fe_sqr(z1z1, p.z);
  fe_sqr(z2z2, q.z);
  fe_mul(u1, p.x, z2z2);
  fe_mul(u2, q.x, z1z1);
  Fe t;
  fe_mul(t, q.z, z2z2);
  fe_mul(s1, p.y, t);
  fe_mul(t, p.z, z1z1);
  fe_mul(s2, q.y, t);
  if (fe_cmp(u1, u2) == 0) {
    if (fe_cmp(s1, s2) != 0) {
      r = {{{0}}, {{0}}, {{0}}};
      return;
    }
    pt_double(r, p);
    return;
  }
  Fe h, rr, hh, hhh, v;
  fe_sub(h, u2, u1);
  fe_sub(rr, s2, s1);
  fe_sqr(hh, h);
  fe_mul(hhh, h, hh);
  fe_mul(v, u1, hh);
  Fe x3, two = {{2, 0, 0, 0}};
  fe_sqr(x3, rr);
  fe_sub(x3, x3, hhh);
  fe_mul(t, v, two);
  fe_sub(x3, x3, t);
  Fe y3;
  fe_sub(t, v, x3);
  fe_mul(y3, rr, t);
  Fe s1hhh;
  fe_mul(s1hhh, s1, hhh);
  fe_sub(y3, y3, s1hhh);
  Fe z3;
  fe_mul(z3, p.z, q.z);
  fe_mul(z3, z3, h);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

// mixed addition: q is affine (z == 1) — saves ~4 field muls per add
// versus the general Jacobian formula (the ladder's adds are all
// against precomputed tables, so this is the common case)
void pt_add_affine(Pt& r, const Pt& p, const Fe& qx, const Fe& qy) {
  if (pt_is_inf(p)) {
    r.x = qx;
    r.y = qy;
    r.z = {{1, 0, 0, 0}};
    return;
  }
  Fe z1z1, u2, s2, t;
  fe_sqr(z1z1, p.z);
  fe_mul(u2, qx, z1z1);
  fe_mul(t, p.z, z1z1);
  fe_mul(s2, qy, t);
  if (fe_cmp(p.x, u2) == 0) {
    if (fe_cmp(p.y, s2) != 0) {
      r = {{{0}}, {{0}}, {{0}}};
      return;
    }
    pt_double(r, p);
    return;
  }
  Fe h, rr, hh, hhh, v;
  fe_sub(h, u2, p.x);
  fe_sub(rr, s2, p.y);
  fe_sqr(hh, h);
  fe_mul(hhh, h, hh);
  fe_mul(v, p.x, hh);
  Fe x3, two = {{2, 0, 0, 0}};
  fe_sqr(x3, rr);
  fe_sub(x3, x3, hhh);
  fe_mul(t, v, two);
  fe_sub(x3, x3, t);
  Fe y3;
  fe_sub(t, v, x3);
  fe_mul(y3, rr, t);
  Fe s1hhh;
  fe_mul(s1hhh, p.y, hhh);
  fe_sub(y3, y3, s1hhh);
  Fe z3;
  fe_mul(z3, p.z, h);
  r.x = x3;
  r.y = y3;
  r.z = z3;
}

// ------------------------------------------------------ wNAF machinery
//
// Width-4 non-adjacent form: odd digits in [-15, 15], ~1/5 density, so
// a 256-bit scalar costs ~256 doublings + ~51 table adds instead of the
// double-and-add ladder's ~128 adds. Both scalars of a double-scalar
// multiplication share ONE doubling ladder (Strauss-Shamir), which is
// where the 2x over two independent ladders comes from.

int wnaf4(int8_t out[260], const Fe& k) {
  uint64_t d[5] = {k.v[0], k.v[1], k.v[2], k.v[3], 0};
  int len = 0;
  auto nonzero = [&]() {
    return (d[0] | d[1] | d[2] | d[3] | d[4]) != 0;
  };
  while (nonzero()) {
    int8_t digit = 0;
    if (d[0] & 1) {
      int m = (int)(d[0] & 31);
      digit = (int8_t)((m > 16) ? m - 32 : m);
      if (digit >= 0) {
        uint64_t borrow = (uint64_t)digit;
        for (int i = 0; i < 5 && borrow; ++i) {
          uint64_t nv = d[i] - borrow;
          borrow = (nv > d[i]) ? 1 : 0;
          d[i] = nv;
        }
      } else {
        uint64_t carry = (uint64_t)(-digit);
        for (int i = 0; i < 5 && carry; ++i) {
          uint64_t nv = d[i] + carry;
          carry = (nv < d[i]) ? 1 : 0;
          d[i] = nv;
        }
      }
    }
    out[len++] = digit;
    // shift right one bit
    for (int i = 0; i < 4; ++i) d[i] = (d[i] >> 1) | (d[i + 1] << 63);
    d[4] >>= 1;
  }
  return len;
}

struct OddTable {  // 1P, 3P, 5P, ..., 15P (Jacobian)
  Pt p[8];
};

void odd_table(OddTable& t, const Pt& base) {
  t.p[0] = base;
  Pt twoP;
  pt_double(twoP, base);
  for (int i = 1; i < 8; ++i) pt_add(t.p[i], t.p[i - 1], twoP);
}

struct AffTable {  // affine odd multiples (for the fixed base G)
  Fe x[8], y[8];
};

const AffTable& g_table() {
  static AffTable t = [] {
    AffTable a;
    OddTable j;
    Pt g = {GX, GY, {{1, 0, 0, 0}}};
    odd_table(j, g);
    for (int i = 0; i < 8; ++i) {  // one-time: plain per-point inverts
      Fe zinv, zinv2, zinv3;
      fe_inv(zinv, j.p[i].z);
      fe_sqr(zinv2, zinv);
      fe_mul(zinv3, zinv2, zinv);
      fe_mul(a.x[i], j.p[i].x, zinv2);
      fe_mul(a.y[i], j.p[i].y, zinv3);
    }
    return a;
  }();
  return t;
}

// acc = k1*G + k2*B, one shared doubling ladder (either term optional)
void strauss(Pt& acc, const Fe* k1, const Pt* B, const Fe* k2) {
  int8_t w1[260], w2[260];
  int l1 = 0, l2 = 0;
  if (k1) l1 = wnaf4(w1, *k1);
  OddTable bt;
  if (k2) {
    l2 = wnaf4(w2, *k2);
    odd_table(bt, *B);
  }
  const AffTable& gt = g_table();
  acc = {{{0}}, {{0}}, {{0}}};
  int len = l1 > l2 ? l1 : l2;
  for (int i = len - 1; i >= 0; --i) {
    pt_double(acc, acc);
    if (i < l1 && w1[i]) {
      int d = w1[i];
      int idx = (d > 0 ? d : -d) >> 1;
      if (d > 0) {
        pt_add_affine(acc, acc, gt.x[idx], gt.y[idx]);
      } else {
        Fe ny;
        fe_sub(ny, P, gt.y[idx]);
        pt_add_affine(acc, acc, gt.x[idx], ny);
      }
    }
    if (i < l2 && w2[i]) {
      int d = w2[i];
      int idx = (d > 0 ? d : -d) >> 1;
      Pt q = bt.p[idx];
      if (d < 0) fe_sub(q.y, P, q.y);
      pt_add(acc, acc, q);
    }
  }
}

void fe_from_be(Fe& r, const uint8_t* b) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = 0;
    for (int j = 0; j < 8; ++j) limb = (limb << 8) | b[(3 - i) * 8 + j];
    r.v[i] = limb;
  }
}

void fe_to_be(uint8_t* b, const Fe& a) {
  for (int i = 0; i < 4; ++i) {
    uint64_t limb = a.v[3 - i];
    for (int j = 7; j >= 0; --j) {
      b[i * 8 + j] = (uint8_t)limb;
      limb >>= 8;
    }
  }
}

// ------------------------------------------- scalar field (mod N) ----
//
// The group order n is NOT of the special 2^256-small form, but
// 2^256 mod n = C fits 129 bits ({C0, C1, 1, 0} limbs), so a 512-bit
// product reduces by folding the high half times C — same technique as
// the base field, one extra round.

constexpr Fe N_ORD = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                       0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
constexpr uint64_t NC0 = 0x402DA1732FC9BEBFULL;
constexpr uint64_t NC1 = 0x4551231950B75FC4ULL;
constexpr uint64_t NC2 = 1ULL;

inline void sn_sub_n_if_ge(Fe& a) {
  bool ge = true;
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > N_ORD.v[i]) break;
    if (a.v[i] < N_ORD.v[i]) { ge = false; break; }
  }
  if (ge) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
      u128 d = (u128)a.v[i] - N_ORD.v[i] - (uint64_t)borrow;
      a.v[i] = (uint64_t)d;
      borrow = (d >> 64) ? 1 : 0;
    }
  }
}

// w[0..7] (512-bit) -> Fe mod n
void sn_reduce512(Fe& r, const uint64_t w[8]) {
  const uint64_t C[3] = {NC0, NC1, NC2};
  // t = low4 + high4 * C  (4+3 limb product -> up to 7 limbs)
  uint64_t t[8] = {w[0], w[1], w[2], w[3], 0, 0, 0, 0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 3; ++j) {
      u128 cur = (u128)w[4 + i] * C[j] + t[i + j] + (uint64_t)carry;
      t[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    int idx = i + 3;
    while (carry) {
      u128 cur = (u128)t[idx] + (uint64_t)carry;
      t[idx] = (uint64_t)cur;
      carry = cur >> 64;
      ++idx;
    }
  }
  // fold t[4..6] (<= ~2^131) * C again
  uint64_t t2[6] = {t[0], t[1], t[2], t[3], 0, 0};
  for (int i = 0; i < 3; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 3; ++j) {
      u128 cur = (u128)t[4 + i] * C[j] + t2[i + j] + (uint64_t)carry;
      t2[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    int idx = i + 3;
    while (carry && idx < 6) {
      u128 cur = (u128)t2[idx] + (uint64_t)carry;
      t2[idx] = (uint64_t)cur;
      carry = cur >> 64;
      ++idx;
    }
  }
  // final tiny fold of t2[4..5] (at most a few bits)
  Fe out = {{t2[0], t2[1], t2[2], t2[3]}};
  while (t2[4] | t2[5]) {
    uint64_t hi[2] = {t2[4], t2[5]};
    t2[4] = t2[5] = 0;
    u128 carry = 0;
    uint64_t acc[6] = {out.v[0], out.v[1], out.v[2], out.v[3], 0, 0};
    for (int i = 0; i < 2; ++i) {
      carry = 0;
      for (int j = 0; j < 3; ++j) {
        u128 cur = (u128)hi[i] * C[j] + acc[i + j] + (uint64_t)carry;
        acc[i + j] = (uint64_t)cur;
        carry = cur >> 64;
      }
      int idx = i + 3;
      while (carry && idx < 6) {
        u128 cur = (u128)acc[idx] + (uint64_t)carry;
        acc[idx] = (uint64_t)cur;
        carry = cur >> 64;
        ++idx;
      }
    }
    out = {{acc[0], acc[1], acc[2], acc[3]}};
    t2[4] = acc[4];
    t2[5] = acc[5];
  }
  sn_sub_n_if_ge(out);
  sn_sub_n_if_ge(out);
  r = out;
}

void sn_mul(Fe& r, const Fe& a, const Fe& b) {
  uint64_t w[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      u128 cur = (u128)a.v[i] * b.v[j] + w[i + j] + (uint64_t)carry;
      w[i + j] = (uint64_t)cur;
      carry = cur >> 64;
    }
    w[i + 4] += (uint64_t)carry;
  }
  sn_reduce512(r, w);
}

void sn_inv(Fe& r, const Fe& a) {  // a^(n-2) mod n
  Fe e = N_ORD;
  e.v[0] -= 2;  // low limb ends ...4141, no borrow
  Fe result = {{1, 0, 0, 0}};
  Fe b = a;
  for (int limb = 0; limb < 4; ++limb) {
    uint64_t bits = e.v[limb];
    for (int bit = 0; bit < 64; ++bit) {
      if (bits & 1) sn_mul(result, result, b);
      bits >>= 1;
      sn_mul(b, b, b);
    }
  }
  r = result;
}

inline bool sn_is_zero_or_ge_n(const Fe& a) {
  if (fe_is_zero(a)) return true;
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] > N_ORD.v[i]) return true;
    if (a.v[i] < N_ORD.v[i]) return false;
  }
  return true;  // equal
}

// sqrt mod p via a^((p+1)/4) (p = 3 mod 4); returns false if a is a
// non-residue (caller re-checks y^2 == a)
void fe_sqrt(Fe& r, const Fe& a) {
  // (p+1)/4 = 2^254 - 2^30 - 244
  constexpr Fe E = {{0xFFFFFFFFBFFFFF0CULL, 0xFFFFFFFFFFFFFFFFULL,
                     0xFFFFFFFFFFFFFFFFULL, 0x3FFFFFFFFFFFFFFFULL}};
  fe_pow(r, a, E);
}

}  // namespace

extern "C" {

// k1*A + k2*B in affine out coords. ax/bx NULL => that base is G.
// k1/k2 NULL or zero => term skipped. Returns 1 for infinity.
int khipu_ec_mul_add(const uint8_t* ax, const uint8_t* ay,
                     const uint8_t* k1, const uint8_t* bx,
                     const uint8_t* by, const uint8_t* k2,
                     uint8_t* outx, uint8_t* outy) {
  const Fe one = {{1, 0, 0, 0}};
  Fe s1, s2;
  const Fe* gk = nullptr;   // scalar on the G (fixed-base) ladder
  const Fe* vk = nullptr;   // scalar on the variable-base ladder
  Pt base;
  bool have_base = false;
  if (k1) {
    fe_from_be(s1, k1);
    if (!fe_is_zero(s1)) {
      if (ax) {
        fe_from_be(base.x, ax);
        fe_from_be(base.y, ay);
        base.z = one;
        have_base = true;
        vk = &s1;
      } else {
        gk = &s1;
      }
    }
  }
  if (k2) {
    fe_from_be(s2, k2);
    if (!fe_is_zero(s2)) {
      if (bx) {
        if (have_base) {
          // two distinct variable bases: fold the first into acc via
          // its own strauss pass (rare path — nothing hot uses it)
          Pt acc1;
          strauss(acc1, gk, &base, vk);
          Pt b2;
          fe_from_be(b2.x, bx);
          fe_from_be(b2.y, by);
          b2.z = one;
          Pt acc2;
          strauss(acc2, nullptr, &b2, &s2);
          Pt acc;
          pt_add(acc, acc1, acc2);
          if (pt_is_inf(acc)) return 1;
          Fe zinv, zinv2, zinv3, x, y;
          fe_inv(zinv, acc.z);
          fe_sqr(zinv2, zinv);
          fe_mul(zinv3, zinv2, zinv);
          fe_mul(x, acc.x, zinv2);
          fe_mul(y, acc.y, zinv3);
          fe_to_be(outx, x);
          fe_to_be(outy, y);
          return 0;
        }
        fe_from_be(base.x, bx);
        fe_from_be(base.y, by);
        base.z = one;
        have_base = true;
        vk = &s2;
      } else if (gk) {
        // both scalars on G: combine on one ladder is wrong (distinct
        // wNAFs); just run G twice via strauss's two slots
        Pt g = {GX, GY, one};
        base = g;
        have_base = true;
        vk = &s2;
      } else {
        gk = &s2;
      }
    }
  }
  Pt acc;
  strauss(acc, gk, have_base ? &base : nullptr,
          have_base ? vk : nullptr);
  if (pt_is_inf(acc)) return 1;
  Fe zinv, zinv2, zinv3, x, y;
  fe_inv(zinv, acc.z);
  fe_sqr(zinv2, zinv);
  fe_mul(zinv3, zinv2, zinv);
  fe_mul(x, acc.x, zinv2);
  fe_mul(y, acc.y, zinv3);
  fe_to_be(outx, x);
  fe_to_be(outy, y);
  return 0;
}

// Batched ECDSA public-key recovery — the tx-sender hot loop
// (SignedTransaction.scala:143 role). One C call per block amortizes
// ctypes overhead; a Strauss-Shamir wNAF-4 ladder computes
// u1*G + u2*R, and ONE Montgomery batch inversion converts every
// result to affine (saving a ~256-squaring field inversion per
// signature). msg: n*32 bytes; recid: n bytes (0-3); rs: n*64 bytes
// (r || s big-endian); out: n*64 bytes (x || y); ok: n bytes (1 =
// recovered, 0 = invalid signature). Returns the number recovered.
int khipu_ecdsa_recover_batch(int n, const uint8_t* msg,
                              const uint8_t* recid, const uint8_t* rs,
                              uint8_t* out, uint8_t* ok) {
  int good = 0;
  Pt* results = new Pt[n];
  int* live = new int[n];
  for (int i = 0; i < n; ++i) {
    ok[i] = 0;
    live[i] = 0;
    Fe r, s;
    fe_from_be(r, rs + 64 * i);
    fe_from_be(s, rs + 64 * i + 32);
    if (sn_is_zero_or_ge_n(r) || sn_is_zero_or_ge_n(s)) continue;
    int v = recid[i];
    if (v < 0 || v > 3) continue;
    // x = r (+ n for the high recids), must stay below p
    Fe x = r;
    if (v & 2) {
      u128 carry = 0;
      for (int j = 0; j < 4; ++j) {
        u128 cur = (u128)x.v[j] + N_ORD.v[j] + (uint64_t)carry;
        x.v[j] = (uint64_t)cur;
        carry = cur >> 64;
      }
      if (carry || fe_cmp(x, P) >= 0) continue;
    }
    // y^2 = x^3 + 7
    Fe x2, x3, alpha, seven = {{7, 0, 0, 0}};
    fe_sqr(x2, x);
    fe_mul(x3, x2, x);
    fe_add(alpha, x3, seven);
    Fe y;
    fe_sqrt(y, alpha);
    Fe y2;
    fe_sqr(y2, y);
    if (fe_cmp(y2, alpha) != 0) continue;  // non-residue: invalid
    if ((int)(y.v[0] & 1) != (v & 1)) fe_sub(y, P, y);
    // scalars: u1 = -z/r, u2 = s/r (mod n)
    Fe z;
    fe_from_be(z, msg + 32 * i);
    sn_sub_n_if_ge(z);
    Fe rinv, u1, u2;
    sn_inv(rinv, r);
    sn_mul(u1, z, rinv);
    if (!fe_is_zero(u1)) {  // u1 = n - z/r
      u128 borrow = 0;
      Fe t;
      for (int j = 0; j < 4; ++j) {
        u128 d = (u128)N_ORD.v[j] - u1.v[j] - (uint64_t)borrow;
        t.v[j] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
      }
      u1 = t;
    }
    sn_mul(u2, s, rinv);
    Pt R = {x, y, {{1, 0, 0, 0}}};
    Pt q;
    strauss(q, fe_is_zero(u1) ? nullptr : &u1, &R,
            fe_is_zero(u2) ? nullptr : &u2);
    if (pt_is_inf(q)) continue;
    results[i] = q;
    live[i] = 1;
  }
  // Montgomery batch inversion of every live z
  Fe* prefix = new Fe[n];
  Fe run = {{1, 0, 0, 0}};
  for (int i = 0; i < n; ++i) {
    if (!live[i]) continue;
    prefix[i] = run;
    fe_mul(run, run, results[i].z);
  }
  Fe run_inv;
  fe_inv(run_inv, run);
  for (int i = n - 1; i >= 0; --i) {
    if (!live[i]) continue;
    Fe zinv;
    fe_mul(zinv, run_inv, prefix[i]);
    fe_mul(run_inv, run_inv, results[i].z);
    Fe zinv2, zinv3, xo, yo;
    fe_sqr(zinv2, zinv);
    fe_mul(zinv3, zinv2, zinv);
    fe_mul(xo, results[i].x, zinv2);
    fe_mul(yo, results[i].y, zinv3);
    fe_to_be(out + 64 * i, xo);
    fe_to_be(out + 64 * i + 32, yo);
    ok[i] = 1;
    ++good;
  }
  delete[] results;
  delete[] live;
  delete[] prefix;
  return good;
}

}  // extern "C"
