// Native append-log KV store — the Kesque storage-engine role in C++.
//
// Role parity: khipu-kesque's KesqueNodeDataSource.scala:18-230 (append
// log + 8-byte short-key index, KesqueIndex.scala:7-26, with the
// content-address verify at :61-63: node keys are NOT stored — they are
// recomputed as keccak256(value) on read, so the log stores pure value
// bytes and short-key collisions are disambiguated by hashing). The
// reference embeds a Kafka broker for its log and LMDB/RocksDB for the
// index; here the log is a flat append-only file and the index is an
// in-memory short-key -> offsets multimap checkpointed to a sidecar
// file (crash recovery rebuilds the uncovered tail by scanning the
// log, mirroring Kafka's log-recovery behavior).
//
// Two record modes per store:
//   content-addressed (nodes):  [u32 vlen][value]            key = kec256(value)
//   explicit-key (blocks/kv):   [u16 klen][key][u32 vlen][value]
// get() for explicit keys returns the LATEST record (offsets iterated
// newest-first), so re-puts behave as updates on an immutable log.
//
// C ABI (ctypes, khipu_tpu/native/store.py). Not thread-safe: the
// Python wrapper holds one lock per store.

#include <unistd.h>

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

// Must match keccak.cc:95 exactly — a conflicting declaration of a
// C-linkage symbol across translation units is UB.
extern "C" void khipu_keccak(int rate, const uint8_t* in, uint64_t in_len,
                             uint8_t* out, int out_len);

namespace {

constexpr uint64_t kIdxMagic = 0x4b48495055494458ULL;  // "KHIPUIDX"

struct IdxHeader {
  uint64_t magic;
  uint64_t npairs;
  uint64_t covered_log_len;
};

struct Store {
  FILE* log = nullptr;
  FILE* idx = nullptr;
  bool content_addressed = true;
  uint64_t log_len = 0;
  uint64_t indexed_len = 0;  // log bytes covered by the in-memory index
  uint64_t count = 0;        // records indexed (re-puts count again)
  int64_t max_key8 = -1;     // max value among 8-byte keys (blocknum)
  std::unordered_map<uint64_t, std::vector<uint64_t>> index;
  std::string log_path, idx_path;
};

uint64_t short_key(const uint8_t* key, uint32_t klen) {
  // Last 8 bytes of the key (KesqueIndex.toShortKey keeps the tail).
  uint64_t out = 0;
  uint32_t start = klen > 8 ? klen - 8 : 0;
  for (uint32_t i = start; i < klen; ++i) out = (out << 8) | key[i];
  return out;
}

bool read_exact(FILE* f, uint64_t off, void* buf, size_t n) {
  if (fseeko(f, (off_t)off, SEEK_SET) != 0) return false;
  return fread(buf, 1, n, f) == n;
}

// Parse one record at `off`; fills lengths and returns total size, or 0
// when the record is torn/out of bounds.
uint64_t record_size(Store* s, uint64_t off, uint32_t* klen_out,
                     uint32_t* vlen_out) {
  if (s->content_addressed) {
    uint32_t vlen;
    if (off + 4 > s->log_len || !read_exact(s->log, off, &vlen, 4)) return 0;
    if (off + 4 + vlen > s->log_len) return 0;
    *klen_out = 0;
    *vlen_out = vlen;
    return 4 + (uint64_t)vlen;
  }
  uint16_t klen;
  if (off + 2 > s->log_len || !read_exact(s->log, off, &klen, 2)) return 0;
  uint32_t vlen;
  if (off + 2 + klen + 4 > s->log_len ||
      !read_exact(s->log, off + 2 + klen, &vlen, 4))
    return 0;
  if (off + 2 + klen + 4 + vlen > s->log_len) return 0;
  *klen_out = klen;
  *vlen_out = vlen;
  return 2 + (uint64_t)klen + 4 + (uint64_t)vlen;
}

bool record_key(Store* s, uint64_t off, std::vector<uint8_t>* key) {
  uint32_t klen, vlen;
  uint64_t sz = record_size(s, off, &klen, &vlen);
  if (!sz) return false;
  if (s->content_addressed) {
    std::vector<uint8_t> val(vlen);
    if (!read_exact(s->log, off + 4, val.data(), vlen)) return false;
    key->resize(32);
    khipu_keccak(136, val.data(), vlen, key->data(), 32);
  } else {
    key->resize(klen);
    if (!read_exact(s->log, off + 2, key->data(), klen)) return false;
  }
  return true;
}

void index_record(Store* s, uint64_t off, const uint8_t* key, uint32_t klen) {
  s->index[short_key(key, klen)].push_back(off);
  s->count++;
  if (klen == 8) {
    uint64_t n = short_key(key, 8);
    if ((int64_t)n > s->max_key8 && n <= (uint64_t)INT64_MAX)
      s->max_key8 = (int64_t)n;
  }
}

// Scan log records in [from, log_len) into the index; truncates a torn
// tail (crash mid-append). Appends the new pairs to the idx file.
void recover_tail(Store* s, uint64_t from) {
  uint64_t off = from;
  while (off < s->log_len) {
    uint32_t klen, vlen;
    uint64_t sz = record_size(s, off, &klen, &vlen);
    if (!sz) {  // torn record: drop it
      fflush(s->log);
      (void)!ftruncate(fileno(s->log), (off_t)off);
      s->log_len = off;
      break;
    }
    std::vector<uint8_t> key;
    if (!record_key(s, off, &key)) break;
    index_record(s, off, key.data(), (uint32_t)key.size());
    uint64_t pair[2] = {short_key(key.data(), (uint32_t)key.size()), off};
    fseeko(s->idx, 0, SEEK_END);
    fwrite(pair, 8, 2, s->idx);
    off += sz;
  }
  s->indexed_len = s->log_len;
}

void write_idx_header(Store* s) {
  IdxHeader h{kIdxMagic, s->count, s->indexed_len};
  fseeko(s->idx, 0, SEEK_SET);
  fwrite(&h, sizeof(h), 1, s->idx);
  fflush(s->idx);
}

}  // namespace

extern "C" {

void* kstore_open(const char* path_prefix, int content_addressed) {
  Store* s = new Store();
  s->content_addressed = content_addressed != 0;
  s->log_path = std::string(path_prefix) + ".log";
  s->idx_path = std::string(path_prefix) + ".idx";

  s->log = fopen(s->log_path.c_str(), "a+b");
  if (!s->log) {
    delete s;
    return nullptr;
  }
  fseeko(s->log, 0, SEEK_END);
  s->log_len = (uint64_t)ftello(s->log);

  s->idx = fopen(s->idx_path.c_str(), "r+b");
  if (!s->idx) s->idx = fopen(s->idx_path.c_str(), "w+b");
  if (!s->idx) {
    fclose(s->log);
    delete s;
    return nullptr;
  }

  IdxHeader h{};
  uint64_t covered = 0, npairs = 0;
  if (read_exact(s->idx, 0, &h, sizeof(h)) && h.magic == kIdxMagic) {
    npairs = h.npairs;
    covered = h.covered_log_len <= s->log_len ? h.covered_log_len : 0;
  } else {
    write_idx_header(s);
  }
  // Load checkpointed pairs, then re-scan anything the header does not
  // cover (including pairs written after the last header update — the
  // tail scan re-derives them from the log itself).
  fseeko(s->idx, sizeof(IdxHeader), SEEK_SET);
  for (uint64_t i = 0; i < npairs; ++i) {
    uint64_t pair[2];
    if (fread(pair, 8, 2, s->idx) != 2) break;
    if (pair[1] >= covered) continue;  // tail scan will re-add it
    s->index[pair[0]].push_back(pair[1]);
    s->count++;
  }
  if (!s->content_addressed) {
    // rebuild max_key8 from indexed records
    for (auto& kv : s->index)
      for (uint64_t off : kv.second) {
        std::vector<uint8_t> key;
        if (record_key(s, off, &key) && key.size() == 8) {
          uint64_t n = short_key(key.data(), 8);
          if ((int64_t)n > s->max_key8) s->max_key8 = (int64_t)n;
        }
      }
  }
  // Trim idx to exactly the checkpointed pairs, then index the tail.
  fflush(s->idx);
  (void)!ftruncate(fileno(s->idx),
                   (off_t)(sizeof(IdxHeader) + 16 * s->count));
  s->indexed_len = covered;
  recover_tail(s, covered);
  write_idx_header(s);
  return s;
}

int64_t kstore_get(void* handle, const uint8_t* key, uint32_t klen,
                   uint8_t* out, uint32_t cap) {
  Store* s = (Store*)handle;
  auto it = s->index.find(short_key(key, klen));
  if (it == s->index.end()) return -1;
  const std::vector<uint64_t>& offs = it->second;
  for (size_t i = offs.size(); i-- > 0;) {  // newest record wins
    uint64_t off = offs[i];
    uint32_t rklen, vlen;
    uint64_t sz = record_size(s, off, &rklen, &vlen);
    if (!sz) continue;
    uint64_t voff;
    if (s->content_addressed) {
      voff = off + 4;
    } else {
      if (rklen != klen) continue;
      std::vector<uint8_t> rkey(rklen);
      if (!read_exact(s->log, off + 2, rkey.data(), rklen)) continue;
      if (memcmp(rkey.data(), key, klen) != 0) continue;
      voff = off + 2 + rklen + 4;
    }
    std::vector<uint8_t> val(vlen);
    if (!read_exact(s->log, voff, val.data(), vlen)) continue;
    if (s->content_addressed) {
      // short-key collision guard: recompute the content address
      uint8_t digest[32];
      khipu_keccak(136, val.data(), vlen, digest, 32);
      if (klen != 32 || memcmp(digest, key, 32) != 0) continue;
    }
    if (vlen > cap) return (int64_t)vlen;  // caller retries with room
    memcpy(out, val.data(), vlen);
    return (int64_t)vlen;
  }
  return -1;
}

int kstore_put(void* handle, const uint8_t* key, uint32_t klen,
               const uint8_t* val, uint32_t vlen) {
  Store* s = (Store*)handle;
  if (s->content_addressed) {
    // dedup: content-addressed nodes are immutable; skip if present
    uint8_t probe[1];
    int64_t got = kstore_get(handle, key, klen, probe, 0);
    if (got >= 0) return 0;
  }
  fseeko(s->log, 0, SEEK_END);
  uint64_t off = s->log_len;
  bool ok;
  if (s->content_addressed) {
    ok = fwrite(&vlen, 4, 1, s->log) == 1 &&
         fwrite(val, 1, vlen, s->log) == vlen;
  } else {
    uint16_t k16 = (uint16_t)klen;
    ok = fwrite(&k16, 2, 1, s->log) == 1 &&
         fwrite(key, 1, klen, s->log) == klen &&
         fwrite(&vlen, 4, 1, s->log) == 1 &&
         fwrite(val, 1, vlen, s->log) == vlen;
  }
  if (!ok) {
    // disk full / IO error: roll the log back to the pre-write offset
    // so bookkeeping never diverges from the file, and surface -1
    fflush(s->log);
    (void)!ftruncate(fileno(s->log), (off_t)off);
    clearerr(s->log);
    return -1;
  }
  s->log_len = off + (s->content_addressed
                          ? 4 + (uint64_t)vlen
                          : 2 + (uint64_t)klen + 4 + (uint64_t)vlen);
  index_record(s, off, key, klen);
  uint64_t pair[2] = {short_key(key, klen), off};
  fseeko(s->idx, 0, SEEK_END);
  fwrite(pair, 8, 2, s->idx);
  return 0;
}

void kstore_flush(void* handle) {
  Store* s = (Store*)handle;
  fflush(s->log);
  s->indexed_len = s->log_len;
  write_idx_header(s);
}

uint64_t kstore_count(void* handle) { return ((Store*)handle)->count; }

int64_t kstore_max_key8(void* handle) { return ((Store*)handle)->max_key8; }

void kstore_close(void* handle) {
  Store* s = (Store*)handle;
  kstore_flush(handle);
  fclose(s->log);
  fclose(s->idx);
  delete s;
}

}  // extern "C"
