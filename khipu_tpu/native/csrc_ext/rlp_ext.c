/* RLP codec as a CPython extension — the hot host loop of trie commits.
 *
 * Semantics are bit-identical to khipu_tpu/base/rlp.py (the pure-Python
 * reference implementation, kept as the no-toolchain fallback and as
 * the differential oracle in tests): Yellow Paper appendix B encoding,
 * canonical-form enforcement on decode, MAX_DEPTH nesting cap.
 * Role parity: khipu-base/src/main/scala/khipu/rlp/RLP.scala:35.
 *
 * Errors raise the exception class installed via _set_error (the
 * package passes base.rlp.RLPError so callers see one exception type
 * regardless of backend).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#define MAX_DEPTH 64

static PyObject *rlp_error = NULL; /* set via _set_error */
static PyObject *enc_hook = NULL;  /* test-only: runs between passes */

static void set_err(const char *msg) {
  PyErr_SetString(rlp_error ? rlp_error : PyExc_ValueError, msg);
}

/* ------------------------------------------------------------ encode */

static int enc_size(PyObject *o, Py_ssize_t *out, int depth) {
  const char *buf;
  Py_ssize_t n;
  if (PyBytes_CheckExact(o)) {
    buf = PyBytes_AS_STRING(o);
    n = PyBytes_GET_SIZE(o);
  } else if (PyByteArray_CheckExact(o)) {
    buf = PyByteArray_AS_STRING(o);
    n = PyByteArray_GET_SIZE(o);
  } else if (PyList_CheckExact(o) || PyTuple_CheckExact(o)) {
    if (depth >= MAX_DEPTH) {
      set_err("RLP nesting exceeds MAX_DEPTH");
      return -1;
    }
    int is_list = PyList_CheckExact(o);
    Py_ssize_t k = is_list ? PyList_GET_SIZE(o) : PyTuple_GET_SIZE(o);
    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < k; ++i) {
      PyObject *c = is_list ? PyList_GET_ITEM(o, i) : PyTuple_GET_ITEM(o, i);
      Py_ssize_t s;
      if (enc_size(c, &s, depth + 1) < 0) return -1;
      total += s;
    }
    if (total < 56) {
      *out = 1 + total;
    } else {
      Py_ssize_t l = total, lb = 0;
      while (l) { lb++; l >>= 8; }
      *out = 1 + lb + total;
    }
    return 0;
  } else {
    set_err("cannot RLP-encode object (want bytes or list)");
    return -1;
  }
  if (n == 1 && (unsigned char)buf[0] < 0x80) {
    *out = 1;
  } else if (n < 56) {
    *out = 1 + n;
  } else {
    Py_ssize_t l = n, lb = 0;
    while (l) { lb++; l >>= 8; }
    *out = 1 + lb + n;
  }
  return 0;
}

/* The write pass is CLAMPED to the buffer sized by enc_size: a
 * bytearray resized between the two passes (e.g. by a GC finalizer
 * running on an allocation inside py_encode) must never let memcpy
 * run past the output bytes object. Every write site bounds-checks
 * against `end`; py_encode additionally requires the exact sized
 * length to be produced, so a shrink is rejected too. */

static char *write_len(char *p, const char *end, Py_ssize_t n,
                       unsigned char offset) {
  if (n < 56) {
    if (end - p < 1) { set_err("RLP input resized during encode"); return NULL; }
    *p++ = (char)(offset + n);
    return p;
  }
  unsigned char tmp[sizeof(Py_ssize_t)];
  int lb = 0;
  Py_ssize_t l = n;
  while (l) { tmp[lb++] = (unsigned char)(l & 0xFF); l >>= 8; }
  if (end - p < 1 + lb) { set_err("RLP input resized during encode"); return NULL; }
  *p++ = (char)(offset + 55 + lb);
  for (int i = lb - 1; i >= 0; --i) *p++ = (char)tmp[i];
  return p;
}

static char *enc_write(PyObject *o, char *p, const char *end, int depth) {
  const char *buf;
  Py_ssize_t n;
  if (PyBytes_CheckExact(o)) {
    buf = PyBytes_AS_STRING(o);
    n = PyBytes_GET_SIZE(o);
  } else if (PyByteArray_CheckExact(o)) {
    buf = PyByteArray_AS_STRING(o);
    n = PyByteArray_GET_SIZE(o);
  } else {
    int is_list = PyList_CheckExact(o);
    Py_ssize_t k = is_list ? PyList_GET_SIZE(o) : PyTuple_GET_SIZE(o);
    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < k; ++i) {
      PyObject *c = is_list ? PyList_GET_ITEM(o, i) : PyTuple_GET_ITEM(o, i);
      Py_ssize_t s;
      if (enc_size(c, &s, depth + 1) < 0) return NULL;
      total += s;
    }
    p = write_len(p, end, total, 0xC0);
    if (p == NULL) return NULL;
    for (Py_ssize_t i = 0; i < k; ++i) {
      PyObject *c = is_list ? PyList_GET_ITEM(o, i) : PyTuple_GET_ITEM(o, i);
      p = enc_write(c, p, end, depth + 1);
      if (p == NULL) return NULL;
    }
    return p;
  }
  if (n == 1 && (unsigned char)buf[0] < 0x80) {
    if (end - p < 1) { set_err("RLP input resized during encode"); return NULL; }
    *p++ = buf[0];
    return p;
  }
  p = write_len(p, end, n, 0x80);
  if (p == NULL) return NULL;
  if (n > end - p) { set_err("RLP input resized during encode"); return NULL; }
  memcpy(p, buf, n);
  return p + n;
}

static PyObject *py_encode(PyObject *self, PyObject *o) {
  Py_ssize_t size;
  if (enc_size(o, &size, 0) < 0) return NULL;
  if (enc_hook != NULL) { /* test-only seam for the resize race */
    PyObject *r = PyObject_CallObject(enc_hook, NULL);
    if (!r) return NULL;
    Py_DECREF(r);
  }
  PyObject *out = PyBytes_FromStringAndSize(NULL, size);
  if (!out) return NULL;
  char *buf = PyBytes_AS_STRING(out);
  char *end = enc_write(o, buf, buf + size, 0);
  if (end == NULL) {
    Py_DECREF(out);
    return NULL;
  }
  if (end != buf + size) { /* shrank between passes */
    Py_DECREF(out);
    set_err("RLP input resized during encode");
    return NULL;
  }
  return out;
}

/* ------------------------------------------------------------ decode */

static PyObject *dec_at(const unsigned char *d, Py_ssize_t len,
                        Py_ssize_t pos, Py_ssize_t *end_out, int depth);

static PyObject *dec_list(const unsigned char *d, Py_ssize_t len,
                          Py_ssize_t start, Py_ssize_t end, int depth) {
  if (depth >= MAX_DEPTH) {
    set_err("RLP nesting exceeds MAX_DEPTH");
    return NULL;
  }
  PyObject *items = PyList_New(0);
  if (!items) return NULL;
  Py_ssize_t pos = start;
  while (pos < end) {
    Py_ssize_t next;
    PyObject *item = dec_at(d, len, pos, &next, depth + 1);
    if (!item) { Py_DECREF(items); return NULL; }
    if (next > end) {
      Py_DECREF(item);
      Py_DECREF(items);
      set_err("list element overruns list payload");
      return NULL;
    }
    if (PyList_Append(items, item) < 0) {
      Py_DECREF(item);
      Py_DECREF(items);
      return NULL;
    }
    Py_DECREF(item);
    pos = next;
  }
  return items;
}

static PyObject *dec_at(const unsigned char *d, Py_ssize_t len,
                        Py_ssize_t pos, Py_ssize_t *end_out, int depth) {
  if (pos >= len) {
    set_err("truncated RLP input");
    return NULL;
  }
  unsigned char b0 = d[pos];
  if (b0 < 0x80) {
    *end_out = pos + 1;
    return PyBytes_FromStringAndSize((const char *)d + pos, 1);
  }
  if (b0 <= 0xB7) { /* short string */
    Py_ssize_t n = b0 - 0x80;
    Py_ssize_t end = pos + 1 + n;
    if (end > len) { set_err("truncated string"); return NULL; }
    if (n == 1 && d[pos + 1] < 0x80) {
      set_err("non-canonical single byte");
      return NULL;
    }
    *end_out = end;
    return PyBytes_FromStringAndSize((const char *)d + pos + 1, n);
  }
  if (b0 <= 0xBF) { /* long string */
    Py_ssize_t ll = b0 - 0xB7;
    if (pos + 1 + ll > len) { set_err("truncated length"); return NULL; }
    Py_ssize_t n = 0;
    for (Py_ssize_t i = 0; i < ll; ++i) {
      if (n > (PY_SSIZE_T_MAX >> 8)) { set_err("length overflow"); return NULL; }
      n = (n << 8) | d[pos + 1 + i];
    }
    if (n < 56 || (ll > 1 && d[pos + 1] == 0)) {
      set_err("non-canonical length");
      return NULL;
    }
    Py_ssize_t start = pos + 1 + ll;
    /* n can be near PY_SSIZE_T_MAX: compare by subtraction, never
       compute start + n (signed overflow is UB) */
    if (n > len - start) { set_err("truncated string"); return NULL; }
    Py_ssize_t end = start + n;
    *end_out = end;
    return PyBytes_FromStringAndSize((const char *)d + start, n);
  }
  if (b0 <= 0xF7) { /* short list */
    Py_ssize_t n = b0 - 0xC0;
    Py_ssize_t end = pos + 1 + n;
    if (end > len) { set_err("truncated list"); return NULL; }
    PyObject *items = dec_list(d, len, pos + 1, end, depth);
    if (!items) return NULL;
    *end_out = end;
    return items;
  }
  /* long list */
  Py_ssize_t ll = b0 - 0xF7;
  if (pos + 1 + ll > len) { set_err("truncated length"); return NULL; }
  Py_ssize_t n = 0;
  for (Py_ssize_t i = 0; i < ll; ++i) {
    if (n > (PY_SSIZE_T_MAX >> 8)) { set_err("length overflow"); return NULL; }
    n = (n << 8) | d[pos + 1 + i];
  }
  if (n < 56 || (ll > 1 && d[pos + 1] == 0)) {
    set_err("non-canonical length");
    return NULL;
  }
  Py_ssize_t start = pos + 1 + ll;
  if (n > len - start) { set_err("truncated list"); return NULL; }
  Py_ssize_t end = start + n;
  PyObject *items = dec_list(d, len, start, end, depth);
  if (!items) return NULL;
  *end_out = end;
  return items;
}

static PyObject *py_decode(PyObject *self, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  Py_ssize_t end;
  PyObject *item =
      dec_at((const unsigned char *)view.buf, view.len, 0, &end, 0);
  if (item && end != view.len) {
    Py_DECREF(item);
    item = NULL;
    set_err("trailing bytes after RLP item");
  }
  PyBuffer_Release(&view);
  return item;
}

static PyObject *py_set_error(PyObject *self, PyObject *cls) {
  Py_XINCREF(cls);
  Py_XDECREF(rlp_error);
  rlp_error = cls;
  Py_RETURN_NONE;
}

static PyObject *py_set_encode_hook(PyObject *self, PyObject *cb) {
  /* Test-only: install a callable invoked between the size and write
   * passes of encode (None clears). Lets tests exercise the
   * resized-input guard deterministically. */
  if (cb == Py_None) cb = NULL;
  Py_XINCREF(cb);
  Py_XDECREF(enc_hook);
  enc_hook = cb;
  Py_RETURN_NONE;
}

/* -------------------------------------------------- snappy compress
 *
 * Greedy Snappy block-format compressor (the devp2p p2p/v5 frame
 * codec): a 16-bit hash table finds 4-byte matches within a 64 KiB
 * window; matches emit copy-with-2-byte-offset ops (<= 64 bytes per
 * op), gaps emit literals. Output is accepted by any spec decoder —
 * the Python decompress in network/snappy_codec.py round-trips it in
 * tests. Role parity: the reference links snappy-java (SURVEY §2.10).
 */

#define SNAPPY_HASH_BITS 14
#define SNAPPY_HASH_SIZE (1 << SNAPPY_HASH_BITS)

static inline uint32_t snappy_hash(const unsigned char *p) {
  uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
               ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
  return (v * 0x1E35A7BDu) >> (32 - SNAPPY_HASH_BITS);
}

static unsigned char *emit_literal(unsigned char *op,
                                   const unsigned char *base,
                                   Py_ssize_t len) {
  while (len > 0) {
    Py_ssize_t n = len;
    if (n > 65536) n = 65536; /* keep extended length <= 2 bytes */
    if (n <= 60) {
      *op++ = (unsigned char)((n - 1) << 2);
    } else if (n <= 256) {
      *op++ = 60 << 2;
      *op++ = (unsigned char)(n - 1);
    } else {
      *op++ = 61 << 2;
      *op++ = (unsigned char)((n - 1) & 0xFF);
      *op++ = (unsigned char)(((n - 1) >> 8) & 0xFF);
    }
    memcpy(op, base, n);
    op += n;
    base += n;
    len -= n;
  }
  return op;
}

static unsigned char *emit_copy(unsigned char *op, Py_ssize_t offset,
                                Py_ssize_t len) {
  /* copy2: 6-bit (len-1), 16-bit LE offset; split long matches */
  while (len > 0) {
    Py_ssize_t n = len;
    if (n > 64) n = 64;
    if (n < 4) break; /* never emit a <4-byte copy (tail folds into
                         the next literal) */
    *op++ = (unsigned char)(((n - 1) << 2) | 2);
    *op++ = (unsigned char)(offset & 0xFF);
    *op++ = (unsigned char)((offset >> 8) & 0xFF);
    len -= n;
  }
  return op;
}

static PyObject *py_snappy_compress(PyObject *self, PyObject *arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0) return NULL;
  const unsigned char *src = (const unsigned char *)view.buf;
  Py_ssize_t n = view.len;
  /* worst-case output bound (snappy's MaxCompressedLength formula):
     greedy emission can EXPAND — e.g. alternating short literal runs
     (2-3 header bytes each) with 4-byte copies that save only 1 — so
     the slack must scale with n/6, not per-64KiB */
  Py_ssize_t cap = 32 + n + n / 6;
  unsigned char *buf = (unsigned char *)PyMem_Malloc(cap < 16 ? 16 : cap);
  if (!buf) {
    PyBuffer_Release(&view);
    return PyErr_NoMemory();
  }
  unsigned char *op = buf;
  Py_ssize_t v = n;
  do { /* varint uncompressed length */
    unsigned char b = (unsigned char)(v & 0x7F);
    v >>= 7;
    *op++ = v ? (b | 0x80) : b;
  } while (v);

  uint16_t table[SNAPPY_HASH_SIZE];
  memset(table, 0, sizeof(table));
  /* table stores pos+1 within the current 64 KiB-aligned region, so a
     zero entry means empty; offsets are validated against the window */
  Py_ssize_t lit_start = 0;
  Py_ssize_t i = 0;
  while (i + 4 <= n) {
    uint32_t h = snappy_hash(src + i);
    Py_ssize_t cand = (Py_ssize_t)table[h] - 1 +
                      (i & ~(Py_ssize_t)0xFFFF);
    if (cand >= i) cand -= 65536;
    table[h] = (uint16_t)((i & 0xFFFF) + 1);
    if (cand >= 0 && cand < i && i - cand <= 65535 &&
        memcmp(src + cand, src + i, 4) == 0) {
      /* extend the match */
      Py_ssize_t len = 4;
      while (i + len < n && src[cand + len] == src[i + len] &&
             len < 65536)
        ++len;
      op = emit_literal(op, src + lit_start, i - lit_start);
      /* emit_copy splits at 64 and refuses a <4-byte tail — compute
         the coverable length so the tail folds into the next literal */
      Py_ssize_t covered = len - (len % 64);
      Py_ssize_t tail = len % 64;
      if (tail >= 4) covered += tail;
      op = emit_copy(op, i - cand, covered);
      i += covered;
      lit_start = i;
      continue;
    }
    ++i;
  }
  op = emit_literal(op, src + lit_start, n - lit_start);
  PyObject *out = PyBytes_FromStringAndSize((const char *)buf, op - buf);
  PyMem_Free(buf);
  PyBuffer_Release(&view);
  return out;
}

static PyMethodDef methods[] = {
    {"encode", py_encode, METH_O, "RLP-encode bytes / nested lists."},
    {"decode", py_decode, METH_O, "RLP-decode one item (strict)."},
    {"_set_error", py_set_error, METH_O, "Install the error class."},
    {"_set_encode_hook", py_set_encode_hook, METH_O,
     "Test-only: callable run between encode's size and write passes."},
    {"snappy_compress", py_snappy_compress, METH_O,
     "Greedy Snappy block-format compression."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "khipu_rlp_ext", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit_khipu_rlp_ext(void) {
  return PyModule_Create(&moduledef);
}
