"""ctypes bindings for the native append-log store (csrc/store.cc) and
the DataSource implementations over it — the ``db.engine = "native"``
persistent engine (Kesque role; SURVEY.md §2.3).

Content-addressed node stores never store keys: reads recompute
keccak256(value) to disambiguate 8-byte short-key collisions, exactly
the reference's KesqueNodeDataSource.scala:61-63 design. Explicit-key
stores serve blocks/KV; a zero-length value is a tombstone (all stored
values here are RLP, which is never empty).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, Iterable, List, Mapping, Optional

from khipu_tpu.native.build import load_library
from khipu_tpu.storage.datasource import (
    BlockDataSource,
    KeyValueDataSource,
    NodeDataSource,
)

_configured = False
_lib = None


class NativeStoreError(Exception):
    pass


def _get_lib():
    global _configured, _lib
    if not _configured:
        _configured = True
        lib = load_library()
        if lib is not None:
            lib.kstore_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.kstore_open.restype = ctypes.c_void_p
            lib.kstore_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.kstore_get.restype = ctypes.c_int64
            lib.kstore_put.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_uint32,
            ]
            lib.kstore_put.restype = ctypes.c_int
            lib.kstore_flush.argtypes = [ctypes.c_void_p]
            lib.kstore_count.argtypes = [ctypes.c_void_p]
            lib.kstore_count.restype = ctypes.c_uint64
            lib.kstore_max_key8.argtypes = [ctypes.c_void_p]
            lib.kstore_max_key8.restype = ctypes.c_int64
            lib.kstore_close.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    return _get_lib() is not None


class _NativeStore:
    """One log+index pair; wraps the C handle with a lock (the C side
    is single-threaded by contract)."""

    def __init__(self, data_dir: str, topic: str, content_addressed: bool):
        lib = _get_lib()
        if lib is None:
            raise NativeStoreError(
                "native store requires a working g++ toolchain "
                "(khipu_tpu/native/build.py could not build the library)"
            )
        os.makedirs(data_dir, exist_ok=True)
        prefix = os.path.join(data_dir, topic)
        self._lib = lib
        self._lock = threading.RLock()
        self._handle = lib.kstore_open(
            prefix.encode(), 1 if content_addressed else 0
        )
        if not self._handle:
            raise NativeStoreError(f"cannot open store at {prefix}")

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            if self._handle is None:
                raise NativeStoreError("store is closed")
            cap = 4096  # one SSD block, the Kesque fetchMaxBytes default
            while True:
                buf = ctypes.create_string_buffer(cap)
                n = self._lib.kstore_get(
                    self._handle, bytes(key), len(key), buf, cap
                )
                if n < 0:
                    return None
                if n <= cap:
                    return buf.raw[:n]
                cap = int(n)

    def put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if self._handle is None:
                raise NativeStoreError("store is closed")
            rc = self._lib.kstore_put(
                self._handle, bytes(key), len(key), bytes(value), len(value)
            )
            if rc != 0:
                raise NativeStoreError(
                    "append failed (disk full / IO error); log rolled back"
                )

    def flush(self) -> None:
        with self._lock:
            if self._handle is not None:
                self._lib.kstore_flush(self._handle)

    @property
    def count(self) -> int:
        with self._lock:
            if self._handle is None:
                return 0
            return int(self._lib.kstore_count(self._handle))

    @property
    def max_key8(self) -> int:
        with self._lock:
            if self._handle is None:
                return -1
            return int(self._lib.kstore_max_key8(self._handle))

    def close(self) -> None:
        with self._lock:
            if self._handle is not None:
                self._lib.kstore_close(self._handle)
                self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeNodeDataSource(NodeDataSource):
    """Persistent content-addressed node store (hash -> node RLP)."""

    def __init__(self, data_dir: str, topic: str):
        super().__init__()
        self._store = _NativeStore(data_dir, topic, content_addressed=True)

    def get(self, key: bytes) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            return self._store.get(key)
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        # content-addressed archive: removes are swallowed (NodeStorage
        # semantics), upserts dedup inside the C side
        for k, v in to_upsert.items():
            self._store.put(bytes(k), bytes(v))

    @property
    def count(self) -> int:
        return self._store.count

    def flush(self) -> None:
        self._store.flush()

    def stop(self) -> None:
        self._store.close()


class NativeKeyValueDataSource(KeyValueDataSource):
    """Persistent bytes -> bytes store (blocknum / tx / appState
    topics). Zero-length value = tombstone."""

    def __init__(self, data_dir: str, topic: str):
        super().__init__()
        self._store = _NativeStore(data_dir, topic, content_addressed=False)

    def get(self, key: bytes) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            v = self._store.get(key)
            return v if v else None  # b"" is the tombstone
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        for k in to_remove:
            self._store.put(bytes(k), b"")
        for k, v in to_upsert.items():
            self._store.put(bytes(k), bytes(v))

    @property
    def count(self) -> int:
        return self._store.count

    def flush(self) -> None:
        self._store.flush()

    def stop(self) -> None:
        self._store.close()


class NativeBlockDataSource(BlockDataSource):
    """Persistent number -> bytes store; keys are 8-byte big-endian so
    the C side can track bestBlockNumber (max_key8)."""

    def __init__(self, data_dir: str, topic: str):
        super().__init__()
        self._store = _NativeStore(data_dir, topic, content_addressed=False)
        self._lock = threading.Lock()
        # max_key8 counts every appended 8-byte key, tombstones included
        # — walk down to the highest LIVE block so a pre-restart reorg
        # cannot leave best pointing at a removed record
        best = self._store.max_key8
        while best >= 0 and not self._store.get(self._key(best)):
            best -= 1
        self._best = best

    @staticmethod
    def _key(number: int) -> bytes:
        return int(number).to_bytes(8, "big")

    def get(self, number: int) -> Optional[bytes]:
        t0 = self.clock.start()
        try:
            v = self._store.get(self._key(number))
            return v if v else None
        finally:
            self.clock.elapse(t0)

    def update(self, to_remove, to_upsert) -> None:
        with self._lock:
            for n in to_remove:
                self._store.put(self._key(n), b"")
                if int(n) == self._best:
                    # conservative: walk down to the previous live block
                    m = self._best - 1
                    while m >= 0 and not self._store.get(self._key(m)):
                        m -= 1
                    self._best = m
            for n, v in to_upsert.items():
                self._store.put(self._key(n), bytes(v))
                if int(n) > self._best:
                    self._best = int(n)

    @property
    def best_block_number(self) -> int:
        return self._best

    @property
    def count(self) -> int:
        return self._store.count

    def flush(self) -> None:
        self._store.flush()

    def stop(self) -> None:
        self._store.close()
