"""On-demand g++ build of the native shared library, cached by mtime."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_DIR, "csrc")
_OUT = os.path.join(_DIR, "_libkhipu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _sources():
    return sorted(
        os.path.join(_CSRC, f)
        for f in os.listdir(_CSRC)
        if f.endswith(".cc")
    )


def _needs_build() -> bool:
    if not os.path.exists(_OUT):
        return True
    out_mtime = os.path.getmtime(_OUT)
    return any(os.path.getmtime(s) > out_mtime for s in _sources())


def load_library() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and dlopen the native library.

    Returns None when no working toolchain is available; callers fall
    back to pure Python.
    """
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if _needs_build():
                # Compile to a process-unique temp path and os.replace()
                # into place: concurrent builders (pytest-xdist, multi-
                # process runs) must never dlopen a half-written .so.
                tmp = f"{_OUT}.{os.getpid()}.tmp"
                cmd = [
                    "g++", "-O3", "-march=native", "-shared", "-fPIC",
                    "-std=c++17", "-o", tmp, *_sources(),
                ]
                try:
                    subprocess.run(
                        cmd, check=True, capture_output=True, timeout=300
                    )
                    os.replace(tmp, _OUT)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            _lib = ctypes.CDLL(_OUT)
        except Exception:
            _failed = True
            _lib = None
        return _lib
