"""On-demand g++ build of the native shared library, cached by mtime."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.join(_DIR, "csrc")
_OUT = os.path.join(_DIR, "_libkhipu_native.so")
_CSRC_EXT = os.path.join(_DIR, "csrc_ext")
_OUT_EXT = os.path.join(_DIR, "_khipu_rlp_ext.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False
_ext_lock = threading.Lock()
_ext_mod = None
_ext_failed = False


def _sources():
    return sorted(
        os.path.join(_CSRC, f)
        for f in os.listdir(_CSRC)
        if f.endswith(".cc")
    )


def _needs_build() -> bool:
    if not os.path.exists(_OUT):
        return True
    out_mtime = os.path.getmtime(_OUT)
    return any(os.path.getmtime(s) > out_mtime for s in _sources())


def load_library() -> Optional[ctypes.CDLL]:
    """Compile (if stale) and dlopen the native library.

    Returns None when no working toolchain is available; callers fall
    back to pure Python.
    """
    global _lib, _failed
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            if _needs_build():
                # Compile to a process-unique temp path and os.replace()
                # into place: concurrent builders (pytest-xdist, multi-
                # process runs) must never dlopen a half-written .so.
                tmp = f"{_OUT}.{os.getpid()}.tmp"
                cmd = [
                    "g++", "-O3", "-march=native", "-shared", "-fPIC",
                    "-std=c++17", "-o", tmp, *_sources(),
                ]
                try:
                    subprocess.run(
                        cmd, check=True, capture_output=True, timeout=300
                    )
                    os.replace(tmp, _OUT)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            _lib = ctypes.CDLL(_OUT)
        except Exception:
            _failed = True
            _lib = None
        return _lib


def rlp_ext_is_fresh() -> bool:
    """True when the compiled RLP extension exists and is newer than
    its source — THE staleness rule, shared by load_rlp_ext and the
    import-time binding decision in base/rlp.py."""
    src = os.path.join(_CSRC_EXT, "rlp_ext.c")
    return os.path.exists(_OUT_EXT) and (
        not os.path.exists(src)
        or os.path.getmtime(src) <= os.path.getmtime(_OUT_EXT)
    )


def load_rlp_ext():
    """Compile (if stale) and import the CPython RLP extension module
    (csrc_ext/rlp_ext.c). Returns the module or None — callers fall
    back to the pure-Python codec."""
    global _ext_mod, _ext_failed
    if _ext_mod is not None or _ext_failed:
        return _ext_mod
    with _ext_lock:
        if _ext_mod is not None or _ext_failed:
            return _ext_mod
        try:
            import importlib.util
            import sysconfig

            src = os.path.join(_CSRC_EXT, "rlp_ext.c")
            if not rlp_ext_is_fresh():
                tmp = f"{_OUT_EXT}.{os.getpid()}.tmp"
                cmd = [
                    "gcc", "-O3", "-shared", "-fPIC",
                    f"-I{sysconfig.get_paths()['include']}",
                    "-o", tmp, src,
                ]
                try:
                    subprocess.run(
                        cmd, check=True, capture_output=True, timeout=300
                    )
                    os.replace(tmp, _OUT_EXT)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            spec = importlib.util.spec_from_file_location(
                "khipu_rlp_ext", _OUT_EXT
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _ext_mod = mod
        except Exception:
            _ext_failed = True
            _ext_mod = None
        return _ext_mod
