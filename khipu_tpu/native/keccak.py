"""ctypes bindings for the native Keccak (csrc/keccak.cc)."""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence

from khipu_tpu.native.build import load_library

_RATE_256 = 136
_RATE_512 = 72

_configured = False
_lib = None


def _get_lib():
    global _configured, _lib
    if not _configured:
        _configured = True
        lib = load_library()
        if lib is not None:
            lib.khipu_keccak.argtypes = [
                ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_int,
            ]
            lib.khipu_keccak_batch.argtypes = [
                ctypes.c_int, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
                ctypes.c_char_p, ctypes.c_int,
            ]
        _lib = lib
    return _lib


def available() -> bool:
    return _get_lib() is not None


def _digest(data: bytes, rate: int, out_len: int) -> bytes:
    lib = _get_lib()
    if lib is None:
        # Same self-healing as keccak256_batch: pure sponge directly
        # (base.crypto.keccak's public fns may be bound to this module).
        from khipu_tpu.base.crypto.keccak import keccak256_py, keccak512_py

        return keccak256_py(data) if rate == _RATE_256 else keccak512_py(data)
    out = ctypes.create_string_buffer(out_len)
    lib.khipu_keccak(rate, bytes(data), len(data), out, out_len)
    return out.raw


def keccak256(data: bytes) -> bytes:
    return _digest(data, _RATE_256, 32)


def keccak512(data: bytes) -> bytes:
    return _digest(data, _RATE_512, 64)


def keccak256_batch(messages: Sequence[bytes]) -> List[bytes]:
    lib = _get_lib()
    n = len(messages)
    if n == 0:
        return []
    if lib is None:
        # Use the pure sponge directly — base.crypto.keccak.keccak256
        # may itself be bound to this module (circular).
        from khipu_tpu.base.crypto.keccak import keccak256_py

        return [keccak256_py(m) for m in messages]
    blob = b"".join(messages)
    offsets = (ctypes.c_uint64 * (n + 1))()
    pos = 0
    for i, m in enumerate(messages):
        offsets[i] = pos
        pos += len(m)
    offsets[n] = pos
    out = ctypes.create_string_buffer(32 * n)
    lib.khipu_keccak_batch(_RATE_256, blob, offsets, n, out, 32)
    raw = out.raw
    return [raw[i * 32 : (i + 1) * 32] for i in range(n)]
