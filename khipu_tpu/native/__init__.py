"""Native (C++) runtime components, loaded via ctypes.

The compute path is JAX/XLA/Pallas; this package holds the host-side
native pieces whose roles the reference fills with JVM/JNI code
(SURVEY.md §2.10): the Keccak hot loop (KeccakCore.scala) and the
append-log node store (khipu-kesque). Built on demand with g++; every
consumer has a pure-Python fallback so the framework still works where
no toolchain exists.
"""

from khipu_tpu.native.build import load_library

__all__ = ["load_library"]
