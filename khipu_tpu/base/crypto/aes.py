"""Pure-Python AES (forward cipher only) — CTR and single-block ECB.

Fallback for environments without the ``cryptography`` wheel: RLPx
handshakes/frames (network/ecies.py, network/rlpx.py) and V3 keyfiles
(keystore.py) only ever use the ENCRYPT direction (CTR decrypts with
the forward cipher; the RLPx frame-MAC uses one ECB block), so the
inverse cipher is deliberately omitted.

Table-based (four 32-bit T-tables, computed at import from GF(2^8)
log/antilog tables rather than transcribed constants); throughput is
plenty for handshake- and keyfile-sized payloads. Not constant-time —
acceptable for the transport layer this backs (the reference client's
JCE provider isn't the trust boundary either), not for signing keys
handled by adversarial-timing-exposed services.
"""

from __future__ import annotations

from typing import List


def _gmul(a: int, b: int) -> int:
    r = 0
    for _ in range(8):
        if b & 1:
            r ^= a
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1B
        b >>= 1
    return r


def _build_tables():
    # log/antilog over generator 3 -> multiplicative inverses -> S-box
    alog = [0] * 255
    log = [0] * 256
    x = 1
    for i in range(255):
        alog[i] = x
        log[x] = i
        x = _gmul(x, 3)
    sbox = [0] * 256
    sbox[0] = 0x63
    for a in range(1, 256):
        b = alog[(255 - log[a]) % 255]  # a^-1
        s = b
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            s ^= b
        sbox[a] = s ^ 0x63
    te0 = [0] * 256
    for a in range(256):
        s = sbox[a]
        s2 = _gmul(s, 2)
        s3 = s2 ^ s
        te0[a] = (s2 << 24) | (s << 16) | (s << 8) | s3
    te1 = [((t >> 8) | ((t & 0xFF) << 24)) & 0xFFFFFFFF for t in te0]
    te2 = [((t >> 8) | ((t & 0xFF) << 24)) & 0xFFFFFFFF for t in te1]
    te3 = [((t >> 8) | ((t & 0xFF) << 24)) & 0xFFFFFFFF for t in te2]
    return sbox, te0, te1, te2, te3


_SBOX, _TE0, _TE1, _TE2, _TE3 = _build_tables()
_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36,
         0x6C, 0xD8, 0xAB, 0x4D]


class AES:
    """Forward AES-128/192/256 over 16-byte blocks."""

    __slots__ = ("_rk", "_rounds")

    def __init__(self, key: bytes):
        nk = len(key) // 4
        if len(key) not in (16, 24, 32):
            raise ValueError(f"bad AES key length {len(key)}")
        self._rounds = nk + 6
        w: List[int] = [
            int.from_bytes(key[4 * i : 4 * i + 4], "big")
            for i in range(nk)
        ]
        sbox = _SBOX
        for i in range(nk, 4 * (self._rounds + 1)):
            t = w[i - 1]
            if i % nk == 0:
                t = ((t << 8) | (t >> 24)) & 0xFFFFFFFF  # RotWord
                t = (
                    (sbox[(t >> 24) & 0xFF] << 24)
                    | (sbox[(t >> 16) & 0xFF] << 16)
                    | (sbox[(t >> 8) & 0xFF] << 8)
                    | sbox[t & 0xFF]
                )
                t ^= _RCON[i // nk - 1] << 24
            elif nk > 6 and i % nk == 4:
                t = (
                    (sbox[(t >> 24) & 0xFF] << 24)
                    | (sbox[(t >> 16) & 0xFF] << 16)
                    | (sbox[(t >> 8) & 0xFF] << 8)
                    | sbox[t & 0xFF]
                )
            w.append(w[i - nk] ^ t)
        self._rk = w

    def encrypt_block(self, block: bytes) -> bytes:
        if len(block) != 16:
            raise ValueError("AES block must be 16 bytes")
        rk = self._rk
        te0, te1, te2, te3 = _TE0, _TE1, _TE2, _TE3
        s0 = int.from_bytes(block[0:4], "big") ^ rk[0]
        s1 = int.from_bytes(block[4:8], "big") ^ rk[1]
        s2 = int.from_bytes(block[8:12], "big") ^ rk[2]
        s3 = int.from_bytes(block[12:16], "big") ^ rk[3]
        k = 4
        for _ in range(self._rounds - 1):
            t0 = (
                te0[(s0 >> 24) & 0xFF] ^ te1[(s1 >> 16) & 0xFF]
                ^ te2[(s2 >> 8) & 0xFF] ^ te3[s3 & 0xFF] ^ rk[k]
            )
            t1 = (
                te0[(s1 >> 24) & 0xFF] ^ te1[(s2 >> 16) & 0xFF]
                ^ te2[(s3 >> 8) & 0xFF] ^ te3[s0 & 0xFF] ^ rk[k + 1]
            )
            t2 = (
                te0[(s2 >> 24) & 0xFF] ^ te1[(s3 >> 16) & 0xFF]
                ^ te2[(s0 >> 8) & 0xFF] ^ te3[s1 & 0xFF] ^ rk[k + 2]
            )
            t3 = (
                te0[(s3 >> 24) & 0xFF] ^ te1[(s0 >> 16) & 0xFF]
                ^ te2[(s1 >> 8) & 0xFF] ^ te3[s2 & 0xFF] ^ rk[k + 3]
            )
            s0, s1, s2, s3 = t0, t1, t2, t3
            k += 4
        sbox = _SBOX
        out = bytearray(16)
        for col, (a, b, c, d) in enumerate(
            ((s0, s1, s2, s3), (s1, s2, s3, s0),
             (s2, s3, s0, s1), (s3, s0, s1, s2))
        ):
            v = (
                (sbox[(a >> 24) & 0xFF] << 24)
                | (sbox[(b >> 16) & 0xFF] << 16)
                | (sbox[(c >> 8) & 0xFF] << 8)
                | sbox[d & 0xFF]
            ) ^ rk[k + col]
            out[4 * col : 4 * col + 4] = v.to_bytes(4, "big")
        return bytes(out)


class CtrCipher:
    """Incremental AES-CTR keystream (big-endian 128-bit counter over
    the whole IV, as both RLPx and V3 keyfiles use). Mirrors the
    ``cryptography`` encryptor surface: ``update`` accepts arbitrary
    chunk sizes across calls, ``finalize`` returns nothing."""

    __slots__ = ("_aes", "_counter", "_leftover")

    def __init__(self, key: bytes, iv: bytes = b"\x00" * 16):
        if len(iv) != 16:
            raise ValueError("CTR iv must be 16 bytes")
        self._aes = AES(key)
        self._counter = int.from_bytes(iv, "big")
        self._leftover = b""

    def update(self, data: bytes) -> bytes:
        n = len(data)
        stream = [self._leftover]
        have = len(self._leftover)
        enc = self._aes.encrypt_block
        while have < n:
            stream.append(
                enc(self._counter.to_bytes(16, "big"))
            )
            self._counter = (self._counter + 1) % (1 << 128)
            have += 16
        ks = b"".join(stream)
        self._leftover = ks[n:]
        return (
            int.from_bytes(data, "big") ^ int.from_bytes(ks[:n], "big")
        ).to_bytes(n, "big") if n else b""

    def finalize(self) -> bytes:
        return b""


def ctr_crypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    """One-shot AES-CTR (encrypt == decrypt)."""
    return CtrCipher(key, iv).update(data)


def ecb_encrypt_block(key: bytes, block16: bytes) -> bytes:
    """One forward AES block (the RLPx frame-MAC update primitive)."""
    return AES(key).encrypt_block(block16)
