"""Keccak-256/512 (original Keccak padding 0x01, NOT NIST SHA-3 0x06).

Scalar reference implementation; role of the reference's JVM sponge
(khipu-base/src/main/scala/khipu/crypto/hash/KeccakCore.scala:38,
Keccak256.scala:37, Keccak512.scala). The production batched path is
khipu_tpu.ops.keccak (jnp / Pallas); tests assert the two agree.
"""

from __future__ import annotations

MASK64 = (1 << 64) - 1

# Round constants for Keccak-f[1600] (KeccakCore.scala RC table :39-63).
ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# rho rotation offsets, indexed [x][y] with lane index = x + 5*y.
ROTATION = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)


def _rotl(value: int, shift: int) -> int:
    return ((value << shift) | (value >> (64 - shift))) & MASK64


def keccak_f1600(state: list) -> None:
    """In-place Keccak-f[1600] permutation over 25 int lanes."""
    for rc in ROUND_CONSTANTS:
        # theta
        c = [
            state[x] ^ state[x + 5] ^ state[x + 10] ^ state[x + 15] ^ state[x + 20]
            for x in range(5)
        ]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                state[x + 5 * y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(
                    state[x + 5 * y], ROTATION[x][y]
                )
        # chi
        for x in range(5):
            for y in range(5):
                state[x + 5 * y] = b[x + 5 * y] ^ (
                    (~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y] & MASK64
                )
        # iota
        state[0] ^= rc


def keccak_pad(data: bytes, rate: int, domain: int = 0x01) -> bytes:
    """Multi-rate pad10*1. domain=0x01 is original Keccak (Ethereum);
    0x06 is NIST SHA-3 — exposed so tests can cross-validate the
    permutation/absorb loop against an independent SHA3 implementation."""
    pad_len = rate - (len(data) % rate)
    padding = bytearray(pad_len)
    padding[0] = domain
    padding[-1] |= 0x80
    return data + bytes(padding)


def _keccak(data: bytes, rate: int, out_len: int, domain: int = 0x01) -> bytes:
    state = [0] * 25
    padded = keccak_pad(data, rate, domain)
    lanes = rate // 8
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(lanes):
            state[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        keccak_f1600(state)
    out = bytearray()
    while len(out) < out_len:
        for i in range(lanes):
            out += state[i].to_bytes(8, "little")
            if len(out) >= out_len:
                break
        if len(out) < out_len:
            keccak_f1600(state)
    return bytes(out[:out_len])


def keccak256_py(data: bytes) -> bytes:
    """Pure-Python keccak-256 — the bootstrap oracle the native and
    device paths are tested against."""
    return _keccak(bytes(data), 136, 32)


def keccak512_py(data: bytes) -> bytes:
    return _keccak(bytes(data), 72, 64)


_keccak256_impl = None
_keccak512_impl = None


def _bind():
    """Prefer the native C++ sponge (khipu_tpu/native/csrc/keccak.cc,
    ~500x the pure-Python speed); fall back to Python where g++ is
    unavailable. Bound lazily on first hash — binding may compile the
    library, which must not happen at import time. Tests pin
    native == python == device."""
    global _keccak256_impl, _keccak512_impl
    try:
        from khipu_tpu.native import keccak as native

        if native.available():
            _keccak256_impl = native.keccak256
            _keccak512_impl = native.keccak512
            return
    except Exception:
        pass
    import logging

    logging.getLogger(__name__).warning(
        "native keccak unavailable; using the ~500x slower pure-Python path"
    )
    _keccak256_impl = keccak256_py
    _keccak512_impl = keccak512_py


def keccak256(data: bytes) -> bytes:
    """keccak-256 (rate 136); == reference kec256 (crypto/package.scala:37)."""
    if _keccak256_impl is None:
        _bind()
    return _keccak256_impl(bytes(data))


def keccak512(data: bytes) -> bytes:
    """keccak-512 (rate 72); used by Ethash dataset generation."""
    if _keccak512_impl is None:
        _bind()
    return _keccak512_impl(bytes(data))


def sha3_256(data: bytes) -> bytes:
    """NIST SHA3-256 (domain 0x06) — same sponge, used only to
    cross-validate the permutation against hashlib."""
    return _keccak(bytes(data), 136, 32, domain=0x06)
