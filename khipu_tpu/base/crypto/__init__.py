"""Host-side cryptography.

Covers the roles of the reference's khipu-base crypto package
(khipu-base/src/main/scala/khipu/crypto/: kec256/kec512, sha256,
ripemd160, secp256k1 ECDSA) and khipu-eth's zksnark/BN128 + Blake2bf —
all pure Python (no external crypto deps in the image). The *batched*
Keccak hot path lives on-device in khipu_tpu.ops; these are the scalar
reference implementations and the test oracle.
"""

from khipu_tpu.base.crypto.keccak import keccak256, keccak512  # noqa: F401
