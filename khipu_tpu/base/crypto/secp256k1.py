"""secp256k1 ECDSA: sign (RFC 6979 deterministic) + public-key recovery.

Role of the reference's ECDSASignature (khipu-eth/.../crypto/
ECDSASignature.scala:115 recover, :480 sign via spongycastle): tx-sender
recovery with EIP-155 replay protection and low-s (EIP-2) enforcement.
The curve's double-scalar multiplication — the hot loop of
recover/verify/ECDH/keygen — runs in C++ (native/csrc/secp256k1.cc,
differential-tested against the pure-Python Jacobian ladder kept here
as the no-toolchain fallback); protocol math (RFC 6979, mod-n algebra,
recid bookkeeping) stays in Python.

Tested against the EIP-155 example transaction (signing hash, v/r/s,
sender round-trip) and cross-validated against the OpenSSL-backed
``cryptography`` package where available.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

from khipu_tpu.base.crypto.keccak import keccak256

# Curve: y^2 = x^3 + 7 over F_P
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
HALF_N = N // 2

# Affine point = (x, y) ints, or None for infinity.
Point = Optional[Tuple[int, int]]

# ---------------------------------------------------------- native path
# C++ double-scalar multiplication (native/csrc/secp256k1.cc) — the hot
# ~4k field mults of recover/verify/ECDH/keygen. Protocol math (RFC
# 6979, mod-n algebra, recid bookkeeping) stays in Python; falls back
# to the pure-Python Jacobian ladder when no toolchain is available.

_native_checked = False
_native_lib = None


def _native():
    global _native_checked, _native_lib
    if not _native_checked:
        _native_checked = True
        try:
            from khipu_tpu.native.build import load_library

            lib = load_library()
            if lib is not None and hasattr(lib, "khipu_ec_mul_add"):
                import ctypes

                lib.khipu_ec_mul_add.argtypes = [ctypes.c_char_p] * 6 + [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                ]
                lib.khipu_ec_mul_add.restype = ctypes.c_int
                if hasattr(lib, "khipu_ecdsa_recover_batch"):
                    lib.khipu_ecdsa_recover_batch.argtypes = [
                        ctypes.c_int
                    ] + [ctypes.c_char_p] * 5
                    lib.khipu_ecdsa_recover_batch.restype = ctypes.c_int
                _native_lib = lib
        except Exception:
            _native_lib = None
    return _native_lib


def _mul_add(p1: Point, k1: int, p2: Point, k2: int,
             use_g1: bool = False, use_g2: bool = False) -> Point:
    """k1*P1 + k2*P2 (use_gN selects the generator for that base)."""
    lib = _native()
    if lib is not None:
        import ctypes

        def enc(p, use_g):
            if use_g:
                return None, None
            return (p[0].to_bytes(32, "big"), p[1].to_bytes(32, "big"))

        outx = ctypes.create_string_buffer(32)
        outy = ctypes.create_string_buffer(32)
        a = enc(p1, use_g1) if k1 else (None, None)
        b = enc(p2, use_g2) if k2 else (None, None)
        rc = lib.khipu_ec_mul_add(
            a[0], a[1], k1.to_bytes(32, "big") if k1 else None,
            b[0], b[1], k2.to_bytes(32, "big") if k2 else None,
            outx, outy,
        )
        if rc == 1:
            return None
        return (
            int.from_bytes(outx.raw, "big"),
            int.from_bytes(outy.raw, "big"),
        )
    # pure-Python fallback
    acc: _JPoint = _J_INF
    if k1:
        base1 = (GX, GY) if use_g1 else p1
        acc = _j_mul(_to_jacobian(base1), k1)
    if k2:
        base2 = (GX, GY) if use_g2 else p2
        acc = _j_add(acc, _j_mul(_to_jacobian(base2), k2))
    return _from_jacobian(acc)


class SignatureError(Exception):
    pass


# ---------------------------------------------------------------- group ops
# Jacobian coordinates (X, Y, Z): x = X/Z^2, y = Y/Z^3. Avoids a modular
# inverse per addition; one inverse at the end of a scalar multiply.

_JPoint = Tuple[int, int, int]  # Z == 0 encodes infinity
_J_INF: _JPoint = (1, 1, 0)


def _j_double(p: _JPoint) -> _JPoint:
    X, Y, Z = p
    if Z == 0 or Y == 0:
        return _J_INF
    S = (4 * X * Y * Y) % P
    M = (3 * X * X) % P  # a == 0
    X2 = (M * M - 2 * S) % P
    Y2 = (M * (S - X2) - 8 * Y * Y * Y * Y) % P
    Z2 = (2 * Y * Z) % P
    return (X2, Y2, Z2)


def _j_add(p: _JPoint, q: _JPoint) -> _JPoint:
    if p[2] == 0:
        return q
    if q[2] == 0:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = (Z1 * Z1) % P
    Z2Z2 = (Z2 * Z2) % P
    U1 = (X1 * Z2Z2) % P
    U2 = (X2 * Z1Z1) % P
    S1 = (Y1 * Z2 * Z2Z2) % P
    S2 = (Y2 * Z1 * Z1Z1) % P
    if U1 == U2:
        if S1 != S2:
            return _J_INF
        return _j_double(p)
    H = (U2 - U1) % P
    R = (S2 - S1) % P
    HH = (H * H) % P
    HHH = (H * HH) % P
    V = (U1 * HH) % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - S1 * HHH) % P
    Z3 = (H * Z1 * Z2) % P
    return (X3, Y3, Z3)


def _to_jacobian(p: Point) -> _JPoint:
    if p is None:
        return _J_INF
    return (p[0], p[1], 1)


def _from_jacobian(p: _JPoint) -> Point:
    X, Y, Z = p
    if Z == 0:
        return None
    zinv = pow(Z, -1, P)
    zinv2 = (zinv * zinv) % P
    return ((X * zinv2) % P, (Y * zinv2 * zinv) % P)


def _j_mul(p: _JPoint, k: int) -> _JPoint:
    k %= N
    acc = _J_INF
    while k:
        if k & 1:
            acc = _j_add(acc, p)
        p = _j_double(p)
        k >>= 1
    return acc


def point_mul(p: Point, k: int) -> Point:
    if p is None or k % N == 0:
        return None
    return _mul_add(p, k % N, None, 0)


def point_add(p: Point, q: Point) -> Point:
    return _from_jacobian(_j_add(_to_jacobian(p), _to_jacobian(q)))


_G: _JPoint = (GX, GY, 1)


def is_on_curve(p: Point) -> bool:
    if p is None:
        return False
    x, y = p
    return (y * y - x * x * x - 7) % P == 0


# ---------------------------------------------------------------- key ops


def privkey_to_pubkey(priv: bytes) -> bytes:
    """32-byte private key -> 64-byte uncompressed pubkey (x || y)."""
    d = int.from_bytes(priv, "big")
    if not 0 < d < N:
        raise SignatureError("private key out of range")
    pub = _mul_add(None, d, None, 0, use_g1=True)
    return pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


def pubkey_to_address(pubkey_xy: bytes) -> bytes:
    """64-byte pubkey -> 20-byte address (keccak256(pub)[12:],
    SignedTransaction.scala:143 semantics)."""
    if len(pubkey_xy) != 64:
        raise SignatureError("expected 64-byte uncompressed pubkey")
    return keccak256(pubkey_xy)[12:]


# ------------------------------------------------------------------- sign


def _rfc6979_gen(msg_hash: bytes, priv: bytes):
    """Deterministic nonce stream (RFC 6979 §3.2, HMAC-SHA256) — what
    geth/parity use, so fixture signatures are reproducible across runs.
    Yields candidate k values; the caller advances the generator (the
    §3.2.h K/V update) when a candidate produces r == 0 or s == 0.
    Per §2.3.4/§3.2, h1 enters the HMAC as bits2octets = int(h1) mod N."""
    holen = 32
    V = b"\x01" * holen
    K = b"\x00" * holen
    x = priv.rjust(32, b"\x00")
    h1 = (int.from_bytes(msg_hash, "big") % N).to_bytes(32, "big")
    K = hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 0 < k < N:
            yield k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


def ecdsa_sign(msg_hash: bytes, priv: bytes) -> Tuple[int, int, int]:
    """Sign a 32-byte hash; returns (recovery_id, r, s) with low s
    (EIP-2: s <= N/2, flipping the recovery bit when normalizing)."""
    if len(msg_hash) != 32:
        raise SignatureError("message hash must be 32 bytes")
    d = int.from_bytes(priv, "big")
    if not 0 < d < N:
        raise SignatureError("private key out of range")
    z = int.from_bytes(msg_hash, "big")
    for k in _rfc6979_gen(msg_hash, priv):
        R = _mul_add(None, k, None, 0, use_g1=True)
        r = R[0] % N
        if r == 0:
            continue  # next k from the RFC 6979 K/V update loop
        s = (pow(k, -1, N) * (z + r * d)) % N
        if s == 0:
            continue
        recid = (R[1] & 1) | (2 if R[0] >= N else 0)
        if s > HALF_N:
            s = N - s
            recid ^= 1
        return recid, r, s


# ---------------------------------------------------------------- recover


def ecdsa_recover(msg_hash: bytes, recid: int, r: int, s: int) -> bytes:
    """Recover the 64-byte public key from a signature.

    recid in 0..3 (bit 0: parity of R.y, bit 1: r overflowed N).
    Raises SignatureError for invalid signatures.
    """
    if not 0 <= recid <= 3:
        raise SignatureError(f"recovery id {recid} out of range")
    if not (0 < r < N and 0 < s < N):
        raise SignatureError("r/s out of range")
    x = r + (N if recid & 2 else 0)
    if x >= P:
        raise SignatureError("r + N >= P")
    # lift x: y^2 = x^3 + 7; sqrt via exponent (P+1)/4 (P % 4 == 3)
    y_sq = (pow(x, 3, P) + 7) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if (y * y) % P != y_sq:
        raise SignatureError("r is not an x-coordinate on the curve")
    if (y & 1) != (recid & 1):
        y = P - y
    z = int.from_bytes(msg_hash, "big")
    rinv = pow(r, -1, N)
    # Q = r^-1 * (s*R - z*G)
    u1 = (-z * rinv) % N
    u2 = (s * rinv) % N
    Q = _mul_add(None, u1, (x, y), u2, use_g1=True)
    if Q is None:
        raise SignatureError("recovered point at infinity")
    return Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")


def ecdsa_recover_batch(items) -> list:
    """Recover many signatures in ONE native call (the tx-sender hot
    loop: one ctypes crossing per block, Strauss-Shamir wNAF ladders,
    one Montgomery batch inversion across the whole batch). ``items``
    is a list of (msg_hash, recid, r, s); returns a list of 64-byte
    public keys, None where the signature is invalid. Falls back to
    per-item :func:`ecdsa_recover` without the native library."""
    lib = _native()
    if lib is None or not hasattr(lib, "khipu_ecdsa_recover_batch"):
        out = []
        for msg_hash, recid, r, s in items:
            if len(msg_hash) != 32:  # same verdict as the native path
                out.append(None)
                continue
            try:
                out.append(ecdsa_recover(msg_hash, recid, r, s))
            except SignatureError:
                out.append(None)
        return out
    import ctypes

    n = len(items)
    if n == 0:
        return []
    msg = bytearray(32 * n)
    rec = bytearray(n)
    rs = bytearray(64 * n)
    for i, (msg_hash, recid, r, s) in enumerate(items):
        if len(msg_hash) != 32 or not (
            0 <= recid <= 3 and 0 < r < N and 0 < s < N
        ):
            # a non-32-byte hash slice-assigned below would RESIZE the
            # packed buffer, misaligning every later entry — mark the
            # entry invalid instead, like the r/s range check
            rec[i] = 255  # native rejects recid 255 -> None
            continue
        msg[32 * i : 32 * i + 32] = msg_hash
        rec[i] = recid
        rs[64 * i : 64 * i + 32] = r.to_bytes(32, "big")
        rs[64 * i + 32 : 64 * i + 64] = s.to_bytes(32, "big")
    out_buf = ctypes.create_string_buffer(64 * n)
    ok_buf = ctypes.create_string_buffer(n)
    lib.khipu_ecdsa_recover_batch(
        ctypes.c_int(n),
        bytes(msg),
        bytes(rec),
        bytes(rs),
        out_buf,
        ok_buf,
    )
    results = []
    raw = out_buf.raw
    oks = ok_buf.raw
    for i in range(n):
        results.append(raw[64 * i : 64 * i + 64] if oks[i] else None)
    return results


def ecdsa_verify(msg_hash: bytes, pubkey_xy: bytes, r: int, s: int) -> bool:
    if not (0 < r < N and 0 < s < N):
        return False
    x = int.from_bytes(pubkey_xy[:32], "big")
    y = int.from_bytes(pubkey_xy[32:], "big")
    if not is_on_curve((x, y)):
        return False
    z = int.from_bytes(msg_hash, "big")
    sinv = pow(s, -1, N)
    u1 = (z * sinv) % N
    u2 = (r * sinv) % N
    p = _mul_add(None, u1, (x, y), u2, use_g1=True)
    if p is None:
        return False
    return p[0] % N == r
