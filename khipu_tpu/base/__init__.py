"""Primitives layer (L0): bytes, nibbles, RLP, hashing, big-int helpers.

Mirrors the role of the reference's ``khipu-base`` module
(khipu-base/src/main/scala/khipu/): DataWord/Hash/RLP/MPT primitives —
except arbitrary-precision arithmetic uses Python ints (the EVM word is a
plain ``int`` reduced mod 2**256, see khipu_tpu.evm) and the hashing hot
path is delegated to batched device kernels in khipu_tpu.ops.
"""

from khipu_tpu.base.bytes_util import (  # noqa: F401
    big_endian_to_int,
    bytes_to_hex,
    hex_to_bytes,
    int_to_big_endian,
    int_to_fixed_bytes,
    xor_bytes,
)
from khipu_tpu.base.crypto.keccak import keccak256, keccak512  # noqa: F401
from khipu_tpu.base.rlp import (  # noqa: F401
    RLPList,
    rlp_decode,
    rlp_encode,
)

# keccak256(b"") — ubiquitous sentinel (empty account code hash).
EMPTY_KECCAK = bytes.fromhex(
    "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
)
# keccak256(rlp(b"")) — root hash of an empty Merkle Patricia Trie.
EMPTY_TRIE_HASH = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)
