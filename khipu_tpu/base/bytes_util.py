"""Byte-level helpers.

Role of the reference's ``khipu-base`` BytesUtil/DataWord byte plumbing
(khipu-base/src/main/scala/khipu/util/BytesUtil.scala,
khipu-base/src/main/scala/khipu/DataWord.scala) in plain Python.
"""

from __future__ import annotations


def int_to_big_endian(value: int) -> bytes:
    """Minimal big-endian encoding; 0 encodes to b'' (RLP scalar rule)."""
    if value == 0:
        return b""
    return value.to_bytes((value.bit_length() + 7) // 8, "big")


def big_endian_to_int(data: bytes) -> int:
    return int.from_bytes(data, "big")


def int_to_fixed_bytes(value: int, length: int) -> bytes:
    """Big-endian, left-zero-padded to exactly ``length`` bytes."""
    return value.to_bytes(length, "big")


def xor_bytes(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b, strict=True))


def hex_to_bytes(s: str) -> bytes:
    if s.startswith(("0x", "0X")):
        s = s[2:]
    if len(s) % 2:
        s = "0" + s
    return bytes.fromhex(s)


def bytes_to_hex(b: bytes, prefix: bool = True) -> str:
    return ("0x" if prefix else "") + b.hex()
