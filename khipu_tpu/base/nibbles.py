"""Hex-prefix (compact) nibble encoding for Merkle Patricia Tries.

Parity with the reference (khipu-base/src/main/scala/khipu/trie/
HexPrefix.scala: encode:11, bytesToNibbles:47). A nibble path is
represented as ``bytes`` whose elements are 0-15.

Compact encoding packs the leaf/extension flag and odd-length bit into
the first nibble:  flags = 2*is_leaf + is_odd.
"""

from __future__ import annotations

from typing import Tuple


# byte -> (hi, lo) nibble pair, precomputed: this runs per trie node on
# every replay/commit hot path (2x the loop formulation)
_EXPAND = [bytes((b >> 4, b & 0x0F)) for b in range(256)]


def bytes_to_nibbles(data: bytes) -> bytes:
    """Expand each byte into (hi, lo) nibbles."""
    return b"".join(map(_EXPAND.__getitem__, data))


def nibbles_to_bytes(nibbles: bytes) -> bytes:
    if len(nibbles) % 2:
        raise ValueError("odd nibble count cannot pack to bytes")
    it = iter(nibbles)
    return bytes(a << 4 | b for a, b in zip(it, it))


def hp_encode(nibbles: bytes, is_leaf: bool) -> bytes:
    """Hex-prefix encode a nibble path (HexPrefix.encode:11)."""
    odd = len(nibbles) % 2
    flag = (2 if is_leaf else 0) + odd
    if odd:
        prefixed = bytes([flag]) + nibbles
    else:
        prefixed = bytes([flag, 0]) + nibbles
    return nibbles_to_bytes(prefixed)


def hp_decode(data: bytes) -> Tuple[bytes, bool]:
    """Inverse of hp_encode → (nibbles, is_leaf)."""
    if not data:
        raise ValueError("empty hex-prefix encoding")
    nibbles = bytes_to_nibbles(data)
    flag = nibbles[0]
    is_leaf = bool(flag & 2)
    if flag & 1:  # odd
        return nibbles[1:], is_leaf
    return nibbles[2:], is_leaf
