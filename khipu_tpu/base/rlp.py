"""Recursive Length Prefix (RLP) codec.

Behavioral parity with the reference codec
(khipu-base/src/main/scala/khipu/rlp/RLP.scala:35 — encode/decode of the
RLPValue/RLPList ADT). Items are ``bytes`` or (nested) lists of items;
``RLPList`` is an alias kept for call-site readability.

Encoding rules (Yellow Paper app. B):
  * single byte < 0x80 encodes as itself
  * 0-55 byte string: 0x80+len prefix
  * longer string: 0xb7+len(len) prefix then big-endian length
  * 0-55 byte list payload: 0xc0+len prefix
  * longer list payload: 0xf7+len(len) prefix then big-endian length
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple, Union

from khipu_tpu.base.bytes_util import big_endian_to_int, int_to_big_endian

RLPItem = Union[bytes, bytearray, Sequence[Any]]
RLPList = list  # decoded lists are plain Python lists


class RLPError(Exception):
    pass


# Real chain objects nest a handful of levels (block = list of lists of
# tx fields; MPT nodes encode one node at a time). A cap well below
# Python's recursion limit turns adversarial deeply-nested peer input
# into a clean RLPError instead of an uncatchable RecursionError.
MAX_DEPTH = 64


def _encode_length(length: int, offset: int) -> bytes:
    if length < 56:
        return bytes([offset + length])
    length_bytes = int_to_big_endian(length)
    return bytes([offset + 55 + len(length_bytes)]) + length_bytes


# single-byte string headers, precomputed (hot: every trie node field)
_STR_HDR = [bytes([0x80 + n]) for n in range(56)]


def _py_rlp_encode(item: RLPItem, _depth: int = 0) -> bytes:
    """Encode bytes / nested lists of bytes (pure-Python reference)."""
    if type(item) is bytes:  # fast path: the overwhelmingly common case
        n = len(item)
        if n == 1 and item[0] < 0x80:
            return item
        if n < 56:
            return _STR_HDR[n] + item
        return _encode_length(n, 0x80) + item
    if isinstance(item, bytearray):
        return _py_rlp_encode(bytes(item), _depth)
    if isinstance(item, (list, tuple)):
        if _depth >= MAX_DEPTH:
            raise RLPError("RLP nesting exceeds MAX_DEPTH")
        payload = b"".join(
            [_py_rlp_encode(sub, _depth + 1) for sub in item]
        )
        return _encode_length(len(payload), 0xC0) + payload
    raise RLPError(f"cannot RLP-encode {type(item)!r}")


rlp_encode = _py_rlp_encode  # rebound to the C codec below when built


def _decode_at(data: bytes, pos: int, _depth: int = 0) -> Tuple[Any, int]:
    if pos >= len(data):
        raise RLPError("truncated RLP input")
    b0 = data[pos]
    if b0 < 0x80:
        return bytes([b0]), pos + 1
    if b0 <= 0xB7:  # short string
        length = b0 - 0x80
        end = pos + 1 + length
        if end > len(data):
            raise RLPError("truncated string")
        s = data[pos + 1 : end]
        if length == 1 and s[0] < 0x80:
            raise RLPError("non-canonical single byte")
        return s, end
    if b0 <= 0xBF:  # long string
        ll = b0 - 0xB7
        if pos + 1 + ll > len(data):
            raise RLPError("truncated length")
        length = int.from_bytes(data[pos + 1 : pos + 1 + ll], "big")
        if length < 56 or (ll > 1 and data[pos + 1] == 0):
            raise RLPError("non-canonical length")
        start = pos + 1 + ll
        end = start + length
        if end > len(data):
            raise RLPError("truncated string")
        return data[start:end], end
    if b0 <= 0xF7:  # short list
        length = b0 - 0xC0
        end = pos + 1 + length
        if end > len(data):
            raise RLPError("truncated list")
        return _decode_list(data, pos + 1, end, _depth), end
    # long list
    ll = b0 - 0xF7
    if pos + 1 + ll > len(data):
        raise RLPError("truncated length")
    length = int.from_bytes(data[pos + 1 : pos + 1 + ll], "big")
    if length < 56 or (ll > 1 and data[pos + 1] == 0):
        raise RLPError("non-canonical length")
    start = pos + 1 + ll
    end = start + length
    if end > len(data):
        raise RLPError("truncated list")
    return _decode_list(data, start, end, _depth), end


def _decode_list(data: bytes, start: int, end: int, _depth: int = 0) -> List[Any]:
    if _depth >= MAX_DEPTH:
        raise RLPError("RLP nesting exceeds MAX_DEPTH")
    items: List[Any] = []
    pos = start
    while pos < end:
        item, pos = _decode_at(data, pos, _depth + 1)
        if pos > end:
            raise RLPError("list element overruns list payload")
        items.append(item)
    return items


def _py_rlp_decode(data: bytes) -> Any:
    """Decode a single RLP item; raises on trailing bytes."""
    item, pos = _decode_at(bytes(data), 0)
    if pos != len(data):
        raise RLPError(f"trailing bytes after RLP item ({len(data) - pos})")
    return item


rlp_decode = _py_rlp_decode  # rebound to the C codec below when built


# Native C codec (khipu_tpu/native/csrc_ext/rlp_ext.c): bit-identical
# semantics, ~5-7x faster — RLP encode/decode is the hottest host loop
# of trie commits (every node rebuild encodes; every node read
# decodes). The pure-Python implementations above remain the
# no-toolchain fallback and the differential oracle (tests fuzz
# equality).
#
# Binding: a FRESH .so binds directly at import (a dlopen; zero
# per-call overhead, the steady-state case). A missing/stale .so
# compiles on a background thread; until it lands, the module exports
# one-hop forwarders whose target is swapped on completion — so even
# callers that imported the names BY VALUE during the compile get the
# fast codec, and no import ever stalls on a gcc subprocess.
def _bind_rlp_ext(forwarded: bool) -> bool:
    global rlp_encode, rlp_decode
    try:
        from khipu_tpu.native.build import load_rlp_ext

        ext = load_rlp_ext()
        if ext is None:
            return False
        ext._set_error(RLPError)
        if forwarded:
            _impl[0] = ext.encode
            _impl[1] = ext.decode
        rlp_encode = ext.encode  # type: ignore[assignment]
        rlp_decode = ext.decode  # type: ignore[assignment]
        return True
    except Exception:  # toolchain quirks must never break the codec
        return False


_impl = [_py_rlp_encode, _py_rlp_decode]


def _init_rlp_ext() -> None:
    from khipu_tpu.native.build import rlp_ext_is_fresh

    if rlp_ext_is_fresh():
        _bind_rlp_ext(forwarded=False)
    else:
        global rlp_encode, rlp_decode

        def rlp_encode(item):  # noqa: F811 - forwarder until compiled
            return _impl[0](item)

        def rlp_decode(data):  # noqa: F811
            return _impl[1](data)

        import threading

        threading.Thread(
            target=_bind_rlp_ext, args=(True,), daemon=True
        ).start()


try:
    _init_rlp_ext()
except Exception:
    pass


def rlp_decode_first(data: bytes):
    """Decode the first RLP item, tolerating trailing bytes — EIP-8
    handshake bodies append random padding after the list. Returns
    (item, bytes_consumed)."""
    return _decode_at(data, 0)


def rlp_encode_int(value: int) -> bytes:
    """Encode a non-negative scalar (minimal big-endian, 0 -> empty string)."""
    if value < 0:
        raise RLPError("RLP scalars are non-negative")
    return rlp_encode(int_to_big_endian(value))


def decode_int(data: bytes) -> int:
    """Interpret a decoded RLP string as a scalar."""
    if len(data) > 0 and data[0] == 0:
        raise RLPError("leading zero in RLP scalar")
    return big_endian_to_int(data)
