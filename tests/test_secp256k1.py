"""secp256k1 ECDSA tests (SURVEY.md §4 unit-test plan; parity target
khipu-eth/.../crypto/ECDSASignature.scala:115 recover, :480 sign).

The EIP-155 example transaction is the golden vector: signing hash,
deterministic r/s under RFC 6979, and sender-address recovery must all
match the published values.
"""

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.base.crypto.secp256k1 import (
    HALF_N,
    N,
    SignatureError,
    ecdsa_recover,
    ecdsa_sign,
    ecdsa_verify,
    is_on_curve,
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.base.rlp import rlp_encode

# EIP-155 example: nonce=9, gasprice=20 gwei, gas=21000,
# to=0x3535...35, value=1 ether, chainId=1, priv=0x46..46.
EIP155_PRIV = bytes.fromhex(
    "4646464646464646464646464646464646464646464646464646464646464646"
)
EIP155_SIGNING_HASH = bytes.fromhex(
    "daf5a779ae972f972197303d7b574746c7ef83eadac0f2791ad23db92e4c8e53"
)
EIP155_R = 18515461264373351373200002665853028612451056578545711640558177340181847433846
EIP155_S = 46948507304638947509940763649030358759909902576025900602547168820602576006531
EIP155_V = 37  # chain_id 1, parity 0 -> 35 + 0


def eip155_signing_payload():
    from khipu_tpu.base.bytes_util import int_to_big_endian as i2b

    return rlp_encode(
        [
            i2b(9),
            i2b(20 * 10**9),
            i2b(21000),
            bytes.fromhex("3535353535353535353535353535353535353535"),
            i2b(10**18),
            b"",
            i2b(1),  # chain id
            b"",
            b"",
        ]
    )


class TestEIP155Vector:
    def test_signing_hash(self):
        assert keccak256(eip155_signing_payload()) == EIP155_SIGNING_HASH

    def test_deterministic_signature(self):
        recid, r, s = ecdsa_sign(EIP155_SIGNING_HASH, EIP155_PRIV)
        assert r == EIP155_R
        assert s == EIP155_S
        assert 35 + 2 * 1 + recid == EIP155_V

    def test_recover_matches_signer(self):
        pub = privkey_to_pubkey(EIP155_PRIV)
        recid, r, s = ecdsa_sign(EIP155_SIGNING_HASH, EIP155_PRIV)
        rec = ecdsa_recover(EIP155_SIGNING_HASH, recid, r, s)
        assert rec == pub
        assert pubkey_to_address(rec) == pubkey_to_address(pub)


class TestSignRecoverVerify:
    def test_round_trips(self):
        for i in range(1, 6):
            priv = i.to_bytes(32, "big")
            pub = privkey_to_pubkey(priv)
            assert is_on_curve(
                (int.from_bytes(pub[:32], "big"), int.from_bytes(pub[32:], "big"))
            )
            msg = keccak256(b"khipu" + bytes([i]))
            recid, r, s = ecdsa_sign(msg, priv)
            assert ecdsa_recover(msg, recid, r, s) == pub
            assert ecdsa_verify(msg, pub, r, s)

    def test_low_s_enforced(self):
        for i in range(1, 20):
            msg = keccak256(bytes([i]) * 7)
            _, _, s = ecdsa_sign(msg, (i * 7919).to_bytes(32, "big"))
            assert 0 < s <= HALF_N

    def test_wrong_message_does_not_verify(self):
        priv = (42).to_bytes(32, "big")
        pub = privkey_to_pubkey(priv)
        msg = keccak256(b"a")
        recid, r, s = ecdsa_sign(msg, priv)
        assert not ecdsa_verify(keccak256(b"b"), pub, r, s)
        assert ecdsa_recover(keccak256(b"b"), recid, r, s) != pub


class TestInvalidInputs:
    def test_recid_out_of_range(self):
        with pytest.raises(SignatureError):
            ecdsa_recover(b"\x01" * 32, 4, 1, 1)

    def test_r_s_out_of_range(self):
        for r, s in ((0, 1), (1, 0), (N, 1), (1, N)):
            with pytest.raises(SignatureError):
                ecdsa_recover(b"\x01" * 32, 0, r, s)
            assert not ecdsa_verify(b"\x01" * 32, b"\x00" * 64, r, s)

    def test_r_not_on_curve(self):
        # x = 5 has no curve point with the tested parity... pick an x
        # known to be a non-residue: search deterministically.
        from khipu_tpu.base.crypto.secp256k1 import P

        x = next(
            x
            for x in range(2, 50)
            if pow((pow(x, 3, P) + 7) % P, (P - 1) // 2, P) != 1
        )
        with pytest.raises(SignatureError):
            ecdsa_recover(b"\x01" * 32, 0, x, 1)

    def test_bad_hash_length(self):
        with pytest.raises(SignatureError):
            ecdsa_sign(b"\x01" * 31, (1).to_bytes(32, "big"))

    def test_bad_priv(self):
        with pytest.raises(SignatureError):
            ecdsa_sign(b"\x01" * 32, b"\x00" * 32)
        with pytest.raises(SignatureError):
            ecdsa_sign(b"\x01" * 32, N.to_bytes(32, "big"))


class TestNativeCurveOps:
    """C++ double-scalar multiplication (native/csrc/secp256k1.cc) vs
    the pure-Python Jacobian ladder — bit-identical on random scalars,
    generator bases, infinity, and the protocol round trips."""

    def test_differential_vs_python(self):
        import random

        from khipu_tpu.base.crypto import secp256k1 as S

        if S._native() is None:
            pytest.skip("native toolchain unavailable")
        random.seed(5)
        for trial in range(25):
            k1 = random.randrange(0, S.N)
            k2 = random.randrange(0, S.N)
            d = random.randrange(1, S.N)
            base = S._from_jacobian(S._j_mul(S._G, d))
            want = S._from_jacobian(
                S._j_add(
                    S._j_mul(S._G, k1),
                    S._j_mul((base[0], base[1], 1), k2),
                )
            )
            got = S._mul_add(None, k1, base, k2, use_g1=True)
            assert got == want, f"trial {trial}"

    def test_infinity_and_zero_scalars(self):
        import random

        from khipu_tpu.base.crypto import secp256k1 as S

        if S._native() is None:
            pytest.skip("native toolchain unavailable")
        random.seed(6)
        k = random.randrange(1, S.N)
        # k*G + (N-k)*G == infinity
        assert S._mul_add(
            None, k, None, S.N - k, use_g1=True, use_g2=True
        ) is None
        assert S._mul_add(None, 0, None, 0) is None
        one_g = S._mul_add(None, 1, None, 0, use_g1=True)
        assert one_g == (S.GX, S.GY)


class TestRecoverBatchGuards:
    def test_bad_hash_length_flagged_not_packed(self):
        """A non-32-byte msg_hash must yield None for THAT item only —
        not corrupt the packed buffer layout for its neighbours."""
        from khipu_tpu.base.crypto.secp256k1 import ecdsa_recover_batch

        pub = privkey_to_pubkey(EIP155_PRIV)
        addr = pubkey_to_address(pub)
        msgs = [b"a" * 32, b"short", b"b" * 31, b"c" * 33, b"d" * 32]
        items = []
        for m in msgs:
            if len(m) == 32:
                recid, r, s = ecdsa_sign(m, EIP155_PRIV)
                items.append((m, recid, r, s))
            else:
                items.append((m, 0, 1, 1))
        out = ecdsa_recover_batch(items)
        assert len(out) == len(msgs)
        for m, got in zip(msgs, out):
            if len(m) == 32:
                assert got is not None, m
                assert pubkey_to_address(got) == addr
            else:
                assert got is None, m

    def test_bad_scalars_still_rejected(self):
        from khipu_tpu.base.crypto.secp256k1 import ecdsa_recover_batch

        h = keccak256(b"x")
        recid, r, s = ecdsa_sign(h, EIP155_PRIV)
        out = ecdsa_recover_batch(
            [(h, recid, r, s), (h, 9, r, s), (h, recid, 0, s),
             (h, recid, r, N)]
        )
        assert out[0] is not None
        assert out[1] is None and out[2] is None and out[3] is None
