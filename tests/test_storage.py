"""Storage layer: SPI engines, FIFO cache, unconfirmed ring, façade."""

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.storage import (
    AppStateStorage,
    FIFOCache,
    MemoryBlockDataSource,
    MemoryKeyValueDataSource,
    MemoryNodeDataSource,
    NodeStorage,
    ReadOnlyNodeStorage,
    SimpleMapWithUnconfirmed,
    Storages,
)
from khipu_tpu.storage.block_storage import BlockNumbers, BlockNumberStorage
from khipu_tpu.storage.datasource import verify_content_address
from khipu_tpu.trie.mpt import MerklePatriciaTrie


def test_fifo_cache_eviction_and_hit_rate():
    c = FIFOCache(2)
    c.put(b"a", 1)
    c.put(b"b", 2)
    c.put(b"c", 3)  # evicts a
    assert c.get(b"a") is None
    assert c.get(b"b") == 2
    assert c.get(b"c") == 3
    assert c.read_count == 3
    assert abs(c.hit_rate - 2 / 3) < 1e-9


def test_memory_kv_roundtrip():
    s = MemoryKeyValueDataSource()
    s.update([], {b"k1": b"v1", b"k2": b"v2"})
    assert s.get(b"k1") == b"v1"
    s.update([b"k1"], {})
    assert s.get(b"k1") is None
    assert s.count == 1


def test_content_address_verify():
    v = b"some node rlp"
    assert verify_content_address(keccak256(v), v)
    assert not verify_content_address(b"\x00" * 32, v)


def test_block_data_source_best_number():
    s = MemoryBlockDataSource()
    assert s.best_block_number == -1
    s.put(5, b"five")
    s.put(3, b"three")
    assert s.best_block_number == 5
    assert s.get(3) == b"three"


def test_unconfirmed_ring_trails_tip():
    src = MemoryKeyValueDataSource()
    ring = SimpleMapWithUnconfirmed(src, depth=3)
    for i in range(5):  # 5 block batches, depth 3
        ring.update([], {f"k{i}".encode(): f"v{i}".encode()})
    # oldest 2 flushed, newest 3 buffered
    assert src.get(b"k0") == b"v0" and src.get(b"k1") == b"v1"
    assert src.get(b"k4") is None
    assert ring.get(b"k4") == b"v4"  # visible through the ring
    ring.clear_unconfirmed()  # reorg: buffered batches dropped
    assert ring.get(b"k4") is None
    assert ring.get(b"k0") == b"v0"


def test_unconfirmed_flush_on_disable():
    src = MemoryKeyValueDataSource()
    ring = SimpleMapWithUnconfirmed(src, depth=10)
    ring.update([], {b"a": b"1"})
    assert src.get(b"a") is None
    ring.set_buffering(False)
    assert src.get(b"a") == b"1"
    ring.update([], {b"b": b"2"})  # unbuffered: straight through
    assert src.get(b"b") == b"2"


def test_node_storage_never_deletes():
    src = MemoryNodeDataSource()
    ns = NodeStorage(src, cache_size=4)
    h = keccak256(b"node")
    ns.put(h, b"node")
    ns.update([h], {})  # delete request swallowed
    assert ns.get(h) == b"node"
    assert src.get(h) == b"node"


def test_node_storage_reorg_buffering():
    src = MemoryNodeDataSource()
    ns = NodeStorage(src, depth=2, cache_size=1024)
    ns.switch_to_unconfirmed()
    h = keccak256(b"x")
    ns.update([], {h: b"x"})
    assert src.get(h) is None  # still buffered
    assert ns.get(h) == b"x"


def test_node_storage_reorg_drops_cached_unconfirmed():
    """After a reorg (clear_unconfirmed), nodes that only ever lived in
    the unconfirmed ring must be gone — including from the read cache —
    so MPTNodeMissingException can drive a re-fetch (ADVICE r1 medium)."""
    src = MemoryNodeDataSource()
    ns = NodeStorage(src, depth=4, cache_size=1024)
    ns.switch_to_unconfirmed()
    h = keccak256(b"orphan")
    ns.update([], {h: b"orphan"})
    assert ns.get(h) == b"orphan"  # populates the cache
    ns.clear_unconfirmed()
    assert ns.get(h) is None


def test_node_storage_reorg_evicts_trie_decode_cache():
    """The MPT layer attaches a decoded-node cache to its source; a
    reorg must evict dropped unconfirmed nodes from it too, or tries
    would keep resolving orphaned hashes instead of raising
    MPTNodeMissingException (which drives the heal/fetch path)."""
    import pytest

    from khipu_tpu.trie.mpt import MerklePatriciaTrie, MPTNodeMissingException

    src = MemoryNodeDataSource()
    ns = NodeStorage(src, depth=4, cache_size=1024)
    trie = MerklePatriciaTrie(ns)
    for i in range(40):  # enough to hash the root (>=32B nodes)
        trie = trie.put(keccak256(bytes([i])), b"v" * 40)
    ns.switch_to_unconfirmed()
    trie.persist()  # nodes land in the unconfirmed ring only
    root = trie.root_hash
    # resolve through a FRESH trie so the decode cache holds ring nodes
    reopened = MerklePatriciaTrie(ns, root_hash=root)
    assert reopened.get(keccak256(bytes([0]))) == b"v" * 40
    ns.clear_unconfirmed()  # reorg: ring dropped before any flush
    with pytest.raises(MPTNodeMissingException):
        MerklePatriciaTrie(ns, root_hash=root).get(keccak256(bytes([0])))


def test_block_numbers_header_storage_fallback():
    """hash_of falls back to the persisted header after a 'restart'
    (fresh BlockNumbers over the same storages) — BlockNumbers.scala
    getHashByBlockNumber semantics."""
    from khipu_tpu.storage.block_storage import BlockBytesStorage
    from khipu_tpu.storage.datasource import MemoryBlockDataSource

    headers = BlockBytesStorage(MemoryBlockDataSource())
    header_rlp = b"\xc3\x01\x02\x03"
    headers.put(7, header_rlp)
    nums = BlockNumberStorage(MemoryKeyValueDataSource())
    nums.put(keccak256(header_rlp), 7)  # persisted pre-"restart"
    bn = BlockNumbers(nums, headers)  # fresh maps = post-restart state
    assert bn.hash_of(7) == keccak256(header_rlp)
    assert bn.number_of(keccak256(header_rlp)) == 7
    assert bn.hash_of(8) is None
    # A removed (orphaned) mapping must NOT be resurrected from the
    # stale header left in block storage.
    bn2 = BlockNumbers(nums, headers)
    bn2.remove(keccak256(header_rlp))
    assert bn2.hash_of(7) is None


def test_readonly_node_storage_isolation():
    src = MemoryNodeDataSource()
    ro = ReadOnlyNodeStorage(src)
    ro.put(b"k", b"v")
    assert ro.get(b"k") == b"v"
    assert src.get(b"k") is None


def test_app_state_storage():
    app = AppStateStorage(MemoryKeyValueDataSource())
    assert app.best_block_number == 0
    app.best_block_number = 123456
    assert app.best_block_number == 123456
    assert not app.fast_sync_done
    app.mark_fast_sync_done()
    assert app.fast_sync_done


def test_block_numbers_bidirectional():
    bn = BlockNumbers(BlockNumberStorage(MemoryKeyValueDataSource()))
    h = keccak256(b"blk")
    bn.put(h, 42)
    assert bn.number_of(h) == 42
    assert bn.hash_of(42) == h
    bn.remove(h)
    assert bn.number_of(h) is None


def test_storages_facade_best_block_number():
    st = Storages("memory")
    st.block_body_storage.put(10, b"body")
    st.receipts_storage.put(9, b"rcpt")
    assert st.best_block_number == 9  # min(body, receipts)
    st.switch_to_unconfirmed()
    st.clear_unconfirmed()
    st.stop()


def test_mpt_over_node_storage():
    """MPT persists through NodeStorage + unconfirmed ring, reopens."""
    st = Storages("memory")
    t = MerklePatriciaTrie(st.account_node_storage)
    for i in range(50):
        t = t.put(f"key{i}".encode(), f"value{i}".encode())
    root = t.root_hash
    t.persist()
    reopened = MerklePatriciaTrie(st.account_node_storage, root_hash=root)
    for i in range(50):
        assert reopened.get(f"key{i}".encode()) == f"value{i}".encode()
