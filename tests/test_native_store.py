"""Native append-log engine tests: the db.engine='native' persistent
path (csrc/store.cc; Kesque role, KesqueNodeDataSource.scala:18-230).

Covers content-address verify + dedup, explicit-key updates and
tombstones, restart survival, torn-tail crash recovery, and the full
Storages suite over the engine.
"""

import os
import struct

import pytest

from khipu_tpu.base.crypto.keccak import keccak256
from khipu_tpu.native.store import available

pytestmark = pytest.mark.skipif(
    not available(), reason="native toolchain unavailable"
)


def test_node_source_roundtrip_and_dedup(tmp_path):
    from khipu_tpu.native.store import NativeNodeDataSource

    src = NativeNodeDataSource(str(tmp_path), "account")
    values = [b"node-%d" % i * (i + 1) for i in range(50)]
    upserts = {keccak256(v): v for v in values}
    src.update([], upserts)
    for k, v in upserts.items():
        assert src.get(k) == v
    assert src.get(b"\x00" * 32) is None
    before = os.path.getsize(tmp_path / "account.log")
    src.update([], upserts)  # re-put: content-addressed dedup, no growth
    assert os.path.getsize(tmp_path / "account.log") == before
    src.stop()


def test_content_address_collision_guard(tmp_path):
    """Two keys sharing the 8-byte short key must not cross-read: the
    store recomputes keccak256(value) on every get (:61-63)."""
    from khipu_tpu.native.store import NativeNodeDataSource

    src = NativeNodeDataSource(str(tmp_path), "n")
    v = b"some node"
    k = keccak256(v)
    src.put(k, v)
    fake = b"\xde\xad" * 12 + k[-8:]  # same short key, different hash
    assert src.get(fake) is None
    src.stop()


def test_kv_update_and_tombstone(tmp_path):
    from khipu_tpu.native.store import NativeKeyValueDataSource

    src = NativeKeyValueDataSource(str(tmp_path), "kv")
    src.put(b"alpha", b"1")
    src.put(b"alpha", b"2")  # newest record wins
    assert src.get(b"alpha") == b"2"
    src.remove(b"alpha")
    assert src.get(b"alpha") is None
    src.put(b"alpha", b"3")  # resurrect after tombstone
    assert src.get(b"alpha") == b"3"
    src.stop()


def test_block_source_best_number(tmp_path):
    from khipu_tpu.native.store import NativeBlockDataSource

    src = NativeBlockDataSource(str(tmp_path), "header")
    assert src.best_block_number == -1
    src.update([], {0: b"h0", 1: b"h1", 2: b"h2"})
    assert src.best_block_number == 2
    src.update([2], {})  # reorg orphaning walks best down
    assert src.best_block_number == 1
    src.stop()
    reopened = NativeBlockDataSource(str(tmp_path), "header")
    assert reopened.get(1) == b"h1"
    assert reopened.get(2) is None  # tombstoned
    # reopen walks down past the tombstone to the highest live block
    assert reopened.best_block_number == 1
    reopened.stop()


def test_restart_survival(tmp_path):
    from khipu_tpu.native.store import NativeNodeDataSource

    src = NativeNodeDataSource(str(tmp_path), "account")
    upserts = {keccak256(b"x%d" % i): b"x%d" % i for i in range(100)}
    src.update([], upserts)
    src.stop()
    again = NativeNodeDataSource(str(tmp_path), "account")
    assert again.count == 100
    for k, v in upserts.items():
        assert again.get(k) == v
    again.stop()


def test_torn_tail_recovery(tmp_path):
    """A crash mid-append leaves a torn record; reopen must truncate it
    and keep everything before (Kafka log-recovery semantics)."""
    from khipu_tpu.native.store import NativeNodeDataSource

    src = NativeNodeDataSource(str(tmp_path), "account")
    good = {keccak256(b"keep%d" % i): b"keep%d" % i for i in range(10)}
    src.update([], good)
    src.stop()
    # simulate torn append: a length header promising more than exists
    with open(tmp_path / "account.log", "ab") as f:
        f.write(struct.pack("<I", 1000) + b"only-a-fragment")
    again = NativeNodeDataSource(str(tmp_path), "account")
    assert again.count == 10
    for k, v in good.items():
        assert again.get(k) == v
    again.stop()


def test_stale_index_rebuilt_from_log(tmp_path):
    """Deleting the index sidecar must not lose data — the log is the
    source of truth and the tail scan rebuilds the index."""
    from khipu_tpu.native.store import NativeNodeDataSource

    src = NativeNodeDataSource(str(tmp_path), "account")
    upserts = {keccak256(b"v%d" % i): b"v%d" % i for i in range(20)}
    src.update([], upserts)
    src.stop()
    os.unlink(tmp_path / "account.idx")
    again = NativeNodeDataSource(str(tmp_path), "account")
    for k, v in upserts.items():
        assert again.get(k) == v
    again.stop()


def test_storages_native_engine_full_chain(tmp_path):
    """Storages(engine='native') + MPT over it + restart: identical
    roots (round-3 brief item 4's 'Done =' bar)."""
    from khipu_tpu.config import fixture_config
    from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
    from khipu_tpu.domain.transaction import Transaction, sign_transaction
    from khipu_tpu.base.crypto.secp256k1 import (
        privkey_to_pubkey,
        pubkey_to_address,
    )
    from khipu_tpu.storage.storages import Storages
    from khipu_tpu.sync.chain_builder import ChainBuilder

    cfg = fixture_config(chain_id=1)
    keys = [(i + 1).to_bytes(32, "big") for i in range(3)]
    addrs = [pubkey_to_address(privkey_to_pubkey(k)) for k in keys]
    alloc = {a: 10**21 for a in addrs}

    st = Storages(engine="native", data_dir=str(tmp_path))
    builder = ChainBuilder(
        Blockchain(st, cfg), cfg, GenesisSpec(alloc=alloc)
    )
    for n in range(3):
        txs = [
            sign_transaction(
                Transaction(n, 10**9, 21000, addrs[(i + 1) % 3], 777),
                keys[i],
                chain_id=1,
            )
            for i in range(3)
        ]
        builder.add_block(txs, coinbase=b"\xaa" * 20)
    head = builder.head
    st.stop()

    st2 = Storages(engine="native", data_dir=str(tmp_path))
    bc2 = Blockchain(st2, cfg)
    assert bc2.best_block_number == 3
    h = bc2.get_header_by_number(3)
    assert h.hash == head.hash
    world = bc2.get_world_state(h.state_root)
    assert world.get_balance(addrs[0]) > 0
    assert bc2.get_account(addrs[1], h.state_root).nonce == 3
    st2.stop()
