"""gRPC bridge tests (SURVEY §2.9 north-star channel): block batches
over real gRPC -> executed, persisted, roots returned; invalid blocks
rejected with a status error."""

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder

grpc = pytest.importorskip("grpc")

from khipu_tpu.bridge import BridgeClient, BridgeServer  # noqa: E402

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {a: 10**21 for a in ADDRS}


def build_blocks(n=4):
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    return [
        builder.add_block(
            [sign_transaction(
                Transaction(i, 10**9, 21000, ADDRS[1], 5), KEYS[0],
                chain_id=1,
            )],
            coinbase=b"\xaa" * 20,
        )
        for i in range(n)
    ]


@pytest.fixture()
def bridge():
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    server = BridgeServer(bc, CFG)
    port = server.start()
    client = BridgeClient(f"127.0.0.1:{port}")
    yield client, bc
    client.close()
    server.stop()


class TestBridge:
    def test_ping(self, bridge):
        client, _ = bridge
        assert client.ping(b"khipu") == b"khipu"

    def test_execute_batch_and_query(self, bridge):
        client, bc = bridge
        blocks = build_blocks(4)
        results = client.execute_blocks(blocks)
        assert [n for n, _ in results] == [1, 2, 3, 4]
        for block, (n, root) in zip(blocks, results):
            assert root == block.header.state_root
        # server persisted the chain
        n, h = client.best_block()
        assert n == 4 and h == blocks[-1].hash
        assert client.get_state_root(4) == blocks[-1].header.state_root
        assert bc.get_account(ADDRS[1], blocks[-1].header.state_root)

    def test_incremental_batches(self, bridge):
        client, _ = bridge
        blocks = build_blocks(4)
        client.execute_blocks(blocks[:2])
        client.execute_blocks(blocks[2:])
        assert client.best_block()[0] == 4

    def test_invalid_block_aborts(self, bridge):
        import dataclasses

        from khipu_tpu.domain.block import Block

        client, _ = bridge
        blocks = build_blocks(1)
        bad = Block(
            dataclasses.replace(blocks[0].header, state_root=b"\x13" * 32),
            blocks[0].body,
        )
        with pytest.raises(grpc.RpcError) as e:
            client.execute_blocks([bad])
        assert e.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert client.best_block()[0] == 0  # nothing persisted

    def test_malformed_batch_rejected(self, bridge):
        client, _ = bridge
        with pytest.raises(grpc.RpcError) as e:
            client._call("ExecuteBlocks", b"\xff\xff not rlp")
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_unknown_root_empty(self, bridge):
        client, _ = bridge
        assert client.get_state_root(99) is None

    def test_stream_node_data_paged_and_range_filtered(self, bridge):
        """ISSUE 11: the rebalance bridge RPC — cursor-paged key
        streaming filtered by ring point ranges, values verifiable by
        content address."""
        from khipu_tpu.base.crypto.keccak import keccak256
        from khipu_tpu.cluster.ring import RING_SIZE, _point

        client, _ = bridge
        nodes = {
            keccak256(b"streamed node %d" % i): b"streamed node %d" % i
            for i in range(20)
        }
        assert client.put_node_data(nodes) == 20
        # full-ring range, small pages: every key comes back exactly
        # once, in cursor order, bit-exact
        got = {}
        cursor, pages = b"", 0
        while True:
            done, cursor, pairs = client.stream_node_data(
                [(0, RING_SIZE)], cursor, count=6
            )
            pages += 1
            for h, v in pairs:
                assert keccak256(v) == h
                assert h not in got
                got[h] = v
            if done:
                break
        assert pages >= 4  # 20 keys / 6 per page actually paged
        for h, v in nodes.items():
            assert got[h] == v  # superset: genesis nodes stream too
        # a half-ring range returns exactly the keys whose point falls
        # inside it
        half = [(0, RING_SIZE // 2)]
        done, _, pairs = client.stream_node_data(half, b"", count=1024)
        assert done
        in_half = {h for h in got if _point(h) < RING_SIZE // 2}
        assert {h for h, _ in pairs} == in_half


SERVER_SCRIPT = r"""
import sys
sys.path.insert(0, {repo!r})
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.base.crypto.secp256k1 import privkey_to_pubkey, pubkey_to_address
from khipu_tpu.bridge import BridgeServer

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ALLOC = {{a: 10**21 for a in ADDRS}}
bc = Blockchain(Storages(), CFG)
builder = ChainBuilder(bc, CFG, GenesisSpec(alloc=ALLOC))
for i in range(4):
    builder.add_block(
        [sign_transaction(Transaction(i, 10**9, 21000, ADDRS[1], 5),
                          KEYS[0], chain_id=1)],
        coinbase=b"\xaa" * 20,
    )
server = BridgeServer(bc, CFG)
port = server.start()
root = bc.get_header_by_number(4).state_root
print(f"{{port}} {{root.hex()}}", flush=True)
sys.stdin.readline()  # parent closes stdin to stop us
"""


class TestServedNodeCache:
    def test_cross_process_heal(self):
        """P6 (DistributedNodeStorage role): a SEPARATE PROCESS serves
        its node cache over the bridge's GetNodeData; this process,
        with an EMPTY local store, walks the remote state trie through
        RemoteReadThroughNodeStorage — every node heals across the
        process boundary, content-address verified."""
        import os
        import subprocess
        import sys

        from khipu_tpu.storage.datasource import MemoryKeyValueDataSource
        from khipu_tpu.storage.node_storage import NodeStorage
        from khipu_tpu.storage.remote import RemoteReadThroughNodeStorage
        from khipu_tpu.trie.mpt import MerklePatriciaTrie
        from khipu_tpu.domain.account import Account, address_key

        repo = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", SERVER_SCRIPT.format(repo=repo)],
            stdout=subprocess.PIPE,
            stdin=subprocess.PIPE,
            text=True,
        )
        try:
            line = proc.stdout.readline().split()
            port, root = int(line[0]), bytes.fromhex(line[1])
            client = BridgeClient(f"127.0.0.1:{port}")
            local = RemoteReadThroughNodeStorage(
                NodeStorage(MemoryKeyValueDataSource()),
                client.get_node_data,
            )
            trie = MerklePatriciaTrie(local, root_hash=root)
            raw = trie.get(address_key(ADDRS[1]))
            assert raw is not None, "remote account unreadable"
            acc = Account.decode(raw)
            assert acc.balance == 10**21 + 4 * 5
            assert local.healed > 0  # nodes really crossed processes
            # a second read serves locally (healed nodes persisted)
            healed_before = local.healed
            assert trie.get(address_key(ADDRS[1])) == raw
            assert local.healed == healed_before
            client.close()
        finally:
            proc.stdin.close()
            proc.wait(timeout=10)
