"""personal_* namespace: account lifecycle, message signing, and the
keystore -> tx-pool sending path, driven through the real HTTP server.

Parity: jsonrpc/PersonalService.scala:72-182.
"""

import json
import urllib.request

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import SignedTransaction
from khipu_tpu.jsonrpc import EthService, JsonRpcServer, PersonalService
from khipu_tpu.keystore import KeyStore
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.txpool import PendingTransactionsPool

PRIV = (42).to_bytes(32, "big")
ADDR = pubkey_to_address(privkey_to_pubkey(PRIV))
CFG = fixture_config(chain_id=1)


@pytest.fixture
def rpc(tmp_path):
    bc = Blockchain(Storages(), CFG)
    ChainBuilder(bc, CFG, GenesisSpec(alloc={ADDR: 10**21}))
    pool = PendingTransactionsPool()
    eth = EthService(bc, CFG, pool)
    personal = PersonalService(
        KeyStore(str(tmp_path / "keys")), bc, CFG, pool
    )
    server = JsonRpcServer(eth, extra_services=(personal,))
    port = server.start()

    calls = {}

    def call(method, *params):
        req = json.dumps(
            {
                "jsonrpc": "2.0",
                "method": method,
                "params": list(params),
                "id": 1,
            }
        ).encode()
        resp = json.loads(
            urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}",
                    req,
                    {"Content-Type": "application/json"},
                )
            ).read()
        )
        calls["last"] = resp
        if "error" in resp:
            raise RuntimeError(resp["error"]["message"])
        return resp["result"]

    call.url = f"http://127.0.0.1:{port}"
    yield call, pool, bc
    server.stop()


class TestBrowserOriginGuard:
    def test_signing_methods_rejected_for_browser_origins(self, rpc):
        """A request carrying an Origin header (i.e. sent by a web
        page through the open-CORS endpoint) must never reach keystore
        signing methods."""
        call, _, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        call("personal_unlockAccount", "0x" + ADDR.hex(), "pw")

        def browser_call(method, *params):
            req = json.dumps(
                {
                    "jsonrpc": "2.0",
                    "method": method,
                    "params": list(params),
                    "id": 1,
                }
            ).encode()
            return json.loads(
                urllib.request.urlopen(
                    urllib.request.Request(
                        call.url,
                        req,
                        {
                            "Content-Type": "application/json",
                            "Origin": "https://evil.example",
                        },
                    )
                ).read()
            )

        for method, params in (
            ("eth_sendTransaction", [{"from": "0x" + ADDR.hex()}]),
            ("eth_sign", ["0x" + ADDR.hex(), "0xdead"]),
            ("personal_unlockAccount", ["0x" + ADDR.hex(), "pw"]),
            ("personal_listAccounts", []),
        ):
            resp = browser_call(method, *params)
            assert "error" in resp, method
            assert "browser origins" in resp["error"]["message"]
        # non-signing methods still work for browser origins
        assert "result" in browser_call("eth_blockNumber")

    def test_unlock_duration_zero_means_indefinite(self, rpc):
        call, _, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        assert call(
            "personal_unlockAccount", "0x" + ADDR.hex(), "pw", "0x0"
        )
        # still unlocked (geth: 0 = until lock/restart)
        call("eth_sign", "0x" + ADDR.hex(), "0xdeadbeef")


class TestPersonalAccounts:
    def test_new_import_list_roundtrip(self, rpc):
        call, _, _ = rpc
        created = call("personal_newAccount", "pw1")
        imported = call("personal_importRawKey", "0x" + PRIV.hex(), "pw2")
        assert imported == "0x" + ADDR.hex()
        accounts = call("personal_listAccounts")
        assert created in accounts and imported in accounts

    def test_unlock_required_and_lock(self, rpc):
        call, _, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        with pytest.raises(RuntimeError, match="locked"):
            call("eth_sign", "0x" + ADDR.hex(), "0xdeadbeef")
        with pytest.raises(RuntimeError, match="MAC mismatch"):
            call("personal_unlockAccount", "0x" + ADDR.hex(), "wrong")
        assert call("personal_unlockAccount", "0x" + ADDR.hex(), "pw")
        call("eth_sign", "0x" + ADDR.hex(), "0xdeadbeef")  # now works
        assert call("personal_lockAccount", "0x" + ADDR.hex())
        with pytest.raises(RuntimeError, match="locked"):
            call("eth_sign", "0x" + ADDR.hex(), "0xdeadbeef")


class TestPersonalSign:
    def test_sign_recover_roundtrip(self, rpc):
        call, _, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        sig = call("personal_sign", "0x11223344", "0x" + ADDR.hex(), "pw")
        assert len(bytes.fromhex(sig[2:])) == 65
        recovered = call("personal_ecRecover", "0x11223344", sig)
        assert recovered == "0x" + ADDR.hex()
        # a different message must NOT recover to the same address
        other = call("personal_ecRecover", "0x55667788", sig)
        assert other != recovered


class TestSendTransaction:
    def test_eth_send_transaction_roundtrip(self, rpc):
        call, pool, bc = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        call("personal_unlockAccount", "0x" + ADDR.hex(), "pw")
        tx_hash = call(
            "eth_sendTransaction",
            {
                "from": "0x" + ADDR.hex(),
                "to": "0x" + (b"\x99" * 20).hex(),
                "value": hex(12345),
            },
        )
        stx = pool.get(bytes.fromhex(tx_hash[2:]))
        assert isinstance(stx, SignedTransaction)
        # EIP-155-signed and recoverable to the unlocked account
        assert stx.sender == ADDR
        assert stx.tx.value == 12345
        assert stx.tx.nonce == 0
        # a second send advances the nonce past the pooled tx
        tx2 = call(
            "eth_sendTransaction",
            {"from": "0x" + ADDR.hex(), "to": "0x" + (b"\x99" * 20).hex()},
        )
        assert pool.get(bytes.fromhex(tx2[2:])).tx.nonce == 1

    def test_send_with_passphrase_no_unlock_needed(self, rpc):
        call, pool, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        tx_hash = call(
            "personal_sendTransaction",
            {"from": "0x" + ADDR.hex(), "to": "0x" + (b"\x77" * 20).hex()},
            "pw",
        )
        assert pool.get(bytes.fromhex(tx_hash[2:])).sender == ADDR

    def test_locked_send_rejected(self, rpc):
        call, _, _ = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        with pytest.raises(RuntimeError, match="locked"):
            call(
                "eth_sendTransaction",
                {"from": "0x" + ADDR.hex(), "to": "0x" + ("11" * 20)},
            )

    def test_sent_tx_is_minable(self, rpc):
        """The pooled tx executes in a real block (keystore -> pool ->
        chain round-trip)."""
        call, pool, bc = rpc
        call("personal_importRawKey", "0x" + PRIV.hex(), "pw")
        call("personal_unlockAccount", "0x" + ADDR.hex(), "pw")
        dest = b"\x99" * 20
        call(
            "eth_sendTransaction",
            {
                "from": "0x" + ADDR.hex(),
                "to": "0x" + dest.hex(),
                "value": hex(10**18),
                "gas": hex(21000),
            },
        )
        builder = ChainBuilder.from_head(bc, CFG)
        block = builder.add_block(pool.pending(), coinbase=b"\xaa" * 20)
        assert len(block.body.transactions) == 1
        acc = bc.get_account(dest, block.header.state_root)
        assert acc.balance == 10**18
