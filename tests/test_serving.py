"""Serving plane: admission control, read-your-writes view, SLO
tracking, RPC surface hardening, and the load harness.

Fast tests run in tier-1. The heavy multi-threaded load tests carry
``@pytest.mark.serve`` (AND ``slow``, so the default `-m "not slow"`
run skips them); run them with `pytest -m serve`.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import ServingConfig, SyncConfig, fixture_config
from khipu_tpu.domain.account import Account
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.jsonrpc import EthService, JsonRpcServer
from khipu_tpu.jsonrpc.filters import FilterManager, LogQuery
from khipu_tpu.serving import (
    AdmissionController,
    ReadView,
    ServerBusy,
    ServingPlane,
    SloTracker,
    classify_method,
)
from khipu_tpu.serving.admission import txpool_pressure
from khipu_tpu.serving.loadgen import (
    MIXED,
    READ_ONLY,
    HttpTransport,
    InProcessTransport,
    LoadGenerator,
    WorkloadProfile,
)
from khipu_tpu.serving.slo import LATENCY_BUCKETS, quantile
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.txpool import PendingTransactionsPool

CFG = fixture_config(chain_id=1)
KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
ETH = 10**18
ALLOC = {a: 1000 * ETH for a in ADDRS}
MINER = b"\xaa" * 20


def _tx(key, nonce, to, value, gas_price=10**9):
    return sign_transaction(
        Transaction(nonce, gas_price, 21_000, to, value),
        key, chain_id=1,
    )


def _fresh():
    bc = Blockchain(Storages(), CFG)
    bc.load_genesis(GenesisSpec(alloc=ALLOC))
    return bc


@pytest.fixture(scope="module")
def chain_bc():
    """A 4-block chain of transfers for read-path tests."""
    builder = ChainBuilder(
        Blockchain(Storages(), CFG), CFG, GenesisSpec(alloc=ALLOC)
    )
    nonces = [0, 0, 0]
    for n in range(4):
        i = n % len(KEYS)
        builder.add_block(
            [_tx(KEYS[i], nonces[i], ADDRS[(i + 1) % 3], 100 + n)],
            coinbase=MINER,
        )
        nonces[i] += 1
    return builder.blockchain


# ------------------------------------------------------- admission


class TestClassify:
    def test_table_prefix_and_default(self):
        assert classify_method("eth_call") == "execute"
        assert classify_method("eth_sendRawTransaction") == "write"
        assert classify_method("eth_blockNumber") == "cheap"
        assert classify_method("net_version") == "cheap"
        assert classify_method("personal_sign") == "write"
        assert classify_method("khipu_metrics") == "read"
        # unknown eth_* state reads default to the read class
        assert classify_method("eth_getBalance") == "read"
        assert classify_method("eth_somethingNew") == "read"


class TestAdmission:
    def _ctl(self, **kw):
        cfg = kw.pop("cfg", ServingConfig(queue_timeout=0.02,
                                          max_queue=2))
        return AdmissionController(cfg, **kw)

    def test_acquire_release_counts(self):
        ctl = self._ctl(limits={"read": 2})
        t1 = ctl.acquire("eth_getBalance")
        t2 = ctl.acquire("eth_getBalance")
        snap = ctl.snapshot()
        assert snap["read"]["inflight"] == 2
        assert snap["read"]["peakInflight"] == 2
        ctl.release(t1)
        ctl.release(t2)
        assert ctl.snapshot()["read"]["inflight"] == 0

    def test_over_limit_sheds_after_timeout(self):
        ctl = self._ctl(limits={"execute": 2})
        ctl.acquire("eth_call")
        ctl.acquire("eth_call")
        with pytest.raises(ServerBusy):
            ctl.acquire("eth_call")  # queue, then 20ms timeout, shed
        assert ctl.snapshot()["execute"]["shed"]["queueTimeout"] == 1

    def test_full_queue_sheds_immediately(self):
        cfg = ServingConfig(queue_timeout=5.0, max_queue=0)
        ctl = AdmissionController(cfg, limits={"write": 2})
        ctl.acquire("eth_sendRawTransaction")
        ctl.acquire("eth_sendRawTransaction")
        t0 = time.monotonic()
        with pytest.raises(ServerBusy):
            ctl.acquire("eth_sendRawTransaction")
        assert time.monotonic() - t0 < 1.0  # no queue: instant shed
        assert ctl.snapshot()["write"]["shed"]["queueFull"] == 1

    def test_released_slot_admits_queued_waiter(self):
        ctl = self._ctl(cfg=ServingConfig(queue_timeout=2.0,
                                          max_queue=2),
                        limits={"read": 2})
        t1 = ctl.acquire("eth_getBalance")
        ctl.acquire("eth_getBalance")
        got = []

        def waiter():
            got.append(ctl.acquire("eth_getBalance"))

        th = threading.Thread(target=waiter)
        th.start()
        time.sleep(0.05)
        ctl.release(t1)  # frees the slot the waiter is queued for
        th.join(timeout=5)
        assert got and got[0] is not None

    def test_aimd_grows_under_target_and_cuts_over(self):
        cfg = ServingConfig(decrease_cooldown=0.0)
        ctl = AdmissionController(cfg, limits={"read": 4},
                                  targets={"read": 0.050})
        for _ in range(40):  # fast completions: additive increase
            ctl.release(ctl.acquire("eth_getBalance"))
        grown = ctl.snapshot()["read"]["limit"]
        assert grown > 4
        # one over-target completion: multiplicative decrease
        lim = ctl._classes["read"]
        exact = lim.limit
        lim.release(seconds=1.0)
        lim.inflight += 1  # undo the release bookkeeping for the fake
        assert lim.limit == pytest.approx(exact * cfg.aimd_beta)

    def test_decrease_cooldown_bounds_the_cut_rate(self):
        cfg = ServingConfig(decrease_cooldown=60.0)
        ctl = AdmissionController(cfg, limits={"read": 100})
        lim = ctl._classes["read"]
        lim.inflight = 2
        lim.release(seconds=9.9)
        after_first = lim.limit
        lim.release(seconds=9.9)  # within cooldown: no second cut
        assert lim.limit == after_first

    def test_pressure_sheds_writes_first_cheap_never(self):
        pressure = {"v": 0.0}
        ctl = self._ctl(signals=[lambda: pressure["v"]])
        cfg = ctl.config
        pressure["v"] = (cfg.shed_write_at + cfg.shed_execute_at) / 2
        with pytest.raises(ServerBusy):
            ctl.acquire("eth_sendRawTransaction")
        # same pressure: execute/read/cheap still admitted
        for m in ("eth_call", "eth_getBalance", "eth_blockNumber"):
            ctl.release(ctl.acquire(m))
        pressure["v"] = 1.0  # saturated: everything but cheap sheds
        for m in ("eth_sendRawTransaction", "eth_call",
                  "eth_getBalance"):
            with pytest.raises(ServerBusy):
                ctl.acquire(m)
        ctl.release(ctl.acquire("eth_blockNumber"))
        assert ctl.snapshot()["write"]["shed"]["pressure"] == 2

    def test_txpool_pressure_signal(self):
        pool = PendingTransactionsPool(capacity=4)
        sig = txpool_pressure(pool)
        assert sig() == 0.0
        for n in range(4):
            pool.add(_tx(KEYS[0], n, ADDRS[1], 1))
        assert sig() == 1.0

    def test_registry_exposition_single_family(self):
        from khipu_tpu.observability.registry import REGISTRY

        self._ctl()  # register_collector replaces by key: no dup
        text = REGISTRY.prometheus_text()
        assert text.count("# TYPE khipu_admission_limit gauge") == 1
        assert text.count(
            "# TYPE khipu_admission_shed_total counter"
        ) == 1


# -------------------------------------------------------- read view


class TestReadView:
    def _header(self, number):
        class H:
            pass

        h = H()
        h.number = number
        return h

    def test_overlay_first_store_second(self, chain_bc):
        rv = ReadView(chain_bc)
        best = chain_bc.best_block_number
        n0, acc0 = rv.get_account(ADDRS[0])
        assert n0 == best and acc0 is not None
        rv.publish_block(
            self._header(best + 1),
            {ADDRS[0]: Account(nonce=acc0.nonce + 1,
                               balance=acc0.balance - 5)},
        )
        n1, acc1 = rv.get_account(ADDRS[0])
        assert n1 == best + 1
        assert acc1.nonce == acc0.nonce + 1
        assert rv.head_number() == best + 1
        # addresses the overlay does not cover fall through to store
        n2, _ = rv.get_account(ADDRS[1])
        assert n2 == best

    def test_retire_respects_newer_entries(self, chain_bc):
        rv = ReadView(chain_bc)
        a = Account(nonce=1, balance=10)
        b = Account(nonce=2, balance=20)
        rv.publish_block(self._header(100), {ADDRS[0]: a})
        rv.publish_block(self._header(101), {ADDRS[0]: b})
        rv.retire_through(100)  # block 101's entry must survive
        _, acc = rv.get_account(ADDRS[0])
        assert acc.nonce == 2
        rv.retire_through(101)
        assert rv.snapshot()["overlayAddrs"] == 0

    def test_invalidate_rolls_back_to_durable(self, chain_bc):
        rv = ReadView(chain_bc)
        best = chain_bc.best_block_number
        rv.publish_block(self._header(best + 1),
                         {ADDRS[0]: Account(nonce=9)})
        rv.publish_block(self._header(best + 2),
                         {ADDRS[0]: Account(nonce=10)})
        rv.invalidate_above(best + 1)
        _, acc = rv.get_account(ADDRS[0])
        assert acc.nonce == 9  # block best+1 survived the abort
        rv.invalidate_above(best)
        n, acc = rv.get_account(ADDRS[0])
        assert n == best  # back to the committed store entirely
        assert rv.snapshot()["invalidated"] == 2

    def test_deletion_reads_as_absent_not_store_fallthrough(
        self, chain_bc
    ):
        rv = ReadView(chain_bc)
        best = chain_bc.best_block_number
        rv.publish_block(self._header(best + 1), {ADDRS[0]: None})
        _, acc = rv.get_account(ADDRS[0])
        assert acc is None  # deleted in-overlay, NOT the store account


# -------------------------------------------------------------- slo


class TestSlo:
    def test_quantile_interpolates_and_floors(self):
        hist = {"count": 100, "sum": 1.0,
                "buckets": {0.001: 50, 0.01: 100, float("inf"): 100}}
        assert quantile(hist, 0.25) == pytest.approx(0.0005)
        assert quantile(hist, 0.75) == pytest.approx(0.0055)
        assert quantile({"count": 0, "sum": 0, "buckets": {}}, 0.99) == 0
        tail = {"count": 10, "sum": 60.0,
                "buckets": {**{b: 0 for b in LATENCY_BUCKETS},
                            float("inf"): 10}}
        # all observations beyond the last bound: floored, not inf
        assert quantile(tail, 0.99) == LATENCY_BUCKETS[-1]

    def _tracker(self):
        # fresh registry: instruments are process-global truth keyed by
        # (family, labels); an isolated tracker needs its own
        from khipu_tpu.observability.registry import MetricsRegistry

        return SloTracker(registry=MetricsRegistry())

    def test_shed_is_counted_not_timed(self):
        slo = self._tracker()
        slo.observe("eth_call", 0.004, "ok")
        slo.observe("eth_call", 0.0, "shed")
        ev = slo.evaluate()
        m = ev["methods"]["eth_call"]
        assert m["count"] == 1  # the shed never entered the histogram
        assert m["shed"] == 1
        assert m["class"] == "execute"
        assert m["withinSlo"] is True

    def test_error_budget_accounting(self):
        slo = self._tracker()
        for _ in range(99):
            slo.observe("eth_getBalance", 0.001, "ok")
        slo.observe("eth_getBalance", 0.001, "error")
        budget = slo.evaluate()["errorBudget"]
        assert budget["requests"] == 100
        assert budget["bad"] == 1
        assert budget["badFraction"] == pytest.approx(0.01)


class TestServingPlane:
    def test_admit_finish_and_shed_recording(self):
        from khipu_tpu.observability.registry import MetricsRegistry

        pressure = {"v": 0.0}
        # fresh registry: instruments are process-global truth keyed
        # by (family, labels), so an isolated tracker needs its own
        plane = ServingPlane(
            ServingConfig(),
            admission=AdmissionController(
                ServingConfig(), signals=[lambda: pressure["v"]],
                registry=MetricsRegistry(),
            ),
            slo=SloTracker(registry=MetricsRegistry()),
        )
        ticket = plane.admit("eth_getBalance")
        plane.finish("eth_getBalance", ticket)
        pressure["v"] = 1.0
        with pytest.raises(ServerBusy):
            plane.admit("eth_sendRawTransaction")
        ev = plane.slo.evaluate()["methods"]
        assert ev["eth_getBalance"]["count"] == 1
        assert ev["eth_sendRawTransaction"]["shed"] == 1


# ------------------------------------------------- rpc surface caps


class TestServerCaps:
    def _server(self, **kw):
        bc = _fresh()
        service = EthService(bc, CFG, PendingTransactionsPool())
        return JsonRpcServer(service, **kw)

    def test_batch_cap(self):
        server = self._server(max_batch=3)
        req = {"jsonrpc": "2.0", "id": 1, "method": "eth_blockNumber",
               "params": []}
        assert isinstance(server.handle([req] * 3), list)
        out = server.handle([req] * 4)
        assert out["error"]["code"] == -32600
        assert "batch too large" in out["error"]["message"]

    def test_serving_config_overrides_caps(self):
        bc = _fresh()
        plane = ServingPlane(ServingConfig(max_batch=7,
                                           max_body_bytes=1234))
        server = JsonRpcServer(
            EthService(bc, CFG, PendingTransactionsPool()),
            serving=plane, max_batch=999,
        )
        assert server.max_batch == 7
        assert server.max_body_bytes == 1234

    def test_unknown_method_bypasses_admission(self):
        bc = _fresh()
        calls = []

        class SpyPlane(ServingPlane):
            def admit(self, method):
                calls.append(method)
                return super().admit(method)

        server = JsonRpcServer(
            EthService(bc, CFG, PendingTransactionsPool()),
            serving=SpyPlane(ServingConfig()),
        )
        out = server.handle({"jsonrpc": "2.0", "id": 1,
                             "method": "eth_noSuchThing", "params": []})
        assert out["error"]["code"] == -32601
        assert calls == []  # -32601 consumed no admission slot

    def test_body_cap_over_http(self):
        server = self._server(max_body_bytes=2048)
        port = server.start()
        try:
            url = f"http://127.0.0.1:{port}"
            ok = json.loads(
                urllib.request.urlopen(
                    urllib.request.Request(
                        url,
                        data=json.dumps(
                            {"jsonrpc": "2.0", "id": 1,
                             "method": "eth_blockNumber",
                             "params": []}
                        ).encode(),
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=10,
                ).read()
            )
            assert ok["result"] == "0x0"
            big = json.dumps(
                {"jsonrpc": "2.0", "id": 1, "method": "eth_blockNumber",
                 "params": ["x" * 4096]}
            ).encode()
            resp = json.loads(
                urllib.request.urlopen(
                    urllib.request.Request(
                        url, data=big,
                        headers={"Content-Type": "application/json"},
                    ),
                    timeout=10,
                ).read()
            )
            assert resp["error"]["code"] == -32600
            assert "body too large" in resp["error"]["message"]
        finally:
            server.stop()


# ------------------------------------------------------ filter TTL


class TestFilterTtl:
    def _mgr(self, chain_bc, ttl=300.0):
        mgr = FilterManager(chain_bc, ttl=ttl)
        clock = {"t": 1000.0}
        mgr._now = lambda: clock["t"]
        return mgr, clock

    def test_unpolled_filter_expires(self, chain_bc):
        mgr, clock = self._mgr(chain_bc)
        fid = mgr.new_block_filter()
        clock["t"] += 301.0
        # installing another filter sweeps; the stale one is evicted
        mgr.new_block_filter()
        assert mgr.changes(fid) is None  # geth: "filter not found"
        snap = mgr.snapshot()
        assert snap["evictions"] == 1
        assert snap["active"] == 1

    def test_polling_keeps_a_filter_alive(self, chain_bc):
        mgr, clock = self._mgr(chain_bc)
        fid = mgr.new_log_filter(LogQuery(0, None))
        for _ in range(4):
            clock["t"] += 200.0  # each poll resets the TTL window
            assert mgr.changes(fid) is not None
        assert mgr.snapshot()["evictions"] == 0

    def test_uninstall_is_not_an_eviction(self, chain_bc):
        mgr, clock = self._mgr(chain_bc)
        fid = mgr.new_block_filter()
        assert mgr.uninstall(fid) is True
        assert mgr.snapshot()["evictions"] == 0


# ------------------------------------------------- txpool semantics


class TestTxPoolReplacement:
    def test_higher_gas_price_replaces(self):
        pool = PendingTransactionsPool()
        low = _tx(KEYS[0], 0, ADDRS[1], 1, gas_price=10**9)
        high = _tx(KEYS[0], 0, ADDRS[1], 1, gas_price=2 * 10**9)
        assert pool.add(low)
        assert pool.add(high)
        assert len(pool) == 1
        assert pool.get(low.hash) is None
        assert pool.get(high.hash) is not None
        assert pool.replacements == 1

    def test_equal_or_lower_price_rejected(self):
        pool = PendingTransactionsPool()
        a = _tx(KEYS[0], 0, ADDRS[1], 1, gas_price=10**9)
        b = _tx(KEYS[0], 0, ADDRS[2], 2, gas_price=10**9)  # same slot
        assert pool.add(a)
        assert not pool.add(b)
        assert pool.rejected_underpriced == 1
        assert pool.get(a.hash) is not None

    def test_distinct_nonces_do_not_interact(self):
        pool = PendingTransactionsPool()
        assert pool.add(_tx(KEYS[0], 0, ADDRS[1], 1))
        assert pool.add(_tx(KEYS[0], 1, ADDRS[1], 1))
        assert len(pool) == 2
        assert pool.replacements == 0

    def test_eviction_frees_the_slot_index(self):
        pool = PendingTransactionsPool(capacity=2)
        t0 = _tx(KEYS[0], 0, ADDRS[1], 1)
        pool.add(t0)
        pool.add(_tx(KEYS[0], 1, ADDRS[1], 1))
        pool.add(_tx(KEYS[0], 2, ADDRS[1], 1))  # evicts t0
        assert pool.evictions == 1
        assert pool.get(t0.hash) is None
        # the evicted slot is free again: a fresh nonce-0 tx is NEW,
        # not an underpriced replacement of a ghost
        assert pool.add(_tx(KEYS[0], 0, ADDRS[1], 2))
        assert pool.rejected_underpriced == 0

    def test_remove_mined_frees_the_slot_index(self):
        pool = PendingTransactionsPool()
        t0 = _tx(KEYS[0], 0, ADDRS[1], 1)
        pool.add(t0)
        assert pool.remove_mined([t0]) == 1
        assert pool.add(_tx(KEYS[0], 0, ADDRS[1], 2))

    def test_gauges_in_exposition(self):
        from khipu_tpu.observability.registry import REGISTRY

        PendingTransactionsPool()
        text = REGISTRY.prometheus_text()
        for family in ("khipu_txpool_size", "khipu_txpool_capacity",
                       "khipu_txpool_replacements_total"):
            assert f"# TYPE {family} " in text


class TestSendRawTransactionParity:
    def _service(self):
        bc = _fresh()
        pool = PendingTransactionsPool()
        return EthService(bc, CFG, pool), pool

    def _raw(self, stx):
        return "0x" + stx.encode().hex()

    def test_duplicate_is_already_known(self):
        service, _ = self._service()
        stx = _tx(KEYS[0], 0, ADDRS[1], 1)
        service.eth_sendRawTransaction(self._raw(stx))
        from khipu_tpu.jsonrpc.eth_service import RpcError

        with pytest.raises(RpcError, match="already known") as e:
            service.eth_sendRawTransaction(self._raw(stx))
        assert e.value.code == -32000

    def test_underpriced_replacement_is_named(self):
        service, _ = self._service()
        service.eth_sendRawTransaction(
            self._raw(_tx(KEYS[0], 0, ADDRS[1], 1, gas_price=10**9))
        )
        from khipu_tpu.jsonrpc.eth_service import RpcError

        with pytest.raises(
            RpcError, match="replacement transaction underpriced"
        ):
            service.eth_sendRawTransaction(
                self._raw(_tx(KEYS[0], 0, ADDRS[2], 2,
                              gas_price=10**9))
            )

    def test_outbidding_replacement_is_accepted(self):
        service, pool = self._service()
        service.eth_sendRawTransaction(
            self._raw(_tx(KEYS[0], 0, ADDRS[1], 1, gas_price=10**9))
        )
        h = service.eth_sendRawTransaction(
            self._raw(_tx(KEYS[0], 0, ADDRS[1], 1,
                          gas_price=3 * 10**9))
        )
        assert len(pool) == 1
        assert pool.get(bytes.fromhex(h[2:])) is not None

    def test_empty_pool_argument_is_kept(self):
        """Regression: `tx_pool or ...` swapped an EMPTY caller pool
        (falsy: __len__ == 0) for a private one, so the node's pool
        and the RPC pool silently diverged."""
        pool = PendingTransactionsPool()
        service = EthService(_fresh(), CFG, pool)
        assert service.tx_pool is pool


# ------------------------------------------------------ rpc + view


class TestReadYourWritesOverRpc:
    def test_latest_reads_resolve_through_the_view(self, chain_bc):
        rv = ReadView(chain_bc)
        service = EthService(chain_bc, CFG, PendingTransactionsPool(),
                             read_view=rv)
        best = chain_bc.best_block_number
        bal0 = int(service.eth_getBalance("0x" + MINER.hex(),
                                          "latest"), 16)
        nonce0 = int(service.eth_getTransactionCount(
            "0x" + ADDRS[0].hex(), "latest"), 16)

        class H:
            number = best + 1

        rv.publish_block(H(), {
            MINER: Account(balance=bal0 + 7),
            ADDRS[0]: Account(nonce=nonce0 + 1, balance=1),
        })
        assert int(service.eth_blockNumber(), 16) == best + 1
        assert int(service.eth_getBalance("0x" + MINER.hex(),
                                          "latest"), 16) == bal0 + 7
        assert int(service.eth_getTransactionCount(
            "0x" + ADDRS[0].hex(), "latest"), 16) == nonce0 + 1
        # historical tags still read the committed store
        assert int(service.eth_getBalance("0x" + MINER.hex(),
                                          hex(best)), 16) == bal0

    def test_metrics_embed_serving_snapshot(self, chain_bc):
        rv = ReadView(chain_bc)
        plane = ServingPlane(ServingConfig(), read_view=rv)
        service = EthService(chain_bc, CFG, PendingTransactionsPool(),
                             read_view=rv, serving=plane)
        out = service.khipu_metrics()
        assert "admission" in out["serving"]
        assert "slo" in out["serving"]
        assert out["serving"]["readView"]["head"] >= 0
        assert "filters" in out


# ---------------------------------------------------------- loadgen


class _StubTransport:
    """Scripted responses; records every call."""

    def __init__(self, responder):
        self.responder = responder
        self.calls = []

    def call(self, method, params):
        self.calls.append((method, params))
        return self.responder(method, params)


class TestLoadgen:
    def test_same_seed_same_request_stream(self):
        def run():
            t = _StubTransport(lambda m, p: {"jsonrpc": "2.0", "id": 1,
                                             "result": "0x0"})
            LoadGenerator(t, READ_ONLY, clients=2, max_requests=30,
                          seed=77,
                          nonce_addresses=["0x" + ADDRS[0].hex()],
                          balance_addresses=["0x" + MINER.hex()],
                          ).run()
            return t.calls

        assert run() == run()

    def test_nonce_regression_is_a_violation(self):
        answers = iter(["0x5", "0x4"])  # nonce goes BACKWARDS

        def responder(method, params):
            if method == "eth_getTransactionCount":
                return {"jsonrpc": "2.0", "id": 1,
                        "result": next(answers, "0x4")}
            return {"jsonrpc": "2.0", "id": 1, "result": "0x0"}

        profile = WorkloadProfile("nonce_only",
                                  {"eth_getTransactionCount": 1.0})
        report = LoadGenerator(
            _StubTransport(responder), profile, clients=1,
            max_requests=2, seed=1,
            nonce_addresses=["0x" + ADDRS[0].hex()],
        ).run()
        assert len(report.violations) == 1
        assert "regressed" in report.violations[0].detail

    def test_shed_responses_counted_not_timed(self):
        def responder(method, params):
            return {"jsonrpc": "2.0", "id": 1,
                    "error": {"code": -32005, "message": "busy"}}

        report = LoadGenerator(
            _StubTransport(responder), READ_ONLY, clients=1,
            max_requests=10, seed=3,
        ).run()
        assert report.shed == 10
        assert report.errors == 0
        assert report.latencies == {}  # sheds never enter percentiles

    def test_invisible_own_tx_is_a_violation(self):
        def responder(method, params):
            if method == "eth_getTransactionByHash":
                return {"jsonrpc": "2.0", "id": 1, "result": None}
            return {"jsonrpc": "2.0", "id": 1, "result": "0x" + "ab" * 32}

        profile = WorkloadProfile("writes",
                                  {"eth_sendRawTransaction": 1.0})
        report = LoadGenerator(
            _StubTransport(responder), profile, clients=1,
            max_requests=1, seed=4,
            balance_addresses=["0x" + MINER.hex()],
        ).run()
        assert len(report.violations) == 1
        assert "invisible" in report.violations[0].detail


# ----------------------------------------------- heavy load (serve)


def _serving_stack():
    from khipu_tpu.observability.registry import MetricsRegistry

    bc = _fresh()
    pool = PendingTransactionsPool()
    rv = ReadView(bc)
    plane = ServingPlane(
        ServingConfig(),
        read_view=rv,
        admission=AdmissionController(ServingConfig(),
                                      signals=[txpool_pressure(pool)],
                                      registry=MetricsRegistry()),
        slo=SloTracker(registry=MetricsRegistry()),
    )
    service = EthService(bc, CFG, pool, read_view=rv, serving=plane)
    return JsonRpcServer(service, serving=plane), plane


@pytest.mark.serve
@pytest.mark.slow
class TestHeavyLoad:
    def test_in_process_mixed_load_clean(self):
        server, plane = _serving_stack()
        report = LoadGenerator(
            InProcessTransport(server), MIXED, clients=8,
            max_requests=250, seed=42,
            nonce_addresses=["0x" + a.hex() for a in ADDRS],
            balance_addresses=["0x" + MINER.hex()],
            chain_id=1,
        ).run()
        assert report.requests == 2000
        assert report.violations == []
        assert report.errors == 0
        ev = plane.slo.evaluate()
        assert ev["errorBudget"]["bad"] == report.shed

    def test_http_load_clean(self):
        server, _ = _serving_stack()
        port = server.start()
        try:
            report = LoadGenerator(
                HttpTransport(f"http://127.0.0.1:{port}"), READ_ONLY,
                clients=4, max_requests=50, seed=43,
                nonce_addresses=["0x" + a.hex() for a in ADDRS],
                balance_addresses=["0x" + MINER.hex()],
            ).run()
            assert report.requests == 200
            assert report.violations == []
            assert report.errors == 0
        finally:
            server.stop()

    def test_open_loop_overload_sheds_not_collapses(self):
        cfg = ServingConfig(queue_timeout=0.005, max_queue=4)
        bc = _fresh()
        pressure = {"v": 0.0}
        plane = ServingPlane(
            cfg,
            admission=AdmissionController(
                cfg, limits={"read": 2, "cheap": 2},
                signals=[lambda: pressure["v"]],
            ),
        )
        server = JsonRpcServer(
            EthService(bc, CFG, PendingTransactionsPool(),
                       serving=plane),
            serving=plane,
        )
        pressure["v"] = 1.0  # saturated node: reads shed, cheap serves
        report = LoadGenerator(
            InProcessTransport(server), READ_ONLY, clients=8,
            max_requests=100, seed=44,
            nonce_addresses=["0x" + ADDRS[0].hex()],
            balance_addresses=["0x" + MINER.hex()],
        ).run()
        assert report.shed > 0
        assert report.violations == []
