"""DAO fork: config knobs, irregular state change, fork-block identity,
extraData window rule, and the peer-handshake fork challenge.

Parity targets: config/KhipuConfig.scala:219-220,264-265,
network/ForkResolver.scala:18-31, handshake/EtcHandshake.scala
(respondToStatus -> respondToBlockHeaders).
"""

import pytest

from khipu_tpu.base.crypto.secp256k1 import (
    privkey_to_pubkey,
    pubkey_to_address,
)
from khipu_tpu.config import fixture_config
from khipu_tpu.domain.blockchain import Blockchain, GenesisSpec
from khipu_tpu.domain.transaction import Transaction, sign_transaction
from khipu_tpu.ledger.ledger import BlockExecutionError, execute_block
from khipu_tpu.storage.storages import Storages
from khipu_tpu.sync.chain_builder import ChainBuilder
from khipu_tpu.validators.validators import (
    BlockHeaderValidator,
    HeaderValidationError,
)

KEYS = [(i + 1).to_bytes(32, "big") for i in range(3)]
ADDRS = [pubkey_to_address(privkey_to_pubkey(k)) for k in KEYS]
REFUND = b"\xbf" * 20
MARKER = bytes.fromhex("64616f2d686172642d666f726b")  # "dao-hard-fork"


def dao_config(**overrides):
    base = dict(
        dao_fork_block_number=2,
        dao_drain_list=(ADDRS[2],),
        dao_refund_contract=REFUND,
        dao_fork_extra_data=None,
        dao_fork_block_hash=None,
    )
    base.update(overrides)
    return fixture_config(chain_id=1, **base)


def build_chain(cfg, n_blocks=3, coinbase=b"\xaa" * 20):
    bc = Blockchain(Storages(), cfg)
    builder = ChainBuilder(
        bc, cfg, GenesisSpec(alloc={a: 10**21 for a in ADDRS})
    )
    for n in range(n_blocks):
        builder.add_block(
            [
                sign_transaction(
                    Transaction(n, 10**9, 21000, ADDRS[1], 5),
                    KEYS[0],
                    chain_id=1,
                )
            ],
            coinbase=coinbase,
        )
    return bc


class TestDaoStateChange:
    def test_drain_applies_exactly_at_fork_block(self):
        cfg = dao_config()
        bc = build_chain(cfg)
        # before the fork block the drained account is untouched
        pre = bc.get_account(
            ADDRS[2], bc.get_header_by_number(1).state_root
        )
        assert pre.balance == 10**21
        refund_pre = bc.get_account(
            REFUND, bc.get_header_by_number(1).state_root
        )
        assert refund_pre is None
        # at the fork block the FULL balance moved to the refund
        # contract (under this compressed schedule EIP-161 is already
        # active, so the now-empty touched account is cleared — on real
        # mainnet the fork predates Spurious Dragon and it would remain
        # with balance 0)
        post = bc.get_account(
            ADDRS[2], bc.get_header_by_number(2).state_root
        )
        assert post is None or post.balance == 0
        refund_post = bc.get_account(
            REFUND, bc.get_header_by_number(2).state_root
        )
        assert refund_post.balance == 10**21
        # and it does not re-apply on the next block
        refund_later = bc.get_account(
            REFUND, bc.get_header_by_number(3).state_root
        )
        assert refund_later.balance == 10**21

    def test_fork_block_identity_gates_replay(self):
        cfg = dao_config()
        bc = build_chain(cfg)
        block2 = bc.get_block_by_number(2)
        parent_root = bc.get_header_by_number(1).state_root

        good = dao_config(dao_fork_block_hash=block2.hash)
        execute_block(
            block2, parent_root, bc.get_world_state, good
        )  # must not raise

        bad = dao_config(dao_fork_block_hash=b"\xff" * 32)
        with pytest.raises(BlockExecutionError, match="DAO fork block"):
            execute_block(block2, parent_root, bc.get_world_state, bad)


class TestDaoExtraDataRule:
    def test_marker_required_in_fork_window(self):
        cfg = dao_config(dao_fork_extra_data=MARKER)
        bc = build_chain(dao_config(), n_blocks=2)
        parent = bc.get_header_by_number(1)
        header = bc.get_header_by_number(2)  # built without the marker
        validator = BlockHeaderValidator(cfg.blockchain)
        with pytest.raises(HeaderValidationError, match="dao-hard-fork"):
            validator.validate(header, parent)

    def test_marker_satisfies_rule_and_outside_window_unchecked(self):
        cfg = dao_config(
            dao_fork_extra_data=MARKER, dao_fork_extra_data_range=1
        )
        bc = Blockchain(Storages(), cfg)
        builder = ChainBuilder(
            bc, cfg, GenesisSpec(alloc={a: 10**21 for a in ADDRS})
        )
        builder.add_block([])  # block 1: outside window, no marker
        builder.add_block([], extra_data=MARKER)  # block 2: fork block
        builder.add_block([])  # block 3: window is 1 block wide
        validator = BlockHeaderValidator(cfg.blockchain)
        validator.validate(
            bc.get_header_by_number(2), bc.get_header_by_number(1)
        )
        validator.validate(
            bc.get_header_by_number(3), bc.get_header_by_number(2)
        )


class TestForkChallenge:
    def _status_factory(self, bc):
        from khipu_tpu.network.messages import Status

        def status():
            best = bc.best_block_number
            return Status(
                63,
                1,
                bc.get_total_difficulty(best) or 0,
                bc.get_header_by_number(best).hash,
                bc.get_header_by_number(0).hash,
            )

        return status

    def test_wrong_fork_peer_rejected_and_blacklisted(self):
        from khipu_tpu.network.fork_resolver import ForkResolver
        from khipu_tpu.network.host_service import HostService
        from khipu_tpu.network.peer import PeerError, PeerManager

        cfg = dao_config()
        ours = build_chain(cfg, coinbase=b"\xaa" * 20)
        theirs = build_chain(cfg, coinbase=b"\xcc" * 20)  # same genesis,
        # divergent fork block
        assert (
            ours.get_header_by_number(0).hash
            == theirs.get_header_by_number(0).hash
        )
        assert (
            ours.get_header_by_number(2).hash
            != theirs.get_header_by_number(2).hash
        )

        priv_a, priv_b = KEYS[0], KEYS[1]
        pub_b = privkey_to_pubkey(priv_b)
        server = PeerManager(priv_b, "other-side", self._status_factory(theirs))
        HostService(theirs).install(server)
        port = server.listen()

        resolver = ForkResolver(2, ours.get_header_by_number(2).hash)
        client = PeerManager(
            priv_a, "our-side", self._status_factory(ours),
            fork_resolver=resolver,
        )
        try:
            with pytest.raises(PeerError, match="fork check failed"):
                client.connect("127.0.0.1", port, pub_b)
            assert client.blacklist.is_blacklisted(pub_b)
            with pytest.raises(PeerError, match="blacklisted"):
                client.connect("127.0.0.1", port, pub_b)
        finally:
            client.stop()
            server.stop()

    def test_same_fork_peers_connect_with_mutual_challenge(self):
        from khipu_tpu.network.fork_resolver import ForkResolver
        from khipu_tpu.network.host_service import HostService
        from khipu_tpu.network.peer import PeerManager

        cfg = dao_config()
        chain_a = build_chain(cfg)
        chain_b = build_chain(cfg)
        fork_hash = chain_a.get_header_by_number(2).hash
        assert chain_b.get_header_by_number(2).hash == fork_hash

        priv_a, priv_b = KEYS[0], KEYS[1]
        pub_b = privkey_to_pubkey(priv_b)
        server = PeerManager(
            priv_b, "b", self._status_factory(chain_b),
            fork_resolver=ForkResolver(2, fork_hash),
        )
        HostService(chain_b).install(server)
        port = server.listen()
        client = PeerManager(
            priv_a, "a", self._status_factory(chain_a),
            fork_resolver=ForkResolver(2, fork_hash),
        )
        HostService(chain_a).install(client)
        try:
            peer = client.connect("127.0.0.1", port, pub_b)
            assert peer.alive
            assert peer.status is not None
        finally:
            client.stop()
            server.stop()

    def test_unchallengeable_short_peer_assumed_friendly(self):
        from khipu_tpu.network.fork_resolver import ForkResolver
        from khipu_tpu.network.host_service import HostService
        from khipu_tpu.network.peer import PeerManager

        cfg = dao_config()
        long_chain = build_chain(cfg, n_blocks=3)
        short_chain = build_chain(cfg, n_blocks=1)  # pre-fork peer

        priv_a, priv_b = KEYS[0], KEYS[1]
        pub_b = privkey_to_pubkey(priv_b)
        server = PeerManager(
            priv_b, "short", self._status_factory(short_chain)
        )
        HostService(short_chain).install(server)
        port = server.listen()
        client = PeerManager(
            priv_a, "long", self._status_factory(long_chain),
            fork_resolver=ForkResolver(
                2, long_chain.get_header_by_number(2).hash
            ),
        )
        try:
            peer = client.connect("127.0.0.1", port, pub_b)
            assert peer.alive
        finally:
            client.stop()
            server.stop()
